"""Retry policies and circuit breaking for the serving layers.

Replaces the bare ``RETRYABLE = (RuntimeError, OSError)`` tuple and the
hard-coded "retry once, immediately" sites with one policy object:
bounded attempts, exponential backoff with *deterministic* seeded jitter
(two runs of the same schedule sleep identically — chaos tests and bench
artifacts stay reproducible), per-attempt deadlines, and a classifier
that sends programming errors straight out instead of replaying them.

The :class:`CircuitBreaker` is the consecutive-failure gate in front of
the compiled device path: closed (normal) → open (device presumed down;
callers skip straight to their fallback) → half-open after a cooldown
(one probe re-tests the fast path) → closed on probe success. All
transitions are exported as the ``langdetect_breaker_state`` gauge
(0 = closed, 1 = half-open, 2 = open) so a scrape shows degradation the
moment it starts.

Everything here is host-side stdlib — importing this module never
touches jax.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("resilience.policy")


class DeadlineExceeded(RuntimeError):
    """A failed attempt also blew its per-attempt deadline: stop retrying.

    RuntimeError-shaped on purpose: an *outer* policy (the stream engine
    above a runner) may still classify a blown inner deadline as
    transient and replay the whole unit once.
    """


class BreakerOpen(RuntimeError):
    """Raised by :meth:`RetryPolicy.run` when a gating breaker is open and
    the caller asked for gating (``breaker_gates=True``)."""


# --- retryable-exception classifier ------------------------------------------
# Transient, environment-shaped failures worth replaying: device/tunnel
# runtime errors (jax's XlaRuntimeError is a RuntimeError subclass), host
# I/O, timeouts. NOT retryable even though they subclass RuntimeError:
# NotImplementedError and RecursionError are programming errors — the old
# bare tuple replayed both. BaseExceptions that aren't Exceptions
# (KeyboardInterrupt, SystemExit, GeneratorExit) are never classified
# retryable and :meth:`RetryPolicy.run` never even catches them.
_RETRYABLE_BASES = (RuntimeError, OSError, TimeoutError)
_NON_RETRYABLE_RUNTIME = (NotImplementedError, RecursionError)


def is_retryable(exc: BaseException) -> bool:
    """True when ``exc`` looks transient (worth replaying the work for)."""
    if not isinstance(exc, Exception):
        return False
    if isinstance(exc, _NON_RETRYABLE_RUNTIME):
        return False
    return isinstance(exc, _RETRYABLE_BASES)


# Env knobs (docs/RESILIENCE.md §2): one shared namespace — per-site
# policies are constructed in code, the env sets the process default.
# The spellings below are kept as importable constants (tests pin them);
# the *reads* resolve through exec/config's audited table, so a malformed
# value raises instead of silently meaning "default" and /varz reports
# exactly what a policy built from the environment will do.
RETRY_ATTEMPTS_ENV = "LANGDETECT_RETRY_MAX_ATTEMPTS"
RETRY_BASE_DELAY_ENV = "LANGDETECT_RETRY_BASE_DELAY_S"
RETRY_MAX_DELAY_ENV = "LANGDETECT_RETRY_MAX_DELAY_S"
RETRY_MULTIPLIER_ENV = "LANGDETECT_RETRY_MULTIPLIER"
RETRY_JITTER_ENV = "LANGDETECT_RETRY_JITTER"
RETRY_SEED_ENV = "LANGDETECT_RETRY_SEED"
RETRY_DEADLINE_ENV = "LANGDETECT_RETRY_ATTEMPT_DEADLINE_S"

_U64 = (1 << 64) - 1


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry schedule with deterministic seeded jitter.

    ``max_attempts`` counts the first try: the default of 2 preserves the
    serving layers' historical replay-once semantics, now with backoff
    and classification. ``attempt_deadline_s`` is *post-hoc*: a Python
    thread cannot preempt a blocked XLA dispatch, so an attempt that both
    raised and overran the deadline converts to :class:`DeadlineExceeded`
    instead of being retried — the deadline bounds total retry spend
    rather than pretending to cancel device work.
    """

    max_attempts: int = 2
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    # Fraction of each delay that is jittered *downward*: delay lands in
    # [base*(1-jitter), base]. Deterministic per (seed, attempt).
    jitter: float = 0.5
    seed: int = 0
    attempt_deadline_s: float | None = None
    classify: Callable[[BaseException], bool] = field(default=is_retryable)

    @staticmethod
    def from_env(env=os.environ, **overrides) -> "RetryPolicy":
        """Process-default policy from ``LANGDETECT_RETRY_*``; keyword
        overrides win (call sites pin what must not drift). Knobs resolve
        through exec/config's audited precedence table — a malformed
        value raises rather than silently meaning the default."""
        from ..exec import config as exec_config

        def knob(name):
            return exec_config.resolve(name, env=env)

        kw = dict(
            max_attempts=max(1, int(knob("retry_max_attempts"))),
            base_delay_s=knob("retry_base_delay_s"),
            multiplier=knob("retry_multiplier"),
            max_delay_s=knob("retry_max_delay_s"),
            jitter=min(1.0, max(0.0, knob("retry_jitter"))),
            seed=knob("retry_seed"),
            attempt_deadline_s=knob("retry_attempt_deadline_s"),
        )
        kw.update(overrides)
        return RetryPolicy(**kw)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry number ``attempt`` (1-based: the delay
        between attempt N failing and attempt N+1 starting). Pure function
        of (policy, attempt) — replaying a schedule sleeps identically."""
        base = min(
            self.max_delay_s,
            self.base_delay_s * self.multiplier ** max(0, attempt - 1),
        )
        if self.jitter <= 0.0 or base <= 0.0:
            return base
        # splitmix64-style hash of (seed, attempt): deterministic jitter
        # with no dependence on process-global random state.
        x = (
            (self.seed * 0x9E3779B97F4A7C15) + (attempt * 0xBF58476D1CE4E5B9)
        ) & _U64
        x ^= x >> 30
        x = (x * 0x94D049BB133111EB) & _U64
        x ^= x >> 31
        u = x / float(1 << 64)
        return base * (1.0 - self.jitter * u)

    def run(
        self,
        fn: Callable[[], object],
        *,
        site: str = "",
        breaker: "CircuitBreaker | None" = None,
        breaker_gates: bool = False,
        on_retry: Callable[[int, float, BaseException], None] | None = None,
        sleep: Callable[[float], None] = time.sleep,
        initial_error: BaseException | None = None,
        log_fields: dict | None = None,
    ) -> object:
        """Execute ``fn`` under this policy.

        Only ``Exception`` is ever caught — ``KeyboardInterrupt`` /
        ``SystemExit`` always propagate from the attempt itself. A
        non-retryable exception propagates immediately (no replay, no
        breaker accounting: a programming error says nothing about device
        health). Each retry logs a structured ``resilience.retry`` event
        carrying the site, attempt number, backoff delay, error, and the
        ambient ``trace_id``, and feeds the registry
        (``resilience/retries`` counter, ``resilience/retry_backoff_s``
        histogram, ``langdetect_retry_attempts`` gauge).

        ``breaker``: per-attempt outcomes are recorded on it; with
        ``breaker_gates=True`` an open breaker raises :class:`BreakerOpen`
        instead of attempting at all. ``initial_error``: the caller
        already burned attempt 1 elsewhere (the runner's async fetch
        surfaces the dispatch's failure later) — seed the schedule with
        it so total attempts stay bounded by ``max_attempts``.
        ``on_retry(attempt, delay_s, exc)`` lets call sites keep their
        legacy per-site counters.
        """
        from ..telemetry.tracing import current_trace_id

        attempt = 0

        def _account_retry(exc: BaseException) -> float:
            delay = self.backoff_s(attempt)
            REGISTRY.incr("resilience/retries")
            REGISTRY.observe("resilience/retry_backoff_s", delay)
            REGISTRY.set_gauge(
                "langdetect_retry_attempts", attempt, site=site or "unknown"
            )
            log_event(
                _log,
                "resilience.retry",
                site=site,
                attempt=attempt,
                max_attempts=self.max_attempts,
                backoff_s=round(delay, 6),
                error=repr(exc),
                trace_id=current_trace_id(),
                **(log_fields or {}),
            )
            if on_retry is not None:
                on_retry(attempt, delay, exc)
            return delay

        if initial_error is not None:
            attempt = 1
            if not self.classify(initial_error):
                raise initial_error
            if attempt >= self.max_attempts:
                raise initial_error
            delay = _account_retry(initial_error)
            if delay > 0.0:
                sleep(delay)

        while True:
            if breaker is not None and breaker_gates and not breaker.allow():
                raise BreakerOpen(
                    f"circuit breaker {breaker.name!r} is open at {site!r}"
                )
            attempt += 1
            t0 = time.perf_counter()
            try:
                result = fn()
            except Exception as exc:
                elapsed = time.perf_counter() - t0
                retryable = self.classify(exc)
                if breaker is not None and retryable:
                    breaker.record_failure()
                if not retryable:
                    raise
                if (
                    self.attempt_deadline_s is not None
                    and elapsed > self.attempt_deadline_s
                ):
                    raise DeadlineExceeded(
                        f"attempt {attempt} at {site or 'unknown'} failed "
                        f"after {elapsed:.3f}s (deadline "
                        f"{self.attempt_deadline_s}s)"
                    ) from exc
                if attempt >= self.max_attempts:
                    raise
                delay = _account_retry(exc)
                if delay > 0.0:
                    sleep(delay)
                continue
            if breaker is not None:
                breaker.record_success()
            return result


# --- retry budget ------------------------------------------------------------
RETRY_BUDGET_FRACTION_ENV = "LANGDETECT_RETRY_BUDGET_FRACTION"
RETRY_BUDGET_BURST_ENV = "LANGDETECT_RETRY_BUDGET_BURST"


class RetryBudget:
    """Token-bucket retry budget: retries as a fraction of successes.

    The metastable-failure guard (docs/RESILIENCE.md "Storm defense"):
    every *success* deposits ``fraction`` tokens (capped at ``burst``,
    which is also the starting balance — a quiet service can absorb a
    small incident immediately), and every retry-shaped extra attempt —
    a router failover, a client 503 re-send, a hedge — must withdraw one
    whole token first. During an outage successes dry up, the bucket
    drains, and retry amplification is bounded by
    ``burst + fraction × successes`` over any window instead of
    multiplying the offered load. A denied withdrawal is an explicit shed
    (``fleet/retry_budget_exhausted``), never a queued hope.

    ``fraction <= 0`` disables the budget: :meth:`try_spend` always
    grants, preserving the un-budgeted legacy behavior. Thread-safe; the
    live balance is exported as ``langdetect_retry_budget_tokens``.
    """

    def __init__(
        self,
        fraction: float | None = None,
        burst: float | None = None,
        *,
        name: str = "fleet",
    ):
        from ..exec import config as exec_config

        self.fraction = float(
            exec_config.resolve("retry_budget_fraction", fraction)
        )
        self.burst = max(
            1.0, float(exec_config.resolve("retry_budget_burst", burst))
        )
        self.name = name
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._successes = 0
        self._spent = 0
        self._denied = 0
        self._gauge()

    @property
    def enabled(self) -> bool:
        return self.fraction > 0.0

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def _gauge(self) -> None:
        REGISTRY.set_gauge(
            "langdetect_retry_budget_tokens", round(self._tokens, 6),
            budget=self.name,
        )

    def record_success(self) -> None:
        """Deposit for one successful (non-retry) unit of work."""
        if not self.enabled:
            return
        with self._lock:
            self._successes += 1
            self._tokens = min(self.burst, self._tokens + self.fraction)
            self._gauge()

    def try_spend(self, *, reason: str = "retry") -> bool:
        """Withdraw one token for an extra attempt; False ⇒ shed it."""
        if not self.enabled:
            return True
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self._spent += 1
                granted = True
            else:
                self._denied += 1
                granted = False
            self._gauge()
        if not granted:
            REGISTRY.incr("fleet/retry_budget_exhausted")
            log_event(
                _log, "resilience.retry_budget.exhausted",
                budget=self.name, reason=reason, fraction=self.fraction,
            )
        return granted

    def describe(self) -> dict:
        """Budget state for /varz and the storm drill's assertions."""
        with self._lock:
            return {
                "name": self.name,
                "enabled": self.enabled,
                "fraction": self.fraction,
                "burst": self.burst,
                "tokens": round(self._tokens, 6),
                "successes": self._successes,
                "spent": self._spent,
                "denied": self._denied,
            }


# --- circuit breaker ---------------------------------------------------------
BREAKER_THRESHOLD_ENV = "LANGDETECT_BREAKER_THRESHOLD"
BREAKER_COOLDOWN_ENV = "LANGDETECT_BREAKER_COOLDOWN_S"
BREAKER_PROBES_ENV = "LANGDETECT_BREAKER_PROBES"

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    """Consecutive-failure breaker: closed → open → half-open → closed.

    ``failure_threshold`` consecutive retryable failures open the
    breaker; after ``cooldown_s`` the next :meth:`allow` transitions to
    half-open and admits probes; ``probe_successes`` consecutive
    successes close it again, any probe failure re-opens (and restarts
    the cooldown). Thread-safe; the clock is injectable so tests drive
    the cooldown without sleeping.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 5.0,
        probe_successes: int = 1,
        *,
        name: str = "device",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self.probe_successes = max(1, int(probe_successes))
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._probe_hits = 0
        self._opened_at = 0.0

    @staticmethod
    def from_env(env=os.environ, *, name: str = "device") -> "CircuitBreaker":
        from ..exec import config as exec_config

        return CircuitBreaker(
            failure_threshold=max(
                1, int(exec_config.resolve("breaker_threshold", env=env))
            ),
            cooldown_s=exec_config.resolve("breaker_cooldown_s", env=env),
            probe_successes=max(
                1, int(exec_config.resolve("breaker_probes", env=env))
            ),
            name=name,
        )

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, new_state: str) -> None:
        """Caller holds the lock. Emits the state gauge + transition log."""
        old, self._state = self._state, new_state
        self._consecutive_failures = 0
        self._probe_hits = 0
        if new_state == OPEN:
            self._opened_at = self._clock()
            REGISTRY.incr("resilience/breaker_opened")
        REGISTRY.set_gauge(
            "langdetect_breaker_state", _STATE_GAUGE[new_state],
            breaker=self.name,
        )
        log_event(
            _log, "resilience.breaker", breaker=self.name,
            from_state=old, to_state=new_state,
        )

    def allow(self) -> bool:
        """May the protected (fast) path be attempted right now?"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._transition(HALF_OPEN)
                    return True
                return False
            return True  # HALF_OPEN: probes admitted

    def record_success(self) -> None:
        with self._lock:
            if self._state == CLOSED:
                self._consecutive_failures = 0
                return
            # HALF_OPEN — and OPEN too: a success while open is live probe
            # evidence the path works (it happens when a retry inside one
            # policy run lands *after* the probe attempt that re-opened
            # the breaker). Ignoring it would leave a proven-healthy path
            # gated until the next cooldown.
            hits = self._probe_hits + 1
            if hits >= self.probe_successes:
                self._transition(CLOSED)
            else:
                if self._state == OPEN:
                    self._transition(HALF_OPEN)
                self._probe_hits = hits

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._transition(OPEN)  # probe failed: restart the cooldown
            elif self._state == CLOSED:
                self._consecutive_failures += 1
                if self._consecutive_failures >= self.failure_threshold:
                    self._transition(OPEN)
            # OPEN: already tripped; failures while open don't accumulate.
