"""Dead-letter queue: quarantine for rows a streaming batch cannot score.

Spark Structured Streaming kills the whole query when a batch exhausts
its task retries; the production answer (and this module) is to quarantine
the offending input instead — the query keeps serving every healthy row,
and the poison rows land somewhere a human (or a replayer) can find them
with enough context to debug: batch sequence number, row index, the full
row, the error, and a timestamp.

The queue is an in-memory record list plus an optional append-only JSONL
file (one ``dlq.row`` object per line — the same event-log shape as the
telemetry JSONL, so the usual tooling greps it). Writes are contained:
a full disk must degrade the quarantine to memory-only, never take down
the stream that is busy surviving a poison batch.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("resilience.dlq")


class DeadLetterQueue:
    """Ordered record of quarantined rows; optionally file-backed."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.records: list[dict] = []
        self._lock = threading.Lock()
        self._fh = None
        self._write_warned = False
        if path is not None:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._fh = open(path, "a", encoding="utf-8")

    def put(self, *, batch: int, row_index: int, row: dict, error: str) -> dict:
        """Quarantine one row; returns the stored record."""
        record = {
            "event": "dlq.row",
            "ts": time.time(),
            "batch": int(batch),
            "row_index": int(row_index),
            "row": row,
            "error": error,
        }
        with self._lock:
            self.records.append(record)
            count = len(self.records)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(record, default=str) + "\n")
                    self._fh.flush()
                except Exception as e:
                    REGISTRY.incr("resilience/dlq_write_errors")
                    if not self._write_warned:
                        self._write_warned = True
                        import warnings

                        warnings.warn(
                            f"dead-letter file {self.path!r} write failed, "
                            f"quarantining in memory only: {e}",
                            RuntimeWarning,
                            stacklevel=2,
                        )
        REGISTRY.incr("resilience/dlq_rows")
        REGISTRY.set_gauge("langdetect_dlq_rows", count)
        log_event(
            _log, "dlq.row", batch=batch, row_index=row_index, error=error
        )
        return record

    def rows(self) -> list[dict]:
        """The quarantined row payloads, in arrival order."""
        with self._lock:
            return [r["row"] for r in self.records]

    def __len__(self) -> int:
        with self._lock:
            return len(self.records)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    @staticmethod
    def load(path: str) -> list[dict]:
        """Read a dead-letter JSONL file back into record dicts."""
        out: list[dict] = []
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
