"""Deterministic fault injection: a seeded chaos layer for the serving path.

Every recovery path in this repo must be exercisable on CPU in tier-1 —
waiting for a real TPU preemption to test the breaker is not a test
strategy. A :class:`FaultPlan` describes *exactly* which calls at which
named sites fail and how; the instrumented sites (see :data:`SITES`) ask
the active plan before doing real work. With no plan installed the hooks
are a single ``None`` check — the production hot path pays nothing.

Plan grammar (env ``LANGDETECT_FAULT_PLAN`` or :meth:`FaultPlan.parse`)::

    seed=42;score/dispatch:error@2,5;score/fetch:delay=0.01@1-3;
    stream/batch:poison=2@4;shard_step:error%0.1

``;``-separated entries. ``seed=N`` seeds the deterministic jitter/row
choices. Every other entry is ``site:kind[=value][@calls][%prob]``:

  * ``kind`` — ``error`` (raise an :class:`InjectedFault`, shaped like
    jax's ``XlaRuntimeError``: a ``RuntimeError`` the retry classifier
    treats as transient), ``delay`` (sleep ``value`` seconds — a latency
    spike), or ``poison`` (corrupt ``value`` rows — default 1 — of a
    streaming batch so they fail *deterministically*, exercising the
    DLQ/bisect path).
  * ``@calls`` — 1-based call indices at that site: a comma list of
    numbers and ``lo-hi`` ranges. ``error``/``delay`` count *execution
    attempts* (a retried dispatch advances the counter, so ``@2`` fails
    one attempt and its replay passes); ``poison`` counts *source
    batches*.
  * ``%prob`` — instead of explicit calls, fire with probability ``prob``
    per call, decided by a hash of (seed, site, call) — still fully
    deterministic for a given seed.
  * neither ``@`` nor ``%`` — fire on every call.

Sites (one hook per serving layer; docs/RESILIENCE.md §4):

  * ``score/dispatch`` — :meth:`BatchRunner._dispatch_device` (and the
    degraded ladder's device-gather level: it is still a device dispatch).
  * ``score/fetch``    — the runner's per-batch result fetch.
  * ``score/pack``     — each device-encode wire build (the raw-bytes +
    offsets gather feeding :meth:`BatchRunner._dispatch_encoded`): a
    firing ``error`` fails the zero-copy lane before anything ships, so
    the degraded ladder falls to the host-pack rung — scores stay
    bit-identical, only the wire format degrades
    (docs/PERFORMANCE.md §11).
  * ``stream/batch``   — each streaming transform attempt (error/delay)
    and each pulled source batch (poison).
  * ``fit/count``      — the fit count stage (host pass or each device
    count step).
  * ``shard_step``     — each sharded-mesh fit step.
  * ``serve/admit``    — the online batcher's admission gate
    (:meth:`serve.batcher.ContinuousBatcher.submit`): a firing ``error``
    is converted into a shed (the request is rejected with
    :class:`~..serve.batcher.ServeOverloaded`, exactly like a full
    queue), so chaos plans drive the load-shedding and hot-swap paths
    deterministically on CPU.
  * ``serve/cache``    — every serve score-cache lookup and store
    (:mod:`..serve.cache`): a firing ``error`` makes that one cache
    operation fail, which the cache degrades to a miss (lookups
    recompute, stores are skipped) — an injected cache fault can cost
    throughput but can never produce a wrong or stale answer
    (docs/SERVING.md §10).
  * ``fleet/probe``    — each router health-probe attempt against one
    replica (:meth:`serve.router.FleetRouter.probe_once`): a firing
    ``error`` reads as "replica unreachable", so probe-flap plans drive
    ejection and half-open re-admission deterministically.
  * ``fleet/dispatch`` — each routed dispatch attempt to one replica:
    a firing ``error`` is a replica dying mid-flight, exercising the
    failover/retry-on-another-replica path.
  * ``fleet/swap``     — each per-replica step of the fleet-wide
    two-phase hot-swap (every phase-1 prepare, every phase-2 commit):
    plans abort phase 1 everywhere or crash mid-phase-2 and replay the
    rollback deterministically (docs/SERVING.md §9).
  * ``zoo/load``       — each tenant cold-load attempt in the model zoo
    (:meth:`zoo.ModelZoo`'s residency manager paging a tenant's tables
    back in): a firing ``error`` makes THAT tenant's request degrade to
    an explicit 503 + Retry-After shed — never a wrong-tenant answer —
    while every other tenant keeps serving (docs/SERVING.md §12). The
    call counter advances per attempt, so ``@1`` fails exactly the
    first cold load and its retry reloads cleanly, replaying
    deterministically like ``serve/cache``.
  * ``scale/spawn``    — each subprocess-replica spawn attempt
    (:meth:`scale.replica.ProcessReplica.spawn`): a firing ``error``
    fails that attempt, exercising the supervisor's bounded
    restart-with-backoff (and its give-up path past the budget)
    deterministically on CPU (docs/SERVING.md §13).
  * ``scale/decision`` — each autoscaler control-loop tick
    (:meth:`scale.autoscaler.Autoscaler.tick`): a firing ``error``
    skips that one tick entirely — fail-static, never a wrong scale
    action — counted as ``scale/decision_skips``; ``%prob`` plans
    replay the same skipped ticks for a given seed, like ``fleet/*``.
  * ``fleet/scrape``   — each ``/telemetryz`` scrape of one member by
    the fleet collector (:meth:`scale.elastic.ElasticFleet.
    collect_telemetry`): a firing ``error`` fails that one scrape —
    counted as ``fleet/agg_scrape_failures``, never propagated into
    the tick loop — so the aggregate-staleness (SLO freshness) and
    scrape-failure-regression paths replay deterministically
    (docs/OBSERVABILITY.md §14).
  * ``fleet/hedge``    — each *hedge* dispatch attempt the router issues
    (:meth:`serve.router.FleetRouter`'s hedged dispatch, docs/
    RESILIENCE.md §7): a firing ``error`` kills that hedge in flight —
    the primary still answers, so an injected hedge fault costs the
    latency win but never the request; ``delay`` makes the hedge itself
    the straggler, exercising primary-wins-first ordering.
  * ``fleet/quarantine`` — each query-of-death table operation (every
    quarantine lookup and every correlated-death record,
    :mod:`serve.quarantine`): a firing ``error`` degrades that one
    operation *open* — a failed lookup answers "not quarantined", a
    failed record drops the observation — so chaos can delay poison
    protection but can never reject a healthy request.
"""

from __future__ import annotations

import os
import re
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("resilience.faults")

FAULT_PLAN_ENV = "LANGDETECT_FAULT_PLAN"

SITES = (
    "score/dispatch",
    "score/fetch",
    "score/pack",
    "stream/batch",
    "fit/count",
    "shard_step",
    "serve/admit",
    "serve/cache",
    "fleet/probe",
    "fleet/dispatch",
    "fleet/swap",
    "zoo/load",
    "scale/spawn",
    "scale/decision",
    "fleet/scrape",
    "fleet/hedge",
    "fleet/quarantine",
)

KINDS = ("error", "delay", "poison")

_U64 = (1 << 64) - 1


class InjectedFault(RuntimeError):
    """XlaRuntimeError-shaped injected failure (RuntimeError subclass, so
    the retryable classifier treats it exactly like a device fault)."""


class PoisonRowError(ValueError):
    """Deterministic failure a poison row raises when encoded for scoring.

    A ``ValueError`` on purpose: the classifier must route it to the
    DLQ/raise path, never to a futile replay.
    """


class PoisonText(str):
    """A poisoned document: equal to the original text (str subclass, so
    schema checks and comparisons pass) but impossible to encode — every
    scoring path goes through ``text_to_bytes``, which calls ``encode``.
    """

    def encode(self, *args, **kwargs):  # noqa: D102 - poison contract
        raise PoisonRowError(
            f"injected poison row ({len(self)} chars): cannot encode"
        )


def _mix(*parts: int) -> float:
    """Deterministic uniform [0, 1) from integer parts (splitmix64-ish)."""
    x = 0x9E3779B97F4A7C15
    for p in parts:
        x = ((x ^ (p & _U64)) * 0xBF58476D1CE4E5B9) & _U64
        x ^= x >> 31
    x = (x * 0x94D049BB133111EB) & _U64
    x ^= x >> 29
    return x / float(1 << 64)


def _fnv1a(text: str) -> int:
    """Process-independent string hash (FNV-1a). The builtin ``hash()`` is
    salted per process (PYTHONHASHSEED), which would give every process of
    a multi-host mesh — and every rerun — a different %prob schedule."""
    h = 0xCBF29CE484222325
    for b in text.encode("utf-8"):
        h = ((h ^ b) * 0x100000001B3) & _U64
    return h


@dataclass(frozen=True)
class FaultSpec:
    """One parsed plan entry."""

    site: str
    kind: str
    value: float = 0.0  # delay seconds, or poison row count
    calls: tuple[tuple[int, int], ...] = ()  # inclusive (lo, hi) ranges
    prob: float | None = None

    def fires(self, call: int, seed: int) -> bool:
        if self.calls:
            return any(lo <= call <= hi for lo, hi in self.calls)
        if self.prob is not None:
            # site hashed in so two sites with the same %p don't fire in
            # lockstep; call hashed in so the schedule varies per call.
            h = _mix(seed, _fnv1a(self.site), call)
            return h < self.prob
        return True  # no selector: every call


_ENTRY_RE = re.compile(
    r"^(?P<site>[a-z_/]+):(?P<kind>[a-z]+)"
    r"(?:=(?P<value>[0-9.]+))?"
    r"(?:@(?P<calls>[0-9,\-]+))?"
    r"(?:%(?P<prob>[0-9.]+))?$"
)


def _parse_calls(text: str) -> tuple[tuple[int, int], ...]:
    out: list[tuple[int, int]] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        lo, sep, hi = part.partition("-")
        a = int(lo)
        b = int(hi) if sep else a
        if a < 1 or b < a:
            raise ValueError(f"bad call range {part!r} (1-based, lo <= hi)")
        out.append((a, b))
    if not out:
        raise ValueError("empty @calls selector")
    return tuple(out)


@dataclass(frozen=True)
class FaultPlan:
    """A seeded, deterministic chaos schedule over the named sites."""

    specs: tuple[FaultSpec, ...] = ()
    seed: int = 0

    @staticmethod
    def parse(text: str) -> "FaultPlan":
        specs: list[FaultSpec] = []
        seed = 0
        for raw in text.split(";"):
            entry = raw.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                seed = int(entry[len("seed="):])
                continue
            m = _ENTRY_RE.match(entry)
            if m is None:
                raise ValueError(
                    f"bad {FAULT_PLAN_ENV} entry {entry!r}; expected "
                    "site:kind[=value][@calls][%prob]"
                )
            site, kind = m.group("site"), m.group("kind")
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r}; expected one of {SITES}"
                )
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; expected one of {KINDS}"
                )
            if m.group("calls") and m.group("prob"):
                raise ValueError(
                    f"entry {entry!r}: @calls and %prob are exclusive"
                )
            value = float(m.group("value") or 0.0)
            if kind == "poison" and value <= 0:
                value = 1.0
            specs.append(
                FaultSpec(
                    site=site,
                    kind=kind,
                    value=value,
                    calls=_parse_calls(m.group("calls"))
                    if m.group("calls")
                    else (),
                    prob=float(m.group("prob"))
                    if m.group("prob") is not None
                    else None,
                )
            )
        return FaultPlan(specs=tuple(specs), seed=seed)

    def poison_rows(self, call: int, num_rows: int) -> list[int]:
        """Row indices to poison in source batch number ``call`` (sorted,
        deterministic in (seed, call))."""
        rows: set[int] = set()
        for spec in self.specs:
            if spec.kind != "poison" or not spec.fires(call, self.seed):
                continue
            want = min(num_rows, max(1, int(spec.value)))
            i = 0
            while len(rows) < want and i < 64 * want:
                rows.add(int(_mix(self.seed, call, i) * num_rows) % num_rows)
                i += 1
        return sorted(rows)


# --- process-global active plan ----------------------------------------------
_plan: FaultPlan | None = None
_counters: dict[tuple[str, str], int] = {}
_lock = threading.Lock()


def active() -> FaultPlan | None:
    return _plan


def install(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan`` process-wide (call counters restart at zero)."""
    global _plan
    with _lock:
        _plan = plan
        _counters.clear()
    log_event(_log, "faults.installed", specs=len(plan.specs), seed=plan.seed)
    return plan


def uninstall() -> None:
    global _plan
    with _lock:
        _plan = None
        _counters.clear()


@contextmanager
def plan_scope(plan: FaultPlan):
    """Arm ``plan`` for the duration of a with-block (tests, bench smoke)."""
    prev = _plan
    install(plan)
    try:
        yield plan
    finally:
        with _lock:
            globals()["_plan"] = prev
            _counters.clear()


def install_from_env(env=os.environ) -> FaultPlan | None:
    """Arm the env-declared plan; None when unset. Raises on a bad spec —
    a typo'd chaos schedule must be loud, not a silently clean run. The
    knob resolves through exec/config's audited table (imported lazily:
    this runs at package-import time, before the exec package is up)."""
    from ..exec import config as exec_config

    spec = (exec_config.resolve("fault_plan", env=env) or "").strip()
    if not spec:
        return None
    return install(FaultPlan.parse(spec))


def _next_call(site: str, channel: str) -> int:
    with _lock:
        key = (site, channel)
        _counters[key] = _counters.get(key, 0) + 1
        return _counters[key]


_shield = threading.local()


@contextmanager
def shield():
    """Suppress fault injection on THIS thread for the with-block.

    Diagnostic side-paths — the runner's background roofline lowering
    re-traces ``_dispatch_device`` off the serving path — execute
    instrumented Python bodies without serving anything. Letting a chaos
    plan fire there would consume a test's deterministic call budget in
    a thread that swallows the fault, so the fault the plan aimed at the
    *serving* attempt silently never lands. Shielded calls advance no
    counters: the plan's call indices keep meaning serving attempts.
    """
    prev = getattr(_shield, "on", False)
    _shield.on = True
    try:
        yield
    finally:
        _shield.on = prev


def inject(site: str) -> None:
    """Chaos hook for ``error``/``delay`` faults at one execution attempt.

    No-op without an active plan. A firing ``delay`` sleeps, a firing
    ``error`` raises :class:`InjectedFault`; both are counted
    (``resilience/faults_injected``) and logged with the site and call
    number so a chaos run's timeline is reconstructible from the JSONL.
    """
    plan = _plan
    if plan is None or getattr(_shield, "on", False):
        return
    call = _next_call(site, "exec")
    for spec in plan.specs:
        if spec.site != site or spec.kind == "poison":
            continue
        if not spec.fires(call, plan.seed):
            continue
        REGISTRY.incr("resilience/faults_injected")
        log_event(
            _log, "faults.fired", site=site, call=call, kind=spec.kind,
            value=spec.value,
        )
        if spec.kind == "delay":
            time.sleep(spec.value)
        else:
            raise InjectedFault(
                f"INTERNAL: injected fault at {site} (call {call})"
            )


def corrupt_batch(table, column: str | None, site: str = "stream/batch"):
    """Chaos hook for ``poison`` faults on one pulled source batch.

    Returns ``(table, poisoned_row_indices)`` — the table unchanged when
    nothing fires. Poisoned rows keep their text value (:class:`PoisonText`
    is a str subclass) but fail deterministically when the scoring path
    encodes them, which is what drives the engine's bisect → DLQ flow.
    """
    plan = _plan
    if plan is None:
        return table, []
    call = _next_call(site, "poison")
    if column is None or column not in table.schema:
        return table, []
    rows = plan.poison_rows(call, table.num_rows)
    if not rows:
        return table, []
    values = list(table.column(column))
    for i in rows:
        values[i] = PoisonText(values[i])
    REGISTRY.incr("resilience/faults_injected")
    log_event(_log, "faults.poisoned", site=site, call=call, rows=rows)
    return table.replace_column(column, values), rows


# Env-armed at import, like the telemetry sinks: every instrumented module
# imports this package, so setting LANGDETECT_FAULT_PLAN needs no code
# change. A bad plan degrades to a loud warning rather than an
# ImportError; calling install_from_env directly still raises.
try:
    install_from_env()
except Exception as _e:
    import warnings as _warnings

    _warnings.warn(
        f"{FAULT_PLAN_ENV} ignored — could not arm the fault plan: {_e}",
        RuntimeWarning,
        stacklevel=2,
    )
