"""Resilience subsystem: retry policies, circuit breaking, chaos testing.

The serving layers' failure handling used to be two hard-coded "retry
once" sites (runner dispatch/fetch, stream transform) with no deadlines,
no backoff, no input quarantine, and no way to exercise any of it
deterministically. TPU-fleet practice treats preemption and runtime
faults as routine and recovers via replay (PAPERS.md — the pjit/TPUv4
systems papers); the Spark Structured Streaming model the reference
implicitly relied on provides offset checkpointing and task retry for
free. This package supplies the TPU-native equivalents:

  * :mod:`.policy` — :class:`RetryPolicy` (bounded attempts, exponential
    backoff with deterministic seeded jitter, per-attempt deadlines, a
    retryable-exception classifier) and :class:`CircuitBreaker`
    (closed → open → half-open on consecutive device failures), both
    emitting telemetry (``langdetect_retry_attempts``,
    ``langdetect_breaker_state``).
  * :mod:`.faults` — a deterministic chaos layer: a :class:`FaultPlan`
    (env ``LANGDETECT_FAULT_PLAN`` or test hooks) injects
    XlaRuntimeError-shaped failures, latency spikes, and poison rows at
    named sites with a seeded schedule, so every recovery path is
    exercisable on CPU in tier-1.
  * :mod:`.dlq` — a dead-letter queue that quarantines rows a streaming
    batch cannot score instead of terminating the query.

The streaming engine (:mod:`..stream.microbatch`) layers per-batch
checkpointing and poison-row bisection on top; the batch runner
(:mod:`..api.runner`) layers the breaker-gated degraded-mode fallback
chain (compiled fast path → device gather → host scoring). See
``docs/RESILIENCE.md`` for the full contract.
"""

from __future__ import annotations

from .dlq import DeadLetterQueue
from .faults import FaultPlan, InjectedFault, PoisonRowError, PoisonText
from .policy import (
    BreakerOpen,
    CircuitBreaker,
    DeadlineExceeded,
    RetryPolicy,
    is_retryable,
)

__all__ = [
    "BreakerOpen",
    "CircuitBreaker",
    "DeadLetterQueue",
    "DeadlineExceeded",
    "FaultPlan",
    "InjectedFault",
    "PoisonRowError",
    "PoisonText",
    "RetryPolicy",
    "is_retryable",
]
