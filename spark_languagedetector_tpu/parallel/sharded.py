"""SPMD scoring and fit over a device mesh (jit + GSPMD shardings).

The distributed formulation of the two hot paths (SURVEY.md §5.8, §7.2
"dist"): annotate input/output shardings on the existing single-device ops
and let XLA insert the collectives —

  * **scoring**: batch split over ``data``; weight table replicated (small
    profiles ride ICI broadcast once) or split over ``vocab`` (2^20-bucket
    tables), where the gather of a window's weight row becomes a local-shard
    gather + all-reduce emitted by GSPMD;
  * **fit**: every device scatter-counts its document shard into a dense
    [V, L] table; the ``data``-axis reduction is a psum XLA inserts because
    the output is required replicated (or vocab-sharded, in which case it
    becomes a reduce-scatter). Weighting and per-language top-k stay on
    device, sharded over ``vocab``/
    replicated respectively.

This mirrors the Spark training pipeline's shuffles (groupByKey ×3,
LanguageDetector.scala:52-132) with exactly one collective.
"""

from __future__ import annotations

import itertools
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import fit_tpu
from ..ops.score import score_batch
from ..ops.vocab import VocabSpec
from ..resilience import faults
from ..telemetry import span
from .mesh import DATA_AXIS, VOCAB_AXIS, batch_sharding, replicated, vocab_sharding


def make_sharded_scorer(
    mesh: Mesh,
    spec: VocabSpec,
    *,
    shard_vocab: bool = False,
    block: int = 1024,
):
    """jit-compiled scorer with mesh shardings baked in.

    Returns ``fn(batch [B,S] u8, lengths [B] i32, weights, lut|None)
    -> scores [B,L] f32`` with B divisible by the data-axis size. ``weights``
    is either the dense [V, L] table (lut None — shardable over ``vocab``)
    or the compact [G+1, L] table with its int32 id→row ``lut``.
    """
    w_sharding = vocab_sharding(mesh) if shard_vocab else replicated(mesh)
    in_shardings = (
        batch_sharding(mesh),  # batch
        batch_sharding(mesh),  # lengths
        w_sharding,  # weights
        replicated(mesh),  # lut (small int32 table; replicate over ICI)
    )

    @partial(
        jax.jit,
        in_shardings=in_shardings,
        out_shardings=batch_sharding(mesh),
        static_argnames=(),
    )
    def scorer(batch, lengths, weights, lut):
        return score_batch(
            batch, lengths, weights, lut, spec=spec, block=block
        )

    ndata = int(mesh.shape[DATA_AXIS])
    steps = itertools.count()

    def wrapper(batch, lengths, weights, lut=None):
        if lut is None:
            lut = jnp.zeros(0, jnp.int32)  # sentinel: dense direct indexing
        # Dispatch is one GSPMD program over every shard; the span carries
        # the shard geometry (rows_per_shard × shards), a per-wrapper step
        # sequence (run-over-run ordering on a trace timeline), the
        # ambient request trace id, and — under fencing — the device time
        # through the slowest shard's completion.
        with span(
            "shard_score",
            shards=ndata,
            rows_per_shard=batch.shape[0] // ndata,
            step=next(steps),
        ) as sp:
            out = scorer(batch, lengths, weights, lut)
            sp.fence(out)
        return out

    return wrapper


def make_sharded_fit_step(
    mesh: Mesh,
    spec: VocabSpec,
    num_langs: int,
    *,
    shard_vocab: bool = True,
    donate: bool | None = None,
):
    """jit-compiled distributed fit accumulation step.

    ``fn(batch [B,S], lengths [B], lang_ids [B], counts_acc [V,L])
    -> counts_acc'`` — batch sharded over ``data``, the accumulator sharded
    over ``vocab`` (or replicated). The cross-device count reduction is the
    collective GSPMD derives from the output sharding.

    ``donate``: donate the accumulator buffer so XLA updates the [V, L]
    table in place instead of double-buffering it per step (the table is
    the fit's dominant buffer — 3.4GB per device at config-3 scale when
    replicated). None ⇒ on for accelerator meshes, off on the CPU test
    substrate, whose backend can't consume donations and would warn per
    step — the same gating as the single-device donated step. Callers must
    not reuse a passed accumulator after the call (the ``acc = step(acc)``
    chain every existing caller follows).
    """
    acc_sharding = vocab_sharding(mesh) if shard_vocab else replicated(mesh)
    if donate is None:
        donate = mesh.devices.flat[0].platform != "cpu"

    @partial(
        jax.jit,
        in_shardings=(
            batch_sharding(mesh),
            batch_sharding(mesh),
            batch_sharding(mesh),
            acc_sharding,
        ),
        out_shardings=acc_sharding,
        donate_argnums=(3,) if donate else (),
    )
    def fit_step(batch, lengths, lang_ids, counts_acc):
        return fit_tpu.fit_dense_step(
            batch, lengths, lang_ids, counts_acc, spec=spec, num_langs=num_langs
        )

    ndata = int(mesh.shape[DATA_AXIS])
    steps = itertools.count()

    def timed_step(batch, lengths, lang_ids, counts_acc):
        # Chaos hook BEFORE the dispatch: an injected failure surfaces
        # before any collective is enqueued, so every process of a
        # multi-host mesh (running the same deterministic plan) fails the
        # same step together and the estimator-level retry replays them
        # in lockstep.
        faults.inject("shard_step")
        with span(
            "shard_step",
            shards=ndata,
            rows_per_shard=batch.shape[0] // ndata,
            step=next(steps),
        ) as sp:
            out = fit_step(batch, lengths, lang_ids, counts_acc)
            sp.fence(out)
        return out

    return timed_step


def make_sharded_finalize(
    mesh: Mesh,
    *,
    profile_size: int,
    weight_mode: str = "parity",
    shard_vocab: bool = True,
):
    """jit-compiled profile finalization: counts [V,L] → (weights [V,L],
    top-k row ids [L,k]) with the table sharded over ``vocab``.

    ``lax.top_k`` over a vocab-sharded column is handled by GSPMD as
    local top-k + cross-shard merge.
    """
    acc_sharding = vocab_sharding(mesh) if shard_vocab else replicated(mesh)

    @partial(
        jax.jit,
        in_shardings=(acc_sharding,),
        out_shardings=(acc_sharding, replicated(mesh)),
        static_argnames=("k",),
    )
    def finalize(counts, *, k=profile_size):
        weights = fit_tpu.weights_from_counts(counts, weight_mode=weight_mode)
        top_rows = fit_tpu.top_k_rows(weights, k=k)
        return weights, top_rows

    nshards = int(mesh.shape[VOCAB_AXIS] if shard_vocab else 1)

    def timed_finalize(counts):
        # No k passthrough: pjit raises "does not support kwargs when
        # in_shardings is specified" for any kwarg, static ones included,
        # so the jitted finalize is only ever callable with its baked-in k.
        with span("shard_finalize", shards=nshards) as sp:
            weights, top_rows = finalize(counts)
            sp.fence(weights, top_rows)
        return weights, top_rows

    return timed_finalize


def training_step(
    mesh: Mesh,
    spec: VocabSpec,
    num_langs: int,
    profile_size: int,
    *,
    shard_vocab: bool = True,
    weight_mode: str = "parity",
):
    """One full distributed training step (count → weight → top-k), jitted
    end-to-end over the mesh. This is the step ``__graft_entry__.
    dryrun_multichip`` executes."""
    fit_step = make_sharded_fit_step(mesh, spec, num_langs, shard_vocab=shard_vocab)
    finalize = make_sharded_finalize(
        mesh,
        profile_size=profile_size,
        weight_mode=weight_mode,
        shard_vocab=shard_vocab,
    )

    def step(batch, lengths, lang_ids, counts_acc):
        counts = fit_step(batch, lengths, lang_ids, counts_acc)
        weights, top_rows = finalize(counts)
        return counts, weights, top_rows

    return step
