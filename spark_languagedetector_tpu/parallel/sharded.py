"""SPMD scoring and fit over a device mesh (jit + GSPMD shardings).

The distributed formulation of the two hot paths (SURVEY.md §5.8, §7.2
"dist"): annotate input/output shardings on the existing single-device ops
and let XLA insert the collectives —

  * **scoring**: batch split over ``data``; weight table replicated (small
    profiles ride ICI broadcast once) or split over ``vocab`` (2^20-bucket
    tables), where the gather of a window's weight row becomes a local-shard
    gather + all-reduce emitted by GSPMD;
  * **fit**: every device scatter-counts its document shard into a dense
    [V, L] table; the ``data``-axis reduction is a psum XLA inserts because
    the output is required replicated (or vocab-sharded, in which case it
    becomes a reduce-scatter). Weighting and per-language top-k stay on
    device, sharded over ``vocab``/
    replicated respectively.

This mirrors the Spark training pipeline's shuffles (groupByKey ×3,
LanguageDetector.scala:52-132) with exactly one collective.
"""

from __future__ import annotations

import itertools
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import fit_tpu
from ..ops.score import score_batch
from ..ops.vocab import VocabSpec
from ..resilience import faults
from ..telemetry import span
from .mesh import (
    DATA_AXIS,
    batch_sharding,
    replicated,
    shard_map_compat,
    table_axis,
    table_sharding,
    table_shards,
    vocab_sharding,
)


def make_sharded_scorer(
    mesh: Mesh,
    spec: VocabSpec,
    *,
    shard_vocab: bool = False,
    block: int = 1024,
):
    """jit-compiled scorer with mesh shardings baked in.

    Returns ``fn(batch [B,S] u8, lengths [B] i32, weights, lut|None)
    -> scores [B,L] f32`` with B divisible by the data-axis size. ``weights``
    is either the dense [V, L] table (lut None — shardable over ``vocab``)
    or the compact [G+1, L] table with its int32 id→row ``lut``.
    """
    w_sharding = vocab_sharding(mesh) if shard_vocab else replicated(mesh)
    in_shardings = (
        batch_sharding(mesh),  # batch
        batch_sharding(mesh),  # lengths
        w_sharding,  # weights
        replicated(mesh),  # lut (small int32 table; replicate over ICI)
    )

    @partial(
        jax.jit,
        in_shardings=in_shardings,
        out_shardings=batch_sharding(mesh),
        static_argnames=(),
    )
    def scorer(batch, lengths, weights, lut):
        return score_batch(
            batch, lengths, weights, lut, spec=spec, block=block
        )

    ndata = int(mesh.shape[DATA_AXIS])
    steps = itertools.count()

    def wrapper(batch, lengths, weights, lut=None):
        if lut is None:
            lut = jnp.zeros(0, jnp.int32)  # sentinel: dense direct indexing
        # Dispatch is one GSPMD program over every shard; the span carries
        # the shard geometry (rows_per_shard × shards), a per-wrapper step
        # sequence (run-over-run ordering on a trace timeline), the
        # ambient request trace id, and — under fencing — the device time
        # through the slowest shard's completion.
        with span(
            "shard_score",
            shards=ndata,
            rows_per_shard=batch.shape[0] // ndata,
            step=next(steps),
        ) as sp:
            out = scorer(batch, lengths, weights, lut)
            sp.fence(out)
        return out

    return wrapper


def make_sharded_fit_step(
    mesh: Mesh,
    spec: VocabSpec,
    num_langs: int,
    *,
    shard_vocab: bool | None = None,
    shard_table: bool | None = None,
    donate: bool | None = None,
):
    """jit-compiled distributed fit accumulation step.

    ``fn(batch [B,S], lengths [B], lang_ids [B], counts_acc [V,L])
    -> counts_acc'`` — batch sharded over ``data``, the accumulator sharded
    over the TABLE axis (or replicated). The table axis
    (``mesh.table_axis``) is the vocab axis when it has devices, else the
    data axis — so a data-only fit mesh still stripes the accumulator, and
    the cross-device count reduction GSPMD derives from the output
    sharding becomes a reduce-scatter instead of a full-table all-reduce.
    ``shard_vocab`` is the historical name for the same switch; both
    accept None (→ shard) and ``shard_table`` wins when both are given.

    ``donate``: donate the accumulator buffer so XLA updates the [V, L]
    table in place instead of double-buffering it per step (the table is
    the fit's dominant buffer — 3.4GB per device at config-3 scale when
    replicated). None ⇒ on for accelerator meshes, off on the CPU test
    substrate, whose backend can't consume donations and would warn per
    step — the same gating as the single-device donated step. Callers must
    not reuse a passed accumulator after the call (the ``acc = step(acc)``
    chain every existing caller follows).
    """
    if shard_table is None:
        shard_table = True if shard_vocab is None else shard_vocab
    acc_sharding = table_sharding(mesh) if shard_table else replicated(mesh)
    if donate is None:
        donate = mesh.devices.flat[0].platform != "cpu"

    @partial(
        jax.jit,
        in_shardings=(
            batch_sharding(mesh),
            batch_sharding(mesh),
            batch_sharding(mesh),
            acc_sharding,
        ),
        out_shardings=acc_sharding,
        donate_argnums=(3,) if donate else (),
    )
    def fit_step(batch, lengths, lang_ids, counts_acc):
        return fit_tpu.fit_dense_step(
            batch, lengths, lang_ids, counts_acc, spec=spec, num_langs=num_langs
        )

    # Deduplicated batches carry a per-row multiplicity operand
    # (docs/PERFORMANCE.md §10). jit compiles on first invocation, so a
    # duplicate-free fit never builds this program and keeps the
    # historical collective schedule byte for byte.
    @partial(
        jax.jit,
        in_shardings=(
            batch_sharding(mesh),
            batch_sharding(mesh),
            batch_sharding(mesh),
            batch_sharding(mesh),
            acc_sharding,
        ),
        out_shardings=acc_sharding,
        donate_argnums=(4,) if donate else (),
    )
    def fit_step_mult(batch, lengths, lang_ids, mult, counts_acc):
        return fit_tpu.fit_dense_step(
            batch, lengths, lang_ids, counts_acc, mult,
            spec=spec, num_langs=num_langs,
        )

    ndata = int(mesh.shape[DATA_AXIS])
    steps = itertools.count()

    def timed_step(batch, lengths, lang_ids, counts_acc, mult=None):
        # Chaos hook BEFORE the dispatch: an injected failure surfaces
        # before any collective is enqueued, so every process of a
        # multi-host mesh (running the same deterministic plan) fails the
        # same step together and the estimator-level retry replays them
        # in lockstep.
        faults.inject("shard_step")
        with span(
            "shard_step",
            shards=ndata,
            rows_per_shard=batch.shape[0] // ndata,
            step=next(steps),
        ) as sp:
            if mult is None:
                out = fit_step(batch, lengths, lang_ids, counts_acc)
            else:
                out = fit_step_mult(batch, lengths, lang_ids, mult, counts_acc)
            sp.fence(out)
        return out

    return timed_step


def make_sharded_finalize(
    mesh: Mesh,
    *,
    profile_size: int,
    weight_mode: str = "parity",
    shard_vocab: bool = True,
):
    """jit-compiled profile finalization: counts [V,L] → (weights [V,L],
    top-k row ids [L,k]) with the table sharded over ``vocab``.

    ``lax.top_k`` over a vocab-sharded column is handled by GSPMD as
    local top-k + cross-shard merge. This is the legacy full-table
    finalize (it materializes and RETURNS the [V, L] weight table); the
    fit path's winner-rows-only finalize is
    :func:`make_sharded_finalize_topk`.
    """
    acc_sharding = table_sharding(mesh) if shard_vocab else replicated(mesh)

    @partial(
        jax.jit,
        in_shardings=(acc_sharding,),
        out_shardings=(acc_sharding, replicated(mesh)),
        static_argnames=("k",),
    )
    def finalize(counts, *, k=profile_size):
        weights = fit_tpu.weights_from_counts(counts, weight_mode=weight_mode)
        top_rows = fit_tpu.top_k_rows(weights, k=k)
        return weights, top_rows

    nshards = table_shards(mesh) if shard_vocab else 1

    def timed_finalize(counts):
        # No k passthrough: pjit raises "does not support kwargs when
        # in_shardings is specified" for any kwarg, static ones included,
        # so the jitted finalize is only ever callable with its baked-in k.
        with span("shard_finalize", shards=nshards) as sp:
            weights, top_rows = finalize(counts)
            sp.fence(weights, top_rows)
        return weights, top_rows

    return timed_finalize


@lru_cache(maxsize=16)
def make_sharded_finalize_topk(
    mesh: Mesh,
    *,
    profile_size: int,
    weight_mode: str = "parity",
    block: int = 1 << 21,
):
    """Distributed reduce half of the fit: table-sharded counts [V, L] →
    replicated per-language top-k row ids [L, k], entirely on device.

    DrJAX (arXiv:2403.07128) frames the fit as map(count)/reduce(top-k);
    this is the reduce as one explicit shard_map program over the mesh's
    table axis:

      1. every shard computes its stripe's masked candidate weights and its
         local top-k candidates under the (value desc, id asc) total order,
         with ids lifted to GLOBAL gram ids
         (``ops.fit_tpu.shard_topk_candidates`` — blocked within the shard
         when the stripe exceeds the sort budget);
      2. an ``all_gather`` over the table axis concatenates every shard's
         ``k`` candidate (value, id) pairs — the only collective, moving
         ``shards·k·L`` pairs instead of the ``V·L`` table;
      3. the final selection re-ranks the boundary plateau by the
         candidates' real ids (``_final_candidates_top_k``), so the merge
         preserves the host fit's lowest-index tie order exactly, for any
         shard geometry.

    Exactness is the :func:`ops.fit_tpu.top_k_rows_blocked` argument with
    blocks = shards. Requires V divisible by the table-axis size (shard_map
    needs even stripes); callers fall back to the unsharded finalize
    otherwise (``ops.fit_tpu.finalize_counts``).

    Memoized on (mesh, k, weight_mode, block): the incremental refit
    engine re-runs ONLY this program per refit, so rebuilding the
    shard_map closure (and thus recompiling) every time would make refits
    pay a compile each — the cache keeps a live mesh's program warm.
    """
    from ..ops.fit_tpu import (
        _final_candidates_top_k,
        masked_candidate_weights,
        shard_topk_candidates,
    )

    ax = table_axis(mesh)
    nshards = table_shards(mesh)

    def local_topk(counts_shard):  # [V/shards, L] stripe
        rows = counts_shard.shape[0]
        kk = min(profile_size, rows)
        offset = (jax.lax.axis_index(ax) * rows).astype(jnp.int32)
        masked = masked_candidate_weights(
            counts_shard, weight_mode=weight_mode
        )
        bv, bi = shard_topk_candidates(masked, kk, offset, block=block)
        cand_v = jax.lax.all_gather(bv, ax, axis=1, tiled=True)
        cand_i = jax.lax.all_gather(bi, ax, axis=1, tiled=True)
        return _final_candidates_top_k(
            cand_v, cand_i, min(profile_size, rows * nshards)
        )

    # check_vma off: every shard computes the same merged result from the
    # all_gathered candidates; the rep-checker can't see that through the
    # top_k re-ranking.
    fn = jax.jit(
        shard_map_compat(
            local_topk,
            mesh=mesh,
            in_specs=(P(ax),),
            out_specs=P(),
            check_vma=False,
        )
    )

    def timed_topk(counts):
        with span("shard_finalize_topk", shards=nshards) as sp:
            top = fn(counts)
            sp.fence(top)
        return top

    return timed_topk


def training_step(
    mesh: Mesh,
    spec: VocabSpec,
    num_langs: int,
    profile_size: int,
    *,
    shard_vocab: bool = True,
    weight_mode: str = "parity",
):
    """One full distributed training step (count → weight → top-k), jitted
    end-to-end over the mesh. This is the step ``__graft_entry__.
    dryrun_multichip`` executes."""
    fit_step = make_sharded_fit_step(mesh, spec, num_langs, shard_vocab=shard_vocab)
    finalize = make_sharded_finalize(
        mesh,
        profile_size=profile_size,
        weight_mode=weight_mode,
        shard_vocab=shard_vocab,
    )

    def step(batch, lengths, lang_ids, counts_acc):
        counts = fit_step(batch, lengths, lang_ids, counts_acc)
        weights, top_rows = finalize(counts)
        return counts, weights, top_rows

    return step
