"""parallel subpackage."""
