"""Sequence/context parallelism: one document spread across the mesh.

The reference streams arbitrarily long documents through an O(1)-state
iterator on one executor (SURVEY.md §5.7). The TPU analog must be
fixed-shape AND unbounded, so a long document becomes a [D, C] grid of
overlapping chunks (overlap = max(gram_lengths) - 1, ownership masks as in
``ops.encoding.chunk_document``) laid out over the ``data`` axis; each device
scores its chunks locally and the per-document reduction is a sum of
[L]-vectors — the bag-of-grams analog of ring attention, except the
reduction is a commutative psum, so no ring of partial softmaxes is needed.

Two formulations are provided:

  * :func:`score_long_document` — the idiomatic one: sharding annotations,
    XLA emits the all-reduce.
  * :func:`ring_score_chunks` — an explicit shard_map + ``ppermute`` ring
    accumulation of the same sum. Numerically identical; exists for the
    DCN-unfriendly topologies where a ring schedule overlaps compute with
    neighbor transfers, and as the pattern native extensions build on.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.encoding import chunk_document
from ..ops.score import score_batch
from ..ops.vocab import VocabSpec
from .mesh import (
    DATA_AXIS,
    batch_sharding,
    pad_to_multiple,
    replicated,
    shard_map_compat,
)


def chunk_grid(
    doc: bytes, num_shards: int, chunk_size: int, gram_lengths: tuple[int, ...]
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Lay one document out as [num_chunks_padded, chunk_size] rows plus
    lengths and per-row owned-window limits, padded to a multiple of
    ``num_shards`` rows so the grid shards evenly over the data axis."""
    overlap = max(gram_lengths) - 1
    parts = chunk_document(doc, chunk_size, overlap)
    stride = chunk_size - overlap
    rows = len(parts)
    padded_rows = pad_to_multiple(rows, num_shards)
    batch = np.zeros((padded_rows, chunk_size), dtype=np.uint8)
    lengths = np.zeros(padded_rows, dtype=np.int32)
    limits = np.zeros(padded_rows, dtype=np.int32)
    for i, part in enumerate(parts):
        batch[i, : len(part)] = np.frombuffer(part, dtype=np.uint8)
        lengths[i] = len(part)
        limits[i] = stride if i < rows - 1 else chunk_size
    return batch, lengths, limits


@partial(jax.jit, static_argnames=("spec", "mesh_static"))
def _long_doc_score_jit(b, l, lim, w, ids, *, spec, mesh_static):
    per_chunk = score_batch(
        b, l, w, ids if (ids is not None and ids.size) else None,
        spec=spec, window_limit=lim,
    )
    return per_chunk.sum(axis=0)  # cross-shard sum → GSPMD all-reduce


def make_long_doc_scorer(mesh: Mesh, spec: VocabSpec, chunk_size: int = 8192):
    """Compile-once scorer for arbitrarily long single documents.

    Returns ``fn(doc: bytes, weights, lut|None) -> np.ndarray [L]``.
    The jit cache is keyed on (spec, mesh) — repeated calls with different
    documents reuse the compiled executables per padded grid shape.
    """
    n_data = mesh.shape[DATA_AXIS]
    b_shard, rep = batch_sharding(mesh), replicated(mesh)

    def score(doc: bytes, weights, lut=None) -> np.ndarray:
        batch, lengths, limits = chunk_grid(doc, n_data, chunk_size, spec.gram_lengths)
        args = [
            jax.device_put(batch, b_shard),
            jax.device_put(lengths, b_shard),
            jax.device_put(limits, b_shard),
            jax.device_put(weights, rep),
        ]
        ids = None if lut is None else jax.device_put(lut, rep)
        return np.asarray(
            _long_doc_score_jit(*args, ids, spec=spec, mesh_static=mesh)
        )

    return score


def score_long_document(
    doc: bytes,
    weights,
    lut,
    spec: VocabSpec,
    mesh: Mesh,
    chunk_size: int = 8192,
) -> np.ndarray:
    """Exact [L] score of one document of any length, computed across the
    mesh's data axis. Thin wrapper over :func:`make_long_doc_scorer`; the
    underlying computation is compiled once per (spec, mesh, grid shape)."""
    return make_long_doc_scorer(mesh, spec, chunk_size)(doc, weights, lut)


def ring_score_chunks(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    limits: jnp.ndarray,
    weights: jnp.ndarray,
    lut: jnp.ndarray | None,
    spec: VocabSpec,
    mesh: Mesh,
) -> jnp.ndarray:
    """Explicit ring accumulation of per-shard chunk scores via ppermute.

    Each of the D data shards scores its local chunk rows, then the partial
    [L] sums travel the ring D-1 hops, accumulating at every stop — the
    skeleton of ring attention with the softmax algebra replaced by a plain
    sum. Returns the total [L], replicated on every shard.
    """
    n_data = mesh.shape[DATA_AXIS]
    axis = DATA_AXIS

    def shard_fn(b, l, lim, w, ids):
        local = score_batch(
            b, l, w, ids if ids.size else None, spec=spec, window_limit=lim
        ).sum(axis=0)

        def hop(i, carry):
            acc, moving = carry
            moving = jax.lax.ppermute(
                moving,
                axis,
                perm=[(j, (j + 1) % n_data) for j in range(n_data)],
            )
            return acc + moving, moving

        acc, _ = jax.lax.fori_loop(0, n_data - 1, hop, (local, local))
        return acc[None, :]

    ids_arr = lut if lut is not None else jnp.zeros(0, jnp.int32)
    fn = shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    per_shard_totals = fn(batch, lengths, limits, weights, ids_arr)  # [D, L]
    # Every shard now holds the full sum; take shard 0's copy.
    return per_shard_totals[0]
