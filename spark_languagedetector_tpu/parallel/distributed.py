"""Multi-host initialization and host-level data distribution.

The reference's multi-machine story is Spark's cluster manager + shuffle
service (SURVEY.md §2.3). The TPU-native story: ``jax.distributed`` brings up
the slice-wide runtime (one process per host, ICI inside the slice, DCN
between hosts), after which the mesh in ``mesh.py`` spans every host's
devices and the SPMD code in ``sharded.py`` runs unchanged — GSPMD routes
collectives over ICI within the slice and DCN across slices.

Host-side responsibilities that remain explicit (the ``mapPartitions``
analog): each host feeds only its own shard of documents (``host_shard``),
and globally-addressed arrays are assembled with
``jax.make_array_from_process_local_data``.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ..utils.logging import get_logger, log_event

_log = get_logger("parallel.distributed")


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up the multi-host runtime (idempotent, no-op single-process).

    On Cloud TPU the three arguments are auto-detected from the metadata
    server; elsewhere pass them explicitly or via the env vars
    ``LANGDETECT_TPU_COORDINATOR`` / ``LANGDETECT_TPU_NUM_PROCESSES`` /
    ``LANGDETECT_TPU_PROCESS_ID``, mirroring ``jax.distributed.initialize``.
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("LANGDETECT_TPU_COORDINATOR")
    if num_processes is None:
        env_procs = os.environ.get("LANGDETECT_TPU_NUM_PROCESSES")
        num_processes = int(env_procs) if env_procs else None
    if process_id is None:
        env_pid = os.environ.get("LANGDETECT_TPU_PROCESS_ID")
        process_id = int(env_pid) if env_pid else None
    if coordinator_address is None and num_processes in (None, 1):
        log_event(_log, "distributed.single_process")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log_event(
        _log,
        "distributed.initialized",
        process_index=jax.process_index(),
        process_count=jax.process_count(),
    )


def host_shard(n_items: int) -> slice:
    """This host's contiguous shard of an n_items-long work list."""
    from .mesh import pad_to_multiple

    p, k = jax.process_index(), jax.process_count()
    per = pad_to_multiple(n_items, k) // k
    return slice(p * per, min((p + 1) * per, n_items))


def global_batch(local_batch: np.ndarray, sharding):
    """Assemble a globally-sharded array from per-host local shards."""
    return jax.make_array_from_process_local_data(sharding, local_batch)
