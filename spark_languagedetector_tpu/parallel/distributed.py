"""Multi-host initialization and host-level data distribution.

The reference's multi-machine story is Spark's cluster manager + shuffle
service (SURVEY.md §2.3). The TPU-native story: ``jax.distributed`` brings up
the slice-wide runtime (one process per host, ICI inside the slice, DCN
between hosts), after which the mesh in ``mesh.py`` spans every host's
devices and the SPMD code in ``sharded.py`` runs unchanged — GSPMD routes
collectives over ICI within the slice and DCN across slices.

Host-side responsibilities that remain explicit (the ``mapPartitions``
analog): each host feeds only its own shard of documents (``host_shard``),
and globally-addressed arrays are assembled with
``jax.make_array_from_process_local_data``.
"""

from __future__ import annotations

import jax
import numpy as np

from ..exec import config as exec_config
from ..utils.logging import get_logger, log_event

_log = get_logger("parallel.distributed")


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up the multi-host runtime (idempotent, no-op single-process).

    On Cloud TPU the three arguments are auto-detected from the metadata
    server; elsewhere pass them explicitly or via the env vars
    ``LANGDETECT_TPU_COORDINATOR`` / ``LANGDETECT_TPU_NUM_PROCESSES`` /
    ``LANGDETECT_TPU_PROCESS_ID``, mirroring ``jax.distributed.initialize``.
    The env spellings resolve through ``exec/config``'s audited table
    (type-validated, surfaced in ``/varz`` ``effective_config``) — the
    table itself is importable without JAX, so the bring-up knobs are
    readable before any backend initializes.
    """
    coordinator_address = exec_config.resolve(
        "tpu_coordinator", explicit=coordinator_address
    )
    num_processes = exec_config.resolve(
        "tpu_num_processes", explicit=num_processes
    )
    process_id = exec_config.resolve("tpu_process_id", explicit=process_id)
    if coordinator_address is None and num_processes in (None, 1):
        log_event(_log, "distributed.single_process")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log_event(
        _log,
        "distributed.initialized",
        process_index=jax.process_index(),
        process_count=jax.process_count(),
    )


def host_shard(n_items: int) -> slice:
    """This host's contiguous shard of an n_items-long work list."""
    from .mesh import pad_to_multiple

    p, k = jax.process_index(), jax.process_count()
    per = pad_to_multiple(n_items, k) // k
    return slice(p * per, min((p + 1) * per, n_items))


def global_batch(local_batch: np.ndarray, sharding):
    """Assemble a globally-sharded array from per-host local shards."""
    return jax.make_array_from_process_local_data(sharding, local_batch)
