"""Device mesh construction and axis conventions.

The reference delegates all distribution to Spark (SURVEY.md §2.2/§2.3). The
TPU-native replacement is one ``jax.sharding.Mesh`` with two named axes:

  * ``"data"`` — batch/document parallelism (the analog of Spark's
    data-parallel map over partitions);
  * ``"vocab"`` — model parallelism over the gram-id axis of the weight /
    count tables (the analog of nothing in the reference — its model always
    fit on one JVM — but required at 2^20-bucket × 176-language scale).

All collectives are emitted by XLA from sharding annotations (GSPMD): counts
aggregate with an all-reduce over ``data``; vocab-sharded tables keep their
gathers local to the ``vocab`` shard. Nothing in this package hand-writes a
collective for the SPMD path; ``sequence.py`` shows the explicit shard_map/
ppermute formulation for the ring variant.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
VOCAB_AXIS = "vocab"


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the jax versions this repo runs on.

    jax >= 0.5 exposes ``jax.shard_map(..., check_vma=)``; 0.4.x only has
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` (same knob,
    earlier name). One alias site so every mesh wrapper (runner pallas/hist
    dispatch, the ring scorer) stays version-agnostic.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as sm

    return sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def build_mesh(
    data: int | None = None,
    vocab: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh of shape (data, vocab) over the given (or all) devices.

    ``data=None`` uses every remaining device on the data axis. On a single
    chip this degenerates to a 1×1 mesh and all shardings become no-ops —
    the same code path serves one chip and a slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devices) % vocab:
            raise ValueError(f"{len(devices)} devices not divisible by vocab={vocab}")
        data = len(devices) // vocab
    if data * vocab > len(devices):
        raise ValueError(
            f"mesh {data}x{vocab} needs {data * vocab} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[: data * vocab]).reshape(data, vocab)
    return Mesh(grid, (DATA_AXIS, VOCAB_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[B, ...] arrays split over the data axis, replicated over vocab."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def vocab_sharding(mesh: Mesh) -> NamedSharding:
    """[V, L] tables split over the vocab axis (rows), replicated over data."""
    return NamedSharding(mesh, P(VOCAB_AXIS))


def table_axis(mesh: Mesh) -> str:
    """The mesh axis that shards [V, L] count/weight tables for the fit.

    A dedicated vocab axis wins when it actually has devices; otherwise the
    data axis doubles as the table axis — the fit mesh is usually built
    data-only (``resolve_fit_mesh``), and sharding the count accumulator
    over its devices is what turns the per-step count reduction into a
    reduce-scatter and bounds every device's finalize to V/ndata rows.
    """
    return VOCAB_AXIS if int(mesh.shape[VOCAB_AXIS]) > 1 else DATA_AXIS


def table_sharding(mesh: Mesh) -> NamedSharding:
    """[V, L] tables split over :func:`table_axis` (rows)."""
    return NamedSharding(mesh, P(table_axis(mesh)))


def table_shards(mesh: Mesh) -> int:
    return int(mesh.shape[table_axis(mesh)])


def pad_to_multiple(n: int, k: int) -> int:
    return -(-n // k) * k


def pad_rows_for_mesh(docs: list, ndata: int, *fill_lists):
    """Pad a doc list (and parallel per-row metadata lists) to a multiple of
    the data-axis size with empty rows. Empty docs (length 0) contribute
    nothing to scoring or counting, so pad rows are semantically inert; the
    caller drops their output rows. Returns (docs, *fill_lists) extended.

    ``fill_lists`` are (list, pad_value) pairs.
    """
    short = len(docs) % ndata
    if not short:
        return (docs, *[lst for lst, _ in fill_lists])
    pad = ndata - short
    out = [docs + [b""] * pad]
    for lst, value in fill_lists:
        if isinstance(lst, np.ndarray):
            out.append(np.concatenate([lst, np.full(pad, value, lst.dtype)]))
        else:
            out.append(list(lst) + [value] * pad)
    return tuple(out)
