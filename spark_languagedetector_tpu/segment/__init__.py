"""Span-level code-switch segmentation: the first new result type since
the seed (docs/SEGMENTATION.md).

Device side (:mod:`..ops.score` / :mod:`..ops.score_fused` /
:meth:`..api.runner.BatchRunner.segment_cells`) produces raw per-cell
score tensors; this package is everything after the fetch:

* :mod:`.spans`     — smoothing, per-cell decoding, and the byte-offset
  span merge (min-span, gap healing, UTF-8 boundary snapping);
* :mod:`.calibrate` — per-language temperature scaling fit on held-out
  data, persisted with the model;
* :mod:`.topk`      — top-k languages with calibrated probabilities and
  the unknown/low-confidence reject;
* :mod:`.api`       — :func:`segment_documents`, the orchestrator every
  front end (estimator, stream, serve) dispatches to.
"""

from .api import SegmentOptions, segment_documents  # noqa: F401
from .calibrate import Calibration, fit_calibration  # noqa: F401
from .topk import UNKNOWN, topk_decode  # noqa: F401
