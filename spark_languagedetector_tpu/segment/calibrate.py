"""Per-language temperature calibration for segmentation confidences.

Raw detector scores are sums of log-weight contributions — great for
argmax, meaningless as probabilities: a softmax over raw sums is almost a
one-hot for long documents and near-uniform for short ones. Segmentation's
reject option (:mod:`.topk`) needs an actual probability, so:

* scores are **length-normalized** first (divided by the scored byte
  count — :func:`normalize_scores`), making the logit scale
  length-invariant;
* a **per-language temperature** ``T_l`` divides each language's logit
  before the softmax: ``p = softmax(s_l / T_l)``. One global temperature
  is classic Platt/temperature scaling; the per-language refinement
  absorbs per-language weight-magnitude differences (profile sizes and
  gram coverage differ per language, so one scalar under-corrects).

The fit is **deterministic** (fixed grids, no RNG): a global-temperature
grid search minimizing held-out NLL, then a bounded number of
coordinate-descent passes refining each language's factor. Quality is
reported as expected calibration error (:func:`expected_calibration_error`)
before/after, which the ``--smoke-segment`` gate enforces (≤ 0.10 and
strictly better than uncalibrated).

The fitted state is tiny (one float per language) and persists WITH the
model (``persist.io.save_model(calibration=...)`` embeds it in the
metadata JSON — crash-atomic), provenance
stamped: an uncalibrated model serves segmentation with ``T = 1.0`` and
an explicit ``calibrated: false`` flag on every response, never silently.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# Deterministic fit grids (log-spaced): global pass, then per-language
# multiplicative refinement around the current value.
_GLOBAL_GRID = np.geomspace(0.02, 50.0, 81)
_REFINE_FACTORS = np.geomspace(0.5, 2.0, 15)
_REFINE_PASSES = 2


@dataclass
class Calibration:
    """Fitted per-language temperatures plus held-out provenance.

    ``temperatures`` float64 [L] (> 0); ``meta`` records the held-out doc
    count and the before/after NLL + ECE of the fit. ``version`` is a
    content hash of the temperatures — the serve cache keys segment
    results on it, so recalibrating a model can never cross-answer
    against results computed under the old temperatures.
    """

    temperatures: np.ndarray
    meta: dict = field(default_factory=dict)

    def __post_init__(self):
        t = np.asarray(self.temperatures, dtype=np.float64)
        if t.ndim != 1 or t.size == 0 or not np.all(np.isfinite(t)) or np.any(
            t <= 0
        ):
            raise ValueError(
                "calibration temperatures must be a 1-D positive finite "
                f"array, got shape {t.shape}"
            )
        self.temperatures = t

    @property
    def version(self) -> str:
        import hashlib

        return hashlib.sha256(
            np.ascontiguousarray(self.temperatures).tobytes()
        ).hexdigest()[:12]

    @staticmethod
    def identity(n_langs: int) -> "Calibration":
        """The uncalibrated default: every temperature 1.0 (the softmax of
        the raw normalized scores), ``calibrated: false`` provenance."""
        return Calibration(
            temperatures=np.ones(n_langs, dtype=np.float64),
            meta={"calibrated": False},
        )

    @property
    def calibrated(self) -> bool:
        return bool(self.meta.get("calibrated", True))

    # ------------------------------------------------- persistence codec ----
    def to_dict(self) -> dict:
        """JSON-ready state for ``persist.io.save_model``: temperatures +
        held-out provenance + the content version. JSON ``repr`` round-
        trips doubles exactly, so :meth:`from_dict` reconstructs bit-
        identical temperatures — and therefore the identical ``version``
        the serve cache keys segment entries on."""
        return {
            "temperatures": [float(t) for t in self.temperatures],
            "meta": dict(self.meta),
            "version": self.version,
        }

    @staticmethod
    def from_dict(state: dict) -> "Calibration":
        calib = Calibration(
            temperatures=np.asarray(state["temperatures"], dtype=np.float64),
            meta=dict(state.get("meta", {})),
        )
        stored = state.get("version")
        if stored is not None and stored != calib.version:
            # The version is content-derived; a mismatch means the stored
            # temperatures were edited behind the codec's back.
            raise ValueError(
                f"calibration version {stored!r} does not match its "
                f"temperatures (recomputed {calib.version!r})"
            )
        return calib


def normalize_scores(scores: np.ndarray, byte_lens) -> np.ndarray:
    """Length-normalize raw score rows: float64 ``scores[i] / max(1, len_i)``
    — the logit form every calibration consumer uses (fit and serve must
    agree on this transform or the temperatures mean nothing)."""
    scores = np.asarray(scores, dtype=np.float64)
    denom = np.maximum(np.asarray(byte_lens, dtype=np.float64), 1.0)
    return scores / denom[:, None]


def calibrated_probs(
    norm_scores: np.ndarray, temperatures: np.ndarray
) -> np.ndarray:
    """softmax(norm_scores / T) row-wise, float64, numerically stable."""
    z = np.asarray(norm_scores, dtype=np.float64) / np.asarray(
        temperatures, dtype=np.float64
    )
    z = z - z.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def nll(probs: np.ndarray, labels: np.ndarray) -> float:
    """Mean negative log-likelihood of the true labels (floored so a
    confidently-wrong sample can't produce inf and poison the grid)."""
    p = probs[np.arange(len(labels)), labels]
    return float(-np.mean(np.log(np.maximum(p, 1e-12))))


def expected_calibration_error(
    probs: np.ndarray, labels: np.ndarray, bins: int = 10
) -> float:
    """Standard ECE: bin predictions by top-probability, average
    |accuracy − confidence| weighted by bin mass."""
    conf = probs.max(axis=1)
    pred = probs.argmax(axis=1)
    correct = (pred == np.asarray(labels)).astype(np.float64)
    edges = np.linspace(0.0, 1.0, bins + 1)
    ece = 0.0
    n = len(labels)
    for lo, hi in zip(edges[:-1], edges[1:]):
        sel = (conf > lo) & (conf <= hi) if lo > 0 else (conf <= hi)
        if not sel.any():
            continue
        ece += (sel.sum() / n) * abs(
            correct[sel].mean() - conf[sel].mean()
        )
    return float(ece)


def fit_calibration(
    norm_scores: np.ndarray, label_idx, n_langs: int
) -> Calibration:
    """Fit per-language temperatures on held-out (scores, labels).

    ``norm_scores`` float [N, L] length-normalized (``normalize_scores``);
    ``label_idx`` int [N] true language indices. Deterministic: global
    grid search on NLL, then ``_REFINE_PASSES`` coordinate passes over
    the languages (ascending index) trying multiplicative factors and
    keeping strict improvements. Raises on an empty held-out set — a
    calibration fitted on nothing would be a silent lie.
    """
    s = np.asarray(norm_scores, dtype=np.float64)
    y = np.asarray(label_idx, dtype=np.int64)
    if s.ndim != 2 or s.shape[1] != n_langs:
        raise ValueError(
            f"held-out scores must be [N, {n_langs}], got {s.shape}"
        )
    if len(y) != len(s) or len(y) == 0:
        raise ValueError("calibration needs a non-empty held-out set")
    if y.min() < 0 or y.max() >= n_langs:
        raise ValueError("held-out label index out of range")

    ones = np.ones(n_langs, dtype=np.float64)
    nll_before = nll(calibrated_probs(s, ones), y)
    ece_before = expected_calibration_error(calibrated_probs(s, ones), y)

    # Global temperature first.
    best_t, best_nll = 1.0, nll_before
    for t in _GLOBAL_GRID:
        cur = nll(calibrated_probs(s, np.full(n_langs, t)), y)
        if cur < best_nll:
            best_t, best_nll = float(t), cur
    temps = np.full(n_langs, best_t, dtype=np.float64)

    # Per-language coordinate refinement (strict improvements only, so
    # the result is independent of float noise in equal-valued cells).
    for _ in range(_REFINE_PASSES):
        improved = False
        for lang in range(n_langs):
            base = temps[lang]
            for f in _REFINE_FACTORS:
                trial = temps.copy()
                trial[lang] = base * float(f)
                cur = nll(calibrated_probs(s, trial), y)
                if cur < best_nll - 1e-12:
                    temps, best_nll = trial, cur
                    improved = True
        if not improved:
            break

    probs_after = calibrated_probs(s, temps)
    return Calibration(
        temperatures=temps,
        meta={
            "calibrated": True,
            "heldout_docs": int(len(y)),
            "nll_before": round(nll_before, 6),
            "nll_after": round(nll(probs_after, y), 6),
            "ece_before": round(ece_before, 6),
            "ece_after": round(
                expected_calibration_error(probs_after, y), 6
            ),
        },
    )
