"""The segmentation orchestrator: runner cells → structured results.

:func:`segment_documents` is the one decode path every front end
dispatches to — the estimator's segment mode, ``run_stream`` (via the
model's ``transform``), and the serve batcher's segment requests — so
batch/stream/serve answers are identical by construction for identical
documents and options.

Result shape (one dict per document, JSON-ready — the serve cache stores
exactly this, serialized):

.. code-block:: python

    {
      "label": "en" | "unknown",          # top-1, or the reject label
      "rejected": False,
      "calibrated": True,                 # explicit provenance — an
                                          # uncalibrated model says so
      "topk": [{"lang": "en", "prob": 0.93}, ...],
      "spans": [{"start": 0, "end": 57, "lang": "en",
                 "confidence": 0.91}, ...],
    }

Telemetry (docs/OBSERVABILITY.md §4): counters ``segment/docs`` /
``segment/rejects`` / ``segment/spans``, histograms
``segment/spans_per_doc`` / ``segment/span_len_bytes``, and the host
merge under a ``segment/merge`` span. ``telemetry/compare`` tracks the
whole-run ``segment/reject_rate`` ratio — a reject rate drifting UP on a
fixed workload means the confidence pipeline regressed.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..telemetry import REGISTRY, span
from .calibrate import Calibration, calibrated_probs, normalize_scores
from .spans import decode_cells, merge_spans, smooth_cells
from .topk import UNKNOWN, topk_decode


@dataclass(frozen=True)
class SegmentOptions:
    """Every knob of one segmentation decode, hashable and stringable —
    the serve batcher coalesces on :meth:`key` and the score cache embeds
    it (plus the calibration version) in the entry key, so two requests
    with different knobs can never cross-answer (docs/SERVING.md §11)."""

    cell: int = 256              # device cell width (bytes; multiple of 128)
    smooth: int = 3              # box-smoothing width in cells
    top_k: int = 3               # languages returned per document
    reject_threshold: float = 0.0  # calibrated-prob floor; 0 ⇒ never reject
    min_span_bytes: int = 16     # spans shorter than this heal into neighbors

    def __post_init__(self):
        if self.cell < 128 or self.cell % 128:
            raise ValueError(
                f"cell must be a positive multiple of 128, got {self.cell}"
            )
        if self.smooth < 1:
            raise ValueError(f"smooth must be >= 1, got {self.smooth}")
        if self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if not 0.0 <= self.reject_threshold < 1.0:
            raise ValueError(
                "reject_threshold must be in [0, 1), got "
                f"{self.reject_threshold}"
            )
        if self.min_span_bytes < 1:
            raise ValueError(
                f"min_span_bytes must be >= 1, got {self.min_span_bytes}"
            )

    def key(self) -> str:
        """Canonical string of every knob — the batch/cache key component."""
        return (
            f"cell={self.cell},smooth={self.smooth},k={self.top_k},"
            f"reject={self.reject_threshold!r},min={self.min_span_bytes}"
        )


def segment_documents(
    runner,
    byte_docs,
    languages,
    *,
    options: SegmentOptions | None = None,
    calibration: Calibration | None = None,
) -> list[dict]:
    """Segment ``byte_docs``: per-window device decode → span merge →
    calibrated top-k with reject. One result dict per input document (the
    module docstring shows the shape); input order preserved.

    ``calibration`` None ⇒ the identity calibration (T = 1.0 everywhere)
    with ``calibrated: false`` stamped on every result — uncalibrated
    serving is explicit, never silent.
    """
    opts = options or SegmentOptions()
    languages = [str(l) for l in languages]
    if len(languages) != int(runner.weights.shape[1]):
        raise ValueError(
            f"{len(languages)} language names for a "
            f"{int(runner.weights.shape[1])}-language runner"
        )
    calib = calibration or Calibration.identity(len(languages))
    if calib.temperatures.shape[0] != len(languages):
        raise ValueError(
            f"calibration covers {calib.temperatures.shape[0]} languages, "
            f"model has {len(languages)}"
        )
    calibrated = calib.calibrated

    cells_list, scored_docs = runner.segment_cells(byte_docs, cell=opts.cell)

    results: list[dict] = []
    n_rejects = 0
    n_spans_total = 0
    with span("segment/merge", docs=len(cells_list), cell=opts.cell):
        for cells, doc in zip(cells_list, scored_docs):
            doc_len = len(doc)
            smoothed = smooth_cells(cells, opts.smooth)
            winners, margins = decode_cells(smoothed)
            spans = merge_spans(
                winners, margins,
                cell=opts.cell, doc_len=doc_len, doc=doc,
                min_span_bytes=opts.min_span_bytes,
            )
            # Document-level calibrated distribution from the exact cell
            # sums (length-normalized — the calibration's logit form).
            doc_vec = normalize_scores(
                cells.sum(axis=0, dtype=np.float64)[None, :], [doc_len]
            )
            doc_probs = calibrated_probs(doc_vec, calib.temperatures)[0]
            topk, label, rejected = topk_decode(
                doc_probs, languages, opts.top_k, opts.reject_threshold
            )

            out_spans = []
            for s in spans:
                span_vec = normalize_scores(
                    cells[s.start // opts.cell:
                          -(-s.end // opts.cell)].sum(
                        axis=0, dtype=np.float64
                    )[None, :],
                    [s.end - s.start],
                )
                span_probs = calibrated_probs(
                    span_vec, calib.temperatures
                )[0]
                conf = float(span_probs[s.lang_id])
                out_spans.append({
                    "start": int(s.start),
                    "end": int(s.end),
                    # The span-level reject: a span whose own calibrated
                    # confidence sits below the threshold reports unknown
                    # rather than a coin-flip language.
                    "lang": (
                        UNKNOWN if conf < opts.reject_threshold
                        else languages[s.lang_id]
                    ),
                    "confidence": round(conf, 6),
                })
                REGISTRY.observe(
                    "segment/span_len_bytes", float(s.end - s.start)
                )
            results.append({
                "label": label,
                "rejected": rejected,
                "calibrated": calibrated,
                "topk": [
                    {"lang": e["lang"], "prob": round(e["prob"], 6)}
                    for e in topk
                ],
                "spans": out_spans,
            })
            n_rejects += int(rejected)
            n_spans_total += len(out_spans)
            REGISTRY.observe("segment/spans_per_doc", float(len(out_spans)))
    # Unconditional (0 included): the compare guard derives the tracked
    # ``segment/reject_rate`` ratio from these counters, and a zero-reject
    # baseline must still carry the denominator AND a zero numerator so a
    # candidate that starts rejecting regresses against it.
    REGISTRY.incr("segment/docs", len(results))
    REGISTRY.incr("segment/rejects", n_rejects)
    REGISTRY.incr("segment/spans", n_spans_total)
    return results
