"""Window votes → byte-offset spans (the host half of segmentation).

The device hands back one raw score vector per CELL (a fixed span of
window start positions — ``api.runner.SEGMENT_CELL`` bytes). This module
turns a document's cell matrix into a span list:

1. **smooth** — a box average over the cell axis widens the effective
   decision window without another device pass: per-cell n-gram votes are
   noisy exactly at the code-switch boundaries where they matter;
2. **decode** — per-cell winner (first-maximum, the reference tie rule)
   and margin (top1 − top2 of the smoothed vector), the decoder's
   confidence signal;
3. **merge** — run-length encode the winners, heal sub-``min_span`` runs
   into the neighbor with the stronger adjacent margin (a lone mis-voted
   cell inside a long run is a gap to heal, not a span), convert to byte
   offsets, and snap every interior boundary to a UTF-8 character start
   so a span never splits a multi-byte character.

Invariants (property-tested in ``tests/test_segment.py``): the returned
spans partition ``[0, doc_len)`` exactly — no gaps, no overlaps — every
interior boundary is a UTF-8 character start (for UTF-8 inputs), and
every span is at least ``min_span_bytes`` long unless the whole document
is shorter. Pure functions, no device work, deterministic.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Span:
    """One decoded span: byte offsets ``[start, end)``, the winning
    language index, and the mean smoothed margin of its cells (the
    pre-calibration confidence signal; calibrated probabilities are
    attached by :mod:`.api`)."""

    start: int
    end: int
    lang_id: int
    margin: float


def smooth_cells(cells: np.ndarray, width: int) -> np.ndarray:
    """Box average over the cell axis: float64 [C, L] → [C, L].

    ``width`` is the full window in cells (values < 2 are the identity);
    edges average over the clipped window, so every output row is a true
    mean of real cells. Deterministic float64 — the decoder's argmax must
    not depend on summation order.
    """
    cells = np.asarray(cells, dtype=np.float64)
    if width < 2 or cells.shape[0] < 2:
        return cells
    half = width // 2
    csum = np.cumsum(cells, axis=0, dtype=np.float64)
    csum = np.concatenate([np.zeros((1, cells.shape[1])), csum], axis=0)
    C = cells.shape[0]
    lo = np.maximum(np.arange(C) - half, 0)
    hi = np.minimum(np.arange(C) + half + 1, C)
    return (csum[hi] - csum[lo]) / (hi - lo)[:, None]


def decode_cells(smoothed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(winners int64 [C], margins float64 [C]) of a smoothed cell matrix.

    Winner = first maximum (reference tie behavior); margin = top1 − top2
    (0.0 for single-language models)."""
    winners = np.argmax(smoothed, axis=1)
    if smoothed.shape[1] < 2:
        return winners, np.zeros(smoothed.shape[0], dtype=np.float64)
    part = -np.partition(-smoothed, 1, axis=1)
    return winners, (part[:, 0] - part[:, 1]).astype(np.float64)


def snap_utf8(doc: bytes, pos: int) -> int:
    """Largest p ≤ pos that is a UTF-8 character start (continuation
    bytes 0b10xxxxxx back the boundary off; at most 3 steps for valid
    UTF-8, capped at 4 so arbitrary bytes can't walk the boundary far)."""
    p = pos
    steps = 0
    while 0 < p < len(doc) and (doc[p] & 0xC0) == 0x80 and steps < 4:
        p -= 1
        steps += 1
    return p


def merge_spans(
    winners: np.ndarray,
    margins: np.ndarray,
    *,
    cell: int,
    doc_len: int,
    doc: bytes,
    min_span_bytes: int,
) -> list[Span]:
    """Cell votes → byte-offset spans partitioning ``[0, doc_len)``.

    Runs shorter than ``min_span_bytes`` are healed into the neighboring
    run whose boundary-adjacent margin is stronger (smallest run first,
    so one noisy cell can't cascade); boundaries then snap to UTF-8
    character starts. A snap that empties a span drops the span (its
    bytes go to the neighbor) — the partition invariant always wins over
    span count.
    """
    if doc_len <= 0:
        return []
    n_cells = -(-doc_len // cell)
    winners = np.asarray(winners[:n_cells])
    margins = np.asarray(margins[:n_cells], dtype=np.float64)

    # Run-length encode: [cell_start, cell_end, lang_id].
    runs: list[list[int]] = []
    for c, w in enumerate(winners.tolist()):
        if runs and runs[-1][2] == w:
            runs[-1][1] = c + 1
        else:
            runs.append([c, c + 1, int(w)])

    def run_bytes(r) -> int:
        return min(r[1] * cell, doc_len) - r[0] * cell

    # Heal short runs (gap healing + min-span in one rule). Shortest
    # first: a single mis-voted cell between two long same-language runs
    # merges away and the flanks then coalesce.
    while len(runs) > 1:
        k = min(range(len(runs)), key=lambda i: (run_bytes(runs[i]), i))
        if run_bytes(runs[k]) >= min_span_bytes:
            break
        left = runs[k - 1] if k > 0 else None
        right = runs[k + 1] if k + 1 < len(runs) else None
        if left is not None and right is not None:
            # Merge toward the stronger boundary-adjacent margin.
            into_left = margins[runs[k][0] - 1] >= margins[runs[k][1]]
        else:
            into_left = right is None
        if into_left:
            left[1] = runs[k][1]
            del runs[k]
            if k < len(runs) and runs[k - 1][2] == runs[k][2]:
                runs[k - 1][1] = runs[k][1]
                del runs[k]
        else:
            right[0] = runs[k][0]
            del runs[k]
            if k > 0 and runs[k - 1][2] == runs[k][2]:
                runs[k - 1][1] = runs[k][1]
                del runs[k]

    # Cell runs → byte boundaries: run i starts at its first cell's byte
    # offset, snapped to a character start (run 0 pins to 0); run i ends
    # where run i+1 starts. A snap that empties a run drops it — its
    # bytes already belong to the neighbors — so the emitted spans always
    # partition [0, doc_len) exactly.
    starts = [0] + [
        snap_utf8(doc, min(r[0] * cell, doc_len)) for r in runs[1:]
    ]
    starts = [min(s, doc_len) for s in starts]
    spans: list[Span] = []
    for i, r in enumerate(runs):
        start = starts[i]
        end = starts[i + 1] if i + 1 < len(runs) else doc_len
        if end <= start:
            continue
        m = margins[r[0]:r[1]]
        spans.append(Span(
            start=start,
            end=end,
            lang_id=r[2],
            margin=float(m.mean()) if m.size else 0.0,
        ))
    # Adjacent spans that ended up same-language (possible after a
    # dropped boundary) merge so the output is canonical.
    merged: list[Span] = []
    for s in spans:
        if merged and merged[-1].lang_id == s.lang_id:
            prev = merged.pop()
            merged.append(Span(
                prev.start, s.end, s.lang_id,
                (prev.margin * (prev.end - prev.start)
                 + s.margin * (s.end - s.start)) / (s.end - prev.start),
            ))
        else:
            merged.append(s)
    return merged
