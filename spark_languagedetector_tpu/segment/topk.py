"""Top-k languages with calibrated probabilities + the reject option.

The whole-doc argmax answers "which one language" — structurally wrong
for mixed documents and overconfident for out-of-distribution input. The
segmentation result type answers with the top-k calibrated candidates
and an explicit ``unknown`` when even the best candidate's calibrated
probability sits below the reject threshold: a low-confidence answer is
information the caller must see, never a silently wrong label
(docs/SEGMENTATION.md §reject).
"""

from __future__ import annotations

import numpy as np

# The reject label. Deliberately NOT a language code (ISO 639-1 has no
# "unknown"), so it can never collide with a model's language list.
UNKNOWN = "unknown"


def topk_decode(
    probs: np.ndarray,
    languages,
    k: int,
    reject_threshold: float,
) -> tuple[list[dict], str, bool]:
    """(top-k entries, label, rejected) for one calibrated distribution.

    ``probs`` float [L] (a :func:`..calibrate.calibrated_probs` row);
    entries are ``{"lang", "prob"}`` sorted by descending probability
    (ties broken by ascending index — the reference's first-maximum
    rule). ``label`` is the top language, or :data:`UNKNOWN` when its
    probability is below ``reject_threshold`` (``rejected`` True). The
    top-k list is returned even for rejected documents — the caller sees
    WHAT the low-confidence guesses were.
    """
    p = np.asarray(probs, dtype=np.float64)
    if p.ndim != 1 or len(p) != len(languages):
        raise ValueError(
            f"probs shape {p.shape} disagrees with {len(languages)} languages"
        )
    k = max(1, min(int(k), len(p)))
    # Stable sort on -p: equal probabilities keep ascending language
    # index, matching the first-maximum tie rule everywhere else.
    order = np.argsort(-p, kind="stable")[:k]
    entries = [
        {"lang": str(languages[int(i)]), "prob": float(p[int(i)])}
        for i in order
    ]
    top_prob = entries[0]["prob"]
    rejected = bool(top_prob < float(reject_threshold))
    label = UNKNOWN if rejected else entries[0]["lang"]
    return entries, label, rejected
