"""Fleet-wide telemetry aggregation over per-process registries.

PR 15 made replicas real subprocesses, which fractured the registry: each
replica owns a process-local :data:`~.registry.REGISTRY`, so the
coordinator's counters describe only the coordinator. This module is the
merge/reduce half of the observability plane (the aggregation shape is
DrJAX's reduce-over-workers, arXiv:2403.07128, applied to metrics):

  * :func:`install_process_identity` stamps who-is-recording (replica
    name, pid, accelerator platform) into a registry, from where it rides
    every exported span event, every HTTP response, and the
    ``/telemetryz`` wire form.
  * :func:`merge_snapshots` folds any number of
    :meth:`~.registry.Registry.mergeable_snapshot` dicts into one view —
    counters summed exactly, histogram sketches merged via
    :meth:`~.registry.Histogram.merge` (count/sum/min/max exact,
    percentiles reservoir-approximate), gauges relabelled per replica.
  * :class:`FleetCollector` rides the coordinator's probe/supervision
    loop: it records each replica's ``/telemetryz`` scrape, folds a
    member's **terminal** scrape into a retained per-name base when the
    member drains away or its pid changes (a scale-down or supervised
    restart no longer loses telemetry — counters stay monotone across
    replica generations), and serves the fleet aggregate plus per-replica
    views for the router's ``/varz``.

The collector's own health is itself telemetry: every recorded scrape
counts ``fleet/agg_scrapes`` and every failed one
``fleet/agg_scrape_failures`` (zero-baseline regression-guarded in
:mod:`.compare`), and :meth:`FleetCollector.freshness_s` publishes the
age of the stalest live member's scrape as
``langdetect_fleet_scrape_age_s`` — the SLO layer's freshness input.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable

from .registry import REGISTRY, Histogram, Registry

# Wire-form version of Registry.mergeable_snapshot — bumped only on an
# incompatible shape change; the collector refuses mismatched scrapes
# (counted as scrape failures) instead of merging garbage.
SNAPSHOT_SCHEMA = 1

# --- contract tables (harvested by analysis/, rule R2) ----------------------
# Counter names the aggregation plane READS from the merged stream: the
# autoscaler's fleet-aggregate shed pressure (scale/elastic) sums the
# replica-side and router-side shed odometers out of the collector. Each
# name must exist at a real emit site — a renamed counter would silently
# zero the autoscaler's pressure signal, so the static contract checker
# fails tier-1 instead.
CONSUMED_COUNTERS = (
    "serve/shed_requests",
    "fleet/shed_requests",
)
# Counters the collector itself emits about the scrape loop. The checker
# additionally pins these into telemetry/compare's tracked tables: a
# scrape failure appearing against a clean baseline must regress.
GUARD_COUNTERS = (
    "fleet/agg_scrapes",
    "fleet/agg_scrape_failures",
)


def process_identity(registry: Registry | None = None) -> dict:
    """This process's identity block (replica/pid/platform when installed
    via :func:`install_process_identity`; a bare pid otherwise). Stamped
    into HTTP responses so multi-process captures are attributable."""
    reg = REGISTRY if registry is None else registry
    if reg.identity:
        return dict(reg.identity)
    return {"pid": os.getpid()}


def install_process_identity(
    registry: Registry | None = None,
    *,
    replica: str,
    pid: int | None = None,
    platform: str | None = None,
) -> dict:
    """Stamp (replica, pid, platform) into ``registry.identity``.

    Called once by the replica worker after its jax platform pin;
    ``platform=None`` resolves ``jax.default_backend()`` lazily (and
    degrades to unknown when jax is absent — identity must never take
    down a worker)."""
    reg = REGISTRY if registry is None else registry
    if platform is None:
        try:
            import jax

            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
    reg.identity.update(
        replica=str(replica),
        pid=int(os.getpid() if pid is None else pid),
        platform=str(platform),
    )
    return dict(reg.identity)


# ----------------------------------------------------------- pure merging ---
def _label_str(labels: dict, extra: dict | None = None) -> str:
    merged = dict(labels)
    for k, v in (extra or {}).items():
        merged.setdefault(k, v)
    return ",".join(f"{k}={v}" for k, v in sorted(merged.items()))


def merge_snapshots(snaps: list[tuple[str, dict]]) -> dict:
    """Fold ``(member name, mergeable_snapshot)`` pairs into one view.

    Counters sum exactly. Histograms merge into one sketch per name
    (count/sum/min/max exact; percentiles reservoir-approximate — the
    same fidelity each process had locally). Gauges are NOT summed (the
    last value of ``langdetect_serve_queue_rows`` on r0 plus r1 means
    nothing): each series keeps its value under its member's ``replica``
    label, so per-replica detail survives the merge."""
    counters: dict[str, float] = {}
    hists: dict[str, Histogram] = {}
    gauges: dict[str, dict[str, float]] = {}
    for name, snap in snaps:
        for cname, val in (snap.get("counters") or {}).items():
            if isinstance(val, (int, float)):
                counters[cname] = counters.get(cname, 0) + val
        for hname, state in (snap.get("histograms") or {}).items():
            if isinstance(state, dict):
                hists.setdefault(hname, Histogram()).merge(state)
        ident = snap.get("identity") or {}
        extra = {"replica": ident.get("replica", name)}
        for gname, series in (snap.get("gauges") or {}).items():
            out = gauges.setdefault(gname, {})
            for pair in series or ():
                try:
                    labels, val = pair
                except (TypeError, ValueError):
                    continue
                if isinstance(val, (int, float)) and isinstance(labels, dict):
                    out[_label_str(labels, extra)] = float(val)
    return {"counters": counters, "histograms": hists, "gauges": gauges}


class FleetCollector:
    """Scrape accumulator with terminal-scrape retention.

    One collector per coordinator. The coordinator's own registry is an
    implicit member (``local_name``) read live at aggregation time — the
    router-side counters (``fleet/shed_requests``, probe rounds) belong
    in the fleet view too. Replica members are fed via :meth:`scrape`
    (or :meth:`record` when the caller already holds a snapshot);
    :meth:`retire` folds a member's last scrape into a retained per-name
    base, and a pid change between scrapes folds the dead generation
    automatically — so :meth:`aggregate` counters are monotone across
    scale-downs, crashes, and supervised restarts.
    """

    def __init__(
        self,
        *,
        registry: Registry | None = None,
        local_name: str = "router",
    ):
        self.registry = REGISTRY if registry is None else registry
        self.local_name = local_name
        self._lock = threading.Lock()
        # name -> {"snap": mergeable snapshot, "pid": int|None, "at": mono}
        self._live: dict[str, dict] = {}
        # name -> {"counters": {...}, "histograms": {name: Histogram},
        #          "identity": {...}, "generations": int}
        self._retained: dict[str, dict] = {}
        self.scrapes = 0
        self.scrape_failures = 0

    # ------------------------------------------------------------ feeding ---
    def scrape(self, name: str, fetch: Callable[[], dict]) -> bool:
        """Fetch one member's ``/telemetryz`` (any raising callable) and
        record it. Failures are contained and counted — a mid-death
        member must not take down the probe loop riding this."""
        try:
            snap = fetch()
            if not isinstance(snap, dict) or (
                snap.get("schema") != SNAPSHOT_SCHEMA
            ):
                raise ValueError(
                    f"bad /telemetryz schema from {name!r}: "
                    f"{snap.get('schema') if isinstance(snap, dict) else snap!r}"
                )
        except Exception:
            self.note_failure(name)
            return False
        self.record(name, snap)
        return True

    def record(self, name: str, snap: dict) -> None:
        """Accept one scraped snapshot. A pid change against the previous
        scrape means the member restarted: the dead generation's last
        scrape folds into the retained base first, so its counters are
        never lost and never double-counted."""
        pid = (snap.get("identity") or {}).get("pid")
        with self._lock:
            prev = self._live.get(name)
            if (
                prev is not None
                and prev.get("pid") is not None
                and pid != prev.get("pid")
            ):
                self._fold_locked(name, prev["snap"])
            self._live[name] = {
                "snap": snap, "pid": pid, "at": time.monotonic(),
            }
            self.scrapes += 1
        self.registry.incr("fleet/agg_scrapes")

    def note_failure(self, name: str) -> None:
        with self._lock:
            self.scrape_failures += 1
        self.registry.incr("fleet/agg_scrape_failures")

    def retire(self, name: str) -> None:
        """Terminal retention: fold the member's last scrape into the
        per-name base (scale-down / gave-up). Idempotent; a name with no
        scrape history is a no-op."""
        with self._lock:
            entry = self._live.pop(name, None)
            if entry is not None:
                self._fold_locked(name, entry["snap"])

    def _fold_locked(self, name: str, snap: dict) -> None:
        base = self._retained.setdefault(
            name,
            {"counters": {}, "histograms": {}, "identity": {},
             "generations": 0},
        )
        for cname, val in (snap.get("counters") or {}).items():
            if isinstance(val, (int, float)):
                base["counters"][cname] = (
                    base["counters"].get(cname, 0) + val
                )
        for hname, state in (snap.get("histograms") or {}).items():
            if isinstance(state, dict):
                base["histograms"].setdefault(
                    hname, Histogram()
                ).merge(state)
        base["identity"] = dict(snap.get("identity") or {})
        base["generations"] += 1

    # ----------------------------------------------------------- reading ----
    def _member_snaps_locked(self) -> list[tuple[str, dict]]:
        out: list[tuple[str, dict]] = []
        for name, base in self._retained.items():
            out.append((name, {
                "counters": dict(base["counters"]),
                "histograms": {
                    h: hist.state()
                    for h, hist in base["histograms"].items()
                },
                "gauges": {},  # a gone generation's gauges are stale truth
                "identity": dict(base["identity"]),
            }))
        for name, entry in self._live.items():
            out.append((name, entry["snap"]))
        return out

    def aggregate(self) -> dict:
        """The fleet-wide merged view: live members + retained terminal
        scrapes + the coordinator's own registry, via
        :func:`merge_snapshots`. Histograms come back as display
        snapshots (count/sum/min/max/percentiles)."""
        with self._lock:
            snaps = self._member_snaps_locked()
        snaps.append((self.local_name, self.registry.mergeable_snapshot()))
        merged = merge_snapshots(snaps)
        merged["histograms"] = {
            name: h.snapshot() for name, h in merged["histograms"].items()
        }
        merged["members"] = self.members()
        merged["scrapes"] = self.scrapes
        merged["scrape_failures"] = self.scrape_failures
        return merged

    def counter(self, name: str, *, include_local: bool = True) -> float:
        """One aggregate counter, cheaply: retained base + each live
        member's last scrape + (optionally) the coordinator's live value.
        Monotone by construction — the autoscaler differentiates it
        without per-member clamping."""
        total = 0.0
        with self._lock:
            for base in self._retained.values():
                total += base["counters"].get(name, 0)
            for entry in self._live.values():
                val = (entry["snap"].get("counters") or {}).get(name, 0)
                if isinstance(val, (int, float)):
                    total += val
        if include_local:
            total += self.registry.counters.get(name, 0)
        return total

    def per_replica(self) -> dict[str, dict]:
        """Per-member condensed views (identity, state, counters) — the
        fleet ``/varz`` drill-down. Retained (drained/dead) members keep
        their folded counters under ``state: "retired"``."""
        out: dict[str, dict] = {}
        with self._lock:
            for name, base in self._retained.items():
                out[name] = {
                    "state": "retired",
                    "identity": dict(base["identity"]),
                    "generations": base["generations"],
                    "counters": dict(base["counters"]),
                }
            for name, entry in self._live.items():
                snap = entry["snap"]
                prev = out.pop(name, None)
                counters = dict(snap.get("counters") or {})
                generations = 1
                if prev is not None:
                    # A restarted member: live generation rides on top of
                    # its folded predecessors, same as aggregate().
                    for cname, val in prev["counters"].items():
                        counters[cname] = counters.get(cname, 0) + val
                    generations += prev["generations"]
                out[name] = {
                    "state": "live",
                    "identity": dict(snap.get("identity") or {}),
                    "generations": generations,
                    "counters": counters,
                    "scrape_ts": snap.get("ts"),
                }
        return out

    def members(self) -> dict[str, dict]:
        """Identity/state roster without the counter payloads."""
        now = time.monotonic()
        out: dict[str, dict] = {}
        with self._lock:
            for name, base in self._retained.items():
                out[name] = {
                    "state": "retired",
                    "identity": dict(base["identity"]),
                    "generations": base["generations"],
                }
            for name, entry in self._live.items():
                info = out.get(name) or {"generations": 0}
                out[name] = {
                    "state": "live",
                    "identity": dict(
                        (entry["snap"].get("identity") or {})
                    ),
                    "generations": info.get("generations", 0) + 1,
                    "age_s": round(now - entry["at"], 3),
                }
        return out

    def freshness_s(self) -> float:
        """Age of the stalest live member's scrape (0.0 with no live
        members — an empty fleet is vacuously fresh), published as the
        ``langdetect_fleet_scrape_age_s`` gauge: the SLO layer's
        guard-freshness input."""
        now = time.monotonic()
        with self._lock:
            ages = [now - entry["at"] for entry in self._live.values()]
        age = max(ages) if ages else 0.0
        self.registry.set_gauge("langdetect_fleet_scrape_age_s", age)
        return age
