"""Declared SLOs evaluated as multi-window burn rates over the fleet
aggregate.

The collector (:mod:`.aggregate`) gives the coordinator one merged,
monotone view of the fleet; this module turns that stream into the three
objectives a serving fleet owes its callers (docs/OBSERVABILITY.md §15):

  * **availability** — 1 − shed rate: fleet-level sheds
    (``fleet/shed_requests``) plus replica-side sheds
    (``serve/shed_requests``) over admitted traffic, differentiated per
    evaluation window so the burn reflects *current* traffic, not fleet
    history.
  * **latency_p99** — p99 of the router's end-to-end request histogram
    (``fleet/request_s``) against a declared millisecond target.
  * **freshness** — the guard signals themselves must be current: the
    stalest live member's scrape age
    (``langdetect_fleet_scrape_age_s``) against a staleness bound. A
    collector that stops scraping burns this objective rather than
    silently reporting a healthy-looking stale aggregate.

Each objective's **burn rate** is error-budget consumption speed: for
availability the windowed error rate over the budget (1 − target); for
the threshold objectives the windowed violation fraction over the same
budget form. Burn 1.0 = consuming exactly the budget; an alert fires
only when BOTH the short and the long window burn at or past
``burn_threshold`` (the classic multi-window rule: the long window
proves it is sustained, the short window proves it is still happening),
and clears when the short window recovers — which is what makes the
smoke gate's trip-then-clear sequence deterministic.

Every evaluation observes the worst burn into the ``slo/burn_rate``
histogram (upward-regressing in :mod:`.compare`) and publishes
per-objective ``langdetect_slo_burn_rate`` gauges; alert transitions
count ``slo/alerts``. The autoscaler consumes :meth:`SloEvaluator.
burning` as an additional scale-up pressure signal, and the fleet
``/healthz`` surfaces :meth:`SloEvaluator.status` reasons.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .registry import REGISTRY, Registry

# --- contract tables (harvested by analysis/, rule R2) ----------------------
# Every SLO input must exist at a real telemetry emit site: a renamed
# counter/histogram/gauge would quietly evaluate every objective against
# zeros, so the static contract checker fails tier-1 instead.
SLO_INPUT_COUNTERS = (
    "fleet/requests",
    "fleet/shed_requests",
    "serve/shed_requests",
)
SLO_INPUT_HISTOGRAMS = ("fleet/request_s",)
SLO_INPUT_GAUGES = ("langdetect_fleet_scrape_age_s",)


class Objective:
    """One declared objective: a name, a target, and how to read it.

    ``kind`` picks the evaluation: ``"availability"`` (good/total ratio
    from counter deltas), ``"latency_p99"`` (aggregate p99 seconds vs
    ``threshold``), ``"freshness"`` (gauge seconds vs ``threshold``).
    ``target`` is the success-ratio objective (0.99 = 1% error budget);
    the budget ``1 − target`` also scales the threshold objectives'
    violation burn, so one ``burn_threshold`` means the same thing for
    every objective.
    """

    __slots__ = ("name", "kind", "target", "threshold")

    def __init__(
        self,
        name: str,
        kind: str,
        *,
        target: float = 0.99,
        threshold: float | None = None,
    ):
        if kind not in ("availability", "latency_p99", "freshness"):
            raise ValueError(f"unknown objective kind {kind!r}")
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if kind != "availability" and (
            threshold is None or threshold <= 0
        ):
            raise ValueError(
                f"objective {name!r} ({kind}) needs a positive threshold"
            )
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.threshold = None if threshold is None else float(threshold)

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def describe(self) -> dict:
        return {
            "kind": self.kind,
            "target": self.target,
            "threshold": self.threshold,
        }


def default_objectives(
    *,
    latency_p99_ms: float = 250.0,
    availability_target: float = 0.99,
    freshness_s: float = 10.0,
) -> tuple[Objective, ...]:
    """The serving fleet's declared objectives (docs/OBSERVABILITY.md §15)."""
    return (
        Objective(
            "availability", "availability", target=availability_target
        ),
        Objective(
            "latency_p99", "latency_p99",
            target=availability_target, threshold=latency_p99_ms / 1e3,
        ),
        Objective(
            "freshness", "freshness",
            target=availability_target, threshold=freshness_s,
        ),
    )


class SloEvaluator:
    """Feed :meth:`ingest` one fleet aggregate per collector round; read
    :meth:`status`/:meth:`burning` anywhere. Thread-safe (the autoscaler
    tick ingests while the fleet ``/healthz`` reads)."""

    def __init__(
        self,
        objectives: tuple[Objective, ...] | None = None,
        *,
        registry: Registry | None = None,
        short_window_s: float = 30.0,
        long_window_s: float = 120.0,
        burn_threshold: float = 1.0,
    ):
        if short_window_s <= 0 or long_window_s < short_window_s:
            raise ValueError(
                "windows must satisfy 0 < short_window_s <= long_window_s "
                f"(got {short_window_s}, {long_window_s})"
            )
        self.objectives = (
            default_objectives() if objectives is None else tuple(objectives)
        )
        self.registry = REGISTRY if registry is None else registry
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.burn_threshold = float(burn_threshold)
        self._lock = threading.Lock()
        # Per objective: deque of (ts, bad, total) window samples. For
        # availability bad/total are counter DELTAS; for the threshold
        # objectives each evaluation is one sample (bad ∈ {0, 1}).
        self._samples: dict[str, deque] = {
            o.name: deque() for o in self.objectives
        }
        self._alerting: dict[str, bool] = {
            o.name: False for o in self.objectives
        }
        self._last: dict[str, dict] = {}
        self._seen_counters: dict[str, float] = {}

    # ---------------------------------------------------------- ingestion ---
    def _counter_delta(self, counters: dict, name: str) -> float:
        val = counters.get(name, 0)
        val = float(val) if isinstance(val, (int, float)) else 0.0
        seen = self._seen_counters.get(name, 0.0)
        # The aggregate is monotone by construction (terminal retention in
        # the collector); clamp anyway so a collector reset can never
        # manufacture negative traffic.
        delta = val - seen if val >= seen else val
        self._seen_counters[name] = val
        return delta

    def ingest(self, aggregate: dict, *, now: float | None = None) -> dict:
        """Evaluate every objective against one merged aggregate (the
        :meth:`~.aggregate.FleetCollector.aggregate` form: counters,
        histogram snapshots, gauges). Returns :meth:`status`."""
        ts = time.monotonic() if now is None else float(now)
        counters = aggregate.get("counters") or {}
        hists = aggregate.get("histograms") or {}
        gauges = aggregate.get("gauges") or {}
        with self._lock:
            worst = 0.0
            for obj in self.objectives:
                bad, total = self._measure(obj, counters, hists, gauges)
                window = self._samples[obj.name]
                window.append((ts, bad, total))
                cutoff = ts - self.long_window_s
                while window and window[0][0] < cutoff:
                    window.popleft()
                burn_short = self._burn(obj, window, ts - self.short_window_s)
                burn_long = self._burn(obj, window, cutoff)
                was = self._alerting[obj.name]
                if was:
                    alerting = burn_short >= self.burn_threshold
                else:
                    alerting = (
                        burn_short >= self.burn_threshold
                        and burn_long >= self.burn_threshold
                    )
                if alerting and not was:
                    self.registry.incr("slo/alerts")
                self._alerting[obj.name] = alerting
                self._last[obj.name] = {
                    **obj.describe(),
                    "burn_short": round(burn_short, 4),
                    "burn_long": round(burn_long, 4),
                    "alerting": alerting,
                }
                worst = max(worst, burn_short)
                self.registry.set_gauge(
                    "langdetect_slo_burn_rate", burn_short,
                    objective=obj.name,
                )
        self.registry.observe("slo/burn_rate", worst)
        return self.status()

    def _measure(
        self, obj: Objective, counters: dict, hists: dict, gauges: dict
    ) -> tuple[float, float]:
        """One evaluation's (bad, total) sample for an objective."""
        if obj.kind == "availability":
            sheds = sum(
                self._counter_delta(counters, name)
                for name in ("fleet/shed_requests", "serve/shed_requests")
            )
            served = self._counter_delta(counters, "fleet/requests")
            return sheds, served + sheds
        if obj.kind == "latency_p99":
            # The merged sketch is cumulative, so its p99 carries fleet
            # HISTORY — a verdict is recorded only when this window saw
            # new completions. Otherwise one slow burst would burn the
            # objective forever (and pin the autoscaler's pressure high
            # through dead silence); with no new evidence the old bad
            # samples age out of the short window and the alert clears.
            snap = hists.get("fleet/request_s") or {}
            count = snap.get("count")
            count = float(count) if isinstance(count, (int, float)) else 0.0
            seen = self._seen_counters.get("hist:fleet/request_s", 0.0)
            fresh = count - seen if count >= seen else count
            self._seen_counters["hist:fleet/request_s"] = count
            if fresh <= 0:
                return 0.0, 0.0
            p99 = snap.get("p99")
            bad = (
                1.0 if isinstance(p99, (int, float))
                and p99 > obj.threshold else 0.0
            )
            return bad, 1.0
        # freshness: the aggregate's flat gauge form keys label strings;
        # the scrape-age series is unlabelled at source, so any value of
        # the series counts (max across label sets is the stalest view).
        series = gauges.get("langdetect_fleet_scrape_age_s") or {}
        ages = [
            v for v in series.values() if isinstance(v, (int, float))
        ]
        bad = 1.0 if ages and max(ages) > obj.threshold else 0.0
        return bad, 1.0

    def _burn(self, obj: Objective, window, cutoff: float) -> float:
        bad = total = 0.0
        for ts, b, t in window:
            if ts >= cutoff:
                bad += b
                total += t
        if total <= 0:
            return 0.0
        return (bad / total) / obj.budget

    # ------------------------------------------------------------- status ---
    def status(self) -> dict:
        with self._lock:
            objectives = {
                o.name: dict(
                    self._last.get(o.name)
                    or {**o.describe(), "burn_short": 0.0,
                        "burn_long": 0.0, "alerting": False}
                )
                for o in self.objectives
            }
        reasons = [
            f"slo_{name}_burn" for name, st in objectives.items()
            if st["alerting"]
        ]
        return {
            "burning": bool(reasons),
            "reasons": reasons,
            "burn_threshold": self.burn_threshold,
            "short_window_s": self.short_window_s,
            "long_window_s": self.long_window_s,
            "objectives": objectives,
        }

    def burning(self) -> bool:
        with self._lock:
            return any(self._alerting.values())

    def reasons(self) -> list[str]:
        return self.status()["reasons"]
