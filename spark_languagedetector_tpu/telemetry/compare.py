"""Regression guard: diff two telemetry captures per-stage, exit nonzero.

    python -m spark_languagedetector_tpu.telemetry.compare \
        baseline.jsonl candidate.jsonl [--threshold 0.25] \
        [--metrics p50,p90,p99] [--min-seconds 0.0]

Turns the bench trajectory into an enforceable contract: capture A is the
accepted baseline (a BENCH_r* run's JSONL, a CI artifact), capture B is
the candidate; for every span path present in both, the wall-time
percentiles (and fenced device totals, the snapshot-carried
fill/waste/stall histograms — the serving latency legs
``serve/queue_wait_s`` / ``serve/dispatch_s`` / ``serve/total_s``
included, so a serve p99 regression past threshold fails the run — and
the snapshot's recovery counters: retries, breaker trips, DLQ rows,
degraded batches, shed requests) are compared, and
any metric that moved past
``--threshold`` (relative, in the *worse* direction — slower, less
filled, more wasted) fails the run with exit code 1. Stages present in
only one capture are reported but never fail the diff (instrumentation
legitimately grows between rounds).

Pure stdlib + this package's Histogram, like the report CLI — runs on the
zero-accelerator CI host against checked-in fixtures.
"""

from __future__ import annotations

import math
import sys

from .registry import Histogram
from .report import load_events

DEFAULT_THRESHOLD = 0.25
# --metrics replaces this set wholesale: a user passing "--metrics p50"
# has opted out of everything else, device metrics included.
DEFAULT_METRICS = ("p50", "p90", "p99", "device_total_s", "device_p99")

# Snapshot histograms where *higher* is better; everything else (stall
# seconds, latency, padding waste, retries) regresses upward. Matches by
# substring, so the serve path's coalescing health rides automatically:
# ``serve/fill_ratio`` regresses when it *drops* (emptier dispatches) and
# ``serve/padding_waste`` when it *rises* — the two sides of the padding
# tax docs/PERFORMANCE.md §9 describes, pinned by tests/test_exec.py.
# ``cache/hit_rate`` joins it for the redundancy-elimination contract
# (docs/PERFORMANCE.md §10): on the same replayed workload, a candidate
# whose serve cache stops hitting has regressed downward.
_HIGHER_BETTER = ("fill_ratio", "hit_rate")

# Tracked gauges (last snapshot): byte-traffic contract metrics, keyed to
# a short stable name. A change that silently de-quantizes a profile
# (table_bytes jumps 4x), re-balloons a program's memory traffic
# (est_bytes_utilization climbs back toward the HBM roof), or falls back
# to a full-[V,L]-table fit collect (fit_collect_bytes jumps from k·L
# winner rows to the whole table — docs/PERFORMANCE.md §8) regresses here
# even when every latency percentile held steady.
_TRACKED_GAUGES = {
    "langdetect_table_bytes": "table_bytes",
    "langdetect_fit_collect_bytes": "fit_collect_bytes",
}

# Cold-start histograms (docs/PERFORMANCE.md §12): spawn-to-READY and
# zoo cold-load walls are tracked regression metrics — their p50 is
# diffed alongside the default mean/p99, because the cold-start plane's
# whole value proposition is the *typical* spawn collapsing once the
# compile cache and baked artifacts are warm; a p99 blown out by one
# first-ever spawn must not mask a p50 regression (or hide a p50 win).
# Module-level on purpose: the static contract checker (analysis/, R2)
# verifies each name is emitted somewhere, so a renamed histogram fails
# tier-1 instead of silently never regressing.
_COLD_START_HISTOGRAMS = ("scale/spawn_ready_s", "zoo/cold_load_s")

# Aggregate fill-ratio contract metrics re-derived from the last
# snapshot's exact byte/row counters (the per-batch histograms are sampled
# reservoirs; these are whole-run truth): real bytes over capacity bytes
# for each wire path, coalesced rows over dispatch capacity for serving.
# Names carry "fill_ratio" so the tracked diff treats them higher-better —
# a change that quietly unfills the compiled shapes (lattice drift, a
# mis-tuned profile, a coalescing regression) fails here even when every
# latency percentile held steady.
# Recovery-behavior counters the guard diffs as reliability regressions
# (a zero-baseline appearance regresses — see compare_captures). Only
# counters that measure *rejection or recovery* belong here; throughput
# counters (serve/coalesced_rows) and good-news counters
# (fleet/readmissions) legitimately grow. Module-level on purpose: these
# names are a cross-module contract with the emit sites, and the static
# contract checker (analysis/, R2) verifies every row is actually
# emitted somewhere — a misspelled or retired counter fails tier-1
# instead of silently never regressing.
_RELIABILITY_COUNTER_PREFIXES = ("resilience/", "serve/shed", "zoo/shed")
_RELIABILITY_COUNTERS = (
    "score/retries",
    "stream/retries",
    "serve/deadline_rejects",
    "serve/dispatch_errors",
    "serve/client_retries",
    "fleet/failovers",
    "fleet/ejections",
    "fleet/shed_requests",
    "fleet/swap_aborts",
    # Multi-tenant zoo (docs/SERVING.md §12): a cross-tenant routing
    # reject or a failed tenant cold load appearing against a clean
    # baseline is an isolation/availability regression, full stop.
    "zoo/cross_tenant_rejects",
    "zoo/load_errors",
    # Cold-start plane (docs/PERFORMANCE.md §12): a baked artifact being
    # refused (torn/foreign) on a fixed workload means cold loads are
    # silently falling back to the parquet parse — the fast path is
    # dark, the spawn budget quietly regresses.
    "artifacts/load_errors",
    # Elastic fleet (docs/SERVING.md §13): a replica spawn failing or a
    # supervised restart firing against a clean baseline means replicas
    # are dying or failing to come up — reliability regressions both.
    # Scale-ups/downs are the autoscaler doing its job (informational).
    "scale/spawn_failures",
    "scale/restarts",
    # Fleet observability plane (docs/OBSERVABILITY.md §14): a telemetry
    # scrape failing against a clean baseline means the coordinator is
    # flying partially blind — the aggregate (and everything reading it:
    # autoscaler pressure, SLO burn rates) silently under-counts.
    "fleet/agg_scrape_failures",
    # Storm defense (docs/RESILIENCE.md §7). Each of these appearing
    # against a clean baseline is a reliability event on a fixed
    # workload: the retry budget draining means retries outran the
    # success fraction (an outage or a retry-amplification bug), a
    # router-side deadline reject means requests arrived at the fleet
    # tier with no budget left, and a quarantine firing means requests
    # started killing replicas. The protective response working is
    # exactly the signal the guard must surface.
    "fleet/retry_budget_exhausted",
    "fleet/deadline_rejects",
    "fleet/quarantined_signatures",
    "fleet/quarantine_rejects",
    "serve/client_deadline_gaveups",
)

# Informational counters: diffed and shown like the reliability set but
# NEVER a regression — evictions and cold loads are normal life under a
# residency budget (a bigger tenant population legitimately pages more),
# so their movement is operator signal, not a gate. The static contract
# checker (analysis/, R2) still verifies every row is emitted somewhere.
_INFORMATIONAL_COUNTERS = (
    "zoo/evictions",
    "zoo/cold_loads",
    # Autoscaler actions and coordinator-crash cleanup: capacity
    # following traffic (and a reaper doing its job on the NEXT start)
    # is normal elastic life, not a regression — operator signal only.
    "scale/ups",
    "scale/downs",
    "scale/orphans_reaped",
    # Observability-plane volume: scrape rounds happening and SLO alert
    # transitions firing are the plane working (the alert may be the
    # CORRECT response to induced load) — the regression gates live on
    # fleet/agg_scrape_failures and the slo/burn_rate histogram instead.
    "fleet/agg_scrapes",
    "slo/alerts",
    # Storm-defense volume: wire dispatches (the retry-amplification
    # denominator's partner) and hedges firing/winning are the hedging
    # plane doing its latency job when enabled — the regression gates
    # live on fleet/retry_budget_exhausted and the latency histograms.
    "fleet/dispatches",
    "fleet/hedges",
    "fleet/hedge_wins",
)

_TRACKED_RATIOS = {
    "fill_ratio[score/wire]": ("score/real_bytes", "score/capacity_bytes"),
    "fill_ratio[fit/wire]": ("fit/real_bytes", "fit/capacity_bytes"),
    "fill_ratio[serve/coalesce]": (
        "serve/coalesced_rows", "serve/dispatch_capacity_rows"
    ),
    # Redundancy-elimination contract metrics (docs/PERFORMANCE.md §10),
    # exact whole-run ratios from the dedup/cache counters. On a fixed
    # replayed workload: ``cache/hit_rate`` regresses DOWNWARD (substring
    # match in _HIGHER_BETTER — fewer hits on the same traffic means the
    # cache layer broke), while ``dedup/unique_ratio`` (rows the wire
    # still carries / rows submitted) regresses UPWARD like any other
    # cost ratio — a dedup layer that stops collapsing the same
    # duplicates drifts toward 1.0.
    "cache/hit_rate": ("cache/hits", "cache/lookups"),
    "dedup/unique_ratio": ("dedup/rows_unique", "dedup/rows_in"),
    # Segmentation confidence contract (docs/SEGMENTATION.md): the
    # whole-run reject fraction, exact from the decode's counters. On a
    # FIXED workload the reject rate drifting UP regresses (the default
    # direction): rejects on the same documents mean the confidence
    # pipeline — scores, length normalization, or a recalibration —
    # got worse, even when every latency percentile held steady. The
    # decode increments ``segment/docs`` unconditionally (zero-reject
    # runs still carry the denominator and a zero numerator), so a
    # candidate that STARTS rejecting fails against a clean baseline.
    "segment/reject_rate": ("segment/rejects", "segment/docs"),
    # Wire-wall contract metric (docs/PERFORMANCE.md §11): bytes shipped
    # per scored document, exact from the dispatch's wire accounting. On
    # a fixed replayed workload this regresses UPWARD (the default
    # lower-is-better direction): the same corpus suddenly costing more
    # wire per doc means the device-encode lane silently fell back to
    # host padding — exactly the drift the fill_ratio[score/wire] guard
    # can miss when the padded lattice happens to fill well.
    "score/wire_bytes_per_doc": ("score/wire_bytes", "score/wire_docs"),
}


def _tracked_metrics(events: list[dict], stages: dict) -> dict[str, float]:
    """Gauge-derived contract metrics from a capture's LAST snapshot.

    ``table_bytes[...]`` is the raw gauge per label set;
    ``est_bytes_utilization[<program>]`` is re-derived exactly like
    ``Registry.stage_summary`` joins it: program_bytes_accessed per call /
    measured per-call seconds (fenced device mean preferred) / the
    platform peak — so the guard sees the same number the bench telemetry
    block reports.
    """
    gauges: dict = {}
    counters: dict = {}
    for ev in events:
        if ev.get("event") != "telemetry.snapshot":
            continue
        payload = ev.get("gauges")
        if isinstance(payload, dict):
            gauges = payload
        cpayload = ev.get("counters")
        if isinstance(cpayload, dict):
            counters = cpayload
    out: dict[str, float] = {}
    for name, (num_key, den_key) in _TRACKED_RATIOS.items():
        num, den = counters.get(num_key), counters.get(den_key)
        if (
            isinstance(num, (int, float))
            and isinstance(den, (int, float))
            and den > 0
        ):
            out[name] = round(float(num) / float(den), 6)
    for name, short in _TRACKED_GAUGES.items():
        series = gauges.get(name)
        if not isinstance(series, dict):
            continue
        # Keyed by PROGRAM only, max over label sets: the quant/strategy
        # labels change when a profile de-quantizes, and a key that moves
        # with them would downgrade exactly that regression to an
        # informational one-sided line. Under one program key, an int8 →
        # f32 flip is a same-key 4x value jump and fails the diff.
        for label, val in series.items():
            if not isinstance(val, (int, float)):
                continue
            program = dict(
                p.split("=", 1) for p in label.split(",") if "=" in p
            ).get("program", label)
            key = f"{short}[{program}]"
            out[key] = max(out.get(key, 0.0), float(val))
    peak = None
    for label, val in (gauges.get("device_peak_bytes_per_s") or {}).items():
        if isinstance(val, (int, float)) and val > 0:
            peak = float(val)
            break
    if peak:
        for label, per_call in (
            gauges.get("program_bytes_accessed") or {}
        ).items():
            if not isinstance(per_call, (int, float)):
                continue
            program = dict(
                p.split("=", 1) for p in label.split(",") if "=" in p
            ).get("program")
            entry = stages.get(program)
            if not entry:
                continue
            seconds = entry.get("mean")
            if entry.get("device_total_s") and entry.get("count"):
                seconds = entry["device_total_s"] / entry["count"]
            if not seconds:
                continue
            out[f"est_bytes_utilization[{program}]"] = round(
                per_call / seconds / peak, 6
            )
    return out


def capture_stats(events: list[dict]) -> dict:
    """One capture's comparable stats: per-stage wall/device aggregates +
    the last snapshot's plain histograms."""
    stages: dict[str, dict] = {}
    wall: dict[str, Histogram] = {}
    device: dict[str, Histogram] = {}
    for ev in events:
        if ev.get("event") != "telemetry.span":
            continue
        path, w = ev.get("path"), ev.get("wall_s")
        if not isinstance(path, str) or not isinstance(w, (int, float)):
            continue
        wall.setdefault(path, Histogram()).record(float(w))
        d = ev.get("device_s")
        if isinstance(d, (int, float)):
            device.setdefault(path, Histogram()).record(float(d))
    for path, h in wall.items():
        s = h.snapshot()
        entry = {
            "count": s["count"],
            "total_s": s["sum"],
            **{k: s[k] for k in ("mean", "p50", "p90", "p99") if k in s},
        }
        dh = device.get(path)
        if dh is not None:
            ds = dh.snapshot()
            entry["device_total_s"] = ds["sum"]
            if "p99" in ds:
                entry["device_p99"] = ds["p99"]
        stages[path] = entry

    hists: dict[str, dict] = {}
    counters: dict[str, float] = {}
    for ev in events:
        if ev.get("event") != "telemetry.snapshot":
            continue
        payload = ev.get("histograms")
        if isinstance(payload, dict):
            hists = {
                str(k): v for k, v in payload.items()
                if isinstance(v, dict) and v.get("count")
            }
        # Recovery-behavior counters (retries, breaker trips, DLQ rows,
        # degraded batches, serve sheds/deadline rejections, fleet
        # failovers/ejections/swap aborts): a regression here is a
        # reliability story even when every latency percentile held
        # steady, so the guard diffs them like any other metric
        # (docs/RESILIENCE.md §8, docs/SERVING.md §6, §9).
        cpayload = ev.get("counters")
        if isinstance(cpayload, dict):
            counters = {
                str(k): v for k, v in cpayload.items()
                if isinstance(v, (int, float))
                and (
                    str(k).startswith(_RELIABILITY_COUNTER_PREFIXES)
                    or str(k) in _RELIABILITY_COUNTERS
                    or str(k) in _INFORMATIONAL_COUNTERS
                )
            }
    return {
        "stages": stages,
        "histograms": hists,
        "counters": counters,
        "tracked": _tracked_metrics(events, stages),
    }


def _rel_delta(base: float, new: float) -> float | None:
    if not isinstance(base, (int, float)) or not isinstance(new, (int, float)):
        return None
    if base <= 0:
        return None
    return (new - base) / base


def compare_captures(
    base: dict,
    new: dict,
    *,
    threshold: float = DEFAULT_THRESHOLD,
    metrics: tuple[str, ...] = DEFAULT_METRICS,
    min_seconds: float = 0.0,
) -> tuple[list[str], list[str]]:
    """(report lines, regression descriptions) for two capture_stats."""
    lines: list[str] = []
    regressions: list[str] = []
    b_stages, n_stages = base["stages"], new["stages"]
    shared = sorted(set(b_stages) & set(n_stages))
    only_base = sorted(set(b_stages) - set(n_stages))
    only_new = sorted(set(n_stages) - set(b_stages))

    header = (
        f"{'stage':<28} {'metric':<14} {'base':>12} {'new':>12} {'delta':>9}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    span_metrics = tuple(metrics)
    for path in shared:
        b, n = b_stages[path], n_stages[path]
        if b.get("total_s", 0.0) < min_seconds:
            continue
        for m in span_metrics:
            if m not in b or m not in n:
                continue
            delta = _rel_delta(b[m], n[m])
            if delta is None:
                continue
            flag = ""
            if delta > threshold:
                flag = "  REGRESSION"
                regressions.append(
                    f"{path} {m}: {b[m]:.6f} -> {n[m]:.6f} (+{delta:.1%})"
                )
            if flag or abs(delta) > threshold / 2:
                lines.append(
                    f"{path:<28} {m:<14} {b[m]:>12.6f} {n[m]:>12.6f} "
                    f"{delta:>+8.1%}{flag}"
                )

    b_h, n_h = base["histograms"], new["histograms"]
    for name in sorted(set(b_h) & set(n_h)):
        b, n = b_h[name], n_h[name]
        hist_metrics = ("mean", "p99")
        if name in _COLD_START_HISTOGRAMS:
            hist_metrics = ("mean", "p50", "p99")
        for m in hist_metrics:
            delta = _rel_delta(b.get(m), n.get(m))
            if delta is None:
                continue
            higher_better = any(t in name for t in _HIGHER_BETTER)
            worse = -delta if higher_better else delta
            flag = ""
            if worse > threshold:
                flag = "  REGRESSION"
                regressions.append(
                    f"{name} {m}: {b[m]:.6f} -> {n[m]:.6f} ({delta:+.1%})"
                )
            if flag or abs(delta) > threshold / 2:
                lines.append(
                    f"{name:<28} {m:<14} {b[m]:>12.6f} {n[m]:>12.6f} "
                    f"{delta:>+8.1%}{flag}"
                )

    b_c, n_c = base.get("counters", {}), new.get("counters", {})
    for name in sorted(set(b_c) | set(n_c)):
        bv = float(b_c.get(name, 0) or 0)
        nv = float(n_c.get(name, 0) or 0)
        if bv <= 0 and nv <= 0:
            continue
        if bv > 0:
            delta = (nv - bv) / bv
            shown = f"{delta:>+8.1%}"
        else:
            # Zero/absent baseline: the most common reliability regression
            # IS a recovery counter appearing at all (0 retries -> 50, a
            # first breaker trip) — a relative delta can't express it, so
            # any appearance regresses regardless of threshold.
            delta = math.inf
            shown = f"{'new':>8}"
        if name in _INFORMATIONAL_COUNTERS:
            # Tracked for the operator, exempt from the gate: paging
            # activity moving with the tenant population is expected.
            if delta == math.inf or abs(delta) > threshold / 2:
                lines.append(
                    f"{name:<28} {'count':<14} {bv:>12.6f} "
                    f"{nv:>12.6f} {shown}  informational"
                )
            continue
        flag = ""
        if delta > threshold:
            flag = "  REGRESSION"
            suffix = "new" if delta == math.inf else f"+{delta:.1%}"
            regressions.append(f"{name}: {bv:g} -> {nv:g} ({suffix})")
        if flag or abs(delta) > threshold / 2:
            lines.append(
                f"{name:<28} {'count':<14} {bv:>12.6f} "
                f"{nv:>12.6f} {shown}{flag}"
            )

    # Tracked contract metrics: table-traffic gauges regress upward (more
    # table bytes resident / streamed, more of the HBM roof consumed);
    # the aggregate fill ratios regress downward (emptier shapes). Unlike
    # the recovery counters, a metric appearing in only one capture is
    # informational — instrumentation grows between rounds, and a
    # freshly-tracked metric has no contract yet.
    b_t, n_t = base.get("tracked", {}), new.get("tracked", {})
    for name in sorted(set(b_t) | set(n_t)):
        if name not in b_t or name not in n_t:
            lines.append(
                f"tracked metric only in "
                f"{'baseline' if name in b_t else 'candidate'}: {name}"
            )
            continue
        delta = _rel_delta(b_t[name], n_t[name])
        higher_better = any(t in name for t in _HIGHER_BETTER)
        if delta is None:
            # A lower-better ratio rising off an exactly-zero baseline
            # (a zero-reject run that starts rejecting: segment/
            # reject_rate 0 -> anything) has no finite relative delta —
            # like the reliability counters, the appearance itself is
            # the regression.
            if not higher_better and b_t[name] == 0 and n_t[name] > 0:
                delta = math.inf
            else:
                continue
        worse = -delta if higher_better else delta
        flag = ""
        if worse > threshold:
            flag = "  REGRESSION"
            regressions.append(
                f"{name}: {b_t[name]:g} -> {n_t[name]:g} ({delta:+.1%})"
            )
        if flag or abs(delta) > threshold / 2:
            lines.append(
                f"{name:<28} {'gauge':<14} {b_t[name]:>12.6f} "
                f"{n_t[name]:>12.6f} {delta:>+8.1%}{flag}"
            )

    if only_base:
        lines.append(f"only in baseline: {', '.join(only_base)}")
    if only_new:
        lines.append(f"only in candidate: {', '.join(only_new)}")
    return lines, regressions


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    threshold = DEFAULT_THRESHOLD
    metrics = DEFAULT_METRICS
    min_seconds = 0.0
    paths: list[str] = []
    i = 0
    try:
        while i < len(argv):
            a = argv[i]
            if a in ("-h", "--help"):
                raise ValueError
            if a == "--threshold":
                threshold = float(argv[i + 1])
                i += 2
            elif a == "--metrics":
                metrics = tuple(
                    m.strip() for m in argv[i + 1].split(",") if m.strip()
                )
                i += 2
            elif a == "--min-seconds":
                min_seconds = float(argv[i + 1])
                i += 2
            elif a.startswith("-"):
                raise ValueError(f"unknown option {a!r}")
            else:
                paths.append(a)
                i += 1
        if len(paths) != 2:
            raise ValueError
    except (ValueError, IndexError) as e:
        msg = f"error: {e}\n" if str(e) else ""
        print(
            msg + "usage: python -m spark_languagedetector_tpu.telemetry."
            "compare <baseline.jsonl> <candidate.jsonl> "
            "[--threshold 0.25] [--metrics p50,p90,p99] [--min-seconds 0.0]",
            file=sys.stderr,
        )
        return 2
    try:
        base = capture_stats(load_events(paths[0]))
        new = capture_stats(load_events(paths[1]))
    except OSError as e:
        print(f"cannot read capture: {e}", file=sys.stderr)
        return 2
    if not base["stages"] and not base["histograms"]:
        print(f"no comparable telemetry in {paths[0]}", file=sys.stderr)
        return 2
    lines, regressions = compare_captures(
        base, new, threshold=threshold, metrics=metrics,
        min_seconds=min_seconds,
    )
    print("\n".join(lines))
    if regressions:
        print(
            f"\n{len(regressions)} regression(s) past threshold "
            f"{threshold:.0%}:"
        )
        for r in regressions:
            print(f"  {r}")
        return 1
    print(f"\nok: no regression past threshold {threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
