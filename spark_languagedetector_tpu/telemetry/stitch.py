"""Cross-process trace stitching: one Perfetto timeline from N captures.

:mod:`.tracing` renders ONE process's JSONL capture; a subprocess fleet
produces one capture per process (the coordinator's sink plus each
replica worker's ``--metrics-jsonl``), each stamped with its own
process identity (:mod:`.aggregate`) and each on its own wall clock. The
CLI::

    python -m spark_languagedetector_tpu.telemetry.stitch \
        router.jsonl replica-*.jsonl [-o out.trace.json]

merges them into one Chrome/Perfetto trace: one ``pid`` per capture
(named by the recording process's identity), lanes per recording thread
within it, and every timestamp aligned to the **coordinator's clock**
via the offset recorded at the spawn/READY handshake — the child stamps
its wall clock onto the READY line, the coordinator differences it and
emits a ``telemetry.clock_sync`` event into its own capture
(:meth:`~..scale.replica.ProcessReplica.spawn`), and the stitcher reads
those events back. A restart re-syncs (the last handshake per replica
wins).

Request flows cross processes by the ``trace_id`` that already rides the
HTTP payload: the router's ``fleet/dispatch`` span, the replica's
``serve/dispatch`` span, and the runner's nested ``score/*`` spans all
carry it, so one request reads top-to-bottom across process lanes.
:func:`trace_flows`/:func:`nesting_slack_s` expose the same join
programmatically — the ``--smoke-obs`` gate checks a stitched flow's
spans nest with non-negative slack (a child span can never out-last the
parent that enclosed it in real time, whatever the clocks said).
"""

from __future__ import annotations

import json
import os
import sys
from collections import Counter

from .tracing import _DEVICE_LANE_BASE, _SPAN_FIELDS, _span_events

CLOCK_SYNC_EVENT = "telemetry.clock_sync"


# ------------------------------------------------------------ clock sync ----
def clock_offsets(events: list[dict]) -> dict[str, float]:
    """``replica name -> offset_s`` (coordinator clock − replica clock)
    from the coordinator capture's clock-sync events. The last handshake
    per name wins — a supervised restart re-syncs its replica."""
    offsets: dict[str, float] = {}
    for ev in events:
        if ev.get("event") != CLOCK_SYNC_EVENT:
            continue
        name, off = ev.get("replica"), ev.get("offset_s")
        if isinstance(name, str) and isinstance(off, (int, float)):
            offsets[name] = float(off)
    return offsets


def capture_label(events: list[dict], fallback: str) -> str:
    """Which process wrote this capture? The identity stamp on its span
    records answers for replica workers; a capture holding clock-sync
    events is the coordinator. Falls back to the file stem."""
    if any(ev.get("event") == CLOCK_SYNC_EVENT for ev in events):
        return "router"
    names = Counter(
        ev["replica"] for ev in _span_events(events)
        if isinstance(ev.get("replica"), str)
        and isinstance(ev.get("pid"), int)
    )
    if names:
        return names.most_common(1)[0][0]
    return fallback


def load_captures(paths: list[str]) -> list[dict]:
    """Load + label + clock-align captures. Returns, per file:
    ``{"label", "path", "events", "offset_s", "identity"}``; offsets come
    from whichever capture carries the clock-sync events (the
    coordinator's), keyed by the other captures' labels."""
    from .report import load_events

    raw = []
    for path in paths:
        events = load_events(path)
        stem = os.path.basename(path)
        stem = stem[:-6] if stem.endswith(".jsonl") else stem
        raw.append({"path": path, "events": events, "stem": stem})
    offsets: dict[str, float] = {}
    for cap in raw:
        offsets.update(clock_offsets(cap["events"]))
    out = []
    for cap in raw:
        label = capture_label(cap["events"], cap["stem"])
        identity: dict = {}
        for ev in _span_events(cap["events"]):
            if isinstance(ev.get("pid"), int):
                identity = {
                    k: ev[k] for k in ("replica", "pid", "platform")
                    if k in ev
                }
                break
        out.append({
            "label": label,
            "path": cap["path"],
            "events": cap["events"],
            "offset_s": offsets.get(label, 0.0),
            "identity": identity,
        })
    return out


# --------------------------------------------------------------- stitching --
def render_stitched_trace(captures: list[dict]) -> dict:
    """:func:`load_captures` output → Chrome trace-event JSON (dict).

    The single-capture exporter's conventions generalized per process:
    capture ordinal + 1 is the ``pid`` (named by the capture label),
    thread idents remap to dense per-process lane ordinals (device
    siblings at ``_DEVICE_LANE_BASE + lane``), timestamps shift by each
    capture's clock offset, become microseconds relative to the earliest
    aligned span start, and clamp per-lane monotonic."""
    trace_events: list[dict] = []
    per_proc: list[dict] = []
    t0: float | None = None
    for ordinal, cap in enumerate(captures):
        pid = ordinal + 1
        off = float(cap.get("offset_s") or 0.0)
        lane_ord: dict = {}
        lanes: dict[int, list[tuple[float, float, dict, bool]]] = {}
        lane_ident: dict[int, object] = {}
        for ev in _span_events(cap["events"]):
            ident = ev.get("tid")
            if not isinstance(ident, int):
                ident = 0
            lane = lane_ord.setdefault(ident, len(lane_ord))
            lane_ident[lane] = ident
            start = float(ev["ts"]) + off - float(ev["wall_s"])
            if t0 is None or start < t0:
                t0 = start
            lanes.setdefault(lane, []).append(
                (start, float(ev["wall_s"]), ev, False)
            )
            dev = ev.get("device_s")
            if isinstance(dev, (int, float)):
                lanes.setdefault(_DEVICE_LANE_BASE + lane, []).append(
                    (start, float(dev), ev, True)
                )
        name = str(cap.get("label") or f"process {pid}")
        ident_blk = cap.get("identity") or {}
        if ident_blk.get("pid") is not None:
            name = f"{name} (pid {ident_blk['pid']})"
        trace_events.append(
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": name}}
        )
        per_proc.append({
            "pid": pid, "off": off, "lanes": lanes,
            "lane_ident": lane_ident, "events": cap["events"],
        })
    if t0 is None:
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    for proc in per_proc:
        pid, lanes, lane_ident = (
            proc["pid"], proc["lanes"], proc["lane_ident"]
        )
        for lane in sorted(lanes):
            if lane >= _DEVICE_LANE_BASE:
                label = (
                    f"device (thread "
                    f"{lane_ident[lane - _DEVICE_LANE_BASE]})"
                )
            else:
                label = f"thread {lane_ident[lane]}"
            trace_events.append(
                {"name": "thread_name", "ph": "M", "pid": pid,
                 "tid": lane, "args": {"name": label}}
            )
        for lane, items in sorted(lanes.items()):
            items.sort(key=lambda it: it[0])
            last_us = 0.0
            for start, dur, ev, is_device in items:
                ts_us = max((start - t0) * 1e6, last_us)
                last_us = ts_us
                args = {
                    k: v for k, v in ev.items() if k not in _SPAN_FIELDS
                }
                name = ev["path"] + (" [device]" if is_device else "")
                trace_events.append({
                    "name": name, "cat": "span", "ph": "X", "pid": pid,
                    "tid": lane, "ts": round(ts_us, 3),
                    "dur": round(dur * 1e6, 3), "args": args,
                })
        for ev in proc["events"]:
            if ev.get("event") != "telemetry.snapshot":
                continue
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            ts_us = max((float(ts) + proc["off"] - t0) * 1e6, 0.0)
            for gname, series in (ev.get("gauges") or {}).items():
                if not isinstance(series, dict):
                    continue
                numeric = {
                    (k or "value"): v
                    for k, v in series.items()
                    if isinstance(v, (int, float))
                }
                if numeric:
                    trace_events.append({
                        "name": str(gname), "ph": "C", "pid": proc["pid"],
                        "tid": 0, "ts": round(ts_us, 3), "args": numeric,
                    })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


# ------------------------------------------------------------ flow checks ---
def trace_flows(captures: list[dict]) -> dict[str, list[dict]]:
    """``trace_id -> [{"process", "path", "start_s", "wall_s"}, ...]``
    across every capture, clock-aligned — the programmatic form of the
    stitched timeline's request join."""
    flows: dict[str, list[dict]] = {}
    for cap in captures:
        off = float(cap.get("offset_s") or 0.0)
        label = str(cap.get("label"))
        for ev in _span_events(cap["events"]):
            tid = ev.get("trace_id")
            if not isinstance(tid, str):
                continue
            flows.setdefault(tid, []).append({
                "process": label,
                "path": ev["path"],
                "start_s": float(ev["ts"]) + off - float(ev["wall_s"]),
                "wall_s": float(ev["wall_s"]),
            })
    for spans in flows.values():
        spans.sort(key=lambda s: s["start_s"])
    return flows


def nesting_slack_s(spans: list[dict]) -> float | None:
    """Minimum parent-minus-child duration slack for one flow's
    router→replica→runner chain, or None when the chain is incomplete.

    Duration containment is clock-offset independent: the router's
    ``fleet/dispatch`` span encloses the replica's HTTP handling (which
    encloses its ``serve/dispatch``), and ``serve/dispatch`` encloses
    the runner's ``score/*`` work — in real time, whatever each
    process's wall clock reads. Non-negative slack is therefore the
    honest stitched-nesting gate."""
    router = [
        s["wall_s"] for s in spans
        if s["path"].split("/")[0] == "fleet"
        and s["path"].startswith("fleet/dispatch")
    ]
    replica = [
        s["wall_s"] for s in spans if s["path"] == "serve/dispatch"
    ]
    runner = [
        s["wall_s"] for s in spans
        if s["path"].startswith("serve/dispatch/") and "score" in s["path"]
    ]
    if not (router and replica and runner):
        return None
    return min(
        max(router) - max(replica),
        max(replica) - max(runner),
    )


def write_stitched_trace(paths: list[str], out_path: str) -> str:
    captures = load_captures(paths)
    trace = render_stitched_trace(captures)
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(trace, fh, default=str)
    os.replace(tmp, out_path)
    return out_path


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out = None
    if "-o" in argv:
        i = argv.index("-o")
        if i + 1 >= len(argv):
            print("-o needs a path", file=sys.stderr)
            return 2
        out = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m spark_languagedetector_tpu.telemetry.stitch "
            "<router.jsonl> [replica-*.jsonl ...] [-o out.trace.json]",
            file=sys.stderr,
        )
        return 2
    if out is None:
        src = argv[0]
        out = (
            (src[:-6] if src.endswith(".jsonl") else src) + ".stitched.json"
        )
    try:
        captures = load_captures(argv)
    except OSError as e:
        print(f"cannot load captures: {e}", file=sys.stderr)
        return 2
    trace = render_stitched_trace(captures)
    parent = os.path.dirname(os.path.abspath(out))
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(trace, fh, default=str)
    os.replace(tmp, out)
    flows = trace_flows(captures)
    cross = sum(
        1 for spans in flows.values()
        if len({s["process"] for s in spans}) > 1
    )
    print(out)
    print(
        f"stitched {len(captures)} captures, {len(flows)} traces "
        f"({cross} crossing processes)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
