"""Request tracing and Chrome/Perfetto trace export.

Two halves of one feature — isolating *one request* out of an aggregate:

**Trace context.** A ``trace_id`` rides a :mod:`contextvars` variable the
same way the active span does. ``trace_request()`` opens a request scope
(reusing an ambient one by default, so a ``BatchRunner.score`` call inside
a streaming transform joins the stream batch's trace instead of starting
its own); every span opened inside the scope stamps ``trace_id`` onto its
exported JSONL record. Cross-thread work inherits the id through the
explicit span ``parent`` (a worker thread has no ambient context), so the
runner's dispatch workers and the streaming engine's prefetch workers
attribute correctly without touching the contextvar themselves.

**Chrome trace export.** ``render_chrome_trace`` turns a captured JSONL
event stream (the ``jsonl`` sink's output, or a flight-recorder dump)
into ``chrome://tracing`` / Perfetto trace-event JSON: one lane per
recording thread (plus a device lane for fenced spans, whose ``device_s``
covers completion rather than enqueue), span attrs — the trace id
included — in ``args``, and gauge snapshots as counter tracks. The CLI::

    python -m spark_languagedetector_tpu.telemetry.tracing events.jsonl [out.json]

complements the raw ``jax.profiler`` hook in ``utils/profiling.py``: XProf
shows op-level device timelines for one capture; this shows the host-side
stage/request timeline for a whole run, cheap enough to leave on.
"""

from __future__ import annotations

import contextvars
import json
import os
import sys
import uuid
from contextlib import contextmanager

_TRACE_ID: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "langdetect_trace_id", default=None
)


def new_trace_id() -> str:
    """16-hex random request/trace id (collision odds are irrelevant at
    per-request cardinality; short enough to grep and to read aloud)."""
    return uuid.uuid4().hex[:16]


def current_trace_id() -> str | None:
    """The calling context's active trace id, or None outside any request."""
    return _TRACE_ID.get()


@contextmanager
def trace_request(trace_id: str | None = None):
    """Open a request scope; yields the trace id spans will stamp.

    ``trace_id=None`` *reuses* an ambient scope when one is active (a
    score call inside a stream batch joins the batch's trace) and mints a
    fresh id otherwise. Passing an explicit id always (re)binds — the
    streaming engine passes one per source batch.
    """
    if trace_id is None:
        existing = _TRACE_ID.get()
        if existing is not None:
            yield existing
            return
        trace_id = new_trace_id()
    token = _TRACE_ID.set(trace_id)
    try:
        yield trace_id
    finally:
        _TRACE_ID.reset(token)


# ------------------------------------------------------- chrome export ------

# Synthetic lane offset for fenced device timings: a span whose device_s
# was recorded gets a second complete event on a per-source-thread device
# lane, so enqueue (host lane) and completion (device lane) read side by
# side without nesting one inside the other. Raw thread idents (pthread
# addresses on Linux — huge, collision-prone under any masking) are never
# used as lane ids; threads are remapped to small ordinals first.
_DEVICE_LANE_BASE = 1 << 20

# Span-record fields that are structural, not user attrs.
_SPAN_FIELDS = ("event", "ts", "path", "wall_s", "device_s", "tid")


def _span_events(events: list[dict]) -> list[dict]:
    return [
        e for e in events
        if e.get("event") == "telemetry.span"
        and isinstance(e.get("path"), str)
        and isinstance(e.get("wall_s"), (int, float))
        and isinstance(e.get("ts"), (int, float))
    ]


def render_chrome_trace(events: list[dict]) -> dict:
    """JSONL telemetry events → Chrome trace-event JSON (dict form).

    Timestamps are microseconds relative to the earliest span start. Each
    lane's events are sorted by start time and clamped non-decreasing, so
    the output is valid for viewers that require per-lane monotonic ``ts``
    (the captured ``ts`` is span *end* time; starts are reconstructed as
    ``ts - wall_s`` and can interleave across producers).
    """
    spans = _span_events(events)
    pid = 1
    trace_events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "spark_languagedetector_tpu"}},
    ]
    if not spans:
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    # Thread idents are remapped to dense ordinals (first-seen order, by
    # earliest event): a host lane n and its device sibling
    # _DEVICE_LANE_BASE + n. Idents are only ever dict keys and labels —
    # a 140TB pthread address must not become a lane id, and masking one
    # could collide two real threads onto one lane.
    lane_ord: dict = {}
    lanes: dict[int, list[tuple[float, float, dict, bool]]] = {}
    lane_ident: dict[int, object] = {}
    t0 = None
    for ev in spans:
        ident = ev.get("tid")
        if not isinstance(ident, int):
            ident = 0
        lane = lane_ord.setdefault(ident, len(lane_ord))
        lane_ident[lane] = ident
        start = float(ev["ts"]) - float(ev["wall_s"])
        if t0 is None or start < t0:
            t0 = start
        lanes.setdefault(lane, []).append(
            (start, float(ev["wall_s"]), ev, False)
        )
        dev = ev.get("device_s")
        if isinstance(dev, (int, float)):
            lanes.setdefault(_DEVICE_LANE_BASE + lane, []).append(
                (start, float(dev), ev, True)
            )

    for lane in sorted(lanes):
        if lane >= _DEVICE_LANE_BASE:
            label = f"device (thread {lane_ident[lane - _DEVICE_LANE_BASE]})"
        else:
            label = f"thread {lane_ident[lane]}"
        trace_events.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": lane,
             "args": {"name": label}}
        )

    for lane, items in sorted(lanes.items()):
        items.sort(key=lambda it: it[0])
        last_us = 0.0
        for start, dur, ev, is_device in items:
            ts_us = max((start - t0) * 1e6, last_us)
            last_us = ts_us
            args = {
                k: v for k, v in ev.items() if k not in _SPAN_FIELDS
            }
            name = ev["path"] + (" [device]" if is_device else "")
            trace_events.append({
                "name": name, "cat": "span", "ph": "X", "pid": pid,
                "tid": lane, "ts": round(ts_us, 3),
                "dur": round(dur * 1e6, 3), "args": args,
            })

    # Gauge snapshots → counter tracks (Perfetto renders them as graphs).
    for ev in events:
        if ev.get("event") != "telemetry.snapshot":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        ts_us = max((float(ts) - t0) * 1e6, 0.0)
        for gname, series in (ev.get("gauges") or {}).items():
            if not isinstance(series, dict):
                continue
            numeric = {
                (k or "value"): v
                for k, v in series.items()
                if isinstance(v, (int, float))
            }
            if numeric:
                trace_events.append({
                    "name": str(gname), "ph": "C", "pid": pid, "tid": 0,
                    "ts": round(ts_us, 3), "args": numeric,
                })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def write_chrome_trace(events_path: str, out_path: str) -> str:
    """Convert one JSONL capture to a Chrome trace file; returns out_path."""
    from .report import load_events

    trace = render_chrome_trace(load_events(events_path))
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(trace, fh, default=str)
    os.replace(tmp, out_path)
    return out_path


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not 1 <= len(argv) <= 2 or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m spark_languagedetector_tpu.telemetry.tracing "
            "<events.jsonl> [out.trace.json]",
            file=sys.stderr,
        )
        return 2
    src = argv[0]
    out = argv[1] if len(argv) == 2 else (
        (src[:-6] if src.endswith(".jsonl") else src) + ".trace.json"
    )
    try:
        path = write_chrome_trace(src, out)
    except OSError as e:
        print(f"cannot convert {src}: {e}", file=sys.stderr)
        return 2
    print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
