"""The metric registry: counters, histograms, gauges, span aggregates.

One coherent, process-global store behind the whole telemetry subsystem.
All writes take the registry lock — producers are per-batch (runner
dispatch, streaming engine, fit loop), never per-row, so the lock cost
stays invisible next to the work it measures (the same cost model as
``utils.metrics``). The registry itself never imports jax and never does
I/O: sinks attached via :meth:`Registry.add_sink` receive span/snapshot
events, and the Prometheus writer renders :meth:`Registry.snapshot`.
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from typing import Any, Callable

# Reservoir size: 512 float samples bound every histogram at ~4KB while
# keeping p99 meaningful for the per-batch populations we record (a bench
# pass is 10s-100s of batches; a long stream is sampled uniformly).
DEFAULT_RESERVOIR = 512


class Histogram:
    """Streaming distribution: exact count/sum/min/max + uniform reservoir.

    The reservoir uses Algorithm R with a deterministic LCG (no dependence
    on process-global random state), so two runs over the same sequence
    report identical percentiles — bench artifacts stay diffable.
    Thread-safety is the owning registry's job; standalone use from several
    threads needs external locking.
    """

    __slots__ = ("count", "total", "min", "max", "_res", "_cap", "_lcg")

    def __init__(self, reservoir_size: int = DEFAULT_RESERVOIR):
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._res: list[float] = []
        self._cap = reservoir_size
        self._lcg = 0x9E3779B9

    def record(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if len(self._res) < self._cap:
            self._res.append(value)
        else:
            self._lcg = (self._lcg * 1103515245 + 12345) & 0x7FFFFFFF
            j = self._lcg % self.count
            if j < self._cap:
                self._res[j] = value

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the reservoir; nan when empty."""
        if not self._res:
            return math.nan
        ordered = sorted(self._res)
        rank = min(len(ordered) - 1, max(0, math.ceil(p / 100.0 * len(ordered)) - 1))
        return ordered[rank]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else math.nan

    # ------------------------------------------------------------ merging ----
    def state(self) -> dict:
        """JSON-safe mergeable form: exact count/sum/min/max + the raw
        reservoir samples. This is what ``/telemetryz`` puts on the wire —
        :meth:`merge` on the far side reconstitutes a fleet-wide sketch
        (count/sum/min/max stay exact; percentiles are reservoir-
        approximate, same as locally)."""
        out: dict = {
            "count": self.count,
            "sum": self.total,
            "reservoir": list(self._res),
        }
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
        return out

    @classmethod
    def from_state(cls, state: dict, reservoir_size: int = DEFAULT_RESERVOIR):
        h = cls(reservoir_size)
        h.merge(state)
        return h

    @staticmethod
    def _thin(samples: list[float], keep: int) -> list[float]:
        # Deterministic uniform thinning (evenly spaced picks over the
        # sample order): two merges of the same scrapes yield the same
        # reservoir, so fleet-aggregate percentiles stay diffable run to
        # run — the same property the per-process LCG reservoir has.
        if keep >= len(samples):
            return list(samples)
        if keep <= 0:
            return []
        step = len(samples) / keep
        return [samples[int(i * step)] for i in range(keep)]

    def merge(self, other: "Histogram | dict") -> "Histogram":
        """Fold another histogram (or its :meth:`state` dict) into this
        one. Count/sum/min/max merge exactly; the reservoirs merge by
        population-weighted deterministic thinning, so the combined
        reservoir approximates a uniform sample over BOTH populations.
        Returns self (chainable folds in the fleet collector)."""
        state = other.state() if isinstance(other, Histogram) else other
        count = int(state.get("count", 0) or 0)
        if count <= 0:
            return self
        prior = self.count
        self.count += count
        self.total += float(state.get("sum", 0.0) or 0.0)
        mn, mx = state.get("min"), state.get("max")
        if isinstance(mn, (int, float)) and mn < self.min:
            self.min = float(mn)
        if isinstance(mx, (int, float)) and mx > self.max:
            self.max = float(mx)
        incoming = [
            float(v) for v in (state.get("reservoir") or ())
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        ]
        if not incoming:
            return self
        if len(self._res) + len(incoming) <= self._cap:
            self._res.extend(incoming)
            return self
        # Over capacity: each side keeps slots proportional to the
        # population it represents (not its reservoir length), clamped so
        # a tiny-but-present side is never thinned to nothing.
        keep_inc = round(self._cap * count / self.count)
        keep_inc = min(len(incoming), max(1, keep_inc))
        keep_own = min(len(self._res), self._cap - keep_inc)
        if prior > 0:
            keep_own = max(1, keep_own)
            keep_inc = min(keep_inc, self._cap - keep_own)
        self._res = (
            self._thin(self._res, keep_own) + self._thin(incoming, keep_inc)
        )
        return self

    def snapshot(self) -> dict:
        out = {
            "count": self.count,
            "sum": self.total,
        }
        if self.count:
            out.update(
                min=self.min,
                max=self.max,
                mean=self.mean,
                p50=self.percentile(50),
                p90=self.percentile(90),
                p99=self.percentile(99),
            )
        return out


def _label_key(labels: dict[str, Any]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Registry:
    """Counters + histograms + gauges + span aggregates, one lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, int] = defaultdict(int)
        self.histograms: dict[str, Histogram] = {}
        # gauge name -> {sorted (label, value) tuple -> last value}
        self.gauges: dict[str, dict[tuple[tuple[str, str], ...], float]] = {}
        self._sinks: list[Any] = []
        # Process identity (replica name, pid, platform), installed once
        # by replica workers (telemetry.aggregate.install_process_identity)
        # and stamped onto every exported span event + the mergeable
        # snapshot — multi-process captures stay attributable without
        # out-of-band context.
        self.identity: dict[str, Any] = {}

    # ------------------------------------------------------------- sinks ----
    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)
        close = getattr(sink, "close", None)
        if close:
            close()

    def clear_sinks(self) -> None:
        with self._lock:
            sinks, self._sinks = self._sinks, []
        for s in sinks:
            close = getattr(s, "close", None)
            if close:
                close()

    @property
    def sinks(self) -> list:
        return list(self._sinks)

    def emit(self, event: dict) -> None:
        """Hand one event dict to every attached per-event sink.

        Sink failures (disk full, closed file) are contained: spans emit
        from inside production fit/score/stream paths, and a metrics sink
        must never take down the computation it observes. Drops are
        counted (``telemetry/sink_errors``) and warned once per sink.
        """
        for sink in list(self._sinks):
            emit = getattr(sink, "emit", None)
            if emit is None:
                continue
            try:
                emit(event)
            except Exception as e:
                with self._lock:
                    self.counters["telemetry/sink_errors"] += 1
                if not getattr(sink, "_emit_warned", False):
                    try:
                        sink._emit_warned = True
                    except Exception:
                        pass
                    import warnings

                    warnings.warn(
                        f"telemetry sink {sink!r} failed, dropping events:"
                        f" {e}",
                        RuntimeWarning,
                        stacklevel=2,
                    )

    # ----------------------------------------------------------- metrics ----
    def incr(self, name: str, value: int = 1) -> None:
        with self._lock:
            self.counters[name] += value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            hist = self.histograms.get(name)
            if hist is None:
                hist = self.histograms[name] = Histogram()
            hist.record(value)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self.gauges.setdefault(name, {})[_label_key(labels)] = float(value)

    def record_span(
        self,
        path: str,
        wall_s: float,
        device_s: float | None = None,
        attrs: dict | None = None,
    ) -> None:
        """Aggregate one finished span and stream it to the event sinks."""
        with self._lock:
            hist = self.histograms.get("span:" + path)
            if hist is None:
                hist = self.histograms["span:" + path] = Histogram()
            hist.record(wall_s)
            if device_s is not None:
                dhist = self.histograms.get("span_device:" + path)
                if dhist is None:
                    dhist = self.histograms["span_device:" + path] = Histogram()
                dhist.record(device_s)
        event = {"event": "telemetry.span", "ts": time.time(), "path": path,
                 "wall_s": wall_s}
        if device_s is not None:
            event["device_s"] = device_s
        if attrs:
            event.update(attrs)
        # Identity labels never override a span's own attrs of the same
        # name (a router span naming the replica it dispatched TO keeps
        # that name; the stamp says who recorded).
        for k, v in self.identity.items():
            event.setdefault(k, v)
        self.emit(event)

    # --------------------------------------------------------- snapshots ----
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "histograms": {
                    name: h.snapshot() for name, h in self.histograms.items()
                },
                "gauges": {
                    name: {",".join(f"{k}={v}" for k, v in key) or "": val
                           for key, val in series.items()}
                    for name, series in self.gauges.items()
                },
            }

    def mergeable_snapshot(self) -> dict:
        """The ``/telemetryz`` wire form: everything :meth:`snapshot`
        carries, but in a shape a fleet collector can MERGE instead of
        merely display — exact counters, histograms as
        :meth:`Histogram.state` sketches (count/sum/min/max exact,
        reservoir for percentiles), gauges with structured label pairs,
        and the recording process's identity block."""
        with self._lock:
            return {
                "schema": 1,
                "ts": time.time(),
                "identity": dict(self.identity),
                "counters": dict(self.counters),
                "histograms": {
                    name: h.state() for name, h in self.histograms.items()
                },
                "gauges": {
                    name: [[dict(key), val] for key, val in series.items()]
                    for name, series in self.gauges.items()
                },
            }

    def gauge_series(self) -> dict[str, list[tuple[dict[str, str], float]]]:
        """Gauges with structured labels: name -> [(labels dict, value)].

        The flat ``snapshot()['gauges']`` keys comma-join label pairs for
        display/JSONL compactness — lossy when a value contains ``,`` or
        ``=``. Exporters that must reconstruct individual labels (the
        Prometheus renderer) use this instead.
        """
        with self._lock:
            return {
                name: [(dict(key), val) for key, val in series.items()]
                for name, series in self.gauges.items()
            }

    def stage_summary(self) -> dict[str, dict]:
        """Per-span-path aggregate — the bench's per-stage breakdown block."""
        with self._lock:
            out = {}
            for name, h in self.histograms.items():
                if not name.startswith("span:"):
                    continue
                path = name[len("span:"):]
                s = h.snapshot()
                entry = {
                    "count": s["count"],
                    "total_s": round(s["sum"], 6),
                    **{k: round(s[k], 6) for k in ("mean", "p50", "p90", "p99")
                       if k in s},
                }
                # Fenced device timings ride along under device_* keys so
                # the bench breakdown shows completion time, not just
                # enqueue time, when fencing was on.
                dh = self.histograms.get("span_device:" + path)
                if dh is not None:
                    ds = dh.snapshot()
                    entry["device_total_s"] = round(ds["sum"], 6)
                    entry.update({
                        "device_" + k: round(ds[k], 6)
                        for k in ("mean", "p50", "p99") if k in ds
                    })
                out[path] = entry
            self._attach_cost_estimates(out)
            return out

    def _attach_cost_estimates(self, out: dict[str, dict]) -> None:
        """Join cost gauges (telemetry.cost) onto matching stage entries.

        A ``program_flops{program=<path>}`` gauge holds the XLA-estimated
        FLOPs of one call of the span at ``<path>``; divided by the
        measured per-call seconds (fenced ``device_mean`` preferred — the
        wall mean of an async dispatch is enqueue time) it yields achieved
        FLOP/s, and against the ``device_peak_*`` roofline anchors a
        utilization fraction. Caller holds the lock.
        """
        peak_f = next(
            iter(self.gauges.get("device_peak_flops", {}).values()), None
        )
        peak_b = next(
            iter(self.gauges.get("device_peak_bytes_per_s", {}).values()), None
        )
        for gauge, unit, peak in (
            ("program_flops", "flops", peak_f),
            ("program_bytes_accessed", "bytes", peak_b),
        ):
            for key, per_call in self.gauges.get(gauge, {}).items():
                path = dict(key).get("program")
                entry = out.get(path)
                if entry is None:
                    continue
                entry[f"est_{unit}_per_call"] = round(per_call, 3)
                seconds = entry.get("device_mean", entry.get("mean"))
                if not seconds:
                    continue
                rate = per_call / seconds
                entry[f"est_{unit}_per_s"] = round(rate, 3)
                if peak:
                    entry[f"{unit}_utilization"] = round(rate / peak, 6)
        for entry in out.values():
            fu = entry.get("flops_utilization")
            bu = entry.get("bytes_utilization")
            if fu is not None and bu is not None:
                entry["roofline_bound"] = "compute" if fu >= bu else "memory"

    def flush(self) -> None:
        """Emit a snapshot event to the per-event sinks and refresh every
        snapshot-style sink (the Prometheus writer)."""
        snap = self.snapshot()
        # Span distributions are reconstructible from the per-span events;
        # the plain histograms (fill ratio, stall time, ...) exist nowhere
        # else in the JSONL stream, so the snapshot must carry them.
        hists = {
            name: h for name, h in snap["histograms"].items()
            if not name.startswith(("span:", "span_device:"))
        }
        self.emit({"event": "telemetry.snapshot", "ts": time.time(),
                   "counters": snap["counters"], "gauges": snap["gauges"],
                   "histograms": hists})
        for sink in list(self._sinks):
            write = getattr(sink, "write_snapshot", None)
            if write is None:
                continue
            try:
                write(self)
            except Exception:
                with self._lock:
                    self.counters["telemetry/sink_errors"] += 1

    def reset(self) -> None:
        """Clear aggregates (not sinks) — test isolation."""
        with self._lock:
            self.counters.clear()
            self.histograms.clear()
            self.gauges.clear()


# The process-global registry every instrumented module records into.
REGISTRY = Registry()
