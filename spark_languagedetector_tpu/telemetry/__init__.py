"""Telemetry subsystem: spans, histograms, gauges, and exporters.

The reference implementation leans entirely on the Spark UI for visibility
(SURVEY.md §5.5 — it "has no metrics at all"); the flat counters/timers in
``utils.metrics`` record *that* time was spent but not *where*. This package
is the stage-level layer the north star needs (per-stage latency
distributions are a prerequisite for multi-chip tuning — the pjit/GSPMD
systems papers treat per-stage profiling and compile-cache accounting as
table stakes):

  * :func:`span` — nestable, thread-safe context managers producing a tree
    of wall/device timings keyed by slash paths (``"score/pack"``). A span
    can register device arrays to fence (``block_until_ready``) at exit so
    the recorded time covers device completion, not just dispatch.
  * :class:`Histogram` — deterministic-reservoir distributions exposing
    p50/p90/p99 (per-batch score latency, batch fill ratio, padding waste,
    retry counts).
  * gauges sampled from JAX itself (:mod:`.gauges`) — live-buffer bytes per
    device, compile-cache hits/misses and compile seconds via
    ``jax.monitoring`` hooks, donated-buffer reuse.
  * exporters (:mod:`.export`) — a JSONL event sink (``log_event``-schema
    compatible) and a Prometheus text-format snapshot writer, both
    selectable via ``LANGDETECT_METRICS_SINK``.
  * request tracing (:mod:`.tracing`) — a ``trace_id`` contextvar opened
    per request (:func:`trace_request`) and stamped onto every span
    record, plus a Chrome/Perfetto trace exporter CLI over any JSONL
    capture.
  * a flight recorder (:mod:`.flightrec`) — a bounded ring of recent
    events that dumps a JSONL post-mortem when fit/score/stream raises;
    gated by ``LANGDETECT_FLIGHT_RECORDER``.
  * cost/roofline gauges (:mod:`.cost`) — XLA ``cost_analysis`` FLOPs and
    bytes for the jitted score/fit programs, joined with measured span
    timings into per-stage utilization estimates in ``stage_summary``.
  * ``python -m spark_languagedetector_tpu.telemetry.report <jsonl>`` — a
    stage-tree summary CLI with percentiles (:mod:`.report`); its sibling
    ``…telemetry.compare A.jsonl B.jsonl --threshold 0.25`` diffs two
    captures per-stage and exits nonzero past threshold (:mod:`.compare`).

Everything aggregates into one process-global :data:`REGISTRY`; sinks are
attached from the environment on first import. Importing this package does
NOT initialize jax — device-touching helpers import it lazily.
"""

from __future__ import annotations

from .aggregate import (
    FleetCollector,
    install_process_identity,
    merge_snapshots,
    process_identity,
)
from .export import (
    SINK_ENV,
    configure_sinks_from_env,
    render_prometheus,
    write_prometheus,
)
from .flightrec import FLIGHT_ENV
from .gauges import install_jax_hooks, sample_device_gauges
from .registry import REGISTRY, Histogram, Registry
from .slo import Objective, SloEvaluator, default_objectives
from .spans import FENCE_ENV, Span, current_span, span
from .tracing import current_trace_id, new_trace_id, trace_request

__all__ = [
    "FENCE_ENV",
    "FLIGHT_ENV",
    "FleetCollector",
    "Histogram",
    "Objective",
    "REGISTRY",
    "Registry",
    "SINK_ENV",
    "SloEvaluator",
    "Span",
    "configure_sinks_from_env",
    "current_span",
    "current_trace_id",
    "default_objectives",
    "install_jax_hooks",
    "install_process_identity",
    "merge_snapshots",
    "new_trace_id",
    "process_identity",
    "render_prometheus",
    "sample_device_gauges",
    "span",
    "trace_request",
    "write_prometheus",
]

# Attach exporters declared in the environment once, at import: every
# instrumented module imports this package, so a process that sets
# LANGDETECT_METRICS_SINK gets its sinks without any code change. A bad
# value (typo'd kind, unwritable path) degrades to a loud warning rather
# than an ImportError — a metrics env var must never take down scoring.
# Calling configure_sinks_from_env directly still raises.
try:
    configure_sinks_from_env(REGISTRY)
except Exception as _e:
    import warnings as _warnings

    _warnings.warn(
        f"{SINK_ENV} ignored — could not attach metric sinks: {_e}",
        RuntimeWarning,
        stacklevel=2,
    )

# The flight recorder is likewise env-armed at import (its ring only
# buffers in memory; disk is touched solely on a crash dump), with the
# same degrade-to-a-warning contract.
try:
    from .flightrec import install_from_env as _flightrec_install

    _flightrec_install(REGISTRY)
except Exception as _e:
    import warnings as _warnings

    _warnings.warn(
        f"{FLIGHT_ENV} ignored — could not arm the flight recorder: {_e}",
        RuntimeWarning,
        stacklevel=2,
    )
