"""Telemetry subsystem: spans, histograms, gauges, and exporters.

The reference implementation leans entirely on the Spark UI for visibility
(SURVEY.md §5.5 — it "has no metrics at all"); the flat counters/timers in
``utils.metrics`` record *that* time was spent but not *where*. This package
is the stage-level layer the north star needs (per-stage latency
distributions are a prerequisite for multi-chip tuning — the pjit/GSPMD
systems papers treat per-stage profiling and compile-cache accounting as
table stakes):

  * :func:`span` — nestable, thread-safe context managers producing a tree
    of wall/device timings keyed by slash paths (``"score/pack"``). A span
    can register device arrays to fence (``block_until_ready``) at exit so
    the recorded time covers device completion, not just dispatch.
  * :class:`Histogram` — deterministic-reservoir distributions exposing
    p50/p90/p99 (per-batch score latency, batch fill ratio, padding waste,
    retry counts).
  * gauges sampled from JAX itself (:mod:`.gauges`) — live-buffer bytes per
    device, compile-cache hits/misses and compile seconds via
    ``jax.monitoring`` hooks, donated-buffer reuse.
  * exporters (:mod:`.export`) — a JSONL event sink (``log_event``-schema
    compatible) and a Prometheus text-format snapshot writer, both
    selectable via ``LANGDETECT_METRICS_SINK``.
  * ``python -m spark_languagedetector_tpu.telemetry.report <jsonl>`` — a
    stage-tree summary CLI with percentiles (:mod:`.report`).

Everything aggregates into one process-global :data:`REGISTRY`; sinks are
attached from the environment on first import. Importing this package does
NOT initialize jax — device-touching helpers import it lazily.
"""

from __future__ import annotations

from .export import (
    SINK_ENV,
    configure_sinks_from_env,
    render_prometheus,
    write_prometheus,
)
from .gauges import install_jax_hooks, sample_device_gauges
from .registry import REGISTRY, Histogram, Registry
from .spans import FENCE_ENV, Span, current_span, span

__all__ = [
    "FENCE_ENV",
    "Histogram",
    "REGISTRY",
    "Registry",
    "SINK_ENV",
    "Span",
    "configure_sinks_from_env",
    "current_span",
    "install_jax_hooks",
    "render_prometheus",
    "sample_device_gauges",
    "span",
    "write_prometheus",
]

# Attach exporters declared in the environment once, at import: every
# instrumented module imports this package, so a process that sets
# LANGDETECT_METRICS_SINK gets its sinks without any code change. A bad
# value (typo'd kind, unwritable path) degrades to a loud warning rather
# than an ImportError — a metrics env var must never take down scoring.
# Calling configure_sinks_from_env directly still raises.
try:
    configure_sinks_from_env(REGISTRY)
except Exception as _e:
    import warnings as _warnings

    _warnings.warn(
        f"{SINK_ENV} ignored — could not attach metric sinks: {_e}",
        RuntimeWarning,
        stacklevel=2,
    )
