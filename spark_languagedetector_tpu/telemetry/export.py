"""Exporters: JSONL event sink and Prometheus text-format snapshots.

Selected by ``LANGDETECT_METRICS_SINK`` — a comma list of ``kind:path``
entries, e.g.::

    LANGDETECT_METRICS_SINK=jsonl:/tmp/telemetry.jsonl,prom:/tmp/metrics.prom

``jsonl`` appends one JSON object per telemetry event (span exits, snapshot
flushes) in the same shape ``utils.logging.log_event`` emits — an ``event``
discriminator plus a float ``ts`` — so existing log-scraping keeps working
and the report CLI can consume either stream. Timestamps are forced
strictly increasing per sink (concurrent producers can otherwise collide
within clock resolution), so a consumer may treat the file as an ordered
event log.

``prom`` writes a full Prometheus text-format snapshot of the registry on
every :meth:`Registry.flush` (atomic rename, so scrapers never read a torn
file). Spans export as summaries with p50/p90/p99 quantiles; counters and
gauges export under one metric name each with a ``name`` label — paths
like ``score/pack`` are not valid Prometheus metric names, labels are.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

from .registry import Registry

SINK_ENV = "LANGDETECT_METRICS_SINK"


class JsonlSink:
    """Append-only JSONL event sink with strictly increasing timestamps."""

    kind = "jsonl"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._last_ts = 0.0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, event: dict) -> None:
        with self._lock:
            ts = float(event.get("ts", 0.0)) or time.time()
            if ts <= self._last_ts:
                ts = math.nextafter(self._last_ts, math.inf)
            self._last_ts = ts
            record = {**event, "ts": ts}
            self._fh.write(json.dumps(record, default=str) + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except ValueError:
                pass


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt(value: float) -> str:
    if isinstance(value, float) and math.isnan(value):
        return "NaN"
    return repr(float(value))


def render_prometheus(registry: Registry) -> str:
    """Registry snapshot as Prometheus text exposition format."""
    snap = registry.snapshot()
    lines: list[str] = []

    span_hists = {
        name[len("span:"):]: h
        for name, h in snap["histograms"].items()
        if name.startswith("span:")
    }
    # Fenced device timings (wall through block_until_ready) — without this
    # block the data fencing exists to capture would be reachable only by
    # grepping raw JSONL events.
    device_hists = {
        name[len("span_device:"):]: h
        for name, h in snap["histograms"].items()
        if name.startswith("span_device:")
    }
    plain_hists = {
        name: h
        for name, h in snap["histograms"].items()
        if not name.startswith(("span:", "span_device:"))
    }
    for metric, hists in (
        ("langdetect_span_seconds", span_hists),
        ("langdetect_span_device_seconds", device_hists),
    ):
        if not hists:
            continue
        lines.append(f"# TYPE {metric} summary")
        for path, h in sorted(hists.items()):
            lbl = f'path="{_escape_label(path)}"'
            for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                if key in h:
                    lines.append(
                        f'{metric}{{{lbl},quantile="{q}"}} {_fmt(h[key])}'
                    )
            lines.append(f"{metric}_sum{{{lbl}}} {_fmt(h['sum'])}")
            lines.append(f"{metric}_count{{{lbl}}} {h['count']}")
    if plain_hists:
        lines.append("# TYPE langdetect_metric summary")
        for name, h in sorted(plain_hists.items()):
            lbl = f'name="{_escape_label(name)}"'
            for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                if key in h:
                    lines.append(
                        f'langdetect_metric{{{lbl},quantile="{q}"}} '
                        f"{_fmt(h[key])}"
                    )
            lines.append(f"langdetect_metric_sum{{{lbl}}} {_fmt(h['sum'])}")
            lines.append(f"langdetect_metric_count{{{lbl}}} {h['count']}")
    if snap["counters"]:
        lines.append("# TYPE langdetect_counter_total counter")
        for name, value in sorted(snap["counters"].items()):
            lines.append(
                f'langdetect_counter_total{{name="{_escape_label(name)}"}} '
                f"{value}"
            )
    gauge_series = registry.gauge_series()
    if gauge_series:
        lines.append("# TYPE langdetect_gauge gauge")
        for name, series in sorted(gauge_series.items()):
            for label_dict, value in sorted(
                series, key=lambda kv: sorted(kv[0].items())
            ):
                labels = [f'name="{_escape_label(name)}"']
                for k, v in sorted(label_dict.items()):
                    labels.append(f'{k}="{_escape_label(v)}"')
                lines.append(
                    f"langdetect_gauge{{{','.join(labels)}}} {_fmt(value)}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(registry: Registry, path: str) -> str:
    """Atomically write the registry's Prometheus snapshot; returns path."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(render_prometheus(registry))
    os.replace(tmp, path)
    return path


class PrometheusSnapshotSink:
    """Snapshot-style sink: rewrites its file on every registry flush."""

    kind = "prom"

    def __init__(self, path: str):
        self.path = path

    def write_snapshot(self, registry: Registry) -> None:
        write_prometheus(registry, self.path)

    def close(self) -> None:
        pass


_SINK_KINDS = {"jsonl": JsonlSink, "prom": PrometheusSnapshotSink}


def parse_sink_spec(spec: str) -> list[tuple[str, str]]:
    """``"jsonl:/a.jsonl,prom:/b.prom"`` → [("jsonl", "/a.jsonl"), ...].

    Unknown kinds raise ValueError — a typo'd env var should be loud, not a
    silently metric-less run.
    """
    out: list[tuple[str, str]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, sep, path = entry.partition(":")
        if not sep or not path or kind not in _SINK_KINDS:
            raise ValueError(
                f"bad {SINK_ENV} entry {entry!r}; expected kind:path with "
                f"kind in {sorted(_SINK_KINDS)}"
            )
        out.append((kind, path))
    return out


def configure_sinks_from_env(registry: Registry, env=os.environ) -> list:
    """Attach the sinks ``LANGDETECT_METRICS_SINK`` declares; returns them.

    All-or-nothing: every sink is constructed before any is attached, so a
    failing entry (unwritable path) can't leave a partial capture running
    behind an "env var ignored" warning. The knob resolves through
    exec/config's audited table (lazily — this armed at package import).
    """
    from ..exec import config as exec_config

    spec = exec_config.resolve("metrics_sink", env=env) or ""
    if not spec:
        return []
    sinks: list = []
    try:
        for kind, path in parse_sink_spec(spec):
            sinks.append(_SINK_KINDS[kind](path))
    except Exception:
        for s in sinks:
            close = getattr(s, "close", None)
            if close:
                close()
        raise
    for s in sinks:
        registry.add_sink(s)
    return sinks
