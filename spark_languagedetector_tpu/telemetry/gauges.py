"""Gauges sampled from JAX itself: buffers, compiles, donation reuse.

Three device-side signals the host-side spans cannot see:

  * **live-buffer bytes per device** — every ``jax.live_arrays()`` buffer,
    attributed to its device(s); the resident-set gauge that localizes an
    HBM blowup to the stage that allocated it.
  * **compile-cache accounting** — ``jax.monitoring`` event hooks count
    compilation-cache hits/misses and sum backend-compile seconds. On a
    tunneled TPU a single new batch shape costs a 20-40s remote compile
    (docs/PERFORMANCE.md §5), so an unexpected miss is the first thing to
    rule out when a bench pass regresses.
  * **donated-buffer reuse** — the fit loop donates its count accumulator;
    ``jax.Array.is_deleted()`` on the pre-step reference observes whether
    XLA actually reused the buffer (donation is best-effort and silently
    degrades on some backends).

All helpers import jax lazily and degrade to no-ops when an API is absent,
so the telemetry package stays importable in stripped environments.
"""

from __future__ import annotations

from .registry import REGISTRY, Registry

_hooks_installed = False
_hooks_registry: Registry | None = None

# jax.monitoring event names this module accounts (jax/_src/compiler.py and
# jax/_src/dispatch.py are the emit sites). The duration match must be
# exact: jax emits three per-compile duration events whose names all
# contain "compile" (trace, MLIR lowering, backend compile) plus a
# compile_time_saved event on persistent-cache HITS — a substring match
# would triple-count and bill time *saved* as time *spent*.
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def install_jax_hooks(registry: Registry | None = None) -> bool:
    """Register jax.monitoring listeners feeding the registry. Idempotent;
    returns whether hooks are (now) installed.

    Counters: ``jax/compile_cache_hits``, ``jax/compile_cache_misses``,
    ``jax/compile_events``. Histogram: ``jax/compile_s`` (per-compile
    backend seconds). Listener registration is process-global in jax —
    there is one receiving registry per process (the most recent caller's;
    the process-global REGISTRY by default), never one per call.
    """
    global _hooks_installed, _hooks_registry
    # Rebind on every call: jax offers no listener deregistration, so the
    # closures below read the module slot instead of capturing a registry.
    _hooks_registry = registry if registry is not None else REGISTRY
    if _hooks_installed:
        return True
    try:
        from jax import monitoring
    except Exception:
        return False

    def on_event(event: str, **kwargs) -> None:
        reg = _hooks_registry
        if reg is None:
            return
        if event == _CACHE_HIT_EVENT:
            reg.incr("jax/compile_cache_hits")
            # Canonical slash-path spelling for /varz and bench telemetry
            # blocks; the legacy jax/ name stays for dashboards that
            # already scrape it.
            reg.incr("compile_cache/hits")
        elif event == _CACHE_MISS_EVENT:
            reg.incr("jax/compile_cache_misses")
            reg.incr("compile_cache/misses")

    def on_duration(event: str, duration: float, **kwargs) -> None:
        reg = _hooks_registry
        if reg is None:
            return
        if event == _BACKEND_COMPILE_EVENT:
            reg.incr("jax/compile_events")
            reg.observe("jax/compile_s", duration)

    # jax offers no deregistration, so once ANY listener lands the module
    # must remember it — a retry after a partial failure would register a
    # duplicate and double-count every cache hit/miss from then on.
    registered = False
    try:
        monitoring.register_event_listener(on_event)
        registered = True
        monitoring.register_event_duration_secs_listener(on_duration)
    except Exception:
        if not registered:
            return False
    _hooks_installed = True
    return True


def _device_label(d) -> str:
    """Short, label-safe device name (``tpu:0``): the full ``str(device)``
    on TPU contains commas/parens/spaces, which are hostile to every flat
    label serialization downstream."""
    try:
        return f"{d.platform}:{d.id}"
    except Exception:
        return str(d)


def sample_device_gauges(registry: Registry | None = None) -> dict:
    """Sample per-device buffer gauges into the registry; returns them too.

    ``live_buffer_bytes{device=...}`` sums ``jax.live_arrays()`` (a sharded
    array's bytes split evenly across its devices);
    ``device_bytes_in_use{device=...}`` comes from the runtime's
    ``memory_stats()`` where the backend provides it (TPU does, CPU does
    not). Sampling walks the live-array list — per-batch/per-flush cost,
    not per-row.
    """
    reg = registry if registry is not None else REGISTRY
    out: dict[str, dict[str, float]] = {}
    try:
        import jax
    except Exception:
        return out

    live: dict[str, float] = {}
    try:
        for arr in jax.live_arrays():
            try:
                devices = list(arr.devices())
                nbytes = float(getattr(arr, "nbytes", 0))
            except Exception:
                continue
            if not devices:
                continue
            per_dev = nbytes / len(devices)
            for d in devices:
                lbl = _device_label(d)
                live[lbl] = live.get(lbl, 0.0) + per_dev
    except Exception:
        pass
    for dev, nbytes in live.items():
        reg.set_gauge("live_buffer_bytes", nbytes, device=dev)
    if live:
        out["live_buffer_bytes"] = live

    in_use: dict[str, float] = {}
    try:
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                pass
            if stats and "bytes_in_use" in stats:
                in_use[_device_label(d)] = float(stats["bytes_in_use"])
    except Exception:
        pass
    for dev, nbytes in in_use.items():
        reg.set_gauge("device_bytes_in_use", nbytes, device=dev)
    if in_use:
        out["device_bytes_in_use"] = in_use
    return out


def note_donation_reuse(prev_array, registry: Registry | None = None) -> bool:
    """Record whether a donated input buffer was actually consumed.

    Call with the pre-step reference after a donating dispatch:
    ``is_deleted()`` True means XLA took the buffer (reuse happened) —
    counted as ``jax/donated_reuse``; False means donation silently
    degraded to a copy — counted as ``jax/donated_copy``. Returns the
    reuse verdict (False when unobservable).
    """
    reg = registry if registry is not None else REGISTRY
    is_deleted = getattr(prev_array, "is_deleted", None)
    if is_deleted is None:
        return False
    try:
        reused = bool(is_deleted())
    except Exception:
        return False
    reg.incr("jax/donated_reuse" if reused else "jax/donated_copy")
    return reused
