"""Cost/roofline gauges: compiled-program FLOPs/bytes vs measured time.

The pjit/TPUv4 scaling workflow treats the hardware roofline as the tuning
target: a stage is done when its achieved FLOP/s (or bytes/s) sits near
the device peak, and a regression is diagnosed by which side of the
roofline moved. This module supplies the static half of that ratio — XLA's
own cost model for the jitted score/fit programs, via
``jit(f).lower(shapes).compile().cost_analysis()`` (post-optimization
numbers; the pre-compile ``Lowered.cost_analysis()`` is the fallback when
backend compilation is not worth forcing, e.g. through a 20-40s remote
TPU compile tunnel) — and records it as registry gauges:

  * ``program_flops{program=<span path>}`` / ``program_bytes_accessed{...}``
    — estimated cost of one call of the span at that path (the runner
    records per-dispatch cost under ``score/dispatch``; the device fit
    records per-step cost × steps under ``fit/count``);
  * ``device_peak_flops{device=<platform>}`` /
    ``device_peak_bytes_per_s{...}`` — roofline anchors per platform
    (order-of-magnitude defaults; override with ``LANGDETECT_PEAK_FLOPS``
    / ``LANGDETECT_PEAK_BYTES_PER_S`` for your exact part).

:meth:`Registry.stage_summary` joins these gauges with the measured span
timings into ``est_flops_per_s`` / ``flops_utilization`` /
``bytes_utilization`` / ``roofline_bound`` per stage — surfaced in the
bench's per-config ``telemetry`` block and (as gauges) in the Prometheus
renderer. Utilization is computed against fenced ``device_*`` timings
when available and wall time otherwise; without
``LANGDETECT_TELEMETRY_FENCE=1`` the wall number is *enqueue* time for
async dispatches, so treat unfenced utilization as an upper bound.

Everything here is diagnostics: every entry point is exception-contained
and returns None rather than disturb the computation it measures.
"""

from __future__ import annotations

import os

from .registry import REGISTRY, Registry

PEAK_FLOPS_ENV = "LANGDETECT_PEAK_FLOPS"
PEAK_BYTES_ENV = "LANGDETECT_PEAK_BYTES_PER_S"

# Order-of-magnitude roofline anchors per platform: (flops/s, bytes/s).
# TPU: v4 bf16 MXU peak + HBM2 bandwidth (the paper's target part); GPU:
# A100-class; CPU: a nominal host anchor so utilization stays defined (and
# obviously approximate) on the zero-accelerator CI substrate.
_PLATFORM_PEAKS: dict[str, tuple[float, float]] = {
    "tpu": (275e12, 1.2e12),
    "gpu": (312e12, 2.0e12),
    "cpu": (1.0e11, 5.0e10),
}

# Guard for forcing a backend compile purely for cost numbers: tiny next
# to a real compile, but unbounded programs (a 16.8M-row scatter table)
# should settle for the pre-compile analysis.
_COMPILE_FOR_COST_MAX_ELEMS = 1 << 24

# Same guard for the runner dispatch program, in resident-table bytes:
# the analysis lambda closes over the runner's device tables, so they
# lower as literal constants — past this size the backend compile spends
# seconds constant-folding a table the real (argument-passing) dispatch
# program never embeds, for a gauge. Pre-compile analysis instead.
_COMPILE_FOR_COST_MAX_TABLE_BYTES = 4 * _COMPILE_FOR_COST_MAX_ELEMS


def peak_rates(platform: str, env=os.environ) -> tuple[float, float] | None:
    """(peak flops/s, peak bytes/s) for a platform; env vars override
    (resolved through exec/config's audited table; a malformed override
    is ignored here — the roofline gauges are advisory — but still shows
    as an ``error`` row in ``/varz`` ``effective_config``)."""
    from ..exec import config as exec_config

    base = _PLATFORM_PEAKS.get(platform)
    try:
        flops = exec_config.resolve("peak_flops", env=env) or None
        byts = exec_config.resolve("peak_bytes_per_s", env=env) or None
    except ValueError:
        flops = byts = None
    if base is None and flops is None and byts is None:
        return None
    return (
        flops if flops is not None else (base[0] if base else 0.0),
        byts if byts is not None else (base[1] if base else 0.0),
    )


def normalize_cost(analysis) -> dict | None:
    """XLA cost_analysis output (dict, or list-of-dict from ``Compiled``)
    → ``{"flops": float, "bytes_accessed": float}`` (keys present only
    when the backend reported them)."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out: dict = {}
    flops = analysis.get("flops")
    if isinstance(flops, (int, float)) and flops >= 0:
        out["flops"] = float(flops)
    byts = analysis.get("bytes accessed")
    if isinstance(byts, (int, float)) and byts >= 0:
        out["bytes_accessed"] = float(byts)
    return out or None


def program_cost(fn, *args, prefer_compiled: bool | None = None) -> dict | None:
    """Cost of ``jit(fn)`` at the given (abstract) operand shapes.

    ``args`` are ``jax.ShapeDtypeStruct``s (or concrete arrays) — lowering
    never executes the program. ``prefer_compiled=None`` forces the
    backend compile only on CPU, where it is cheap and its post-layout
    numbers beat the pre-compile estimate; elsewhere (or when compile
    fails) the ``Lowered`` analysis is used.
    """
    try:
        import jax

        lowered = jax.jit(fn).lower(*args)
    except Exception:
        return None
    if prefer_compiled is None:
        try:
            prefer_compiled = jax.default_backend() == "cpu"
        except Exception:
            prefer_compiled = False
    if prefer_compiled:
        try:
            cost = normalize_cost(lowered.compile().cost_analysis())
            if cost:
                return cost
        except Exception:
            pass
    try:
        return normalize_cost(lowered.cost_analysis())
    except Exception:
        return None


def record_program_cost(
    program: str,
    cost: dict | None,
    *,
    calls: float = 1.0,
    platform: str | None = None,
    registry: Registry | None = None,
) -> None:
    """Record one program's cost gauges (scaled to per-span-call units).

    ``calls`` is the number of compiled-program executions one span at
    ``program``'s path covers (1 for per-dispatch spans; the fit count
    loop's step count for its whole-loop span), so stage_summary's join
    of gauge × span timing stays dimensionally honest.
    """
    if not cost:
        return
    reg = registry if registry is not None else REGISTRY
    if "flops" in cost:
        reg.set_gauge("program_flops", cost["flops"] * calls, program=program)
    if "bytes_accessed" in cost:
        reg.set_gauge(
            "program_bytes_accessed", cost["bytes_accessed"] * calls,
            program=program,
        )
    if platform:
        peaks = peak_rates(platform)
        if peaks:
            reg.set_gauge("device_peak_flops", peaks[0], device=platform)
            reg.set_gauge("device_peak_bytes_per_s", peaks[1], device=platform)


def record_runner_cost(
    runner, rows: int, pad_to: int, registry: Registry | None = None
) -> dict | None:
    """Cost of one of ``runner``'s score dispatches at [rows, pad_to].

    Lowers the runner's own dispatch function (whatever strategy it
    resolved) over abstract operands and records it under
    ``program_flops{program="score/dispatch"}`` — the span path whose
    count matches one dispatch per call. Mesh runners are skipped: the
    GSPMD program's analysis is per-process, not per-chip, and would
    misstate utilization. Runners whose resident tables exceed
    ``_COMPILE_FOR_COST_MAX_TABLE_BYTES`` settle for the pre-compile
    analysis even on CPU — the diagnostic lowering embeds the tables as
    literals, and constant-folding them dwarfs the dispatch compile it
    is modeling.

    Approximation note: the modeled program is the *padded* [rows,
    pad_to] dispatch. Ragged-transfer runners actually run device-side
    unpack + the same scoring math, so flops match but ``bytes_accessed``
    is the padded upper bound (and no variant models the h2d wire —
    cost_analysis is program-side memory traffic, not transfer bytes).

    Alongside the cost gauges this records ``langdetect_table_bytes``
    (the resident weight-side bytes of the strategy's device form, quant
    label included) — the compare guard tracks it so a change that
    silently de-quantizes or re-balloons table traffic fails the diff.
    The fused strategy's program is additionally recorded under
    ``program="score/fused"`` so its roofline shift vs the strategy it
    replaced stays visible when both appear in one capture.
    """
    try:
        import jax
        import jax.numpy as jnp

        reg = registry if registry is not None else REGISTRY
        try:
            table_bytes = float(runner.table_bytes())
        except Exception:
            table_bytes = None
        if table_bytes is not None:
            try:
                reg.set_gauge(
                    "langdetect_table_bytes",
                    table_bytes,
                    program="score/dispatch",
                    quant=getattr(runner, "quantization", None) or "f32",
                    strategy=runner.strategy,
                )
            except Exception:
                pass
        if runner.mesh is not None:
            return None
        batch = jax.ShapeDtypeStruct((int(rows), int(pad_to)), jnp.uint8)
        lengths = jax.ShapeDtypeStruct((int(rows),), jnp.int32)
        platform = runner._target_device().platform
        cost = program_cost(
            lambda b, l: runner._dispatch_device(b, l, None, None),
            batch,
            lengths,
            prefer_compiled=(
                platform == "cpu"
                and table_bytes is not None
                and table_bytes <= _COMPILE_FOR_COST_MAX_TABLE_BYTES
            ),
        )
        record_program_cost(
            "score/dispatch", cost, platform=platform, registry=registry
        )
        if runner.strategy == "fused":
            record_program_cost(
                "score/fused", cost, platform=platform, registry=registry
            )
        return cost
    except Exception:
        return None


# Most frequent step shapes analyzed per fit; a pathological fit (many
# distinct oversized-doc widths) bills the remainder by scaling rather
# than lowering dozens of programs for a diagnostic gauge.
_FIT_COST_MAX_SHAPES = 12


def record_fit_count_cost(
    spec,
    num_langs: int,
    step_shapes: dict,
    registry: Registry | None = None,
) -> dict | None:
    """Cost of the device fit's count loop, recorded under
    ``program="fit/count"`` (that span wraps the whole loop, so per-call
    units are whole-loop units).

    ``step_shapes`` maps each dispatched ``(rows, pad_to)`` to its step
    count — the loop's actual compiled-shape set. Each distinct shape's
    program is analyzed and the costs summed, so small/tail/narrow-bucket
    steps are billed at their own size, not the largest shape's.
    """
    try:
        import jax
        import jax.numpy as jnp

        from ..ops.fit_tpu import fit_dense_step

        shapes = [
            ((int(r), int(p)), int(n))
            for (r, p), n in step_shapes.items()
            if n > 0 and r > 0 and p > 0
        ]
        if not shapes:
            return None
        shapes.sort(key=lambda it: -it[1])
        covered = shapes[:_FIT_COST_MAX_SHAPES]
        V = spec.id_space_size
        platform = jax.devices()[0].platform
        prefer = (
            platform == "cpu"
            and V * num_langs <= _COMPILE_FOR_COST_MAX_ELEMS
        )
        acc = jax.ShapeDtypeStruct((V, num_langs), jnp.int32)
        total: dict = {}
        covered_steps = 0
        for (rows, pad_to), n in covered:
            cost = program_cost(
                lambda b, l, g, a: fit_dense_step(
                    b, l, g, a, spec=spec, num_langs=num_langs
                ),
                jax.ShapeDtypeStruct((rows, pad_to), jnp.uint8),
                jax.ShapeDtypeStruct((rows,), jnp.int32),
                jax.ShapeDtypeStruct((rows,), jnp.int32),
                acc,
                prefer_compiled=prefer,
            )
            if not cost:
                continue
            covered_steps += n
            for k, v in cost.items():
                total[k] = total.get(k, 0.0) + v * n
        if not total or not covered_steps:
            return None
        # Steps not billed directly (shapes past the cap, or whose
        # analysis failed): bill at the billed shapes' per-step average.
        total_steps = sum(n for _, n in shapes)
        if total_steps > covered_steps:
            total = {
                k: v * (total_steps / covered_steps) for k, v in total.items()
            }
        record_program_cost(
            "fit/count", total, platform=platform, registry=registry
        )
        return total
    except Exception:
        return None
