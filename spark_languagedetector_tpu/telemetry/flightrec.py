"""Flight recorder: a bounded ring of recent telemetry, dumped on crash.

A post-mortem needs the events *leading up to* the failure, but leaving a
full JSONL sink on forever costs disk proportional to uptime. The flight
recorder is the middle ground: it attaches to the registry as an ordinary
per-event sink, keeps only the most recent ``capacity`` span/snapshot
events in memory (a deque append — no I/O on the hot path), and writes
them all to a JSONL post-mortem file only when a fit/score/stream entry
point actually raises (their ``except`` hooks call :func:`record_crash`).

Gated by ``LANGDETECT_FLIGHT_RECORDER``: ``1`` enables with a default
directory under the system tmpdir, any other non-empty value is the dump
directory. ``LANGDETECT_FLIGHT_RECORDER_EVENTS`` overrides the ring
capacity. Like the PR-1 exporters, every failure path is contained — a
post-mortem writer that can take down the computation it observes would
be worse than no recorder at all (drops are counted under
``telemetry/flightrec_errors`` and warned once).

The dump file is an ordinary telemetry JSONL capture (with one
``flightrec.dump`` header line), so the ``report`` CLI renders it and the
``tracing`` CLI turns it into a Perfetto timeline of the final moments.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from collections import deque

from .registry import REGISTRY, Registry

FLIGHT_ENV = "LANGDETECT_FLIGHT_RECORDER"
CAPACITY_ENV = "LANGDETECT_FLIGHT_RECORDER_EVENTS"
DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """Ring-buffer sink; ``dump()`` writes the ring as a JSONL post-mortem."""

    kind = "flightrec"

    def __init__(self, out_dir: str, capacity: int = DEFAULT_CAPACITY):
        self.out_dir = out_dir
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._seq = 0

    def emit(self, event: dict) -> None:
        with self._lock:
            self._ring.append(event)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, context: str = "unknown", error: str | None = None) -> str:
        """Write the ring (oldest first) to a fresh post-mortem file."""
        with self._lock:
            events = list(self._ring)
            self._seq += 1
            seq = self._seq
        os.makedirs(self.out_dir, exist_ok=True)
        tag = re.sub(r"[^A-Za-z0-9_.-]+", "_", context) or "unknown"
        path = os.path.join(
            self.out_dir, f"flightrec-{tag}-{os.getpid()}-{seq}.jsonl"
        )
        header = {
            "event": "flightrec.dump",
            "ts": time.time(),
            "context": context,
            "error": error,
            "events": len(events),
        }
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(header, default=str) + "\n")
            for ev in events:
                fh.write(json.dumps(ev, default=str) + "\n")
        return path

    def close(self) -> None:
        pass


# Process-global recorder (one per process, like the env-declared sinks).
_recorder: FlightRecorder | None = None
_last_dump: str | None = None
_warned = False

# A crash that unwinds through nested entry points (score inside a stream
# batch) must dump once, not once per except hook on the way out. The
# dumped exception is marked with this attribute — per-object, so a later
# unrelated exception can never be mistaken for an already-dumped one
# (address-based dedup would break on CPython's eager id reuse, and
# builtin exceptions refuse weakrefs).
_DUMPED_ATTR = "_langdetect_flightrec_dumped"


def active() -> FlightRecorder | None:
    return _recorder


def last_dump_path() -> str | None:
    """Path of the most recent post-mortem this process wrote, if any."""
    return _last_dump


def install(
    out_dir: str,
    capacity: int = DEFAULT_CAPACITY,
    registry: Registry | None = None,
) -> FlightRecorder:
    """Attach a recorder to the registry and make it the crash target.
    Idempotent per process: a second install returns the existing one."""
    global _recorder
    if _recorder is not None:
        return _recorder
    rec = FlightRecorder(out_dir, capacity)
    (registry if registry is not None else REGISTRY).add_sink(rec)
    _recorder = rec
    return rec


def uninstall(registry: Registry | None = None) -> None:
    """Detach the process recorder (tests and the bench smoke path)."""
    global _recorder
    rec, _recorder = _recorder, None
    if rec is not None:
        (registry if registry is not None else REGISTRY).remove_sink(rec)


def install_from_env(
    registry: Registry | None = None, env=os.environ
) -> FlightRecorder | None:
    """Install per ``LANGDETECT_FLIGHT_RECORDER``; None when unset/disabled.

    Knobs resolve through exec/config's audited table (lazily — this is
    armed at package import). A malformed capacity keeps the default:
    the recorder is a crash diagnostic, and refusing to arm it over a
    typo would lose exactly the dump the typo'd run needed.
    """
    from ..exec import config as exec_config

    spec = (exec_config.resolve("flight_recorder", env=env) or "").strip()
    if not spec or spec.lower() in ("0", "false"):
        return None
    if spec.lower() in ("1", "true"):
        out_dir = os.path.join(tempfile.gettempdir(), "langdetect-flightrec")
    else:
        out_dir = spec
    try:
        capacity = exec_config.resolve("flight_recorder_events", env=env)
    except ValueError:
        capacity = DEFAULT_CAPACITY
    return install(out_dir, capacity, registry)


def record_crash(
    context: str, exc: BaseException | None = None,
    registry: Registry | None = None,
) -> str | None:
    """Dump the ring for one failing entry point; contained, never raises.

    Returns the post-mortem path (None when no recorder is installed, the
    same exception was already dumped by an inner hook, or the write
    itself failed — counted + warned once, like exporter sink errors).
    """
    global _last_dump, _warned
    rec = _recorder
    if rec is None:
        return None
    if exc is not None and getattr(exc, _DUMPED_ATTR, False):
        return None
    reg = registry if registry is not None else REGISTRY
    try:
        path = rec.dump(context=context, error=repr(exc) if exc else None)
    except Exception as e:
        reg.incr("telemetry/flightrec_errors")
        if not _warned:
            _warned = True
            import warnings

            warnings.warn(
                f"flight recorder dump failed, post-mortem lost: {e}",
                RuntimeWarning,
                stacklevel=2,
            )
        return None
    if exc is not None:
        try:
            setattr(exc, _DUMPED_ATTR, True)
        except Exception:
            pass  # __slots__-only exception: nested hooks may double-dump
    _last_dump = path
    reg.incr("telemetry/flightrec_dumps")
    return path
