"""Stage-tree report CLI over a telemetry JSONL capture.

    python -m spark_languagedetector_tpu.telemetry.report <events.jsonl>

Reads the JSONL event stream the ``jsonl`` sink appends, aggregates the
``telemetry.span`` records by slash path, and renders the stage tree with
per-stage count, total/mean seconds, and p50/p90/p99 — the artifact that
turns "fit throughput split across configs" into "the count stage did"
(BENCH_r05's unanswerable question). Counter/gauge state from the last
``telemetry.snapshot`` event is appended below the tree.

Pure stdlib + this package's Histogram; never imports jax, so it runs
anywhere the artifact lands (including the zero-accelerator CI host).
"""

from __future__ import annotations

import json
import sys

from .registry import Histogram


def load_events(path: str) -> list[dict]:
    """Parse one JSONL file, skipping blank/garbage lines loudly-but-gently
    (a truncated tail from a killed run must not void the report)."""
    events: list[dict] = []
    bad = 0
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(obj, dict):
                events.append(obj)
    if bad:
        print(f"(skipped {bad} unparseable line(s))", file=sys.stderr)
    return events


def aggregate_spans(events: list[dict]) -> dict[str, Histogram]:
    """path -> Histogram of wall_s over every telemetry.span record."""
    stages: dict[str, Histogram] = {}
    for ev in events:
        if ev.get("event") != "telemetry.span":
            continue
        path = ev.get("path")
        wall = ev.get("wall_s")
        if not isinstance(path, str) or not isinstance(wall, (int, float)):
            continue
        hist = stages.get(path)
        if hist is None:
            hist = stages[path] = Histogram()
        hist.record(float(wall))
    return stages


def _tree_rows(stages: dict[str, Histogram]):
    """(indented label, histogram|None) rows in tree order.

    Intermediate path segments that never recorded a span of their own
    (e.g. only ``score/pack`` events, no bare ``score``) still render as
    headers so the hierarchy reads correctly.
    """
    known = set(stages)
    all_paths = set()
    for path in known:
        parts = path.split("/")
        for i in range(1, len(parts) + 1):
            all_paths.add("/".join(parts[:i]))
    for path in sorted(all_paths):
        depth = path.count("/")
        label = "  " * depth + path.rsplit("/", 1)[-1]
        yield label, path, stages.get(path)


_RESILIENCE_COUNTERS = ("score/retries", "stream/retries")
_RESILIENCE_GAUGES = (
    "langdetect_breaker_state",
    "langdetect_degraded",
    "langdetect_dlq_rows",
    "langdetect_retry_attempts",
)


def _resilience_summary(counters, gauges) -> list[str]:
    """Rendered lines for the recovery-behavior block; [] when the capture
    carries no resilience signals. Defensive like the other sections."""
    out: list[str] = []
    if isinstance(counters, dict):
        for name in sorted(counters, key=str):
            if (
                str(name).startswith("resilience/")
                or str(name) in _RESILIENCE_COUNTERS
            ):
                out.append(f"  {str(name):<40} {counters[name]}")
    if isinstance(gauges, dict):
        for name in _RESILIENCE_GAUGES:
            series = gauges.get(name)
            if not isinstance(series, dict):
                continue
            for labels in sorted(series, key=str):
                tag = f"{name}{{{labels}}}" if labels else name
                out.append(f"  {tag:<40} {series[labels]}")
    return out


def render_report(events: list[dict]) -> str:
    stages = aggregate_spans(events)
    lines: list[str] = []
    if stages:
        header = (
            f"{'stage':<32} {'count':>7} {'total_s':>10} {'mean_s':>9} "
            f"{'p50_s':>9} {'p90_s':>9} {'p99_s':>9}"
        )
        lines.append(header)
        lines.append("-" * len(header))
        for label, _path, hist in _tree_rows(stages):
            if hist is None:
                lines.append(label)
                continue
            s = hist.snapshot()
            lines.append(
                f"{label:<32} {s['count']:>7} {s['sum']:>10.4f} "
                f"{s['mean']:>9.5f} {s['p50']:>9.5f} {s['p90']:>9.5f} "
                f"{s['p99']:>9.5f}"
            )
    else:
        lines.append("no span events found")

    snapshots = [e for e in events if e.get("event") == "telemetry.snapshot"]
    if snapshots:
        # Defensive rendering throughout: a capture may be hand-edited,
        # truncated mid-object, or emitted by a newer schema — a malformed
        # snapshot section must degrade to "skip that entry", never to a
        # report-killing TypeError (the report is most needed exactly when
        # the run that produced the file went wrong).
        last = snapshots[-1]
        hists = last.get("histograms")
        if isinstance(hists, dict) and hists:
            rendered = []
            for name in sorted(hists, key=str):
                h = hists[name]
                if not isinstance(h, dict) or not h.get("count"):
                    continue
                try:
                    rendered.append(
                        f"  {str(name):<32} n={h['count']:<7} "
                        f"mean={float(h.get('mean', 0.0)):.5f} "
                        f"p50={float(h.get('p50', 0.0)):.5f} "
                        f"p99={float(h.get('p99', 0.0)):.5f}"
                    )
                except (TypeError, ValueError):
                    continue
            if rendered:
                lines.append("")
                lines.append("histograms (last snapshot):")
                lines.extend(rendered)
        counters = last.get("counters")
        if isinstance(counters, dict) and counters:
            lines.append("")
            lines.append("counters (last snapshot):")
            for name in sorted(counters, key=str):
                lines.append(f"  {str(name):<40} {counters[name]}")
        gauges = last.get("gauges")
        if isinstance(gauges, dict) and gauges:
            rendered = []
            for name in sorted(gauges, key=str):
                series = gauges[name]
                if not isinstance(series, dict):
                    continue
                for labels in sorted(series, key=str):
                    tag = f"{name}{{{labels}}}" if labels else str(name)
                    rendered.append(f"  {tag:<40} {series[labels]}")
            if rendered:
                lines.append("")
                lines.append("gauges (last snapshot):")
                lines.extend(rendered)
        # Recovery-behavior highlight: the retry/breaker/DLQ/degraded
        # counters and gauges pulled out of the generic sections, so a
        # chaos run's (or an incident's) capture answers "did we degrade,
        # how often did we retry, what got quarantined" at a glance
        # (docs/RESILIENCE.md §8).
        res = _resilience_summary(counters, gauges)
        if res:
            lines.append("")
            lines.append("resilience (last snapshot):")
            lines.extend(res)
    if not events:
        return "empty capture: no telemetry events"
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m spark_languagedetector_tpu.telemetry.report "
            "<events.jsonl>",
            file=sys.stderr,
        )
        return 2
    try:
        events = load_events(argv[0])
    except OSError as e:
        print(f"cannot read {argv[0]}: {e}", file=sys.stderr)
        return 2
    print(render_report(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
