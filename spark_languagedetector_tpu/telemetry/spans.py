"""Hierarchical spans: nestable timing context managers with device fencing.

A span times one stage of a pipeline and records into the registry under a
slash path (``"score/pack"``). Nesting builds the path: within
``span("score")``, ``span("pack")`` records as ``score/pack``. A name that
already carries the parent's path as a prefix is used verbatim, so call
sites may name spans by full path (``span("score/pack")``) and still nest
correctly under ``span("score")`` — and work standalone as roots too.

Threading: the active span is a :mod:`contextvars` variable, so each thread
nests independently and a worker thread starts with no active span. Work
submitted to a pool attaches to the submitting stage by passing the parent
explicitly (``span("stream/transform", parent=root)``) — the streaming
engine's prefetch workers do exactly this. Aggregation is by path into the
registry's histograms, so concurrent children of one parent can never
corrupt any shared tree structure: there is none to corrupt.

Device fencing: JAX dispatch is async — a span around a dispatch measures
enqueue time, not execution. ``sp.fence(arrays)`` registers result arrays
to ``block_until_ready`` at span exit; when fencing is enabled (argument
``fence=True`` or env ``LANGDETECT_TELEMETRY_FENCE=1``) the span records
``device_s`` (wall through device completion) alongside ``wall_s``.
Fencing defeats pipelining, so it is opt-in — a profiling mode, not a
production default.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from contextlib import contextmanager

from .registry import REGISTRY, Registry
from .tracing import current_trace_id

FENCE_ENV = "LANGDETECT_TELEMETRY_FENCE"

_ACTIVE: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "langdetect_active_span", default=None
)
_UNSET = object()


def current_span() -> "Span | None":
    """The calling thread's innermost open span (None outside any span).

    Capture this before handing work to another thread, then pass it as
    ``span(..., parent=captured)`` so the worker's spans attach to the
    right node instead of becoming parentless roots.
    """
    return _ACTIVE.get()


class Span:
    """One open timing region. Created by :func:`span`, not directly."""

    __slots__ = ("name", "path", "parent", "attrs", "trace_id", "_fences")

    def __init__(self, name: str, path: str, parent: "Span | None", attrs: dict):
        self.name = name
        self.path = path
        self.parent = parent
        self.attrs = attrs
        # Request attribution: the ambient trace context wins (a stream
        # batch's per-request scope overrides the engine root's), the
        # explicit parent's id is the cross-thread fallback (worker
        # threads have no ambient context of their own).
        self.trace_id = current_trace_id() or (
            parent.trace_id if parent is not None else None
        )
        self._fences: list = []

    def fence(self, *arrays) -> None:
        """Register device arrays to block on at span exit (when fencing is
        enabled). Accepts None entries so call sites need no conditionals."""
        self._fences.extend(a for a in arrays if a is not None)

    def set(self, **attrs) -> None:
        """Attach/overwrite event fields visible in the exported record."""
        self.attrs.update(attrs)


def _fencing_enabled(flag: bool | None) -> bool:
    if flag is not None:
        return flag
    # Resolved through exec/config's audited table (lazily: spans import
    # before the exec package exists). A malformed value means "off" —
    # fencing is a profiling mode, and raising here would fail every
    # span() on the hot path — but still surfaces as an ``error`` row in
    # /varz effective_config.
    from ..exec import config as exec_config

    try:
        return bool(exec_config.resolve("telemetry_fence"))
    except ValueError:
        return False


def _resolve_path(name: str, parent: "Span | None") -> str:
    if parent is None:
        return name
    if name.startswith(parent.path + "/"):
        return name
    # Full-path call-site names under a re-rooted parent: "score/pack"
    # inside a "score" root that is itself nested (stream/transform/score)
    # merges on the shared segment → stream/transform/score/pack, never
    # .../score/score/pack.
    first, sep, rest = name.partition("/")
    if sep and parent.path.rsplit("/", 1)[-1] == first:
        return parent.path + "/" + rest
    return parent.path + "/" + name


@contextmanager
def span(
    name: str,
    *,
    parent=_UNSET,
    registry: Registry | None = None,
    fence: bool | None = None,
    **attrs,
):
    """Open a timing span; on exit, record wall (and fenced device) seconds.

    ``parent``: defaults to the thread's current span; pass an explicit
    span (or None) for cross-thread attachment. ``fence``: tri-state —
    None defers to ``LANGDETECT_TELEMETRY_FENCE``. Extra keyword args ride
    along as fields on the exported span event.
    """
    reg = registry if registry is not None else REGISTRY
    par = current_span() if parent is _UNSET else parent
    sp = Span(name, _resolve_path(name, par), par, dict(attrs))
    token = _ACTIVE.set(sp)
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        wall_s = time.perf_counter() - t0
        device_s = None
        if sp._fences and _fencing_enabled(fence):
            for arr in sp._fences:
                block = getattr(arr, "block_until_ready", None)
                if block is not None:
                    try:
                        block()
                    except Exception:
                        pass  # fencing must never mask the real error path
            device_s = time.perf_counter() - t0
        _ACTIVE.reset(token)
        # Stamped at exit so the exported record carries the request id and
        # the recording thread (the Chrome-trace exporter's lane key);
        # explicit attrs of the same name win.
        if sp.trace_id is not None:
            sp.attrs.setdefault("trace_id", sp.trace_id)
        sp.attrs.setdefault("tid", threading.get_ident())
        reg.record_span(sp.path, wall_s, device_s, sp.attrs)
