"""Telemetry-driven autoscaler: the control loop the autotuner opened.

:mod:`..exec.tune` solves *shape* knobs offline from a replayed capture
of the serving signals; this module consumes the same signals **live** —
the admission queue's arrival-rate EMA, queue depth, shed counters, and
the estimated-wait SLO — and drives the one knob tuning cannot reach:
replica count. The GSPMD/pjit portability result makes that safe: the
per-replica compiled program is identical at every fleet size, so a
scale decision is pure control plane (docs/SERVING.md §13).

Decision rule, per tick, with hysteresis on both edges:

  * **pressure** — new sheds since the last tick, or the fleet-wide
    estimated wait (queued rows / arrival EMA) at or past
    ``scale_pressure_wait_ms``. ``scale_up_ticks`` *consecutive* pressure
    ticks raise the target by one (clamped to ``LANGDETECT_SCALE_MAX``):
    a single burst spike never spawns a process.
  * **idle** — empty queue, nothing in flight, no new sheds, and the
    arrival EMA below ``scale_idle_rows_per_s``. ``scale_down_ticks``
    consecutive idle ticks (the cooldown) lower the target by one
    (clamped to ``LANGDETECT_SCALE_MIN``): capacity is released an order
    of magnitude slower than it is acquired, the classic asymmetry.
  * **deferral** — while any member breaker is open/half-open or the
    fleet is below target (a supervised restart in progress), the tick
    observes and repairs but makes **no** scale decision: mid-outage the
    breaker/half-open machinery owns the fleet's shape, and an
    autoscaler fighting it would read a dead replica as idleness and
    shrink a fleet that is actually drowning.

The ``scale/decision`` fault site fires at the top of each tick: an
injected error skips that one tick (counted, logged), never a wrong
scale action — the fail-static posture a control loop owes its plant.
"""

from __future__ import annotations

import threading

from ..exec import config as exec_config
from ..resilience import faults
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("scale.autoscaler")


class ScaleSignals:
    """One aggregated snapshot of the fleet's serving signals.

    ``ema_rows_per_s`` is the fleet arrival-rate EMA (decays to zero
    across silence — the idleness signal); ``est_wait_ms`` is the same
    estimate the admission queues shed on, fleet-wide (backlog over the
    summed dispatch-throughput EMAs); ``shed_delta`` is new sheds since
    the previous snapshot — appearance, not level, is the pressure
    signal (a counter's absolute value only says the fleet has history).
    ``shed_delta`` is differentiated from the fleet telemetry aggregate
    (the collector's monotone counters), and ``slo_burning`` carries the
    burn-rate verdict over the same aggregate — a burning objective is
    pressure even before sheds appear (a sustained p99 breach, say).
    """

    __slots__ = (
        "live", "ready", "queued_rows", "inflight_rows", "ema_rows_per_s",
        "est_wait_ms", "shed_delta", "breaker_open", "slo_burning",
    )

    def __init__(
        self,
        *,
        live: int = 0,
        ready: int = 0,
        queued_rows: int = 0,
        inflight_rows: int = 0,
        ema_rows_per_s: float = 0.0,
        est_wait_ms: float = 0.0,
        shed_delta: int = 0,
        breaker_open: bool = False,
        slo_burning: bool = False,
    ):
        self.live = live
        self.ready = ready
        self.queued_rows = queued_rows
        self.inflight_rows = inflight_rows
        self.ema_rows_per_s = ema_rows_per_s
        self.est_wait_ms = est_wait_ms
        self.shed_delta = shed_delta
        self.breaker_open = breaker_open
        self.slo_burning = slo_burning

    def describe(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}


class Autoscaler:
    """Drives ``fleet`` (anything with ``signals()``, ``scale_to(n)``,
    ``check_members()``, and a ``target`` int property — in practice
    :class:`~.elastic.ElasticFleet`) between ``scale_min`` and
    ``scale_max``. ``tick()`` is the whole algorithm and is what the
    deterministic tests drive; :meth:`start` runs it on a background
    thread every ``scale_interval_ms``.
    """

    def __init__(
        self,
        fleet,
        *,
        scale_min: int | None = None,
        scale_max: int | None = None,
        interval_ms: float | None = None,
        up_ticks: int | None = None,
        down_ticks: int | None = None,
        pressure_wait_ms: float | None = None,
        idle_rows_per_s: float | None = None,
    ):
        self.fleet = fleet
        self.scale_min = int(exec_config.resolve("scale_min", scale_min))
        self.scale_max = int(exec_config.resolve("scale_max", scale_max))
        if self.scale_max < self.scale_min:
            raise ValueError(
                f"scale_max ({self.scale_max}) < scale_min "
                f"({self.scale_min})"
            )
        self.interval_s = float(exec_config.resolve(
            "scale_interval_ms", interval_ms
        )) / 1000.0
        self.up_ticks = int(exec_config.resolve("scale_up_ticks", up_ticks))
        self.down_ticks = int(exec_config.resolve(
            "scale_down_ticks", down_ticks
        ))
        self.pressure_wait_ms = float(exec_config.resolve(
            "scale_pressure_wait_ms", pressure_wait_ms
        ))
        self.idle_rows_per_s = float(exec_config.resolve(
            "scale_idle_rows_per_s", idle_rows_per_s
        ))
        self._pressure_streak = 0
        self._idle_streak = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- the loop --
    def tick(self) -> str:
        """One control-loop step; returns the decision taken (``"up"``,
        ``"down"``, ``"hold"``, ``"deferred"``, or ``"skipped"``)."""
        try:
            faults.inject("scale/decision")
        except faults.InjectedFault as e:
            # Fail static: a faulted decision path must never produce a
            # wrong scale action — this tick simply does not happen.
            REGISTRY.incr("scale/decision_skips")
            log_event(_log, "scale.tick_skipped", error=repr(e))
            return "skipped"
        self.fleet.check_members()
        sig = self.fleet.signals()
        target = int(self.fleet.target)
        REGISTRY.set_gauge("langdetect_fleet_target_replicas", float(target))
        REGISTRY.set_gauge("langdetect_fleet_live_replicas", float(sig.live))
        if sig.breaker_open or sig.live < target:
            # Mid-outage: ejection/half-open owns the fleet's shape.
            # Streaks freeze (they neither grow nor reset) so a recovered
            # fleet resumes exactly the trend it had.
            log_event(
                _log, "scale.tick_deferred", live=sig.live, target=target,
                breaker_open=sig.breaker_open,
            )
            return "deferred"
        if target < self.scale_min:
            # Min-floor repair: a member that exhausted its restart
            # budget was detached and dropped the target — replace it
            # with a fresh spawn rather than serving under the floor.
            self.fleet.scale_to(self.scale_min)
            REGISTRY.set_gauge(
                "langdetect_fleet_target_replicas", float(self.scale_min)
            )
            return "up"
        pressure = (
            sig.shed_delta > 0
            or sig.est_wait_ms >= self.pressure_wait_ms
            or sig.slo_burning
        )
        # Idleness explicitly excludes pressure: a tick that shows SLO
        # pressure can never ALSO count toward the scale-down cooldown,
        # even at the ceiling where the pressure has nowhere to go.
        idle = (
            not pressure
            and sig.queued_rows == 0
            and sig.inflight_rows == 0
            and sig.shed_delta == 0
            and sig.ema_rows_per_s < self.idle_rows_per_s
        )
        self._pressure_streak = self._pressure_streak + 1 if pressure else 0
        self._idle_streak = self._idle_streak + 1 if idle else 0
        decision = "hold"
        if pressure and (
            self._pressure_streak >= self.up_ticks
            and target < self.scale_max
        ):
            target += 1
            decision = "up"
            self._pressure_streak = 0
            self._idle_streak = 0
        elif idle and (
            self._idle_streak >= self.down_ticks and target > self.scale_min
        ):
            target -= 1
            decision = "down"
            self._idle_streak = 0
            self._pressure_streak = 0
        if decision != "hold":
            log_event(
                _log, "scale.decision", decision=decision, target=target,
                **sig.describe(),
            )
            self.fleet.scale_to(target)
            REGISTRY.set_gauge(
                "langdetect_fleet_target_replicas", float(target)
            )
        return decision

    # ------------------------------------------------------------ lifecycle --
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="scale-autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # the loop must survive anything
                log_event(_log, "scale.tick_error", error=repr(e))

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
