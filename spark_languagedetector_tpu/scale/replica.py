"""Subprocess serving replicas and the supervisor that keeps them alive.

A :class:`ProcessReplica` is a real OS process (the ``--fit-scaling``
subprocess harness and the jax.distributed ``--probe`` worker are the
patterns): it loads its model from a persisted path, owns its devices via
per-process ``JAX_PLATFORMS``/``XLA_FLAGS``, runs a
:class:`~..serve.server.ServingServer` on its assigned port, and reports
readiness over the existing ``/healthz/ready`` split — to the router it
is indistinguishable from any other HTTP endpoint.

Wire protocol between coordinator and child (docs/SERVING.md §13):

  * The child prints exactly one ``READY {json}`` line on stdout once the
    server is bound and the model is warm; everything else on the merged
    stdout/stderr pipe is diagnostics, retained in a bounded tail for
    spawn-failure messages.
  * The child then blocks on stdin. EOF is the **pipe sentinel**: the
    coordinator closing stdin (graceful stop) — or dying, even by
    SIGKILL, which closes the pipe's write end — makes the child drain
    its accepted work and exit. A replica can therefore never outlive its
    coordinator silently; at worst it finishes in-flight requests and
    leaves.
  * SIGTERM to the child is the same graceful path (the orphan reaper
    and container runtimes both speak it).

The :class:`ReplicaSupervisor` owns the other half of the lifecycle:
spawn with a readiness timeout, abrupt-death detection (``proc.poll()``
plus the stdout-EOF sentinel), bounded restart-with-backoff through
:class:`~..resilience.policy.RetryPolicy`, and **orphan reaping** — every
spawn writes a pidfile, an ``atexit`` hook kills surviving children on
coordinator exit, and a new supervisor on the same pidfile directory
reaps children a SIGKILLed coordinator stranded (verifying
``/proc/<pid>/cmdline`` is actually a replica worker before signalling,
so a recycled pid is never shot).

Chaos: the ``scale/spawn`` fault site fires inside each spawn attempt,
so injected spawn errors exercise the restart-backoff path
deterministically on CPU (docs/RESILIENCE.md §4).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque

from ..exec import config as exec_config
from ..resilience import faults
from ..resilience.policy import RetryPolicy
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("scale.replica")

READY_PREFIX = "READY "
_WORKER_MODULE = "spark_languagedetector_tpu.scale.replica"


class SpawnError(RuntimeError):
    """A replica subprocess failed to reach readiness (spawn timeout,
    early exit, or an injected ``scale/spawn`` fault). RuntimeError-shaped
    so the retry classifier treats it as transient — which it is: the
    supervisor's bounded backoff is the recovery path."""


class ProcessReplica:
    """One serving replica in its own OS process.

    ``port=0`` lets the child bind an ephemeral port, reported back on
    the READY line and **pinned** from then on: a supervisor restart puts
    the replica back at the address the router knows, so the breaker's
    half-open probe re-admits it without a membership change.
    """

    def __init__(
        self,
        name: str,
        model_path: str,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        platform: str = "cpu",
        xla_flags: str | None = None,
        env: dict | None = None,
        prewarm: bool = True,
        spawn_timeout_s: float | None = None,
        tail_lines: int = 40,
        metrics_jsonl: str | None = None,
        compile_cache_dir: str | None = None,
        artifact: str | None = None,
    ):
        self.name = name
        self.model_path = str(model_path)
        self._host = host
        self._port = int(port)
        self._platform = platform
        self._xla_flags = xla_flags
        self._env = dict(env or {})
        self._prewarm = prewarm
        self._metrics_jsonl = metrics_jsonl
        # Cold-start plane handshake (docs/PERFORMANCE.md §12): the
        # persistent compile-cache dir and baked-artifact path ride the
        # child's argv, so the worker reaches READY having mmapped its
        # tables and warmed (or cache-hit) its jit programs.
        self._compile_cache_dir = compile_cache_dir
        self._artifact = artifact
        # Coordinator-side wall time of the last successful spawn (Popen
        # to READY) and the child-reported warmup span (model load +
        # lattice prewarm) off that spawn's READY line.
        self.last_spawn_ready_s: float | None = None
        self.last_warmup_s: float | None = None
        # "full" | "sentinel" | None — how the child's lattice prewarm ran
        # (sentinel = verified-warm manifest fast path).
        self.last_prewarm_mode: str | None = None
        # Coordinator clock − child clock, measured at the READY
        # handshake (the child stamps its wall clock onto the READY
        # line). The stitch CLI uses the clock_sync event this emits to
        # align per-process captures onto one timeline.
        self.clock_offset_s: float | None = None
        self.spawn_timeout_s = float(exec_config.resolve(
            "scale_spawn_timeout_s", spawn_timeout_s
        ))
        self.proc: subprocess.Popen | None = None
        self._eof = threading.Event()
        self._ready_line: list[str] = []
        self._ready_evt = threading.Event()
        self._tail: deque[str] = deque(maxlen=tail_lines)
        self._reader: threading.Thread | None = None

    # ---------------------------------------------------------- properties --
    @property
    def address(self) -> tuple[str, int]:
        return (self._host, self._port)

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    @property
    def alive(self) -> bool:
        """The process exists and has not exited. Death shows up both
        here (``poll()``) and on the stdout-EOF sentinel — the supervisor
        checks either, so a child that dies between polls is still
        caught the moment its pipe closes."""
        return self.proc is not None and self.proc.poll() is None

    def output_tail(self) -> list[str]:
        """Last diagnostics lines from the child (spawn-failure detail)."""
        return list(self._tail)

    # ----------------------------------------------------------- lifecycle --
    def _child_env(self) -> dict:
        env = dict(os.environ)
        # Per-process device ownership: the platform pin rides both the
        # env var and a worker-side jax.config.update (the programmatic
        # form is what wins under sitecustomize overrides).
        env["JAX_PLATFORMS"] = self._platform
        if self._xla_flags:
            base = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = f"{base} {self._xla_flags}".strip()
        env.update(self._env)
        return env

    def spawn(self) -> "ProcessReplica":
        """Start the child and block until its READY line (bounded).

        Raises :class:`SpawnError` on timeout, early exit, or an injected
        ``scale/spawn`` fault; the supervisor wraps this in the bounded
        backoff schedule."""
        if self.alive:
            if not self._eof.is_set():
                return self
            # Alive but its pipe is gone: no longer supervisable — a
            # respawn over it would leak the old process and fight it
            # for the pinned port.
            self.kill()
        faults.inject("scale/spawn")
        t0 = time.monotonic()
        argv = [
            sys.executable, "-m", _WORKER_MODULE, self.model_path,
            "--name", self.name,
            "--host", self._host,
            "--port", str(self._port),
            "--platform", self._platform,
        ]
        if not self._prewarm:
            argv.append("--no-prewarm")
        if self._metrics_jsonl:
            argv += ["--metrics-jsonl", self._metrics_jsonl]
        if self._compile_cache_dir:
            argv += ["--compile-cache-dir", self._compile_cache_dir]
        # Re-resolved per attempt, not pinned at construction: an artifact
        # baked between two spawns of the same member (cold fleet start,
        # then a bake lands) is picked up by the next restart.
        artifact = self._artifact
        if artifact is None:
            from ..artifacts.bake import artifact_path_for

            candidate = artifact_path_for(self.model_path)
            if candidate.exists():
                artifact = str(candidate)
        if artifact:
            argv += ["--artifact", artifact]
        # Fresh per-spawn state, CAPTURED by this spawn's reader thread:
        # a stale reader from the previous incarnation (never joined —
        # it may be blocked on a half-dead pipe) still holds the OLD
        # events/line list, so it can neither flag the new incarnation
        # dead nor deliver the dead child's buffered READY line into the
        # new spawn.
        self._eof = eof = threading.Event()
        self._ready_evt = ready_evt = threading.Event()
        self._ready_line = ready_line = []
        self.proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=self._child_env(),
        )
        self._reader = threading.Thread(
            target=self._drain_stdout,
            args=(self.proc, eof, ready_evt, ready_line),
            name=f"scale-{self.name}-out", daemon=True,
        )
        self._reader.start()
        deadline = time.monotonic() + self.spawn_timeout_s
        while not ready_evt.wait(timeout=0.02):
            if self.proc.poll() is not None:
                raise SpawnError(
                    f"replica {self.name!r} exited rc={self.proc.returncode} "
                    f"before READY; tail={self.output_tail()[-3:]}"
                )
            if time.monotonic() >= deadline:
                self.kill()
                raise SpawnError(
                    f"replica {self.name!r} spawn timed out after "
                    f"{self.spawn_timeout_s}s; tail={self.output_tail()[-3:]}"
                )
        info = json.loads(ready_line[0][len(READY_PREFIX):])
        self._port = int(info["port"])
        # Spawn-to-READY is the cold-start wall the artifacts plane exists
        # to knock down; tracked as a regression histogram
        # (telemetry/compare's cold-start set diffs its p50).
        self.last_spawn_ready_s = time.monotonic() - t0
        REGISTRY.observe("scale/spawn_ready_s", self.last_spawn_ready_s)
        warmup = info.get("warmup_s")
        self.last_warmup_s = (
            float(warmup) if isinstance(warmup, (int, float)) else None
        )
        self.last_prewarm_mode = info.get("prewarm_mode")
        # Clock sync at the handshake: the child stamped its wall clock
        # onto the READY line *just* before we read it, so the difference
        # is the cross-process clock offset (± pipe latency, microseconds
        # on one host). Emitted into the coordinator's own capture —
        # telemetry.stitch reads it back to align the timelines; a
        # restart re-emits, so the last sync per replica stays current.
        child_ts = info.get("ts")
        if isinstance(child_ts, (int, float)):
            self.clock_offset_s = time.time() - float(child_ts)
            REGISTRY.emit({
                "event": "telemetry.clock_sync", "ts": time.time(),
                "replica": self.name, "pid": info.get("pid"),
                "platform": info.get("platform"),
                "offset_s": self.clock_offset_s,
            })
        log_event(
            _log, "scale.replica.ready", replica=self.name, pid=self.pid,
            port=self._port, version=info.get("version"),
            spawn_ready_s=round(self.last_spawn_ready_s, 4),
            warmup_s=info.get("warmup_s"),
        )
        return self

    def _drain_stdout(self, proc, eof, ready_evt, ready_line) -> None:
        # Keeps the pipe from filling (a blocked child is a fake hang)
        # and doubles as the death sentinel: EOF fires the event even if
        # nobody has called poll() yet. Operates ONLY on the captured
        # per-spawn state — never self's — so a stale reader outliving
        # its process cannot poison a later incarnation.
        try:
            for line in proc.stdout:
                line = line.rstrip("\n")
                if line.startswith(READY_PREFIX) and not ready_line:
                    ready_line.append(line)
                    ready_evt.set()
                else:
                    self._tail.append(line)
        finally:
            eof.set()

    def kill(self) -> None:
        """Abrupt death (the chaos drill / spawn-timeout escalation)."""
        if self.proc is None:
            return
        try:
            self.proc.kill()
        except OSError:
            pass
        self.proc.wait(timeout=10.0)
        self._close_pipes()
        log_event(_log, "scale.replica.killed", replica=self.name,
                  pid=self.proc.pid)

    def stop(self, *, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Graceful stop: close stdin (the pipe sentinel) so the child
        drains accepted work and exits; escalate to SIGTERM, then SIGKILL
        if it overruns the bound. ``drain=False`` goes straight to
        :meth:`kill`."""
        if self.proc is None:
            return
        if not drain:
            self.kill()
            return
        try:
            if self.proc.stdin is not None:
                self.proc.stdin.close()
        except OSError:
            pass
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10.0)
        self._close_pipes()
        log_event(_log, "scale.replica.stop", replica=self.name,
                  rc=self.proc.returncode)

    def _close_pipes(self) -> None:
        for pipe in (self.proc.stdin, self.proc.stdout):
            try:
                if pipe is not None:
                    pipe.close()
            except OSError:
                pass


# --------------------------------------------------------------- pidfiles ---
def _pidfile(dirpath: str, name: str) -> str:
    return os.path.join(dirpath, f"{name}.pid")


def _pid_is_replica_worker(pid: int) -> bool:
    """Is ``pid`` alive AND actually a replica worker? The /proc cmdline
    check is what makes reaping safe against pid recycling — a stale
    pidfile must never shoot an innocent process that inherited the pid."""
    try:
        os.kill(pid, 0)
    except (OSError, ProcessLookupError):
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().decode("utf-8", "replace")
    except OSError:
        # Identity unprovable (no /proc, or the pid was recycled to a
        # process we may not inspect): refuse to reap. Leaking an orphan
        # a human can clean up beats shooting an innocent process that
        # inherited the pid.
        return False
    return _WORKER_MODULE in cmdline


class ReplicaSupervisor:
    """Spawns, watches, restarts, and reaps :class:`ProcessReplica`s.

    One supervisor per coordinator process. Construction reaps orphans
    first: any pidfile in ``pidfile_dir`` whose pid is still a live
    replica worker belongs to a coordinator that died without cleanup
    (SIGKILL — atexit never ran), so it is terminated and counted
    (``scale/orphans_reaped``) before this fleet binds ports. Two
    concurrent coordinators must therefore use distinct pidfile dirs
    (the default is keyed by fleet name under the system tempdir).
    """

    def __init__(
        self,
        model_path: str,
        *,
        host: str = "127.0.0.1",
        platform: str = "cpu",
        fleet_name: str = "fleet",
        pidfile_dir: str | None = None,
        spawn_timeout_s: float | None = None,
        max_restarts: int | None = None,
        prewarm: bool = True,
        retry_policy: RetryPolicy | None = None,
        child_env: dict | None = None,
        metrics_dir: str | None = None,
        compile_cache_dir: str | None = None,
        artifact: str | None = None,
        tuning_profile: str | None = None,
    ):
        self.model_path = str(model_path)
        self._host = host
        self._platform = platform
        self._child_env = dict(child_env or {})
        # Cold-start plane (docs/PERFORMANCE.md §12): spawn ships the
        # compile-cache dir + baked-artifact path on the child's argv and
        # the tuning profile through its env, so every member boots into
        # a warm cache and an mmapped model. All resolved through the
        # audited knob table — explicit ctor values beat env.
        self._compile_cache_dir = exec_config.resolve(
            "compile_cache_dir", compile_cache_dir
        )
        self._artifact = artifact
        profile_path = exec_config.resolve("tuning_profile", tuning_profile)
        if profile_path:
            self._child_env.setdefault(
                exec_config.PROFILE_ENV, str(profile_path)
            )
        self.fleet_name = fleet_name
        # When set, every member writes its telemetry JSONL capture to
        # metrics_dir/replica-<name>.jsonl (append mode — restart
        # generations share the file, distinguishable by pid), the
        # per-process half of the stitch CLI's input.
        self.metrics_dir = None if metrics_dir is None else str(metrics_dir)
        if self.metrics_dir:
            os.makedirs(self.metrics_dir, exist_ok=True)
        dirpath = exec_config.resolve("scale_pidfile_dir", pidfile_dir)
        if dirpath is None:
            import tempfile

            dirpath = os.path.join(
                tempfile.gettempdir(), "langdetect_scale", fleet_name
            )
        self.pidfile_dir = str(dirpath)
        os.makedirs(self.pidfile_dir, exist_ok=True)
        self._spawn_timeout_s = spawn_timeout_s
        self.max_restarts = int(exec_config.resolve(
            "scale_max_restarts", max_restarts
        ))
        self._prewarm = prewarm
        # Restart/spawn backoff, bounded by the restart budget. The
        # default schedule deliberately starts at 250 ms (not the
        # process-wide 50 ms retry default): a respawn on the pinned
        # port races the kernel reclaiming the dead child's socket, and
        # three sub-100 ms attempts can all land inside that window.
        self.retry_policy = retry_policy or RetryPolicy.from_env(
            max_attempts=max(1, self.max_restarts),
            base_delay_s=0.25, max_delay_s=5.0,
        )
        self._lock = threading.Lock()
        self.members: dict[str, ProcessReplica] = {}
        # Members stopped on purpose (scale-down) — their death is not an
        # incident; members whose restart budget ran out stay here too.
        self._retired: set[str] = set()
        self._failed: set[str] = set()
        # Crash-loop guard: consecutive death→restart cycles per member
        # (a member seen alive on a later poll resets its streak). The
        # per-spawn backoff bounds one incident; the streak bounds a
        # replica that keeps coming up and falling over.
        self._restart_streak: dict[str, int] = {}
        self.reap_orphans()
        atexit.register(self._atexit_kill)

    # ------------------------------------------------------------- orphans --
    def reap_orphans(self) -> int:
        """Kill replica workers a dead coordinator stranded; returns the
        count. SIGTERM first (the worker's graceful-drain path), SIGKILL
        only on overrun."""
        reaped = 0
        try:
            entries = sorted(os.listdir(self.pidfile_dir))
        except OSError:
            return 0
        for fname in entries:
            if not fname.endswith(".pid"):
                continue
            path = os.path.join(self.pidfile_dir, fname)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    info = json.load(f)
                pid = int(info["pid"])
            except (OSError, ValueError, KeyError):
                self._unlink(path)
                continue
            if _pid_is_replica_worker(pid):
                try:
                    os.kill(pid, signal.SIGTERM)
                    for _ in range(100):
                        if not _pid_is_replica_worker(pid):
                            break
                        time.sleep(0.05)
                    else:
                        os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
                reaped += 1
                REGISTRY.incr("scale/orphans_reaped")
                log_event(
                    _log, "scale.orphan_reaped", pid=pid,
                    replica=info.get("name"), port=info.get("port"),
                )
            self._unlink(path)
        return reaped

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def _write_pidfile(self, rep: ProcessReplica) -> None:
        path = _pidfile(self.pidfile_dir, rep.name)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({
                "pid": rep.pid, "name": rep.name,
                "host": rep.address[0], "port": rep.address[1],
                "coordinator": os.getpid(),
            }, f)
        os.replace(tmp, path)

    # ------------------------------------------------------------ lifecycle --
    def spawn(
        self, name: str, *, port: int = 0, prewarm: bool | None = None
    ) -> ProcessReplica:
        """Spawn one replica to readiness, under the bounded backoff
        schedule. Every failed attempt counts ``scale/spawn_failures``;
        exhaustion raises the last :class:`SpawnError`. ``prewarm``
        overrides the supervisor default for THIS member (and sticks
        across its restarts) — an elastic fleet warms its founders but
        may admit joiners cold, folding their compile into the first
        dispatch instead of the spawn latency."""
        with self._lock:
            existing = self.members.get(name)
        if existing is not None and existing.alive:
            raise ValueError(
                f"replica {name!r} is already a live member; stop it "
                "first or pick a fresh name"
            )
        rep = ProcessReplica(
            name, self.model_path, host=self._host, port=port,
            platform=self._platform,
            prewarm=self._prewarm if prewarm is None else prewarm,
            spawn_timeout_s=self._spawn_timeout_s, env=self._child_env,
            metrics_jsonl=(
                os.path.join(self.metrics_dir, f"replica-{name}.jsonl")
                if self.metrics_dir else None
            ),
            compile_cache_dir=self._compile_cache_dir,
            artifact=self._artifact,
        )
        self._spawn_with_backoff(rep)
        with self._lock:
            self.members[name] = rep
            self._retired.discard(name)
            self._failed.discard(name)
        return rep

    def _spawn_with_backoff(self, rep: ProcessReplica) -> None:
        def attempt():
            try:
                return rep.spawn()
            except Exception:
                REGISTRY.incr("scale/spawn_failures")
                raise

        self.retry_policy.run(attempt, site="scale/spawn")
        self._write_pidfile(rep)

    def stop(self, name: str, *, drain: bool = True) -> None:
        """Planned stop (scale-down): the member's later absence is not
        an incident, so no restart fires."""
        with self._lock:
            rep = self.members.get(name)
            self._retired.add(name)
        if rep is not None:
            rep.stop(drain=drain)
            self._unlink(_pidfile(self.pidfile_dir, name))
        with self._lock:
            self.members.pop(name, None)

    def poll_once(self) -> list[str]:
        """One supervision round: detect abrupt deaths (poll + pipe
        sentinel), restart each within its backoff budget, give up loudly
        past it. Returns compact event strings (``"r1:restarted"``,
        ``"r1:gave_up"``) — the deterministic lifecycle tests pin these.
        """
        events: list[str] = []
        with self._lock:
            snapshot = [
                (name, rep) for name, rep in self.members.items()
                if name not in self._retired and name not in self._failed
            ]
        for name, rep in snapshot:
            if rep.alive and not rep._eof.is_set():
                self._restart_streak[name] = 0
                continue
            streak = self._restart_streak.get(name, 0) + 1
            self._restart_streak[name] = streak
            log_event(
                _log, "scale.replica.death_detected", replica=name,
                rc=rep.proc.returncode if rep.proc else None, streak=streak,
            )
            if streak > self.max_restarts:
                with self._lock:
                    self._failed.add(name)
                log_event(
                    _log, "scale.replica.gave_up", replica=name,
                    reason="crash_loop", budget=self.max_restarts,
                )
                events.append(f"{name}:gave_up")
                continue
            try:
                self._spawn_with_backoff(rep)
            except Exception as e:
                with self._lock:
                    self._failed.add(name)
                log_event(
                    _log, "scale.replica.gave_up", replica=name,
                    error=repr(e), budget=self.max_restarts,
                )
                events.append(f"{name}:gave_up")
                continue
            # Counted on the restart actually HAPPENING — a death whose
            # respawn gave up is visible as scale/spawn_failures + the
            # gave-up event, not as a restart that never occurred.
            REGISTRY.incr("scale/restarts")
            events.append(f"{name}:restarted")
        return events

    def forget(self, name: str) -> None:
        """Drop a member entirely — no restart candidacy, no pidfile, no
        scale-down victim candidacy. The coordinator calls this after
        detaching a gave-up member from routing; anything still running
        is killed (it already failed its budget)."""
        with self._lock:
            rep = self.members.pop(name, None)
            self._retired.discard(name)
            self._failed.discard(name)
            self._restart_streak.pop(name, None)
        if rep is not None and rep.alive:
            try:
                rep.kill()
            except Exception:
                pass
        self._unlink(_pidfile(self.pidfile_dir, name))

    def live_count(self) -> int:
        with self._lock:
            return sum(
                1 for name, rep in self.members.items()
                if rep.alive and name not in self._retired
            )

    def close(self, *, drain: bool = True) -> None:
        with self._lock:
            names = list(self.members)
        for name in names:
            self.stop(name, drain=drain)
        atexit.unregister(self._atexit_kill)

    def abandon(self) -> None:
        """Forget every child WITHOUT killing it — the coordinator-
        SIGKILL simulation for the orphan-reap drill (tests only: a real
        SIGKILL cannot run in-process). Pidfiles stay, atexit disarms;
        the next supervisor on this pidfile dir must reap."""
        with self._lock:
            self.members.clear()
            self._retired.clear()
            self._failed.clear()
        atexit.unregister(self._atexit_kill)

    def _atexit_kill(self) -> None:
        # Last-ditch: a coordinator exiting without close() must not
        # strand children. Abrupt (kill, not drain) — atexit runs late,
        # possibly with daemon threads already dead.
        with self._lock:
            reps = list(self.members.values())
            self.members.clear()
        for rep in reps:
            try:
                rep.kill()
            except Exception:
                pass
            self._unlink(_pidfile(self.pidfile_dir, rep.name))

    def __enter__(self) -> "ReplicaSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------------ worker main ---
def main(argv: list[str] | None = None) -> int:
    """``python -m spark_languagedetector_tpu.scale.replica <model_dir>
    --name r0 --host H --port P --platform cpu [--no-prewarm]`` — the
    child half of :class:`ProcessReplica`. Not intended for direct use;
    the READY-line/stdin-EOF protocol is the module docstring's contract.
    """
    import argparse

    parser = argparse.ArgumentParser(prog=_WORKER_MODULE)
    parser.add_argument("model_dir")
    parser.add_argument("--name", default="replica")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--platform", default="cpu")
    parser.add_argument("--no-prewarm", action="store_true")
    parser.add_argument("--metrics-jsonl", default=None)
    parser.add_argument("--compile-cache-dir", default=None)
    parser.add_argument("--artifact", default=None)
    args = parser.parse_args(argv)

    # Pin this process's devices BEFORE any model load touches the
    # backend. The programmatic update is what wins when a sitecustomize
    # force-sets jax_platforms (same move as the jax.distributed probe
    # worker).
    import jax

    jax.config.update("jax_platforms", args.platform)

    from ..serve.registry import ModelRegistry
    from ..serve.server import ServingServer
    from ..telemetry.aggregate import install_process_identity

    # Identity before any span fires: every record this process exports
    # carries who recorded it (replica name, pid, live backend).
    identity = install_process_identity(replica=args.name)
    if args.metrics_jsonl:
        from ..telemetry.export import JsonlSink

        REGISTRY.add_sink(JsonlSink(args.metrics_jsonl))

    # Cold-start plane: persistent compile cache on (when configured)
    # BEFORE the first jit, then the model load — off the mmapped baked
    # artifact when the handshake shipped one — then the bounded shape
    # lattice traced, so READY means "every geometry this worker can
    # dispatch is compiled or cache-hit" (docs/PERFORMANCE.md §12). The
    # warmup span (load + prewarm, imports excluded) rides the READY line:
    # it is the cold-start wall this plane exists to knock down, measured
    # identically for cold and warm spawns.
    from ..artifacts.compile_cache import enable_compile_cache, prewarm_lattice

    cache_dir = enable_compile_cache(args.compile_cache_dir)
    t_warm = time.perf_counter()
    registry = ModelRegistry()
    # The lattice prewarm below covers every geometry the registry's own
    # two-doc prewarm would trace (and more), so skip the double warm.
    registry.load(args.model_dir, artifact=args.artifact, prewarm=False)
    runner = registry.peek().runner
    prewarm_mode = None
    if not args.no_prewarm:
        # Roofline diagnostics re-lower the dispatch program; on a small
        # host that analysis would serialize with (and dominate) the
        # measured warmup, so defer it until after READY.
        runner._cost_recorded = True
        prewarm_mode = prewarm_lattice(runner, cache_dir=cache_dir)["mode"]
    warmup_s = time.perf_counter() - t_warm
    server = ServingServer(registry, host=args.host, port=args.port).start()
    ready = {
        "name": args.name,
        "host": server.address[0],
        "port": server.address[1],
        "pid": os.getpid(),
        "version": registry.current_version(),
        "platform": identity.get("platform", args.platform),
        # The child's wall clock at handshake — the coordinator
        # differences it against its own to sync the two captures
        # (telemetry.stitch).
        "ts": time.time(),
        "warmup_s": warmup_s,
        "prewarm_mode": prewarm_mode,
    }
    print(READY_PREFIX + json.dumps(ready), flush=True)

    if not args.no_prewarm:
        # The deferred roofline gauges: recorded off the serving path now
        # that READY is out, at the lattice's smallest dispatch geometry.
        def _deferred_cost():
            try:
                from ..resilience import faults
                from ..telemetry import cost as cost_mod

                # Shielded: the analysis re-traces the instrumented
                # dispatch, and an env-armed chaos plan must spend its
                # call budget on serving attempts, not diagnostics.
                with faults.shield():
                    cost_mod.record_runner_cost(
                        runner, 1, runner.length_buckets[0]
                    )
            except Exception:
                pass

        # Non-daemon: a worker told to stop seconds after READY must join
        # this (bounded) analysis rather than let interpreter teardown
        # abort a live XLA compile.
        threading.Thread(
            target=_deferred_cost, name="replica-cost-gauges", daemon=False
        ).start()

    def _sigterm(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        # The pipe sentinel: block until the coordinator closes stdin —
        # on purpose (graceful stop) or by dying (any signal, including
        # SIGKILL, closes the write end). Either way: drain and leave.
        sys.stdin.buffer.read()
    except (KeyboardInterrupt, SystemExit):
        pass
    finally:
        server.stop(drain=True)
        # Final telemetry flush AFTER the drain: the snapshot event this
        # appends to the capture is the process's terminal state —
        # every answered request counted — so a scale-down or restart
        # loses no telemetry even if the coordinator's last HTTP scrape
        # raced the teardown.
        try:
            REGISTRY.flush()
        except Exception:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
