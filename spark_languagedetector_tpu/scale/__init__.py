"""Elastic fleet: real subprocess replicas + telemetry-driven autoscaling.

PR-9's :class:`~..serve.fleet.ServeReplica` is an in-process object
sharing one loaded model — honest for protocol testing, useless for real
capacity. This package goes real (docs/SERVING.md §13):

  * :mod:`.replica` — :class:`ProcessReplica`, a serving replica that is
    its own OS process (own model load, own devices via per-process
    ``JAX_PLATFORMS``/``XLA_FLAGS``, own :class:`~..serve.server.
    ServingServer`), and :class:`ReplicaSupervisor`, which spawns,
    watches, restarts-with-backoff, and reaps them.
  * :mod:`.autoscaler` — the control loop that closes the loop the
    autotuner opened: arrival-rate EMA, queue depth, shed counters, and
    estimated-wait SLO pressure in; replica count out, with hysteresis.
  * :mod:`.elastic` — :class:`ElasticFleet`, wiring supervisor + the
    dynamic-membership :class:`~..serve.router.FleetRouter` together so
    routing, failover, ejection, and re-admission compose unchanged on a
    changing replica set.

The GSPMD/pjit portability result (PAPERS.md: arXiv:2105.04663,
arXiv:2204.06514) is what makes this pure control plane: the per-replica
compiled program is identical at every fleet size, so scale-out never
touches the kernel path — only process lifecycle and router membership.
"""

from .autoscaler import Autoscaler, ScaleSignals
from .elastic import ElasticFleet
from .replica import ProcessReplica, ReplicaSupervisor, SpawnError

__all__ = [
    "Autoscaler",
    "ElasticFleet",
    "ProcessReplica",
    "ReplicaSupervisor",
    "ScaleSignals",
    "SpawnError",
]
