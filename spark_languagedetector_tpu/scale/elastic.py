"""ElasticFleet: subprocess replicas behind the dynamic-membership router.

The composition layer of the elastic fleet (docs/SERVING.md §13): a
:class:`~.replica.ReplicaSupervisor` owns process lifecycle, a
:class:`~..serve.router.FleetRouter` owns routing/health/failover, and
this class owns the mapping between them:

  * **scale-up** — spawn a new replica to readiness (bounded backoff),
    then :meth:`~..serve.router.FleetRouter.add_replica` admits it with a
    fresh breaker; the next probe round makes it eligible.
  * **scale-down** — drain-then-detach: the router stops routing to the
    victim, waits for its outstanding requests, detaches it, and only
    then is the child asked to exit (its own graceful drain answers
    whatever its batcher already accepted) — zero dropped responses by
    construction.
  * **supervised restart** — an abrupt death keeps the member's router
    handle: the prober watches the address fail, the breaker ejects, the
    supervisor restarts the child on its pinned port, and the half-open
    probe re-admits it. Membership only changes on *planned* transitions,
    so failover/ejection/re-admission compose unchanged on a changing
    replica set.

:meth:`signals` aggregates the per-replica admission-queue stats (the
``/healthz`` batcher block: queued/in-flight rows, the admitted-rows
odometer, the dispatch-throughput EMA) into one
:class:`~.autoscaler.ScaleSignals` snapshot — and each round it also
**scrapes every live member's ``/telemetryz``** into the fleet's
:class:`~..telemetry.aggregate.FleetCollector` (fault site
``fleet/scrape``), so the autoscaler's shed pressure is differentiated
from the *fleet aggregate* (replica-side ``serve/shed_requests`` summed
with router-side ``fleet/shed_requests``, terminal scrapes included) —
monotone across restarts and scale-downs by the collector's generation
folding, so no per-member clamping is needed. The aggregate also feeds
the :class:`~..telemetry.slo.SloEvaluator` each round; a burning
objective is an additional scale-up pressure signal. The fleet
**arrival-rate EMA** is differentiated here, coordinator-side, from the
admitted-rows odometers (the same 0.7/0.3 fold the admission queue uses
for its dispatch EMA), so it genuinely decays to zero across silence —
which is what makes the scale-down idleness test honest.
"""

from __future__ import annotations

import threading
import time

from ..exec import config as exec_config
from ..resilience import faults
from ..serve.client import ServeClient
from ..serve.router import FleetRouter
from ..telemetry import REGISTRY
from ..telemetry.aggregate import FleetCollector
from ..telemetry.slo import SloEvaluator
from ..utils.logging import get_logger, log_event
from .autoscaler import ScaleSignals
from .replica import ReplicaSupervisor

_log = get_logger("scale.elastic")


class ElasticFleet:
    """N subprocess replicas behind one router, with elastic membership.

    ``replicas`` is the initial (and minimum sensible) member count —
    default the ``scale_min`` knob. Construction reaps orphans (via the
    supervisor), spawns the initial members to readiness, and builds the
    router over them; :meth:`start` begins probing.
    """

    def __init__(
        self,
        model_path: str,
        *,
        replicas: int | None = None,
        host: str = "127.0.0.1",
        platform: str = "cpu",
        fleet_name: str = "fleet",
        pidfile_dir: str | None = None,
        router_kw: dict | None = None,
        prewarm: bool = True,
        joiner_prewarm: bool | None = None,
        spawn_timeout_s: float | None = None,
        stats_timeout_s: float = 2.0,
        child_env: dict | None = None,
        metrics_dir: str | None = None,
        slo: SloEvaluator | None = None,
        compile_cache_dir: str | None = None,
        artifact: str | None = None,
        tuning_profile: str | None = None,
    ):
        self.supervisor = ReplicaSupervisor(
            model_path, host=host, platform=platform,
            fleet_name=fleet_name, pidfile_dir=pidfile_dir,
            prewarm=prewarm, spawn_timeout_s=spawn_timeout_s,
            child_env=child_env, metrics_dir=metrics_dir,
            # Cold-start plane passthrough: every member (founders and
            # autoscaler joiners alike) boots against the shared compile
            # cache and the baked artifact (docs/PERFORMANCE.md §12).
            compile_cache_dir=compile_cache_dir, artifact=artifact,
            tuning_profile=tuning_profile,
        )
        self._host = host
        # Scale-up joiners may come up cold (compile folded into their
        # first dispatch rather than the spawn-to-READY latency the
        # autoscaler is waiting out); None inherits ``prewarm``.
        self._joiner_prewarm = joiner_prewarm
        self._stats_timeout_s = stats_timeout_s
        self._scale_lock = threading.Lock()
        self._name_seq = 0
        initial = int(exec_config.resolve("scale_min", replicas))
        members = []
        for _ in range(initial):
            members.append(self.supervisor.spawn(self._next_name()))
        self.router = FleetRouter(members, **(router_kw or {}))
        self.target = initial
        self._stats_clients: dict[str, ServeClient] = {}
        # The fleet observability plane (docs/OBSERVABILITY.md §14): the
        # collector accumulates every member's /telemetryz (terminal
        # scrapes retained across scale-downs and restarts), the SLO
        # evaluator rides its aggregate. Both attach to the RouterServer
        # front for /varz + /healthz.
        self.collector = FleetCollector(local_name="router")
        self.slo = SloEvaluator() if slo is None else slo
        # Per-member arrival baselines (restart-aware) + the aggregate
        # shed baseline: delta, not level, is the pressure signal. The
        # aggregate is monotone by collector construction, but the
        # coordinator's process-global registry may carry counts from an
        # earlier fleet in this process — baseline them away.
        self._admitted_seen: dict[str, int] = {}
        self._agg_sheds_seen = self._aggregate_sheds()
        self._arrival_ema: float | None = None
        self._last_signals_t: float | None = None
        REGISTRY.set_gauge(
            "langdetect_fleet_live_replicas", float(len(members))
        )
        REGISTRY.set_gauge(
            "langdetect_fleet_target_replicas", float(self.target)
        )
        log_event(
            _log, "scale.fleet.start", replicas=initial,
            pidfile_dir=self.supervisor.pidfile_dir,
        )

    def _next_name(self) -> str:
        name = f"r{self._name_seq}"
        self._name_seq += 1
        return name

    # ------------------------------------------------------------ lifecycle --
    def start(self, *, probe: bool = True) -> "ElasticFleet":
        self.router.start(probe=probe)
        return self

    def close(self, *, drain: bool = True) -> None:
        self.router.close()
        self.supervisor.close(drain=drain)

    def __enter__(self) -> "ElasticFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def live_count(self) -> int:
        return self.supervisor.live_count()

    # ------------------------------------------------------------ membership --
    def scale_to(self, n: int) -> int:
        """Grow or shrink membership to ``n`` live replicas; returns the
        resulting target. Spawn failures raise (after the bounded
        backoff) with the target reflecting what actually happened —
        the autoscaler simply tries again on a later tick."""
        with self._scale_lock:
            while self.target < n:
                self._add_one_locked()
            while self.target > n:
                self._remove_one_locked()
            return self.target

    def _add_one_locked(self) -> None:
        name = self._next_name()
        rep = self.supervisor.spawn(name, prewarm=self._joiner_prewarm)
        self.router.add_replica(rep, name=name)
        self.target += 1
        REGISTRY.incr("scale/ups")
        REGISTRY.set_gauge(
            "langdetect_fleet_live_replicas", float(self.live_count())
        )
        log_event(
            _log, "scale.up", replica=name, port=rep.address[1],
            target=self.target,
        )

    def _remove_one_locked(self) -> None:
        victim = self._newest_member()
        if victim is None:
            self.target = self.live_count()
            return
        # Drain-then-detach, then ask the child to leave gracefully: the
        # router half stops NEW traffic and waits out routed requests;
        # the child half (stdin EOF) drains whatever its batcher already
        # accepted. Neither half can drop an accepted request. The down
        # is COMMITTED the moment the router detaches — a failure in the
        # child's cleanup must not leave target above live forever (the
        # autoscaler would defer on the phantom member for the rest of
        # its life); the stop escalates SIGTERM→SIGKILL internally and
        # the atexit reaper is the last-ditch backstop.
        self.router.remove_replica(victim, drain=True)
        self.target -= 1
        REGISTRY.incr("scale/downs")
        # Terminal scrape between the router drain (every routed request
        # answered, counters final) and the child's exit: the victim's
        # telemetry folds into the collector's retained base, so the
        # scale-down loses no counters (the worker's own exit-flush into
        # its JSONL capture is the belt to this suspender).
        rep = self.supervisor.members.get(victim)
        if rep is not None and rep.alive:
            host, port = rep.address
            self.collector.scrape(
                victim,
                self._member_client(victim, host, port).telemetryz,
            )
        self.collector.retire(victim)
        self._stats_clients.pop(victim, None)
        self._admitted_seen.pop(victim, None)
        try:
            self.supervisor.stop(victim, drain=True)
        except Exception as e:
            log_event(
                _log, "scale.down_stop_error", replica=victim,
                error=repr(e),
            )
        REGISTRY.set_gauge(
            "langdetect_fleet_live_replicas", float(self.live_count())
        )
        log_event(_log, "scale.down", replica=victim, target=self.target)

    def _newest_member(self) -> str | None:
        """Scale-down victim: the newest member (highest sequence) — the
        oldest replicas hold the longest-lived caches and the most
        settled breaker history, so capacity leaves in LIFO order."""
        with self.supervisor._lock:
            names = [
                name for name in self.supervisor.members
                if name not in self.supervisor._retired
                and name not in self.supervisor._failed
            ]
        if not names:
            return None
        return max(names, key=lambda n: int(n.lstrip("r") or 0))

    def check_members(self) -> list[str]:
        """One supervision round. Restarts keep the member's router
        handle (the breaker machinery re-admits); a member past its
        restart budget is detached from routing and the target drops —
        the autoscaler's min-floor repair spawns a fresh replacement."""
        events = self.supervisor.poll_once()
        for ev in events:
            name, _, what = ev.partition(":")
            # Every supervision event here began as a detected death
            # (restarted or gave up): charge the signature last routed
            # to that member in the query-of-death table, so a poison
            # request that kills subprocess replicas out-of-band (the
            # router never saw a connection drop) still hits its K-death
            # quarantine bound (docs/RESILIENCE.md §7).
            self.router.quarantine.replica_died(name, source="supervisor")
            if what == "gave_up":
                try:
                    self.router.remove_replica(name, drain=False)
                except ValueError:
                    pass
                # Fully forgotten: a gave-up member must never be chosen
                # as a later scale-down victim (its router handle is
                # already gone — removing it again would wedge the
                # shrink path on a ValueError forever).
                self.supervisor.forget(name)
                with self._scale_lock:
                    self.target = max(0, self.target - 1)
                # The process is gone (no farewell scrape possible);
                # retiring retains whatever its last scrape carried.
                self.collector.retire(name)
                self._stats_clients.pop(name, None)
                self._admitted_seen.pop(name, None)
        if events:
            REGISTRY.set_gauge(
                "langdetect_fleet_live_replicas", float(self.live_count())
            )
        return events

    # -------------------------------------------------------------- signals --
    def _aggregate_sheds(self) -> float:
        # The fleet-aggregate shed odometer: replica-side admission sheds
        # plus router-side routing sheds, summed out of the collector
        # (retained generations + live scrapes + the coordinator's own
        # registry). Monotone by construction, so the pressure delta is
        # a plain subtraction — no per-member restart clamping.
        return (
            self.collector.counter("fleet/shed_requests")
            + self.collector.counter("serve/shed_requests")
        )

    def collect_telemetry(self) -> None:
        """Scrape every live member's ``/telemetryz`` into the collector
        (one round of the fleet observability plane; rides every
        :meth:`signals` call). Each scrape runs under the
        ``fleet/scrape`` fault site — an injected failure is counted
        (``fleet/agg_scrape_failures``) exactly like a real mid-death
        member, never propagated into the tick loop."""
        with self.supervisor._lock:
            members = [
                (name, rep)
                for name, rep in self.supervisor.members.items()
                if name not in self.supervisor._retired
            ]
        for name, rep in members:
            if not rep.alive:
                continue
            host, port = rep.address
            client = self._member_client(name, host, port)

            def fetch(client=client):
                faults.inject("fleet/scrape")
                return client.telemetryz()

            self.collector.scrape(name, fetch)
        self.collector.freshness_s()

    def _member_client(self, name: str, host: str, port: int) -> ServeClient:
        client = self._stats_clients.get(name)
        if client is None or client.port != port:
            client = ServeClient(host, port, timeout_s=self._stats_timeout_s)
            self._stats_clients[name] = client
        return client

    def signals(self) -> ScaleSignals:
        """Aggregate the autoscaler's inputs across the live fleet.

        ``ema_rows_per_s`` is the fleet arrival-rate EMA (differentiated
        from the admitted-rows odometers, so it decays across silence);
        ``est_wait_ms`` is backlog over the summed per-replica dispatch-
        throughput EMAs — the same estimate each admission queue sheds
        on, fleet-wide. ``shed_delta`` differentiates the **fleet
        telemetry aggregate** (one scrape round runs first), and
        ``slo_burning`` carries the burn-rate verdict over the same
        aggregate."""
        self.collect_telemetry()
        with self.supervisor._lock:
            members = [
                (name, rep) for name, rep in self.supervisor.members.items()
                if name not in self.supervisor._retired
            ]
        live = 0
        queued = inflight = 0
        service_ema = 0.0
        arrivals = 0
        for name, rep in members:
            if not rep.alive:
                continue
            host, port = rep.address
            try:
                health = self._member_client(name, host, port).healthz()
            except Exception:
                continue  # mid-death: the supervisor round handles it
            live += 1
            stats = health.get("batcher") or {}
            queued += int(stats.get("queued_rows", 0))
            inflight += int(stats.get("inflight_rows", 0))
            service_ema += float(stats.get("ema_rows_per_s", 0.0))
            # A restarted child restarts its odometer: clamp the delta
            # at the fresh count so the reset never reads as negative
            # arrivals. (Shed deltas no longer need this dance — the
            # collector's generation folding keeps the aggregate
            # monotone.)
            admitted = int(stats.get("admitted_rows", 0))
            seen_rows = self._admitted_seen.get(name, 0)
            arrivals += (
                admitted - seen_rows if admitted >= seen_rows else admitted
            )
            self._admitted_seen[name] = admitted
        agg_sheds = self._aggregate_sheds()
        shed_delta = max(0, int(agg_sheds - self._agg_sheds_seen))
        self._agg_sheds_seen = agg_sheds
        aggregate = self.collector.aggregate()
        slo_status = self.slo.ingest(aggregate)
        now = time.monotonic()
        if self._last_signals_t is not None and now > self._last_signals_t:
            rate = arrivals / (now - self._last_signals_t)
            self._arrival_ema = (
                rate if self._arrival_ema is None
                else 0.7 * self._arrival_ema + 0.3 * rate
            )
        self._last_signals_t = now
        router_health = self.router.healthz()
        breaker_open = any(
            h["breaker"] != "closed" for h in router_health["replicas"]
        )
        sig = ScaleSignals(
            live=live,
            ready=len(router_health["ready_replicas"]),
            queued_rows=queued,
            inflight_rows=inflight,
            ema_rows_per_s=self._arrival_ema or 0.0,
            est_wait_ms=(
                queued / service_ema * 1e3 if service_ema > 0
                else (0.0 if queued == 0 else float("inf"))
            ),
            shed_delta=shed_delta,
            breaker_open=breaker_open,
            slo_burning=bool(slo_status.get("burning")),
        )
        REGISTRY.set_gauge("langdetect_fleet_live_replicas", float(live))
        return sig

    # -------------------------------------------------------------- status ---
    def healthz(self) -> dict:
        out = self.router.healthz()
        out["target_replicas"] = self.target
        out["live_replicas"] = self.live_count()
        out["pidfile_dir"] = self.supervisor.pidfile_dir
        slo = self.slo.status()
        out["slo"] = slo
        if slo["burning"]:
            out["reasons"] = list(out.get("reasons") or []) + slo["reasons"]
        out["telemetry"] = {
            "members": self.collector.members(),
            "scrapes": self.collector.scrapes,
            "scrape_failures": self.collector.scrape_failures,
            "freshness_s": round(self.collector.freshness_s(), 3),
        }
        return out
