"""TPU-native byte-n-gram language-identification framework.

Brand-new implementation of the capabilities of
``leifblaese/spark-languagedetector`` (reference mounted at
``/root/reference``), designed for JAX/XLA on TPU: fixed-shape byte batches,
integer gram vocabularies, gather/matmul scoring on device, mesh-sharded
distributed fit, and a Spark-ML-style Estimator/Model API on top.

Public API::

    from spark_languagedetector_tpu import (
        LanguageDetector, LanguageDetectorModel, Language, Table,
        LowerCasePreprocessor, SpecialCharPreprocessor,
    )
"""

from .api.pipeline import Pipeline, PipelineModel
from .api.table import Schema, Table
from .models.language import ISO_LANGUAGE_CODES, Language

__version__ = "0.4.0"

__all__ = [
    "ISO_LANGUAGE_CODES",
    "Language",
    "LanguageDetector",
    "LanguageDetectorModel",
    "LowerCasePreprocessor",
    "Pipeline",
    "PipelineModel",
    "Schema",
    "SpecialCharPreprocessor",
    "Table",
    "init_distributed",
]


def __getattr__(name):
    # Lazy imports keep `import spark_languagedetector_tpu` light (no jax
    # device init) until an estimator/model/preprocessor is actually used.
    if name in ("LanguageDetector", "LanguageDetectorModel"):
        from .models import estimator

        return getattr(estimator, name)
    if name in ("LowerCasePreprocessor", "SpecialCharPreprocessor"):
        from .models import preprocessing

        return getattr(preprocessing, name)
    if name == "init_distributed":
        # Multi-host entry point: call once per host process before building
        # estimators/models; after it, every visible device (all hosts)
        # participates in meshes and `backend="mesh"` scoring / device fit
        # span the slice. No-op in single-process runs, so scripts can call
        # it unconditionally. Args/env: see parallel.distributed.initialize.
        from .parallel.distributed import initialize

        return initialize
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
