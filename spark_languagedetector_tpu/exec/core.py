"""The execution core: one scheduler under batch, stream, and serve.

Three front ends used to re-implement the same machinery independently —
``BatchRunner._execute`` planned micro-batches inline, ``stream/microbatch``
kept its own prefetch deque, ``serve/batcher`` its own admission queue, and
the byte-budget row sizing existed twice (``api.runner.rows_for_bucket`` /
``ops.fit_pipeline.rows_for_fit_bucket``). The pjit/TPUv4 serving lesson
(arXiv:2204.06514) and GSPMD (arXiv:2105.04663) both reduce to the same
economics: a small closed set of compiled shapes reused forever, which makes
the admission/bucketing layer the real throughput ceiling. This module is
that layer, once:

  * :func:`rows_under_byte_budget` / :func:`rows_for_bucket` — the single
    byte-budget row-sizing policy (moved here from ``ops.encoding``; the
    runner and the fit pipeline re-export it);
  * :func:`plan_micro_batches` — the bucket-group / carry / ragged-tail
    micro-batch planner shared by the scoring runner and the device fit;
  * :func:`run_ordered` — the serial-or-threaded plan executor (the batch
    path's dispatch loop);
  * :func:`ordered_prefetch` — the bounded, ordered producer/consumer
    pipeline under both the streaming engine's prefetch path and the fit
    ingest's packer;
  * :func:`guarded_dispatch` — the breaker-gated fast path + classified
    retry + degraded-ladder hand-off (docs/RESILIENCE.md) the runner's
    dispatch rides;
  * :class:`AdmissionQueue` — priority lanes, bounded rows, flush-window
    coalescing and explicit shedding behind ``serve/batcher``.

Everything here is host-side policy: no jax imports, no device work. The
knobs these pieces consume resolve through :mod:`.config` (explicit ctor
values > env > tuning profile > built-in default), and the offline
:mod:`.tune` CLI replays a telemetry capture to pick the profile values.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Iterable, Iterator, Sequence

import numpy as np

from ..telemetry import REGISTRY

# Re-exported bucket helper: the lattice membership function is an encoding
# concept (ops.encoding defines the default lattice too); the planner here
# is its only policy consumer.
from ..ops.encoding import bucket_length  # noqa: F401


# ------------------------------------------------------- byte-budget math ---
def rows_under_byte_budget(
    pad_to: int, byte_budget: int, max_rows: int, floor: int = 64
) -> int:
    """Micro-batch rows for a padded width: ``max_rows`` halved until the
    padded transfer fits ``byte_budget``, never below ``floor``. The single
    halving policy shared by the scoring runner (``batch_bytes``) and the
    fit pipeline (``fit_batch_bytes``), so the two paths' compile-shape
    lattices can't drift. Halving (not dividing) keeps the (rows, pad_to)
    set a small closed lattice — only power-of-two fractions of the cap
    ever compile."""
    rows = max_rows
    while rows * pad_to > byte_budget and rows > floor:
        rows //= 2
    return rows


# ------------------------------------------------------ in-flight dedup -----
def dedup_items(keys: Sequence) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Content-addressed in-flight dedup over one call's work items.

    ``keys`` are hashable content keys (the document bytes themselves for
    scoring; ``(doc, lang)`` pairs for the fit) — dict hashing + equality
    makes the match exact by construction, with no digest-collision risk
    and no per-item Python hash code beyond what ``dict`` already does at
    C speed. Returns ``None`` when every key is distinct (callers skip
    the scatter entirely and pay nothing but the dict build — the
    documented ≤3% all-unique overhead), else
    ``(first_idx, inverse, mult)``:

      * ``first_idx`` — int64 indices of each key's first occurrence, in
        first-seen order (the unique work list is ``[items[i] for i in
        first_idx]``);
      * ``inverse``   — int64 [N] with ``keys[i] == keys[first_idx[inverse[i]]]``
        — the deterministic scatter-back map (``out = unique_out[inverse]``
        restores input order exactly);
      * ``mult``      — int64 multiplicity per unique key (the fit path's
        count weight; scoring ignores it).
    """
    n = len(keys)
    # All-unique fast path at C speed: one set build instead of the
    # Python-level mapping loop below. This is the branch every
    # duplicate-free call takes, so it IS the dedup layer's overhead —
    # ~10x cheaper than the full loop (the ≤3% end-to-end bound in
    # bench --smoke-cache leans on it). The set also warms each key's
    # cached hash, so the duplicate path's dict loop rehashes nothing.
    if len(set(keys)) == n:
        return None
    index: dict = {}
    inverse = np.empty(n, dtype=np.int64)
    first: list[int] = []
    mult: list[int] = []
    for i, key in enumerate(keys):
        j = index.setdefault(key, len(first))
        if j == len(first):
            first.append(i)
            mult.append(1)
        else:
            mult[j] += 1
        inverse[i] = j
    return (
        np.asarray(first, dtype=np.int64),
        inverse,
        np.asarray(mult, dtype=np.int64),
    )


def dedup_counted(keys: Sequence, size_of: Callable = len):
    """:func:`dedup_items` plus the shared telemetry contract.

    The ``dedup/rows_in`` / ``dedup/rows_unique`` / ``dedup/bytes_saved``
    counters and the ``dedup/unique_ratio`` distribution are a cross-path
    contract — ``telemetry/compare`` derives its tracked unique-ratio from
    them and ``exec.tune`` sizes the serve cache off them — so the scoring
    runner and the fit planner record them through this one helper instead
    of keeping two copies that could drift. ``size_of`` maps a key to its
    payload byte length (what ``bytes_saved`` measures); it is only
    evaluated on the duplicate path, keeping the all-unique fast path at
    one set build + three counter bumps (the ≤3% end-to-end bound)."""
    n = len(keys)
    d = dedup_items(keys)
    REGISTRY.incr("dedup/rows_in", n)
    if d is None:
        REGISTRY.incr("dedup/rows_unique", n)
        REGISTRY.observe("dedup/unique_ratio", 1.0)
        return None
    first_idx = d[0]
    REGISTRY.incr("dedup/rows_unique", len(first_idx))
    REGISTRY.incr(
        "dedup/bytes_saved",
        sum(size_of(k) for k in keys)
        - sum(size_of(keys[int(i)]) for i in first_idx),
    )
    REGISTRY.observe("dedup/unique_ratio", len(first_idx) / n)
    return d


# ------------------------------------------------------- micro-batch plan ---
def plan_micro_batches(
    sizes: Sequence[int],
    *,
    length_buckets: Sequence[int],
    rows_for: Callable[[int], int],
    order: Sequence[int] | None = None,
) -> list[tuple[np.ndarray, int]]:
    """The shared micro-batch plan: group work items by padded-length
    bucket, emit ``rows_for(pad_to)``-row batches per bucket, and carry
    each bucket's ragged remainder into the next wider bucket so the whole
    plan ends with at most one ragged tail batch (padding a few items up
    one bucket is far cheaper than an extra dispatch + compile shape).

    ``sizes`` are the item byte lengths; ``order`` is the iteration order
    (the scoring runner passes input order, the fit pipeline a stable
    length sort). Returns ``[(sel indices ndarray, pad_to), ...]`` with
    every ``pad_to`` a member of ``length_buckets`` — callers chunk-split
    oversized items beforehand, so no per-width recompiles ever happen.
    """
    idx_iter: Iterable[int] = range(len(sizes)) if order is None else order
    by_bucket: dict[int, list[int]] = {}
    for i in idx_iter:
        b = bucket_length(sizes[i] or 1, length_buckets)
        by_bucket.setdefault(b, []).append(int(i))
    plan: list[tuple[np.ndarray, int]] = []
    carry: list[int] = []
    for pad_to in sorted(by_bucket):
        idxs = carry + by_bucket[pad_to]
        rows = rows_for(pad_to)
        full_end = len(idxs) - len(idxs) % rows
        for start in range(0, full_end, rows):
            plan.append((np.asarray(idxs[start : start + rows]), pad_to))
        carry = idxs[full_end:]
    if carry:
        pad_to = bucket_length(
            max(sizes[i] for i in carry) or 1, length_buckets
        )
        rows = rows_for(pad_to)
        for start in range(0, len(carry), rows):
            plan.append((np.asarray(carry[start : start + rows]), pad_to))
    return plan


def run_ordered(plan: Sequence, fn: Callable, workers: int) -> list:
    """Run ``fn`` over every planned item, results in plan order.

    ``workers > 1`` overlaps one item's host work (pack + device_put
    round-trips release the GIL) with another's — the batch path's
    dispatch loop. Serial when the plan is short or one worker suffices.
    """
    workers = max(1, min(int(workers), len(plan)))
    if workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as ex:
            return list(ex.map(fn, plan))
    return [fn(item) for item in plan]


# ------------------------------------------------------- ordered prefetch ---
def ordered_prefetch(
    it: Iterable,
    fn: Callable,
    *,
    depth: int = 0,
    workers: int = 1,
    abort_wait: bool = True,
) -> Iterator[tuple[object, Callable, bool, int]]:
    """Bounded, ordered producer/consumer pipeline over ``it``.

    Yields ``(item, thunk, prefetched, pending)`` per source item, in
    source order; ``thunk()`` returns (or raises) ``fn(item)``'s result.
    With ``depth == 0`` nothing runs ahead — ``thunk`` computes inline
    when called (the caller keeps its synchronous semantics and its own
    spans/timers around the call). With ``depth > 0``, up to ``depth``
    items beyond the yielded one are in flight on ``workers`` threads,
    and items are pulled from ``it`` at most ``depth + 1`` ahead of the
    consumer — a consuming source (Kafka) never loses more than the
    pipeline depth on a crash, exactly the old deque's bound.

    ``pending`` counts the in-flight items *including* the yielded one
    (the streaming engine's queue-depth signal). Closing the generator
    cancels not-yet-started work; with ``abort_wait`` (the default) it
    also joins the pool, so a consumer exception leaves no worker behind
    and the next run's device dispatches can't interleave with a
    leftover one's — required wherever dispatch order matters (the
    streaming engine; multi-process meshes enqueue collectives in
    lockstep). ``abort_wait=False`` returns without joining a possibly
    wedged worker (the fit packer's choice: an h2d put stuck on a dead
    link must not turn a fit abort into a hang; the orphan is joined at
    interpreter exit).
    """
    it = iter(it)
    if depth <= 0:
        for item in it:
            yield item, (lambda item=item: fn(item)), False, 1
        return
    from concurrent.futures import ThreadPoolExecutor

    ex = ThreadPoolExecutor(max_workers=max(1, workers))
    in_flight: deque = deque()
    drained = False
    try:
        while True:
            while len(in_flight) <= depth:
                try:
                    item = next(it)
                except StopIteration:
                    break
                in_flight.append((item, ex.submit(fn, item)))
            if not in_flight:
                drained = True
                return
            pending = len(in_flight)
            item, fut = in_flight.popleft()
            yield item, fut.result, True, pending
    finally:
        # Drained normally: the pool is idle, a waiting shutdown is
        # instant. Aborted: cancel what hasn't started, and join (or
        # not) per ``abort_wait`` — see the docstring.
        ex.shutdown(wait=drained or abort_wait, cancel_futures=True)
        # Drop queued (item, future) pairs deterministically: zero-copy
        # producers hand out views into caller-owned buffers (Arrow pools,
        # DocBlock planes), and a generator closed mid-stream must not pin
        # them until the GC gets around to the deque.
        in_flight.clear()


# --------------------------------------------------- retry/degrade wiring ---
def guarded_dispatch(
    fast: Callable[[], object],
    *,
    policy,
    site: str,
    breaker=None,
    degraded: Callable[[BaseException | None], object] | None = None,
    on_retry=None,
    on_recovered: Callable[[], None] | None = None,
    log_fields: dict | None = None,
):
    """The shared failure wiring around one dispatch (docs/RESILIENCE.md):
    breaker-gated fast path under the classified retry ``policy``, then the
    ``degraded`` ladder.

    With ``degraded=None`` (multi-process meshes, or the fallback disabled)
    only the policy replay applies — deterministic plans replay in lockstep
    on every process, but a per-process fallback would desynchronize the
    collective schedule, so there is none. Otherwise: while the breaker
    admits, the fast path runs under the policy; a retryable exhaustion
    falls through to ``degraded(cause)``; a success after degraded batches
    calls ``on_recovered`` once the breaker agrees the path is healthy. An
    open breaker short-circuits straight to the ladder
    (``resilience/breaker_short_circuit``).
    """
    if degraded is None:
        return policy.run(
            fast, site=site, on_retry=on_retry, log_fields=log_fields
        )
    cause: BaseException | None = None
    if breaker is None or breaker.allow():
        try:
            result = policy.run(
                fast,
                site=site,
                breaker=breaker,
                on_retry=on_retry,
                log_fields=log_fields,
            )
        except Exception as e:
            if not policy.classify(e):
                raise
            cause = e
        else:
            if on_recovered is not None:
                on_recovered()
            return result
    else:
        REGISTRY.incr("resilience/breaker_short_circuit")
    return degraded(cause)


# --------------------------------------------------------- admission queue --
class AdmissionQueue:
    """Priority-lane admission queue with flush-window coalescing and
    explicit shedding — the serving front end's half of the core
    (``serve/batcher`` wraps it; the semantics are pinned by
    ``tests/test_serve.py``).

    Items are admitted into lanes (drained in ``lanes`` order — a bulk
    backlog must never delay an interactive request) and popped as one
    coalesced batch by :meth:`next_batch`: the flush fires when
    ``max_rows`` are queued or the oldest admitted item has waited
    ``max_wait_s``. Backpressure is reject-newest and explicit —
    :meth:`admit` returns a shed reason (queue past ``max_queue_rows``,
    estimated wait past ``slo_s``, or the caller's ``shed_probe``) instead
    of queueing into a blown SLO. One consumer thread is assumed (the
    dispatcher); any number of producers may admit concurrently.
    """

    def __init__(
        self,
        *,
        max_rows: int,
        max_wait_s: float,
        max_queue_rows: int,
        slo_s: float = 0.0,
        lanes: Sequence[str] = ("interactive", "bulk"),
        shed_probe: Callable[[str], str | None] | None = None,
        on_change: Callable[[int, int], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_rows < 1 or max_queue_rows < 1:
            raise ValueError("max_rows and max_queue_rows must be >= 1")
        self.max_rows = int(max_rows)
        self.max_wait_s = float(max_wait_s)
        self.max_queue_rows = int(max_queue_rows)
        self.slo_s = float(slo_s)
        self.lanes = tuple(lanes)
        self._shed_probe = shed_probe
        self._on_change = on_change
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # lane -> deque of (item, rows, admitted_at)
        self._queues: dict[str, deque] = {p: deque() for p in self.lanes}
        self.queued_rows = 0
        self.inflight_rows = 0
        # Cumulative rows ever admitted: the monotone arrival odometer a
        # poller (the elastic-fleet autoscaler) differentiates into an
        # arrival rate — the dispatch-throughput EMA below cannot serve
        # that role, since it holds its last value across silence.
        self.admitted_rows = 0
        # Rows/s over recent dispatches (EMA): the estimated-wait shed
        # signal. Zero until the first dispatch lands.
        self.ema_rows_per_s = 0.0
        self.closed = False
        # Queue-local shed accounting (everything except "closed", which
        # is lifecycle, not backpressure). The process-global REGISTRY
        # counters aggregate across queues; these per-queue tallies are
        # what lets a multi-tenant front end attribute sheds to the ONE
        # queue that rejected (docs/SERVING.md §12: a noisy tenant's
        # burst must show up on that tenant's queue and nowhere else).
        self.shed_requests = 0
        self.shed_rows = 0
        self.shed_reasons: dict[str, int] = {}

    # ------------------------------------------------------------- admit ----
    def admit(self, item, rows: int, lane: str) -> tuple[str | None, float]:
        """Atomically admit one item, or return why it was shed.

        Returns ``(None, est_wait_s)`` on admission, else
        ``(reason, est_wait_s)`` with the item NOT queued. Reasons:
        ``"closed"``, ``"queue_full"``, ``"slo"``, or whatever the
        ``shed_probe`` returned for this lane. Reject-newest: queued work
        is never evicted."""
        if lane not in self._queues:
            raise ValueError(
                f"unknown lane {lane!r}; expected one of {self.lanes}"
            )
        with self._cv:
            if self.closed:
                return "closed", 0.0
            backlog = self.queued_rows + self.inflight_rows
            wait_s = (
                backlog / self.ema_rows_per_s
                if self.ema_rows_per_s > 0
                else 0.0
            )
            if self.queued_rows + rows > self.max_queue_rows:
                return self._shed_locked("queue_full", rows), wait_s
            if self.slo_s > 0 and wait_s > self.slo_s:
                return self._shed_locked("slo", rows), wait_s
            if self._shed_probe is not None:
                reason = self._shed_probe(lane)
                if reason is not None:
                    return self._shed_locked(reason, rows), wait_s
            self._queues[lane].append((item, int(rows), self._clock()))
            self.queued_rows += rows
            self.admitted_rows += rows
            self._notify_change_locked()
            self._cv.notify_all()
        return None, wait_s

    def _shed_locked(self, reason: str, rows: int) -> str:
        """Tally one shed in the queue-local accounting (caller holds the
        lock) and hand the reason back for the admit return."""
        self.shed_requests += 1
        self.shed_rows += rows
        self.shed_reasons[reason] = self.shed_reasons.get(reason, 0) + 1
        return reason

    def _notify_change_locked(self) -> None:
        if self._on_change is not None:
            depth = sum(len(q) for q in self._queues.values())
            self._on_change(depth, self.queued_rows)

    def _oldest_locked(self) -> float | None:
        ages = [q[0][2] for q in self._queues.values() if q]
        return min(ages) if ages else None

    def _take_locked(self, key) -> list:
        """Pop one coalesced batch: lanes in priority order, whole items
        only, until ``max_rows`` is reached (the first item is always
        taken, even when larger). ``key(item)`` partitions items that
        cannot share a dispatch — a key flip at a lane front ends the
        batch there (it leads the next one)."""
        batch: list = []
        rows = 0
        lead_key = None
        for lane in self.lanes:
            q = self._queues[lane]
            while q and (rows < self.max_rows or not batch):
                if key is not None:
                    k = key(q[0][0])
                    if batch and k != lead_key:
                        break
                    lead_key = k
                item, item_rows, _ = q.popleft()
                batch.append(item)
                rows += item_rows
        self.queued_rows -= rows
        self.inflight_rows = rows
        self._notify_change_locked()
        return batch

    # -------------------------------------------------------------- take ----
    def next_batch(self, *, key: Callable | None = None) -> list | None:
        """Block until a coalesced batch is due, pop and return it; None
        once the queue is closed and drained. The coalescing window is the
        micro-batch analog of Nagle, bounded by the flush knobs: hold
        until ``max_rows`` are queued or the oldest item has waited
        ``max_wait_s`` (or the queue closes)."""
        while True:
            with self._cv:
                while self.queued_rows == 0 and not self.closed:
                    self._cv.wait()
                if self.queued_rows == 0 and self.closed:
                    return None
                while self.queued_rows < self.max_rows:
                    oldest = self._oldest_locked()
                    if oldest is None:
                        break
                    remaining = oldest + self.max_wait_s - self._clock()
                    if remaining <= 0 or self.closed:
                        break
                    self._cv.wait(remaining)
                if self.queued_rows == 0:
                    continue
                return self._take_locked(key)

    def done(self) -> None:
        """Mark the in-flight batch settled (the consumer calls this after
        every dispatch, success or failure)."""
        with self._cv:
            self.inflight_rows = 0
            self._cv.notify_all()

    def record_rate(self, rows: int, seconds: float) -> None:
        """Fold one dispatch's throughput into the shed-signal EMA."""
        if seconds <= 0:
            return
        rate = rows / seconds
        with self._lock:
            self.ema_rows_per_s = (
                rate
                if self.ema_rows_per_s == 0.0
                else 0.7 * self.ema_rows_per_s + 0.3 * rate
            )

    # ------------------------------------------------------------- admin ----
    def close(self, drain: bool = True) -> list:
        """Stop admitting. With ``drain`` the queued items stay for the
        consumer; otherwise they are evicted and returned so the caller
        can fail them explicitly (never a silent drop)."""
        evicted: list = []
        with self._cv:
            self.closed = True
            if not drain:
                for q in self._queues.values():
                    while q:
                        item, rows, _ = q.popleft()
                        self.queued_rows -= rows
                        evicted.append(item)
                self._notify_change_locked()
            self._cv.notify_all()
        return evicted

    def stats(self) -> dict:
        with self._lock:
            return {
                "queue_depth": sum(len(q) for q in self._queues.values()),
                "queued_rows": self.queued_rows,
                "inflight_rows": self.inflight_rows,
                "admitted_rows": self.admitted_rows,
                "ema_rows_per_s": round(self.ema_rows_per_s, 3),
                "max_rows": self.max_rows,
                "max_wait_ms": self.max_wait_s * 1e3,
                "max_queue_rows": self.max_queue_rows,
                "slo_ms": self.slo_s * 1e3,
                "closed": self.closed,
                "shed_requests": self.shed_requests,
                "shed_rows": self.shed_rows,
                "shed_reasons": dict(self.shed_reasons),
            }
