"""Versioned tuning profiles: the autotuner's output, the config's input.

A :class:`TuningProfile` is the durable artifact ``exec.tune`` emits after
replaying a telemetry capture — the measured defaults that replace the
hand-set ``LANGDETECT_*`` knob zoo for one deployment. Runner, stream, and
serve load it at startup through :mod:`.config` (point
``LANGDETECT_TUNING_PROFILE`` at the JSON file); explicit env/ctor values
still win, so a profile can never override an operator's pinned choice.

The file is plain JSON with a schema version so a profile written by one
release refuses to half-load in another:

    {
      "schema": 1,
      "version": "tp1-<content hash>",       # deterministic over `tuned`
      "created": <capture end unix ts>,      # from the capture, not wall
      "source": {...capture stats...},       # provenance, never re-read
      "constraints": {...solver knobs...},   # provenance, never re-read
      "tuned": {"length_buckets": [...], "batch_bytes": ..., ...}
    }

Only ``tuned`` keys listed in :data:`TUNED_FIELDS` are honored; unknown
keys fail validation loudly (a typo'd field silently falling back to the
default is exactly the failure mode this module exists to end).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

SCHEMA_VERSION = 1

# Every field a profile may tune, with its validator. The names match the
# config knob names (exec.config.KNOBS) one-to-one — config resolution
# falls back to ``profile.tuned[knob]`` before the built-in default.
TUNED_FIELDS: dict[str, callable] = {}


def _tuned(name):
    def register(fn):
        TUNED_FIELDS[name] = fn
        return fn

    return register


@_tuned("length_buckets")
def _check_buckets(v):
    if (
        not isinstance(v, (list, tuple))
        or not 1 <= len(v) <= 64
        or not all(isinstance(x, int) and x > 0 for x in v)
        or list(v) != sorted(set(v))
    ):
        raise ValueError(
            "length_buckets must be a strictly increasing list of positive "
            f"ints, got {v!r}"
        )
    if any(x % 128 for x in v):
        raise ValueError(
            f"length_buckets must be multiples of 128 (TPU lane tile / "
            f"ragged chunk alignment), got {v!r}"
        )
    return tuple(int(x) for x in v)


def _positive_int(name):
    def check(v):
        if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
            raise ValueError(f"{name} must be a positive int, got {v!r}")
        return v

    return check


def _positive_float(name):
    def check(v):
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
            raise ValueError(f"{name} must be a positive number, got {v!r}")
        return float(v)

    return check


TUNED_FIELDS["batch_bytes"] = _positive_int("batch_bytes")
TUNED_FIELDS["fit_batch_bytes"] = _positive_int("fit_batch_bytes")
TUNED_FIELDS["serve_max_rows"] = _positive_int("serve_max_rows")
TUNED_FIELDS["serve_queue_rows"] = _positive_int("serve_queue_rows")
TUNED_FIELDS["serve_max_wait_ms"] = _positive_float("serve_max_wait_ms")
TUNED_FIELDS["cache_rows"] = _positive_int("cache_rows")
TUNED_FIELDS["cache_bytes"] = _positive_int("cache_bytes")


@_tuned("device_encode")
def _check_device_encode(v):
    if not isinstance(v, bool):
        raise ValueError(f"device_encode must be a bool, got {v!r}")
    return v


@dataclass(frozen=True)
class TuningProfile:
    """One deployment's measured execution defaults (validated)."""

    tuned: dict
    source: dict = field(default_factory=dict)
    constraints: dict = field(default_factory=dict)
    created: float = 0.0
    version: str = ""

    def __post_init__(self):
        clean = {}
        for key, value in dict(self.tuned).items():
            check = TUNED_FIELDS.get(key)
            if check is None:
                raise ValueError(
                    f"unknown tuned field {key!r}; expected a subset of "
                    f"{sorted(TUNED_FIELDS)}"
                )
            clean[key] = check(value)
        object.__setattr__(self, "tuned", clean)
        if not self.version:
            object.__setattr__(self, "version", content_version(clean))

    def get(self, name: str):
        return self.tuned.get(name)

    def to_json(self) -> dict:
        return {
            "schema": SCHEMA_VERSION,
            "version": self.version,
            "created": self.created,
            "source": self.source,
            "constraints": self.constraints,
            "tuned": {
                k: (list(v) if isinstance(v, tuple) else v)
                for k, v in self.tuned.items()
            },
        }

    def save(self, path: str) -> str:
        """Write atomically (temp + rename) so a half-written profile can
        never be loaded at startup."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.to_json(), fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)
        return path

    @staticmethod
    def load(path: str) -> "TuningProfile":
        with open(path, "r", encoding="utf-8") as fh:
            raw = json.load(fh)
        if not isinstance(raw, dict):
            raise ValueError(f"tuning profile {path!r} is not a JSON object")
        schema = raw.get("schema")
        if schema != SCHEMA_VERSION:
            raise ValueError(
                f"tuning profile {path!r} has schema {schema!r}; this "
                f"build reads schema {SCHEMA_VERSION}"
            )
        tuned = raw.get("tuned")
        if not isinstance(tuned, dict) or not tuned:
            raise ValueError(
                f"tuning profile {path!r} carries no tuned fields"
            )
        return TuningProfile(
            tuned=tuned,
            source=raw.get("source") or {},
            constraints=raw.get("constraints") or {},
            created=float(raw.get("created") or 0.0),
            version=str(raw.get("version") or ""),
        )


def content_version(tuned: dict) -> str:
    """Deterministic profile id over the tuned values: two captures that
    solve to the same parameters produce the same version string, so
    rollout diffs are content diffs."""
    blob = json.dumps(
        {k: (list(v) if isinstance(v, tuple) else v) for k, v in tuned.items()},
        sort_keys=True,
    ).encode("utf-8")
    return "tp1-" + hashlib.sha256(blob).hexdigest()[:12]
