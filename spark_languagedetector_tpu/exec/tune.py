"""Offline shape autotuner: replay a telemetry capture, emit a profile.

    python -m spark_languagedetector_tpu.exec.tune telemetry.jsonl \
        [-o profile.json] [--max-shapes N] [--min-width 128] \
        [--max-batch-ms MS] [--p99-ms MS]

The compiled-shape economics (arXiv:2204.06514, arXiv:2105.04663): a small
closed set of shapes, reused forever — so throughput is decided by how well
the admission/bucketing layer fills them. The telemetry stack already
measures exactly the needed signals; this CLI turns one capture into a
versioned :class:`~.profile.TuningProfile` the runner/stream/serve load at
startup (``LANGDETECT_TUNING_PROFILE``), replacing hand-set knobs with
measured defaults:

  * **length buckets** — the capture's chunk-length distribution
    (``exec/len/<edge>`` counters, 64-byte bins) is solved exactly by
    dynamic programming: choose at most ``--max-shapes`` bucket widths
    (multiples of 128 — TPU lane tile / ragged chunk alignment) minimizing
    total padded bytes. Fewer padded bytes = less wire, less compute, less
    padding waste; the DP is exact over the binned distribution, and the
    compile-shape-count constraint is the DP's K.
  * **batch / fit byte budgets** — under ``--max-batch-ms``, the measured
    wire rate (real bytes / scoring wall) bounds the per-transfer budget
    so one micro-batch can't blow the latency target; without the
    constraint the budgets keep their defaults (the capture proves the
    lattice, not the link's ceiling).
  * **serve flush window / rows** — from the observed request arrival
    rate and coalescing distribution: the window is sized so a typical
    burst coalesces to the row bound without holding the oldest request
    past ``--p99-ms`` (half of it, leaving the other half for dispatch).

Everything is deterministic: same capture + same constraints ⇒ the same
profile, version and all (the version hashes the tuned values; ``created``
is the capture's last event timestamp, not wall clock).
"""

from __future__ import annotations

import sys

from ..telemetry.report import load_events
from .profile import TuningProfile, content_version

LEN_BIN_PREFIX = "exec/len/"
LEN_BIN = 64  # recording granularity (api.runner); widths align to 128
WIDTH_ALIGN = 128
DEFAULT_MAX_SHAPES = 11  # len(DEFAULT_LENGTH_BUCKETS): no compile-set growth
DEFAULT_MIN_WIDTH = 128
SERVE_WAIT_FLOOR_MS = 1.0
SERVE_WAIT_CAP_MS = 50.0


# ------------------------------------------------------------- signals ------
def capture_signals(events: list[dict]) -> dict:
    """The tuner's view of one capture: last-snapshot counters and
    histograms, plus the event timestamp range (arrival rates)."""
    counters: dict = {}
    hists: dict = {}
    ts_min = ts_max = None
    for ev in events:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            ts_min = ts if ts_min is None else min(ts_min, ts)
            ts_max = ts if ts_max is None else max(ts_max, ts)
        if ev.get("event") != "telemetry.snapshot":
            continue
        c = ev.get("counters")
        if isinstance(c, dict):
            counters = c
        h = ev.get("histograms")
        if isinstance(h, dict):
            hists = h
    bins: dict[int, int] = {}
    for name, val in counters.items():
        if not isinstance(name, str) or not name.startswith(LEN_BIN_PREFIX):
            continue
        try:
            edge = int(name[len(LEN_BIN_PREFIX):])
        except ValueError:
            continue
        if isinstance(val, (int, float)) and val > 0:
            bins[edge] = bins.get(edge, 0) + int(val)
    return {
        "counters": counters,
        "histograms": hists,
        "len_bins": dict(sorted(bins.items())),
        "span_s": (
            max(0.0, ts_max - ts_min) if ts_min is not None else 0.0
        ),
        "events": len(events),
    }


# ------------------------------------------------------- bucket solver ------
def padded_bytes(bins: dict[int, int], buckets: list[int]) -> int:
    """Total padded bytes the lattice pays for the binned distribution
    (each item pads to the smallest bucket >= its bin's upper edge)."""
    total = 0
    bi = 0
    buckets = sorted(buckets)
    for edge in sorted(bins):
        while bi < len(buckets) and buckets[bi] < edge:
            bi += 1
        width = buckets[min(bi, len(buckets) - 1)]
        total += bins[edge] * max(width, edge if bi >= len(buckets) else 0)
    return total


def solve_buckets(
    bins: dict[int, int],
    *,
    max_shapes: int = DEFAULT_MAX_SHAPES,
    min_width: int = DEFAULT_MIN_WIDTH,
) -> list[int]:
    """Exact DP over the binned length distribution: at most ``max_shapes``
    bucket widths (multiples of :data:`WIDTH_ALIGN`, >= ``min_width``)
    minimizing total padded bytes. O(B^2 * K) over B <= ~128 candidate
    edges — milliseconds."""
    if not bins:
        raise ValueError("capture carries no exec/len/* length distribution")
    # Candidate widths: every observed bin edge rounded up to the
    # alignment (merging counts that land on the same candidate), floored
    # at min_width. The DP picks the subset; the largest candidate must be
    # chosen (something has to cover the longest item).
    merged: dict[int, int] = {}
    for edge, count in bins.items():
        width = max(-(-edge // WIDTH_ALIGN) * WIDTH_ALIGN, min_width)
        merged[width] = merged.get(width, 0) + count
    edges = sorted(merged)
    counts = [merged[e] for e in edges]
    B = len(edges)
    K = max(1, min(int(max_shapes), B))
    # prefix[i] = total count of bins[0..i)
    prefix = [0]
    for c in counts:
        prefix.append(prefix[-1] + c)
    INF = float("inf")
    # dp[j][k]: min padded bytes covering edges[0..j] with k buckets where
    # edges[j] is the widest chosen bucket so far.
    dp = [[INF] * (K + 1) for _ in range(B)]
    back = [[-1] * (K + 1) for _ in range(B)]
    for j in range(B):
        dp[j][1] = prefix[j + 1] * edges[j]
        for k in range(2, K + 1):
            for i in range(j):
                if dp[i][k - 1] == INF:
                    continue
                cost = dp[i][k - 1] + (prefix[j + 1] - prefix[i + 1]) * edges[j]
                if cost < dp[j][k]:
                    dp[j][k] = cost
                    back[j][k] = i
    best_k = min(range(1, K + 1), key=lambda k: dp[B - 1][k])
    chosen = []
    j, k = B - 1, best_k
    while j >= 0 and k >= 1:
        chosen.append(edges[j])
        j, k = back[j][k], k - 1
        if j < 0:
            break
    return sorted(chosen)


# --------------------------------------------------------- serve solver -----
def solve_serve(signals: dict, *, p99_ms: float | None) -> dict:
    """Measured serve flush parameters, or {} when the capture carries no
    serving traffic. The window targets "coalesce a typical burst to the
    row bound": rows the arrival stream delivers in the window ~= the
    per-dispatch row cap, clamped to [1, 50]ms and below half the p99
    budget (the other half pays for dispatch)."""
    hists = signals["histograms"]
    counters = signals["counters"]
    rows_h = hists.get("serve/rows_per_dispatch") or {}
    if not rows_h.get("count"):
        return {}
    # Row bound: the observed p90 coalesced size rounded up to a power of
    # two — big enough that measured traffic never truncates a flush,
    # small enough that one dispatch stays inside the compiled lattice.
    p90_rows = max(1.0, float(rows_h.get("p90") or rows_h.get("mean") or 1.0))
    max_rows = 32
    while max_rows < p90_rows and max_rows < 4096:
        max_rows *= 2
    total_rows = float(counters.get("serve/coalesced_rows") or 0.0)
    span_s = signals["span_s"]
    arrival_rows_per_s = total_rows / span_s if span_s > 0 else 0.0
    if arrival_rows_per_s > 0:
        wait_ms = max_rows / arrival_rows_per_s * 1e3
    else:
        wait_ms = SERVE_WAIT_CAP_MS
    if p99_ms is not None:
        wait_ms = min(wait_ms, p99_ms / 2.0)
    wait_ms = min(max(wait_ms, SERVE_WAIT_FLOOR_MS), SERVE_WAIT_CAP_MS)
    return {
        "serve_max_rows": int(max_rows),
        "serve_queue_rows": int(max_rows * 16),
        "serve_max_wait_ms": round(wait_ms, 3),
    }


# ---------------------------------------------------------- cache solver ----
def solve_cache(signals: dict) -> dict:
    """Serve-cache sizing from the capture's observed duplicate mass
    (docs/PERFORMANCE.md §10).

    Emits ``cache_rows``/``cache_bytes`` only when the capture proves
    both (a) serve-cache traffic (``cache/lookups`` > 0 — the replay went
    through a batcher with the cache on) and (b) actual duplicate mass:
    either in the dedup counters (repeats inside a dispatch) or as cache
    hits (repeats ACROSS dispatches — the steady-state shape once the
    cache is warm, where repeats never reach the runner and the dedup
    counters therefore read all-unique). An all-unique capture keeps the
    built-in defaults through normal config fallback rather than
    recording an unmeasured guess as "tuned".

    Sizing: every miss during the capture window is one distinct
    (version, mode, document) entry the cache had to hold, so the row
    bound is the misses count with 2x headroom, rounded up to a power of
    two (clamped [1024, 2^20]); the byte bound multiplies rows by the
    measured mean SERVED-document size plus a flat result/overhead
    allowance (clamped [1MB, 1GB]). The document size comes from the
    cache's own traffic — ``cache/bytes_saved`` counts the hit documents'
    bytes, so ``bytes_saved / hits`` is exactly the mean size of what the
    cache stores; the dedup byte counters are NOT used here because they
    aggregate the fit path too, which would bias the entry size toward
    whatever corpus the capture happened to fit. Deterministic over the
    capture.
    """
    counters = signals["counters"]
    lookups = float(counters.get("cache/lookups") or 0.0)
    hits = float(counters.get("cache/hits") or 0.0)
    rows_in = float(counters.get("dedup/rows_in") or 0.0)
    rows_unique = float(counters.get("dedup/rows_unique") or 0.0)
    dedup_mass = rows_in > 0 and rows_unique < rows_in
    if lookups <= 0 or not (dedup_mass or hits > 0):
        return {}
    misses = max(1.0, lookups - hits)
    rows = 1024
    while rows < 2 * misses and rows < (1 << 20):
        rows *= 2
    saved = float(counters.get("cache/bytes_saved") or 0.0)
    mean_doc = saved / hits if hits > 0 else 0.0
    per_entry = int(mean_doc) + 512  # result row + key/entry overhead
    cache_bytes = 1 << 20
    while cache_bytes < rows * per_entry and cache_bytes < (1 << 30):
        cache_bytes *= 2
    return {"cache_rows": int(rows), "cache_bytes": int(cache_bytes)}


# ----------------------------------------------------------- wire solver ----
def solve_wire(signals: dict) -> dict:
    """Device-encode stamping from the capture's measured padding tax
    (docs/PERFORMANCE.md §11).

    Emits ``device_encode: True`` when the capture proves either (a) the
    replayed deployment already ran the wire path
    (``score/encoded_batches`` > 0 — keep what worked), or (b) scoring
    traffic padded badly: whole-run fill ``score/real_bytes /
    score/capacity_bytes`` below 0.85, meaning ≥15% of every transfer was
    padding the device-encode wire form would simply not ship. A capture
    with no scoring traffic (or dense, well-filled batches where the
    padded path's single pre-padded put is already near-optimal) emits
    nothing — the knob's built-in default stands through normal config
    fallback rather than recording an unmeasured guess as "tuned".
    """
    counters = signals["counters"]
    if float(counters.get("score/encoded_batches") or 0.0) > 0:
        return {"device_encode": True}
    real = float(counters.get("score/real_bytes") or 0.0)
    capacity = float(counters.get("score/capacity_bytes") or 0.0)
    if capacity <= 0:
        return {}
    if real / capacity < 0.85:
        return {"device_encode": True}
    return {}


# --------------------------------------------------------- budget solver ----
def solve_budgets(signals: dict, *, max_batch_ms: float | None) -> dict:
    """Per-transfer byte budgets. Without a latency constraint the
    profile carries NO budget fields — the defaults stand through normal
    config fallback (recording an unmeasured value as "tuned" would lie
    in the /varz provenance and pin a stale default forever); with
    ``--max-batch-ms``, the measured wire rate bounds the budget to the
    largest power-of-two MB whose transfer fits the target."""
    if max_batch_ms is None:
        return {}
    counters = signals["counters"]
    real = float(counters.get("score/real_bytes") or 0.0)
    hists = signals["histograms"]
    lat = hists.get("score/batch_latency_s") or {}
    per_batch_s = float(lat.get("mean") or 0.0)
    batches = float(lat.get("count") or 0.0)
    if real <= 0 or per_batch_s <= 0 or batches <= 0:
        return {}  # constraint given but unmeasurable: stay on defaults
    bytes_per_s = (real / batches) / per_batch_s
    budget = 1 << 20
    while budget * 2 <= bytes_per_s * (max_batch_ms / 1e3) and budget < (
        32 << 20
    ):
        budget *= 2
    return {"batch_bytes": int(budget), "fit_batch_bytes": int(budget)}


# --------------------------------------------------------------- solve ------
def solve(
    events: list[dict],
    *,
    max_shapes: int = DEFAULT_MAX_SHAPES,
    min_width: int = DEFAULT_MIN_WIDTH,
    max_batch_ms: float | None = None,
    p99_ms: float | None = None,
) -> TuningProfile:
    """One capture -> one validated profile (see the module docstring)."""
    from ..ops.encoding import DEFAULT_LENGTH_BUCKETS

    signals = capture_signals(events)
    bins = signals["len_bins"]
    # The DP solves the interior widths; the TOP bucket is special — it is
    # the chunking boundary (BatchRunner.max_chunk), and the exec/len
    # distribution is recorded post-chunking, clamped at the live lattice's
    # top. Shrinking it below the built-in max would (a) re-chunk every
    # longer doc into many small pieces (extra dispatches + overlap
    # rescoring) and (b) ratchet: a narrow live lattice caps what future
    # captures can observe, so re-tuning could never widen it back. One
    # shape slot is therefore reserved for the default top bucket whenever
    # the observed lengths don't reach it — unused shapes never compile,
    # so an idle top bucket costs nothing.
    default_top = DEFAULT_LENGTH_BUCKETS[-1]
    buckets = solve_buckets(
        bins, max_shapes=max(1, max_shapes - 1), min_width=min_width
    )
    if buckets[-1] < default_top:
        buckets = buckets + [default_top]
    tuned: dict = {"length_buckets": buckets}
    tuned.update(solve_budgets(signals, max_batch_ms=max_batch_ms))
    tuned.update(solve_serve(signals, p99_ms=p99_ms))
    tuned.update(solve_cache(signals))
    tuned.update(solve_wire(signals))

    before = padded_bytes(bins, list(DEFAULT_LENGTH_BUCKETS))
    after = padded_bytes(bins, buckets)
    real = sum(edge * count for edge, count in bins.items())  # upper bound
    constraints = {
        "max_shapes": int(max_shapes),
        "min_width": int(min_width),
        "max_batch_ms": max_batch_ms,
        "p99_ms": p99_ms,
    }
    counters = signals["counters"]
    rows_in = float(counters.get("dedup/rows_in") or 0.0)
    rows_unique = float(counters.get("dedup/rows_unique") or 0.0)
    source = {
        "events": signals["events"],
        "capture_span_s": round(signals["span_s"], 3),
        "items": int(sum(bins.values())),
        "len_bins": len(bins),
        # Observed duplicate mass (the cache solver's evidence): fraction
        # of submitted rows the dedup layer collapsed during the capture.
        "duplicate_mass": (
            round(1.0 - rows_unique / rows_in, 6) if rows_in > 0 else 0.0
        ),
        # Whole-run wire fill (the wire solver's evidence): real scored
        # bytes over the capacity that actually shipped.
        "score_wire_fill": (
            round(
                float(counters.get("score/real_bytes") or 0.0)
                / float(counters.get("score/capacity_bytes") or 0.0),
                6,
            )
            if float(counters.get("score/capacity_bytes") or 0.0) > 0
            else None
        ),
        "padded_bytes_default_lattice": int(before),
        "padded_bytes_tuned_lattice": int(after),
        "predicted_padded_reduction": (
            round(1.0 - after / before, 6) if before else 0.0
        ),
        "binned_real_bytes_upper": int(real),
    }
    ts_max = 0.0
    for ev in events:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            ts_max = max(ts_max, float(ts))
    return TuningProfile(
        tuned=tuned,
        source=source,
        constraints=constraints,
        created=ts_max,
        version=content_version(tuned),
    )


# ----------------------------------------------------------------- CLI ------
def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out_path = None
    max_shapes = DEFAULT_MAX_SHAPES
    min_width = DEFAULT_MIN_WIDTH
    max_batch_ms = p99_ms = None
    paths: list[str] = []
    i = 0
    try:
        while i < len(argv):
            a = argv[i]
            if a in ("-h", "--help"):
                raise ValueError
            if a in ("-o", "--out"):
                out_path = argv[i + 1]
                i += 2
            elif a == "--max-shapes":
                max_shapes = int(argv[i + 1])
                i += 2
            elif a == "--min-width":
                min_width = int(argv[i + 1])
                i += 2
            elif a == "--max-batch-ms":
                max_batch_ms = float(argv[i + 1])
                i += 2
            elif a == "--p99-ms":
                p99_ms = float(argv[i + 1])
                i += 2
            elif a.startswith("-"):
                raise ValueError(f"unknown option {a!r}")
            else:
                paths.append(a)
                i += 1
        if len(paths) != 1 or max_shapes < 1 or min_width < WIDTH_ALIGN:
            raise ValueError
    except (ValueError, IndexError) as e:
        msg = f"error: {e}\n" if str(e) else ""
        print(
            msg + "usage: python -m spark_languagedetector_tpu.exec.tune "
            "<telemetry.jsonl> [-o profile.json] [--max-shapes N] "
            "[--min-width 128] [--max-batch-ms MS] [--p99-ms MS]",
            file=sys.stderr,
        )
        return 2
    try:
        events = load_events(paths[0])
    except OSError as e:
        print(f"cannot read capture: {e}", file=sys.stderr)
        return 2
    try:
        profile = solve(
            events, max_shapes=max_shapes, min_width=min_width,
            max_batch_ms=max_batch_ms, p99_ms=p99_ms,
        )
    except ValueError as e:
        print(f"cannot tune from this capture: {e}", file=sys.stderr)
        return 2
    src = profile.source
    print(f"profile {profile.version} from {paths[0]}")
    print(
        f"  items {src['items']} across {src['len_bins']} length bins, "
        f"capture span {src['capture_span_s']}s"
    )
    print(
        f"  length_buckets -> {list(profile.tuned['length_buckets'])}"
    )
    print(
        f"  predicted padded-byte reduction vs default lattice: "
        f"{src['predicted_padded_reduction']:.1%}"
    )
    for key in sorted(profile.tuned):
        if key != "length_buckets":
            print(f"  {key} -> {profile.tuned[key]}")
    if out_path:
        profile.save(out_path)
        print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
