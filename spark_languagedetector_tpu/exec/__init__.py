"""Execution core: one scheduler/planner under batch, stream, and serve.

:mod:`.core` holds the shared machinery (byte-budget row sizing, the
micro-batch planner, ordered prefetch, retry/degrade wiring, the serve
admission queue); :mod:`.config` resolves every ``LANGDETECT_*`` knob with
one precedence rule; :mod:`.profile` is the versioned tuning profile, and
:mod:`.tune` the offline autotuner CLI that emits it:

    python -m spark_languagedetector_tpu.exec.tune telemetry.jsonl -o p.json
    LANGDETECT_TUNING_PROFILE=p.json python serve...
"""

from . import config  # noqa: F401
from .core import (  # noqa: F401
    AdmissionQueue,
    guarded_dispatch,
    ordered_prefetch,
    plan_micro_batches,
    rows_under_byte_budget,
    run_ordered,
)
from .profile import TuningProfile  # noqa: F401
