"""One audited config module for every ``LANGDETECT_*`` knob.

Before this module each subsystem parsed its own env vars with its own
tolerance for garbage (the serve batcher silently swallowed a malformed
float, the fit pipeline raised, the runner read booleans inline). Every
knob now resolves here, once, with type validation and a single precedence
rule:

    explicit ctor/param value  >  env var  >  tuning profile  >  default

The tuning profile (:mod:`.profile`, pointed at by
``LANGDETECT_TUNING_PROFILE``) supplies *measured* defaults for the knobs
the offline autotuner (:mod:`.tune`) solves for — the deprecation table
below names the hand-set knobs it supersedes. An env var still wins over a
profile value (operators pin what must not drift), but the effective
config — every knob, its value, and where the value came from — is
surfaced in ``/varz`` and the bench telemetry block, so "which knob is
actually live" is never archaeology again.

Resolution is cheap (one dict lookup + env read per knob) and un-cached on
purpose: tests and the tuner's A/B smoke flip env vars and expect the next
construction to see them. Only the profile file read is cached (per path +
mtime); :func:`reload_profile` drops the cache.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable

from ..ops.encoding import DEFAULT_LENGTH_BUCKETS
from ..utils.logging import get_logger, log_event
from .profile import TuningProfile

_log = get_logger("exec.config")

PROFILE_ENV = "LANGDETECT_TUNING_PROFILE"


@dataclass(frozen=True)
class Knob:
    """One config knob: canonical name, env spelling, type, default."""

    name: str
    env: str | None
    kind: str  # 'int' | 'float' | 'bool' | 'str' | 'int_tuple'
    default: object
    help: str
    # Resolvable from the active tuning profile's `tuned` dict (same name).
    tunable: bool = False
    positive: bool = False


def _knobs(*knobs: Knob) -> dict[str, Knob]:
    table = {}
    for k in knobs:
        if k.name in table:
            raise ValueError(f"duplicate knob {k.name}")
        table[k.name] = k
    return table


# The full knob zoo, one row per env var (docs/PERFORMANCE.md §9 and the
# per-subsystem docs describe semantics; this table is the authority on
# names, types, and defaults). Defaults mirror the constants at the
# consuming call sites — those modules now resolve through here.
KNOBS: dict[str, Knob] = _knobs(
    # --- execution core (tunable: the autotuner measures these) ----------
    Knob("length_buckets", "LANGDETECT_LENGTH_BUCKETS", "int_tuple",
         DEFAULT_LENGTH_BUCKETS,
         "padded-length bucket lattice (comma-separated, ascending, "
         "multiples of 128)", tunable=True),
    Knob("batch_bytes", "LANGDETECT_BATCH_BYTES", "int", 8 << 20,
         "byte budget per scoring micro-batch transfer", tunable=True,
         positive=True),
    Knob("fit_batch_bytes", "LANGDETECT_FIT_BATCH_BYTES", "int", 8 << 20,
         "byte budget per fit micro-batch transfer", tunable=True,
         positive=True),
    Knob("fit_batch_rows", "LANGDETECT_FIT_BATCH_ROWS", "int", None,
         "fixed fit micro-batch rows (unset: adaptive under the byte "
         "budget)", positive=True),
    Knob("dispatch_workers", "LANGDETECT_DISPATCH_WORKERS", "int", None,
         "concurrent dispatch threads for the batch path (unset: "
         "per-backend auto)", positive=True),
    Knob("stream_prefetch", "LANGDETECT_STREAM_PREFETCH", "int", 0,
         "streaming batches transformed ahead of the sink"),
    Knob("stream_workers", "LANGDETECT_STREAM_WORKERS", "int", None,
         "streaming transform concurrency (unset: min(2, prefetch))",
         positive=True),
    Knob("pack_threads", "LANGDETECT_PACK_THREADS", "int", None,
         "native packer thread count (unset: auto)", positive=True),
    Knob("device_encode", "LANGDETECT_DEVICE_ENCODE", "bool", False,
         "device-side batch encode: ship raw bytes + int32 offsets and "
         "rebuild the padded batch inside the scoring jit instead of "
         "host-packing (docs/PERFORMANCE.md §11)", tunable=True),
    # --- redundancy elimination (docs/PERFORMANCE.md §10) -----------------
    Knob("dedup", "LANGDETECT_DEDUP", "bool", True,
         "in-flight content dedup: unique rows ride the wire/kernel, "
         "duplicates scatter back from the fetched result"),
    Knob("cache_enable", "LANGDETECT_CACHE_ENABLE", "bool", True,
         "version-keyed serve score cache in front of the runner"),
    Knob("cache_rows", "LANGDETECT_CACHE_ROWS", "int", 1 << 16,
         "serve cache entry bound (documents)", tunable=True,
         positive=True),
    Knob("cache_bytes", "LANGDETECT_CACHE_BYTES", "int", 64 << 20,
         "serve cache byte bound (keys + stored results)", tunable=True,
         positive=True),
    # --- serving (tunable: flush window + shape bounds) -------------------
    Knob("serve_max_wait_ms", "LANGDETECT_SERVE_MAX_WAIT_MS", "float", 10.0,
         "serve coalescing window: max ms the oldest queued request "
         "waits before a flush", tunable=True, positive=True),
    Knob("serve_max_rows", "LANGDETECT_SERVE_MAX_ROWS", "int", 256,
         "serve coalescing bound: rows per dispatched batch",
         tunable=True, positive=True),
    Knob("serve_queue_rows", "LANGDETECT_SERVE_QUEUE_ROWS", "int", 4096,
         "serve admission bound: queued rows before shedding",
         tunable=True, positive=True),
    Knob("serve_slo_ms", "LANGDETECT_SERVE_SLO_MS", "float", 0.0,
         "estimated-wait shed threshold (0: off)"),
    # --- model zoo (multi-tenant serving: docs/SERVING.md §12) ------------
    Knob("zoo_resident_bytes", "LANGDETECT_ZOO_RESIDENT_BYTES", "int", None,
         "resident weight-table byte budget for the model zoo (unset: "
         "unlimited)", positive=True),
    Knob("zoo_resident_models", "LANGDETECT_ZOO_RESIDENT_MODELS", "int", None,
         "resident model bound for the model zoo (unset: unlimited)",
         positive=True),
    # --- fleet (replicated serving: router + replicas) --------------------
    Knob("fleet_replicas", "LANGDETECT_FLEET_REPLICAS", "int", 3,
         "serve replicas behind the fleet router", positive=True),
    Knob("fleet_probe_interval_ms", "LANGDETECT_FLEET_PROBE_INTERVAL_MS",
         "float", 100.0, "router health-probe period per round",
         positive=True),
    Knob("fleet_probe_timeout_s", "LANGDETECT_FLEET_PROBE_TIMEOUT_S",
         "float", 2.0, "liveness/readiness probe HTTP timeout",
         positive=True),
    Knob("fleet_dispatch_attempts", "LANGDETECT_FLEET_DISPATCH_ATTEMPTS",
         "int", 3, "distinct replicas tried per request before the fleet "
         "sheds", positive=True),
    Knob("fleet_breaker_threshold", "LANGDETECT_FLEET_BREAKER_THRESHOLD",
         "int", 3, "consecutive probe/dispatch failures that eject a "
         "replica", positive=True),
    Knob("fleet_breaker_cooldown_s", "LANGDETECT_FLEET_BREAKER_COOLDOWN_S",
         "float", 1.0, "ejection -> half-open re-probe cooldown",
         positive=True),
    Knob("fleet_drain_timeout_s", "LANGDETECT_FLEET_DRAIN_TIMEOUT_S",
         "float", 10.0, "per-replica drain bound during the two-phase "
         "fleet swap", positive=True),
    # --- storm defense (budget + hedge + quarantine: RESILIENCE.md §7) ----
    Knob("fleet_deadline_floor_ms", "LANGDETECT_FLEET_DEADLINE_FLOOR_MS",
         "float", 5.0, "remaining-deadline floor below which the router "
         "504s instead of burning another replica", positive=True),
    Knob("retry_budget_fraction", "LANGDETECT_RETRY_BUDGET_FRACTION",
         "float", 0.2, "retry-budget tokens deposited per success "
         "(0: budget off, retries ungated)"),
    Knob("retry_budget_burst", "LANGDETECT_RETRY_BUDGET_BURST", "float",
         10.0, "retry-budget token cap and starting balance",
         positive=True),
    Knob("hedge_enable", "LANGDETECT_HEDGE_ENABLE", "bool", False,
         "hedged fleet dispatch: second replica tried after the observed "
         "latency-quantile delay"),
    Knob("hedge_quantile", "LANGDETECT_HEDGE_QUANTILE", "float", 0.95,
         "observed dispatch-latency quantile that arms the hedge timer",
         positive=True),
    Knob("hedge_min_ms", "LANGDETECT_HEDGE_MIN_MS", "float", 10.0,
         "hedge-delay floor (also the delay before latency history "
         "exists)", positive=True),
    Knob("quarantine_deaths", "LANGDETECT_QUARANTINE_DEATHS", "int", 2,
         "correlated replica deaths that quarantine a request signature",
         positive=True),
    Knob("quarantine_max_entries", "LANGDETECT_QUARANTINE_MAX_ENTRIES",
         "int", 4096, "suspect/quarantine signature-table bound (oldest "
         "evicted first)", positive=True),
    Knob("quarantine_dlq_path", "LANGDETECT_QUARANTINE_DLQ_PATH", "str",
         None, "serve-level dead-letter JSONL for quarantined "
         "query-of-death signatures"),
    # --- elastic scale (subprocess replicas + autoscaler: scale/) ---------
    Knob("scale_min", "LANGDETECT_SCALE_MIN", "int", 1,
         "autoscaler floor: minimum live replicas", positive=True),
    Knob("scale_max", "LANGDETECT_SCALE_MAX", "int", 4,
         "autoscaler ceiling: maximum live replicas", positive=True),
    Knob("scale_interval_ms", "LANGDETECT_SCALE_INTERVAL_MS", "float",
         500.0, "autoscaler control-loop tick period", positive=True),
    Knob("scale_up_ticks", "LANGDETECT_SCALE_UP_TICKS", "int", 2,
         "consecutive pressure ticks before a scale-up", positive=True),
    Knob("scale_down_ticks", "LANGDETECT_SCALE_DOWN_TICKS", "int", 6,
         "consecutive idle ticks (the cooldown) before a scale-down",
         positive=True),
    Knob("scale_pressure_wait_ms", "LANGDETECT_SCALE_PRESSURE_WAIT_MS",
         "float", 50.0, "estimated fleet queue wait that counts as SLO "
         "pressure", positive=True),
    Knob("scale_idle_rows_per_s", "LANGDETECT_SCALE_IDLE_ROWS_PER_S",
         "float", 1.0, "arrival-rate EMA below which an empty-queue tick "
         "counts idle", positive=True),
    Knob("scale_spawn_timeout_s", "LANGDETECT_SCALE_SPAWN_TIMEOUT_S",
         "float", 120.0, "subprocess replica spawn-to-READY bound",
         positive=True),
    Knob("scale_max_restarts", "LANGDETECT_SCALE_MAX_RESTARTS", "int", 3,
         "supervised restarts per replica incident before giving up",
         positive=True),
    Knob("scale_pidfile_dir", "LANGDETECT_SCALE_PIDFILE_DIR", "str", None,
         "pidfile directory for orphan reaping (unset: per-fleet-name "
         "tempdir)"),
    # --- cold-start plane (artifacts/: docs/PERFORMANCE.md §12) -----------
    Knob("compile_cache_dir", "LANGDETECT_COMPILE_CACHE_DIR", "str", None,
         "persistent JAX compilation-cache directory shared across "
         "replica spawns (unset: cache off, every process recompiles)"),
    Knob("artifact_dir", "LANGDETECT_ARTIFACT_DIR", "str", None,
         "baked-artifact directory consulted on model load (unset: look "
         "for a `.baked` sibling of the model tree)"),
    Knob("bake_on_save", "LANGDETECT_BAKE_ON_SAVE", "bool", False,
         "bake an mmap-ready artifact next to every successful model "
         "save so later cold loads page in instead of parsing parquet"),
    # --- resilience -------------------------------------------------------
    Knob("retry_max_attempts", "LANGDETECT_RETRY_MAX_ATTEMPTS", "int", 2,
         "retry attempts incl. the first try"),
    Knob("retry_base_delay_s", "LANGDETECT_RETRY_BASE_DELAY_S", "float",
         0.05, "first backoff delay"),
    Knob("retry_max_delay_s", "LANGDETECT_RETRY_MAX_DELAY_S", "float", 2.0,
         "backoff ceiling"),
    Knob("retry_multiplier", "LANGDETECT_RETRY_MULTIPLIER", "float", 2.0,
         "backoff growth factor"),
    Knob("retry_jitter", "LANGDETECT_RETRY_JITTER", "float", 0.5,
         "downward jitter fraction per delay"),
    Knob("retry_seed", "LANGDETECT_RETRY_SEED", "int", 0,
         "deterministic jitter seed"),
    Knob("retry_attempt_deadline_s", "LANGDETECT_RETRY_ATTEMPT_DEADLINE_S",
         "float", None, "post-hoc per-attempt deadline"),
    Knob("breaker_threshold", "LANGDETECT_BREAKER_THRESHOLD", "int", 5,
         "consecutive retryable failures that open the breaker"),
    Knob("breaker_cooldown_s", "LANGDETECT_BREAKER_COOLDOWN_S", "float",
         5.0, "open -> half-open cooldown"),
    Knob("breaker_probes", "LANGDETECT_BREAKER_PROBES", "int", 1,
         "half-open probe successes required to close"),
    Knob("degraded", "LANGDETECT_DEGRADED", "bool", True,
         "degraded-ladder fallback on retryable exhaustion"),
    Knob("fault_plan", "LANGDETECT_FAULT_PLAN", "str", None,
         "chaos fault plan spec (tests/drills only)"),
    # --- telemetry --------------------------------------------------------
    Knob("metrics_sink", "LANGDETECT_METRICS_SINK", "str", None,
         "metrics sink spec (jsonl:<path> / prometheus:<path>)"),
    Knob("telemetry_fence", "LANGDETECT_TELEMETRY_FENCE", "bool", False,
         "fence spans on device completion"),
    Knob("flight_recorder", "LANGDETECT_FLIGHT_RECORDER", "str", None,
         "crash ring-buffer dump dir (1: tmpdir)"),
    Knob("flight_recorder_events", "LANGDETECT_FLIGHT_RECORDER_EVENTS",
         "int", 2048, "crash ring capacity", positive=True),
    Knob("trace_dir", "LANGDETECT_TRACE_DIR", "str", None,
         "XProf trace output dir"),
    Knob("peak_flops", "LANGDETECT_PEAK_FLOPS", "float", None,
         "roofline FLOP/s anchor override"),
    Knob("peak_bytes_per_s", "LANGDETECT_PEAK_BYTES_PER_S", "float", None,
         "roofline bytes/s anchor override"),
    Knob("loglevel", "LANGDETECT_TPU_LOGLEVEL", "str", None,
         "package log level"),
    # --- multi-process bring-up ------------------------------------------
    Knob("tpu_coordinator", "LANGDETECT_TPU_COORDINATOR", "str", None,
         "jax.distributed coordinator address"),
    Knob("tpu_num_processes", "LANGDETECT_TPU_NUM_PROCESSES", "int", None,
         "jax.distributed process count", positive=True),
    Knob("tpu_process_id", "LANGDETECT_TPU_PROCESS_ID", "int", None,
         "jax.distributed process id"),
    Knob("tuning_profile", PROFILE_ENV, "str", None,
         "path to the tuning profile JSON the autotuner emitted"),
)

# Deprecation table: hand-set env knobs the autotuner supersedes. The old
# spelling keeps working (and keeps winning over the profile — explicit
# beats measured), but deployments should drop it and ship a profile: the
# tuned default is measured per deployment instead of guessed once.
# old env name -> tuned profile field that replaces it
DEPRECATED_ENV: dict[str, str] = {
    "LANGDETECT_LENGTH_BUCKETS": "length_buckets",
    "LANGDETECT_BATCH_BYTES": "batch_bytes",
    "LANGDETECT_FIT_BATCH_BYTES": "fit_batch_bytes",
    "LANGDETECT_SERVE_MAX_WAIT_MS": "serve_max_wait_ms",
    "LANGDETECT_SERVE_MAX_ROWS": "serve_max_rows",
    "LANGDETECT_SERVE_QUEUE_ROWS": "serve_queue_rows",
}


# ------------------------------------------------------------- profile ------
_profile_cache: tuple[str, float, TuningProfile] | None = None
_profile_warned: set[str] = set()


def reload_profile() -> None:
    """Drop the cached profile (tests / the tuner's A-B smoke)."""
    global _profile_cache
    _profile_cache = None


def active_profile(env=os.environ) -> TuningProfile | None:
    """The tuning profile ``LANGDETECT_TUNING_PROFILE`` names, or None.

    Cached per (path, mtime). A missing or invalid profile file is a
    loud failure: startup with a half-rolled-out profile must not
    silently run untuned."""
    global _profile_cache
    path = (env.get(PROFILE_ENV) or "").strip()
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime
    except OSError as e:
        raise ValueError(
            f"{PROFILE_ENV}={path!r} names an unreadable profile: {e}"
        ) from e
    cached = _profile_cache
    if cached is not None and cached[0] == path and cached[1] == mtime:
        return cached[2]
    prof = TuningProfile.load(path)
    _profile_cache = (path, mtime, prof)
    log_event(
        _log, "exec.config.profile_loaded", path=path,
        version=prof.version, fields=sorted(prof.tuned),
    )
    return prof


# ----------------------------------------------------------- resolution -----
def _parse(knob: Knob, raw: str):
    try:
        if knob.kind == "int":
            value = int(raw)
        elif knob.kind == "float":
            value = float(raw)
        elif knob.kind == "bool":
            low = raw.strip().lower()
            if low in ("1", "true", "yes", "on"):
                return True
            if low in ("0", "false", "no", "off"):
                return False
            raise ValueError(raw)
        elif knob.kind == "int_tuple":
            value = tuple(
                int(p) for p in raw.replace(" ", "").split(",") if p
            )
            if not value or list(value) != sorted(set(value)) or min(value) < 1:
                raise ValueError(raw)
            # Same constraint the tuning-profile validator enforces
            # (exec.profile): bucket widths are 128-aligned for TPU lane
            # tiling and the ragged-chunk transfer form. Env and profile
            # must not disagree on what a legal lattice is.
            if any(x % 128 for x in value):
                raise ValueError(
                    f"{knob.env} values must be multiples of 128, got {raw!r}"
                )
        else:  # str
            return raw
    except ValueError as e:
        kind = {"int": "an integer", "float": "a number",
                "bool": "a boolean", "int_tuple":
                "a comma-separated ascending list of positive integers"}[
                    knob.kind]
        raise ValueError(f"{knob.env} must be {kind}, got {raw!r}") from e
    if knob.positive and value is not None and value <= 0:
        raise ValueError(f"{knob.env} must be positive, got {value}")
    return value


def resolve_with_source(
    name: str, explicit=None, env=os.environ
) -> tuple[object, str]:
    """(value, source) for one knob; source is ``explicit`` / ``env`` /
    ``profile`` / ``default``. Precedence: explicit > env > tuning profile
    > built-in default. Raises ValueError on a malformed env value or an
    unknown knob — a typo must never silently mean "default"."""
    knob = KNOBS.get(name)
    if knob is None:
        raise ValueError(
            f"unknown config knob {name!r}; expected one of {sorted(KNOBS)}"
        )
    if explicit is not None:
        return explicit, "explicit"
    raw = env.get(knob.env) if knob.env else None
    if raw is not None and raw != "":
        value = _parse(knob, raw)
        if knob.env in DEPRECATED_ENV and knob.env not in _profile_warned:
            prof = active_profile(env)
            if prof is not None and prof.get(DEPRECATED_ENV[knob.env]) is not None:
                _profile_warned.add(knob.env)
                log_event(
                    _log, "exec.config.env_overrides_profile",
                    env=knob.env, value=raw,
                    tuned=DEPRECATED_ENV[knob.env],
                    profile=prof.version,
                )
        return value, "env"
    if knob.tunable:
        prof = active_profile(env)
        if prof is not None:
            tuned = prof.get(name)
            if tuned is not None:
                return tuned, "profile"
    return knob.default, "default"


def resolve(name: str, explicit=None, env=os.environ):
    """The knob's effective value (see :func:`resolve_with_source`)."""
    return resolve_with_source(name, explicit, env)[0]


def raw_env(name: str, env=os.environ) -> str | None:
    """The raw, unparsed env string behind a knob (diagnostics only).

    For error paths that want to *show* what the operator typed without
    re-spelling the env-var name at the call site — the one place a
    module outside this file may touch a knob's environment string.
    """
    knob = KNOBS.get(name)
    if knob is None:
        raise ValueError(
            f"unknown config knob {name!r}; expected one of {sorted(KNOBS)}"
        )
    return env.get(knob.env) if knob.env else None


def effective_config(env=os.environ) -> dict:
    """Every knob's live value + provenance — the ``/varz`` and bench
    audit block. Malformed env values surface as ``"error"`` entries
    instead of raising: an observability endpoint must render the
    misconfiguration, not 500 on it."""
    prof = None
    prof_error = None
    try:
        prof = active_profile(env)
    except ValueError as e:
        prof_error = str(e)
    out: dict = {
        "profile": None if prof is None else {
            "version": prof.version,
            "created": prof.created,
            "tuned": {
                k: (list(v) if isinstance(v, tuple) else v)
                for k, v in prof.tuned.items()
            },
        },
        "deprecated_env": dict(DEPRECATED_ENV),
        "knobs": {},
    }
    if prof_error:
        out["profile_error"] = prof_error
    for name in sorted(KNOBS):
        try:
            value, source = resolve_with_source(name, env=env)
        except ValueError as e:
            out["knobs"][name] = {"error": str(e), "env": KNOBS[name].env}
            continue
        entry: dict = {
            "value": list(value) if isinstance(value, tuple) else value,
            "source": source,
            "env": KNOBS[name].env,
        }
        out["knobs"][name] = entry
    return out


# The logging root's level was set pre-config (bootstrap: this module's
# own imports emit through it, so the knob table cannot exist yet when
# the root initializes). Re-resolve it through the audited table now that
# the table does exist — the live level and the /varz report can't
# disagree, and the bootstrap read stays the one allowlisted exception
# (analysis/allowlist.py).
from ..utils.logging import sync_level_from_config as _sync_level  # noqa: E402

_sync_level(resolve)
