"""Single-probe bucketized membership table for the histogram scorer.

The two-choice cuckoo table (:mod:`ops.cuckoo`) resolves a window in two
verified gathers; on TPU those gathers are the n >= 3 scoring wall (each is
an issue-bound random row read — measured ~105M windows/s at config-3 table
sizes). This table gets membership down to ONE gather per window:

* **Layout**: ``Mb`` buckets x 8 slots, stored as one int32 [Mb, 16] row per
  bucket — slot keys in columns 0..7, slot payloads in 8..15. A window's
  bucket is ``mix32(key) & (Mb - 1)``; one row gather brings every candidate
  slot, and eight VPU compare/selects finish the lookup (measured ~170-230M
  windows/s depending on table size — 1.6-2.2x the cuckoo pair).
* **Build**: single hash, no evictions — a seed is searched until NO bucket
  overflows 8 slots. ``Mb`` is sized for load ~<= 1.5 keys/bucket, where the
  Poisson tail P(X > 8) is ~1e-5 and a zero-overflow seed appears within a
  few tries with high probability. If ``max_seeds`` seeds all fail
  (pathological key sets), the caller falls back to the cuckoo path.
* **Key forms**: exact vocabs store packed ``(lo, hi)`` gram keys
  (``ops.vocab.gram_key``) with payload ``hi | row << 11`` (real hi fits 11
  bits; empty slots carry the 0x7FF sentinel no real window produces);
  hashed vocabs store the int32 bucket id itself with the row as payload
  (empty slots: id -1, unreachable — device ids are non-negative).

Replaces the reference's JVM hash-map membership
(``/root/reference/src/main/.../LanguageDetectorModel.scala:139-152``) on
the device hot path; the cuckoo table remains the general fallback.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .vocab import mix32

SLOTS = 8
# Target keys/bucket; P(Poisson(1.5) > 8) ~ 1e-5 keeps zero-overflow seeds
# common while wasting at most ~5x slots.
_TARGET_LOAD = 1.5
_MAX_SEEDS = 64

HI_BITS = 11
HI_SENTINEL = 0x7FF  # > max real packed hi (byte | n << 8 <= 1535)


@dataclass(frozen=True)
class BucketTable:
    """Host-built single-probe table, ready to ship to device.

    ``rows``: int32 [Mb, 16] bucket rows (keys cols 0..7, payloads 8..15).
    ``kind``: 'exact' (packed-key slots) or 'hashed' (id slots).
    """

    rows: np.ndarray
    seed: int
    kind: str

    @property
    def num_buckets(self) -> int:
        return int(self.rows.shape[0])


def _size_buckets(G: int) -> int:
    Mb = 16
    while Mb * _TARGET_LOAD < G:
        Mb *= 2
    return Mb


def build_buckets_exact(
    keys_lo: np.ndarray, keys_hi: np.ndarray, *, max_seeds: int = _MAX_SEEDS
) -> BucketTable | None:
    """Place G packed keys (row order = weight-row order); None if no
    zero-overflow seed is found (caller keeps the cuckoo fallback)."""
    G = int(keys_lo.shape[0])
    if G >= 1 << (31 - HI_BITS):
        return None  # row index would not fit the payload packing
    keys_lo = np.ascontiguousarray(keys_lo, dtype=np.int32)
    keys_hi = np.ascontiguousarray(keys_hi, dtype=np.int32)
    payload = keys_hi | (np.arange(G, dtype=np.int32) << HI_BITS)
    empty_key, empty_payload = 0, HI_SENTINEL
    return _build(keys_lo, keys_hi, payload, empty_key, empty_payload,
                  "exact", max_seeds)


def build_buckets_hashed(
    ids: np.ndarray, rows: np.ndarray, *, max_seeds: int = _MAX_SEEDS
) -> BucketTable | None:
    """Place G (id -> weight row) pairs for hashed vocabs (ids are the
    device window ids; rows index the compact weight table)."""
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    return _build(ids, np.zeros_like(ids), rows, -1, 0, "hashed", max_seeds)


def _build(keys_a, keys_b, payload, empty_key, empty_payload, kind, max_seeds):
    G = int(keys_a.shape[0])
    Mb = _size_buckets(max(G, 1))
    rng = np.random.default_rng(0xB0CE7)
    for _ in range(max_seeds):
        seed = int(rng.integers(1, 2**31 - 1))
        h = (mix32(keys_a, keys_b, seed) & np.uint32(Mb - 1)).astype(np.int64)
        counts = np.bincount(h, minlength=Mb)
        if counts.max(initial=0) > SLOTS:
            continue
        table = np.empty((Mb, 2 * SLOTS), dtype=np.int32)
        table[:, :SLOTS] = empty_key
        table[:, SLOTS:] = empty_payload
        order = np.argsort(h, kind="stable")
        starts = np.cumsum(counts) - counts
        slot = np.arange(G, dtype=np.int64) - starts[h[order]]
        table[h[order], slot] = keys_a[order]
        table[h[order], SLOTS + slot] = payload[order]
        return BucketTable(rows=table, seed=seed, kind=kind)
    return None


def lookup_numpy(table: BucketTable, a: np.ndarray, b: np.ndarray, miss: int):
    """Host mirror of the device lookup (``ops.score_hist._bucket_rows``):
    keys (lo, hi) for 'exact', (id, zeros) for 'hashed' -> weight rows."""
    Mb = table.num_buckets
    a = np.ascontiguousarray(a, dtype=np.int32)
    b = np.ascontiguousarray(b, dtype=np.int32)
    h = (mix32(a, b, table.seed) & np.uint32(Mb - 1)).astype(np.int64)
    e = table.rows[h]  # [..., 16]
    out = np.full(a.shape, miss, dtype=np.int32)
    for s in range(SLOTS):
        ek = e[..., s]
        ep = e[..., SLOTS + s]
        if table.kind == "exact":
            hit = (ek == a) & ((ep & ((1 << HI_BITS) - 1)) == b)
            row = ep >> HI_BITS
        else:
            hit = ek == a
            row = ep
        out = np.where(hit, row, out)
    return out
