"""Row-histogram scoring: the n >= 3 accumulate as MXU matmuls, no row gather.

The gather strategies (:mod:`ops.score`) resolve each window to a compact
weight row, then gather that row — a [B, block, L] random-access read that is
issue-bound on TPU (~10ns/row regardless of L or dtype; measured on v5e, see
``exp_xla_gather.py`` history). This module replaces the gather+accumulate
with a dense reformulation:

    scores[b] = sum_w W[r_bw] = hist_b @ W,  hist_b[r] = #{w : r_bw == r}

and computes ``hist_b`` over the compact row space R with the same
digit-decomposition trick the bigram kernel uses for byte pairs
(:mod:`ops.score_pallas`): split r = hi * 256 + lo, build lane-major one-hots
of the hi and lo digits per window block, and accumulate their NT product

    hist2d[hi, lo] += oh_hi [Rhi, blk] . oh_lo [256, blk]^T    (MXU)

in VMEM scratch — fully dense work at R MACs/window, which beats the
issue-bound gather whenever R is compact (profiles here: R ~ 45-70k, so
~0.1-0.2us/window of MXU vs ~10ns+ of serialized gather issue... per *row*;
the win is ~3-5x end-to-end on the n>=3 path). The final contraction
``hist @ W`` runs as one XLA MXU matmul over the whole batch in HIGHEST
precision (counts are exact f32 integers — same parity argument as
``score_pallas._score_from_hist``).

Membership stays in XLA (cuckoo probes / LUT gathers — 2 small gathers per
window; in-kernel table gathers do not lower on Mosaic), masked or missing
windows resolve to the zeros miss row, so the kernel needs no masks at all:
miss counts multiply a zero weight row.

Replaces the reference's per-window hash-map lookup + ``BLAS.axpy`` hot loop
(``/root/reference/src/main/.../LanguageDetectorModel.scala:139-152``) at
full gram depth (n = 1..5), where the one-hot byte factorization of
:mod:`ops.score_pallas` stops at n = 2.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .score import _splice_partial_windows
from .score_pallas import COMPILER_PARAMS
from .vocab import (
    VocabSpec,
    mix32,
    partial_window_ids,
    partial_window_keys,
    window_ids,
    window_keys,
)

# Documents per grid step (sublane tile height of the row planes).
DB = 8

# Window-axis block: lane dimension of the digit one-hots. The MXU
# contraction depth is the block, so larger is better until the one-hot
# operands crowd VMEM (oh_hi [Rhi, blk] bf16 = Rhi*blk*2 bytes); 2048
# measured ~8% (Rhi=184) to ~35% (Rhi=280) faster than 1024 on v5e.
DEFAULT_BLOCK = 2048


def _build_kernel(KW: int, W: int, blk: int, Rhi: int):
    """Histogram kernel over concatenated per-length row segments.

    Inputs are [DB, KW] hi/lo digit planes (KW = k segments of width W, each
    a multiple of blk) plus a per-doc conservative valid-window bound vmax
    (segment-local: block at concat offset ``off`` covers segment-local
    starts [off % W, off % W + blk)). A block whose segment-local start is
    past vmax holds only miss windows for this doc and is skipped.
    """
    n_steps = KW // blk

    def kernel(hi_ref, lo_ref, vmax_ref, o_ref, acc_ref):
        base = pl.program_id(0) * DB
        for d in range(DB):
            dmax = vmax_ref[base + d]
            acc_ref[:, :] = jnp.zeros((Rhi, 256), jnp.float32)
            for k in range(n_steps):
                off = k * blk
                local = off % W  # segment-local start (static)

                def step(off=off):
                    hi = hi_ref[pl.dslice(d, 1), pl.dslice(off, blk)]
                    lo = lo_ref[pl.dslice(d, 1), pl.dslice(off, blk)]
                    iota_hi = jax.lax.broadcasted_iota(
                        jnp.int32, (Rhi, blk), 0
                    )
                    iota_lo = jax.lax.broadcasted_iota(
                        jnp.int32, (256, blk), 0
                    )
                    oh_hi = jnp.where(hi == iota_hi, 1.0, 0.0).astype(
                        jnp.bfloat16
                    )
                    oh_lo = jnp.where(lo == iota_lo, 1.0, 0.0).astype(
                        jnp.bfloat16
                    )
                    acc_ref[:, :] += jax.lax.dot_general(
                        oh_hi, oh_lo, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )

                pl.when(local < dmax)(step)
            o_ref[pl.dslice(d * Rhi, Rhi), :] = acc_ref[:, :]

    return kernel


# Window-axis block for the scan around bucket gathers: each gathered
# bucket row is 16 int32 lane-padded to 128 on TPU (8x), so a full-width
# [B, W] gather materializes B*W*512 bytes — 12.9GB at [4096, 6144]. The
# scan bounds the live temp to B*blk*512 (~2GB at the default batch).
MEMBER_BLOCK = 1024


def _bucket_decode(l, h_k, e, rows, kind: str):
    """Fold one gathered bucket row [..., 16] into verified weight rows."""
    from .bucket import HI_BITS, SLOTS

    for s in range(SLOTS):
        ek = e[..., s]
        ep = e[..., SLOTS + s]
        if kind == "exact":
            hit = (ek == l) & ((ep & ((1 << HI_BITS) - 1)) == h_k)
            row = ep >> HI_BITS
        else:
            hit = ek == l
            row = ep
        rows = jnp.where(hit, row, rows)
    return rows


def _bucket_rows(
    lo: jnp.ndarray,
    hi: jnp.ndarray,
    table: jnp.ndarray,
    miss: int,
    seed: int,
    kind: str,
) -> jnp.ndarray:
    """Single-probe verified bucket lookup (``ops.bucket.BucketTable``):
    one [16]-int row gather per window + eight VPU compare/selects —
    measured 1.6-2.2x the cuckoo probe pair on v5e. Scan-blocked along the
    window axis to bound the lane-padded gather temporary."""
    Mb = table.shape[0]
    B, W = lo.shape
    miss_rows = jnp.full((B, W), miss, jnp.int32)

    def resolve(l, h_k, r):
        hb = (mix32(l, h_k, seed, xp=jnp) & jnp.uint32(Mb - 1)).astype(
            jnp.int32
        )
        return _bucket_decode(l, h_k, table[hb], r, kind)

    if W <= MEMBER_BLOCK:
        return resolve(lo, hi, miss_rows)
    pad = (-W) % MEMBER_BLOCK
    if pad:
        lo = jnp.pad(lo, ((0, 0), (0, pad)))
        hi = jnp.pad(hi, ((0, 0), (0, pad)))
        miss_rows = jnp.pad(miss_rows, ((0, 0), (0, pad)),
                            constant_values=miss)
    nb = lo.shape[1] // MEMBER_BLOCK
    blocks = tuple(
        a.reshape(B, nb, MEMBER_BLOCK).transpose(1, 0, 2)
        for a in (lo, hi, miss_rows)
    )
    _, rows = jax.lax.scan(
        lambda carry, xs: (carry, resolve(*xs)), None, blocks
    )
    return rows.transpose(1, 0, 2).reshape(B, nb * MEMBER_BLOCK)[:, :W]


def _hist_from_rows(
    rows: jnp.ndarray,
    vmax: jnp.ndarray,
    W: int,
    Rhi: int,
    *,
    block: int,
    interpret: bool,
) -> jnp.ndarray:
    """float32 [B, Rhi*256] per-document row histograms.

    ``rows`` is [B, KW] int32 compact row indices (miss windows already
    pointing at a zeros weight row), KW a multiple of the segment width W,
    W a multiple of ``block``.
    """
    B, KW = rows.shape
    hi = (rows >> 8).astype(jnp.int32)
    lo = (rows & 255).astype(jnp.int32)
    out = pl.pallas_call(
        _build_kernel(KW, W, block, Rhi),
        grid=(B // DB,),
        in_specs=[
            pl.BlockSpec((DB, KW), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((DB, KW), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (DB * Rhi, 256), lambda b: (b, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B * Rhi, 256), jnp.float32),
        scratch_shapes=[pltpu.VMEM((Rhi, 256), jnp.float32)],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(hi, lo, vmax.astype(jnp.int32))
    return out.reshape(B, Rhi * 256)


def pad_weights(weights, rhi: int | None = None):
    """Compact [G+1, L] table -> ([Rhi*256, L] f32 zero-padded, Rhi).

    Rows past the table are never counted (no window resolves there), so
    zero padding is semantically inert. Call once per profile, not per
    batch. Rhi is rounded up to a sublane-friendly multiple of 8.
    """
    import numpy as np

    R, L = weights.shape
    if rhi is None:
        ceil_hi = -(-R // 256)
        rhi = -(-ceil_hi // 8) * 8
    padded = np.zeros((rhi * 256, L), dtype=np.float32)
    padded[:R] = np.asarray(weights, dtype=np.float32)
    return padded, rhi


@partial(
    jax.jit,
    static_argnames=(
        "spec", "rhi", "block", "gram_lengths_subset", "interpret",
        "bucket_seed", "bucket_kind",
    ),
)
def score_batch_hist(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    weights_pad: jnp.ndarray,
    lut: jnp.ndarray | None = None,
    bucket: jnp.ndarray | None = None,
    window_limit: jnp.ndarray | None = None,
    *,
    spec: VocabSpec,
    rhi: int,
    bucket_seed: int = 0,
    bucket_kind: str = "exact",
    block: int = DEFAULT_BLOCK,
    gram_lengths_subset: tuple[int, ...] | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Histogram-strategy scores for a padded batch.

    Same contract as :func:`ops.score.score_batch` /
    :func:`ops.score.score_batch_cuckoo` (masking, Scala ``sliding``
    partial-window rule, ``window_limit``, subset), with the weight table
    pre-padded by :func:`pad_weights`. Membership is the single-probe
    bucket table when ``bucket`` is given (``ops.bucket`` — preferred),
    else the dense id->row ``lut`` (vocabs whose bucket build failed).

    ``bucket_kind`` is the bucket table's key form (``BucketTable.kind``):
    'exact' probes with packed gram keys (cuckoo-derived tables), 'hashed'
    probes with int32 window ids (LUT-derived tables — including EXACT
    vocabs with gram lengths <= 3, whose ids fit int32; the vocab mode does
    NOT determine the key form).
    """
    if (lut is None) == (bucket is None):
        raise ValueError("pass exactly one of bucket (preferred) or lut "
                         "for membership")
    kind = bucket_kind
    B, S = batch.shape
    miss = weights_pad.shape[0] - 1  # any zero row works; use the last
    # The compact table's own miss row G is zero too, but rows arrive in
    # [0, G]; masked windows are pointed at `miss` explicitly below.
    lengths_to_score = (
        gram_lengths_subset if gram_lengths_subset is not None
        else spec.gram_lengths
    )

    segs = []
    W = 0
    for n in lengths_to_score:
        W = max(W, S - n + 1 if S >= n else 1)
    # Lane-clamp the block to the (128-aligned) segment width, then round
    # the common segment width up to a whole number of blocks.
    block = min(block, -(-W // 128) * 128)
    W = -(-W // block) * block

    for n in lengths_to_score:
        if bucket is not None and kind == "exact":
            lo_k, hi_k = window_keys(batch, n)
            rows = _bucket_rows(lo_k, hi_k, bucket, miss, bucket_seed, kind)
            plo, phi = partial_window_keys(batch, lengths, n)
            partial_rows = _bucket_rows(
                plo[:, None], phi[:, None], bucket, miss, bucket_seed, kind
            )[:, 0]
        elif bucket is not None:
            ids = window_ids(batch, n, spec)
            rows = _bucket_rows(
                ids, jnp.zeros_like(ids), bucket, miss, bucket_seed, kind
            )
            pids = partial_window_ids(batch, lengths, n, ids[:, 0], spec)
            partial_rows = _bucket_rows(
                pids[:, None], jnp.zeros_like(pids)[:, None],
                bucket, miss, bucket_seed, kind,
            )[:, 0]
        else:
            ids = window_ids(batch, n, spec)
            rows = lut[ids]
            partial_rows = lut[
                partial_window_ids(batch, lengths, n, ids[:, 0], spec)
            ]
        partial_rows = jnp.where(lengths > 0, partial_rows, miss)
        rows, mask = _splice_partial_windows(
            rows, partial_rows, lengths, n, window_limit
        )
        rows = jnp.where(mask, rows, miss)
        pad = W - rows.shape[1]
        if pad:
            rows = jnp.pad(rows, ((0, 0), (0, pad)), constant_values=miss)
        segs.append(rows)

    rows_all = jnp.concatenate(segs, axis=1) if len(segs) > 1 else segs[0]

    # Conservative per-doc valid-window bound, segment-local: every valid
    # start is < min(len, limit), and the partial-window splice lives at
    # start 0 (included whenever len > 0).
    vmax = jnp.minimum(lengths, W).astype(jnp.int32)
    if window_limit is not None:
        vmax = jnp.minimum(vmax, window_limit.astype(jnp.int32))

    B0 = B
    if B % DB:
        padB = DB - B % DB
        rows_all = jnp.pad(
            rows_all, ((0, padB), (0, 0)), constant_values=miss
        )
        vmax = jnp.pad(vmax, (0, padB))
        B = B0 + padB

    hist = _hist_from_rows(
        rows_all, vmax, W, rhi, block=block, interpret=interpret
    )
    scores = jax.lax.dot(
        hist, weights_pad, precision=jax.lax.Precision.HIGHEST
    )
    return scores[:B0]
