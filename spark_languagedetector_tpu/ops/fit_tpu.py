"""Device-side fit: dense gram counting + weighting + top-k, jit-compiled.

The host fit (``fit.py``) is exact and fast for corpora that fit one host.
This module is the *device* fit step for the distributed path (SURVEY.md §5.8,
§7.2 "dist"): counts accumulate as a dense ``[V, L]`` table by scatter-add, so
multiple data shards combine with a single ``psum`` over the data axis and the
table itself can shard over a model axis (`parallel/fit_sharded.py` wires the
mesh; this module is mesh-agnostic math).

Dense tables want a bounded id space: hashed vocabs (any gram lengths) or
exact vocabs with max length ≤ 2 use this path end-to-end; exact trigram
(V ≈ 16.8M) still works on a real chip but tests keep V small.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .vocab import VocabSpec, partial_window_ids, window_ids


@partial(jax.jit, static_argnames=("spec", "num_langs"))
def gram_counts_dense(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    lang_ids: jnp.ndarray,
    *,
    spec: VocabSpec,
    num_langs: int,
) -> jnp.ndarray:
    """Count windows per (gram id, language) for one padded batch.

    Args:
      batch: uint8 [B, S]; lengths: int32 [B]; lang_ids: int32 [B].
    Returns:
      int32 [V, L] occurrence counts (dense; V = spec.id_space_size).
    """
    B, S = batch.shape
    V = spec.id_space_size
    counts = jnp.zeros((V, num_langs), dtype=jnp.int32)
    for n in spec.gram_lengths:
        W = max(S - n + 1, 1)
        ids = window_ids(batch, n, spec)
        starts = jnp.arange(W, dtype=jnp.int32)[None, :]
        mask = starts <= (lengths[:, None] - n)
        # Partial window of short docs (Scala sliding parity; shared helper).
        short_ids = partial_window_ids(batch, lengths, n, ids[:, 0], spec)
        is_short = lengths < n
        ids = ids.at[:, 0].set(jnp.where(is_short, short_ids, ids[:, 0]))
        mask = mask.at[:, 0].set(mask[:, 0] | (is_short & (lengths > 0)))

        # 2-D scatter (row = gram id, col = language) keeps indices int32-safe
        # for any V × L (a flattened V*L index overflows int32 at CLD2 scale).
        # Masked windows scatter a zero update into (0, lang) — harmless.
        rows = jnp.where(mask, ids, 0).reshape(-1)
        cols = jnp.broadcast_to(lang_ids[:, None], ids.shape).reshape(-1)
        updates = mask.astype(jnp.int32).reshape(-1)
        counts = counts.at[rows, cols].add(updates)
    return counts


@partial(jax.jit, static_argnames=("weight_mode",))
def weights_from_counts(counts: jnp.ndarray, *, weight_mode: str = "parity") -> jnp.ndarray:
    """Dense [V, L] counts → dense [V, L] float32 weights.

    parity: log1p(present / #langs containing) — reference formula (Q1).
    counts: log1p(count / total occurrences of the gram).
    """
    present = counts > 0
    if weight_mode == "parity":
        nlangs = present.sum(axis=1, keepdims=True)
        ratio = jnp.where(nlangs > 0, present / jnp.maximum(nlangs, 1), 0.0)
    else:
        totals = counts.sum(axis=1, keepdims=True)
        ratio = jnp.where(totals > 0, counts / jnp.maximum(totals, 1), 0.0)
    return jnp.log1p(ratio.astype(jnp.float32))


@partial(jax.jit, static_argnames=("k",))
def top_k_rows(weights: jnp.ndarray, *, k: int) -> jnp.ndarray:
    """Per-language top-k row indices over the dense table: int32 [L, k].

    Tie rule: lowest gram id wins (this framework's documented rule; the
    reference's tie order is partition-dependent, SURVEY.md §2.9). The
    parity weight formula produces huge equal-weight plateaus, and the TPU
    lowering of ``lax.top_k`` does NOT honor the lowest-index-first tie
    order its CPU lowering exhibits (found by on-chip fit fuzzing — host
    and device fits picked different plateau members). So the boundary
    plateau is re-ranked explicitly:

    1. value top-k: the k-th value ``w*`` is the boundary; entries with
       value > w* are winners outright (they occupy a sorted-descending
       prefix of the result, in whatever order — ties above the boundary
       are impossible to place wrongly since every strictly-above entry is
       selected).
    2. an int32 top-k over ``-id`` restricted to the ``== w*`` plateau
       yields its members lowest-id-first; the remaining ``k - n_above``
       slots are filled from it. The plateau always has at least that many
       members, so every filled slot is valid.

    Integer keys (not f32 -id) keep id order exact beyond 2^24.
    """
    wT = weights.T  # [L, V]
    V = wT.shape[1]
    vals, idx = jax.lax.top_k(wT, k)
    w_star = vals[:, k - 1 : k]  # [L, 1] boundary value
    n_above = (wT > w_star).sum(axis=1, keepdims=True)  # [L, 1], <= k
    iota = jnp.arange(V, dtype=jnp.int32)[None, :]
    plateau_key = jnp.where(
        wT == w_star, -iota, jnp.iinfo(jnp.int32).min
    )
    _, pidx = jax.lax.top_k(plateau_key, k)  # plateau ids, ascending
    j = jnp.arange(k, dtype=jnp.int32)[None, :]
    shifted = jnp.clip(j - n_above, 0, k - 1)
    plateau_rows = jnp.take_along_axis(pidx, shifted, axis=1)
    return jnp.where(j < n_above, idx, plateau_rows).astype(jnp.int32)


def fit_dense_step(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    lang_ids: jnp.ndarray,
    counts_acc: jnp.ndarray,
    *,
    spec: VocabSpec,
    num_langs: int,
) -> jnp.ndarray:
    """One accumulation step: counts_acc += counts(batch). Streaming fit over
    micro-batches keeps HBM bounded regardless of corpus size."""
    return counts_acc + gram_counts_dense(
        batch, lengths, lang_ids, spec=spec, num_langs=num_langs
    )


def fit_profile_device(
    byte_docs,
    lang_indices,
    num_langs: int,
    spec: VocabSpec,
    profile_size: int,
    weight_mode: str = "parity",
    batch_rows: int = 512,
    mesh=None,
    extra_counts=None,
):
    """Full single-device fit: returns (sorted gram ids [G], weights [G, L]).

    Mirrors :func:`ops.fit.fit_profile_numpy` — candidate set = grams
    occurring anywhere in the corpus; per language, top-k by (weight desc,
    id asc); union of winners with full weight vectors — but streams
    micro-batches through the jit-compiled dense counting step, so the corpus
    never has to fit in memory at once and the count/weight/top-k math runs
    on the accelerator. Only the compact winner rows come back to the host
    (the reference's collect-to-driver step, LanguageDetector.scala:252-254).

    Precision: counts accumulate in int32 on device — exact up to 2^31-1
    occurrences per (gram, language) per fit; corpora beyond that need the
    host fit (int64 throughout). Winner *weights* are recomputed on host in
    float64 from the exact integer counts, so the returned weights match the
    host fit bit-for-bit; only the top-k *selection* happens at float32
    precision, which can pick a different winner when two grams' weights
    differ by less than one f32 ulp (only possible in 'counts' mode — parity
    weights take |L|+1 discrete values).

    ``mesh``: optional ``jax.sharding.Mesh`` — batches shard over its "data"
    axis and the count table stays replicated; GSPMD inserts the cross-shard
    psum (the TPU-native analog of the reference's groupByKey shuffles,
    LanguageDetector.scala:52-66). Pad rows (empty docs) contribute nothing.

    ``extra_counts``: optional (ids [E], langs [E], counts [E]) arrays
    scatter-added into the dense table once — the split long-gram fit uses
    it to inject short-doc partial-window contributions owned by this part
    (:func:`fit_profile_device_split`).
    """
    import numpy as np

    from .encoding import DEFAULT_LENGTH_BUCKETS, bucket_length, pad_batch

    V = spec.id_space_size
    counts = jnp.zeros((V, num_langs), dtype=jnp.int32)
    step = fit_dense_step
    ndata = 1
    if mesh is not None:
        from ..parallel.mesh import DATA_AXIS, replicated
        from ..parallel.sharded import make_sharded_fit_step

        ndata = int(mesh.shape[DATA_AXIS])
        counts = jax.device_put(counts, replicated(mesh))
        sharded = make_sharded_fit_step(mesh, spec, num_langs, shard_vocab=False)

        def step(batch, lengths, lang_ids, acc, **_):
            return sharded(batch, lengths, lang_ids, acc)

    lang_arr = np.asarray(lang_indices, dtype=np.int32)
    order = np.argsort([len(d) for d in byte_docs], kind="stable")
    max_bucket = DEFAULT_LENGTH_BUCKETS[-1]
    for start in range(0, len(order), batch_rows):
        sel = order[start : start + batch_rows]
        docs = [byte_docs[i] for i in sel]
        langs = lang_arr[sel]
        if ndata > 1:
            from ..parallel.mesh import pad_rows_for_mesh

            docs, langs = pad_rows_for_mesh(docs, ndata, (langs, 0))
        longest = max((len(d) for d in docs), default=1)
        if longest <= max_bucket:
            pad_to = bucket_length(longest, DEFAULT_LENGTH_BUCKETS)
        else:  # oversized docs: round up (recompiles per distinct width)
            pad_to = -(-longest // 2048) * 2048
        batch, lengths = pad_batch(docs, pad_to=pad_to)
        counts = step(
            jnp.asarray(batch),
            jnp.asarray(lengths),
            jnp.asarray(langs),
            counts,
            spec=spec,
            num_langs=num_langs,
        )

    if extra_counts is not None:
        e_ids, e_langs, e_counts = (
            jnp.asarray(np.asarray(a, dtype=np.int32)) for a in extra_counts
        )
        if e_ids.size:
            counts = counts.at[e_ids, e_langs].add(e_counts)

    dense_w = weights_from_counts(counts, weight_mode=weight_mode)
    occurred = counts.sum(axis=1) > 0
    # Non-occurred rows are not candidates (the reference's table only holds
    # grams seen in training); mask them below any real weight for top-k.
    masked = jnp.where(occurred[:, None], dense_w, -jnp.inf)
    k = min(profile_size, V)
    top = top_k_rows(masked, k=k)  # [L, k]; ties → lowest id (re-ranked)

    top_np = np.unique(np.asarray(top).reshape(-1))
    occurred_np = np.asarray(occurred[jnp.asarray(top_np)])
    rows = top_np[occurred_np]  # dense row index == gram id
    # Recompute winner weights on host in float64 from the exact integer
    # counts (see docstring) instead of fetching the device's float32 table.
    counts_rows = np.asarray(counts[jnp.asarray(rows)], dtype=np.int64)
    if weight_mode == "parity":
        present = counts_rows > 0
        nlangs = present.sum(axis=1, keepdims=True)
        ratio = np.where(present, 1.0 / np.maximum(nlangs, 1), 0.0)
    else:
        totals = counts_rows.sum(axis=1, keepdims=True)
        ratio = counts_rows / np.maximum(totals, 1)
    weights = np.log1p(ratio.astype(np.float64))
    return rows.astype(np.int64), weights


def fit_profile_device_split(
    byte_docs,
    lang_indices,
    num_langs: int,
    spec: VocabSpec,
    profile_size: int,
    weight_mode: str = "parity",
    mesh=None,
):
    """Device fit for exact vocabs with gram lengths > 3 (VERDICT r2 #9).

    No dense device table can hold the 256^4..256^5 long-gram id space, so
    the corpus is counted in two disjoint parts, split by the RESULTING
    gram's length (not the window class — a 2-byte doc's partial window for
    n=5 is a 2-gram):

      * gram length <= 3 -> the device dense fit over the (1..3)-length
        sub-spec (ids identical to the full spec's — exact offsets stack
        lengths ascending), with short docs' extra partial windows for the
        long classes injected via ``extra_counts``;
      * gram length >= 4 -> the exact host counting path, restricted to the
        long window classes with short-gram partials excluded
        (``min_partial_gram_len=4``).

    The id sets are disjoint, and a gram's weight depends only on its own
    per-language counts, so per-part weighting is exact; the final profile
    is the joint per-language top-k over the union of both parts' top-k
    (top-k of a union is contained in the union of top-k's under the total
    (-weight, id) order). Cross-checked bit-for-bit against the pure host
    fit in tests/test_fit_device.py.
    """
    import numpy as np

    from . import fit as fit_ops

    low_lengths = tuple(n for n in spec.gram_lengths if n <= 3)
    long_lengths = tuple(n for n in spec.gram_lengths if n > 3)
    if not long_lengths:
        raise ValueError("split fit is for specs with gram lengths > 3")
    if not low_lengths:
        # Nothing is device-countable: the exact host path is the fit.
        return fit_ops.fit_profile_numpy(
            byte_docs, lang_indices, num_langs, spec, profile_size,
            weight_mode,
        )
    from .vocab import EXACT

    spec_low = VocabSpec(EXACT, low_lengths)

    # Short docs' partial windows for the long classes whose gram (the whole
    # doc) is <= 3 bytes: owned by the device part, injected as extra counts.
    lang_arr = np.asarray(lang_indices, dtype=np.int64)
    corr: dict[tuple[int, int], int] = {}
    for doc, lang in zip(byte_docs, lang_arr):
        n_doc = len(doc)
        if 0 < n_doc <= 3:
            reps = sum(1 for n in long_lengths if n > n_doc)
            if reps:
                key = (spec_low.gram_to_id(bytes(doc)), int(lang))
                corr[key] = corr.get(key, 0) + reps
    extra = None
    if corr:
        e = np.asarray(
            [(i, l, c) for (i, l), c in corr.items()], dtype=np.int64
        )
        extra = (e[:, 0], e[:, 1], e[:, 2])

    ids_low, w_low = fit_profile_device(
        byte_docs, lang_arr, num_langs, spec_low, profile_size,
        weight_mode, mesh=mesh, extra_counts=extra,
    )

    gc = fit_ops.extract_gram_counts(
        byte_docs, lang_arr, num_langs, spec,
        gram_lengths_subset=long_lengths, min_partial_gram_len=4,
    )
    ids_high, w_high = fit_ops.compute_weights(gc, weight_mode)
    ids_high, w_high = fit_ops.select_top_grams(
        ids_high, w_high, profile_size
    )

    all_ids = np.concatenate([np.asarray(ids_low, np.int64), ids_high])
    all_w = np.concatenate(
        [np.asarray(w_low, np.float64), np.asarray(w_high, np.float64)]
    )
    ids, weights = fit_ops.select_top_grams(all_ids, all_w, profile_size)
    order = np.argsort(ids)
    return ids[order], np.ascontiguousarray(weights[order])
