"""Device-side fit: dense gram counting + weighting + top-k, jit-compiled.

The host fit (``fit.py``) is exact and fast for corpora that fit one host.
This module is the *device* fit step for the distributed path (SURVEY.md §5.8,
§7.2 "dist"): counts accumulate as a dense ``[V, L]`` table by scatter-add, so
multiple data shards combine with a single ``psum`` over the data axis and the
table itself can shard over a model axis (`parallel/fit_sharded.py` wires the
mesh; this module is mesh-agnostic math).

Dense tables want a bounded id space: hashed vocabs (any gram lengths) or
exact vocabs with max length ≤ 2 use this path end-to-end; exact trigram
(V ≈ 16.8M) still works on a real chip but tests keep V small.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .vocab import VocabSpec, partial_window_ids, window_ids


@partial(jax.jit, static_argnames=("spec", "num_langs"))
def gram_counts_dense(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    lang_ids: jnp.ndarray,
    *,
    spec: VocabSpec,
    num_langs: int,
) -> jnp.ndarray:
    """Count windows per (gram id, language) for one padded batch.

    Args:
      batch: uint8 [B, S]; lengths: int32 [B]; lang_ids: int32 [B].
    Returns:
      int32 [V, L] occurrence counts (dense; V = spec.id_space_size).
    """
    B, S = batch.shape
    V = spec.id_space_size
    counts = jnp.zeros((V, num_langs), dtype=jnp.int32)
    for n in spec.gram_lengths:
        W = max(S - n + 1, 1)
        ids = window_ids(batch, n, spec)
        starts = jnp.arange(W, dtype=jnp.int32)[None, :]
        mask = starts <= (lengths[:, None] - n)
        # Partial window of short docs (Scala sliding parity; shared helper).
        short_ids = partial_window_ids(batch, lengths, n, ids[:, 0], spec)
        is_short = lengths < n
        ids = ids.at[:, 0].set(jnp.where(is_short, short_ids, ids[:, 0]))
        mask = mask.at[:, 0].set(mask[:, 0] | (is_short & (lengths > 0)))

        # 2-D scatter (row = gram id, col = language) keeps indices int32-safe
        # for any V × L (a flattened V*L index overflows int32 at CLD2 scale).
        # Masked windows scatter a zero update into (0, lang) — harmless.
        rows = jnp.where(mask, ids, 0).reshape(-1)
        cols = jnp.broadcast_to(lang_ids[:, None], ids.shape).reshape(-1)
        updates = mask.astype(jnp.int32).reshape(-1)
        counts = counts.at[rows, cols].add(updates)
    return counts


@partial(jax.jit, static_argnames=("weight_mode",))
def weights_from_counts(counts: jnp.ndarray, *, weight_mode: str = "parity") -> jnp.ndarray:
    """Dense [V, L] counts → dense [V, L] float32 weights.

    parity: log1p(present / #langs containing) — reference formula (Q1).
    counts: log1p(count / total occurrences of the gram).
    """
    present = counts > 0
    if weight_mode == "parity":
        nlangs = present.sum(axis=1, keepdims=True)
        ratio = jnp.where(nlangs > 0, present / jnp.maximum(nlangs, 1), 0.0)
    else:
        totals = counts.sum(axis=1, keepdims=True)
        ratio = jnp.where(totals > 0, counts / jnp.maximum(totals, 1), 0.0)
    return jnp.log1p(ratio.astype(jnp.float32))


@partial(jax.jit, static_argnames=("k",))
def top_k_rows(weights: jnp.ndarray, *, k: int) -> jnp.ndarray:
    """Per-language top-k row indices over the dense table: int32 [L, k].

    ``lax.top_k`` breaks ties by lowest index — deterministic, and documented
    as this framework's tie rule (the reference's tie order is
    partition-dependent, SURVEY.md §2.9).
    """
    _, idx = jax.lax.top_k(weights.T, k)  # [L, k]
    return idx.astype(jnp.int32)


def fit_dense_step(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    lang_ids: jnp.ndarray,
    counts_acc: jnp.ndarray,
    *,
    spec: VocabSpec,
    num_langs: int,
) -> jnp.ndarray:
    """One accumulation step: counts_acc += counts(batch). Streaming fit over
    micro-batches keeps HBM bounded regardless of corpus size."""
    return counts_acc + gram_counts_dense(
        batch, lengths, lang_ids, spec=spec, num_langs=num_langs
    )


def fit_profile_device(
    byte_docs,
    lang_indices,
    num_langs: int,
    spec: VocabSpec,
    profile_size: int,
    weight_mode: str = "parity",
    batch_rows: int = 512,
    mesh=None,
):
    """Full single-device fit: returns (sorted gram ids [G], weights [G, L]).

    Mirrors :func:`ops.fit.fit_profile_numpy` — candidate set = grams
    occurring anywhere in the corpus; per language, top-k by (weight desc,
    id asc); union of winners with full weight vectors — but streams
    micro-batches through the jit-compiled dense counting step, so the corpus
    never has to fit in memory at once and the count/weight/top-k math runs
    on the accelerator. Only the compact winner rows come back to the host
    (the reference's collect-to-driver step, LanguageDetector.scala:252-254).

    Precision: counts accumulate in int32 on device — exact up to 2^31-1
    occurrences per (gram, language) per fit; corpora beyond that need the
    host fit (int64 throughout). Winner *weights* are recomputed on host in
    float64 from the exact integer counts, so the returned weights match the
    host fit bit-for-bit; only the top-k *selection* happens at float32
    precision, which can pick a different winner when two grams' weights
    differ by less than one f32 ulp (only possible in 'counts' mode — parity
    weights take |L|+1 discrete values).

    ``mesh``: optional ``jax.sharding.Mesh`` — batches shard over its "data"
    axis and the count table stays replicated; GSPMD inserts the cross-shard
    psum (the TPU-native analog of the reference's groupByKey shuffles,
    LanguageDetector.scala:52-66). Pad rows (empty docs) contribute nothing.
    """
    import numpy as np

    from .encoding import DEFAULT_LENGTH_BUCKETS, bucket_length, pad_batch

    V = spec.id_space_size
    counts = jnp.zeros((V, num_langs), dtype=jnp.int32)
    step = fit_dense_step
    ndata = 1
    if mesh is not None:
        from ..parallel.mesh import DATA_AXIS, replicated
        from ..parallel.sharded import make_sharded_fit_step

        ndata = int(mesh.shape[DATA_AXIS])
        counts = jax.device_put(counts, replicated(mesh))
        sharded = make_sharded_fit_step(mesh, spec, num_langs, shard_vocab=False)

        def step(batch, lengths, lang_ids, acc, **_):
            return sharded(batch, lengths, lang_ids, acc)

    lang_arr = np.asarray(lang_indices, dtype=np.int32)
    order = np.argsort([len(d) for d in byte_docs], kind="stable")
    max_bucket = DEFAULT_LENGTH_BUCKETS[-1]
    for start in range(0, len(order), batch_rows):
        sel = order[start : start + batch_rows]
        docs = [byte_docs[i] for i in sel]
        langs = lang_arr[sel]
        if ndata > 1:
            from ..parallel.mesh import pad_rows_for_mesh

            docs, langs = pad_rows_for_mesh(docs, ndata, (langs, 0))
        longest = max((len(d) for d in docs), default=1)
        if longest <= max_bucket:
            pad_to = bucket_length(longest, DEFAULT_LENGTH_BUCKETS)
        else:  # oversized docs: round up (recompiles per distinct width)
            pad_to = -(-longest // 2048) * 2048
        batch, lengths = pad_batch(docs, pad_to=pad_to)
        counts = step(
            jnp.asarray(batch),
            jnp.asarray(lengths),
            jnp.asarray(langs),
            counts,
            spec=spec,
            num_langs=num_langs,
        )

    dense_w = weights_from_counts(counts, weight_mode=weight_mode)
    occurred = counts.sum(axis=1) > 0
    # Non-occurred rows are not candidates (the reference's table only holds
    # grams seen in training); mask them below any real weight for top-k.
    masked = jnp.where(occurred[:, None], dense_w, -jnp.inf)
    k = min(profile_size, V)
    top = top_k_rows(masked, k=k)  # [L, k]; lax.top_k ties → lowest id

    top_np = np.unique(np.asarray(top).reshape(-1))
    occurred_np = np.asarray(occurred[jnp.asarray(top_np)])
    rows = top_np[occurred_np]  # dense row index == gram id
    # Recompute winner weights on host in float64 from the exact integer
    # counts (see docstring) instead of fetching the device's float32 table.
    counts_rows = np.asarray(counts[jnp.asarray(rows)], dtype=np.int64)
    if weight_mode == "parity":
        present = counts_rows > 0
        nlangs = present.sum(axis=1, keepdims=True)
        ratio = np.where(present, 1.0 / np.maximum(nlangs, 1), 0.0)
    else:
        totals = counts_rows.sum(axis=1, keepdims=True)
        ratio = counts_rows / np.maximum(totals, 1)
    weights = np.log1p(ratio.astype(np.float64))
    return rows.astype(np.int64), weights
