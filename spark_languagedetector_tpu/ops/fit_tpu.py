"""Device-side fit: dense gram counting + weighting + top-k, jit-compiled.

The host fit (``fit.py``) is exact and fast for corpora that fit one host.
This module is the *device* fit step for the distributed path (SURVEY.md §5.8,
§7.2 "dist"): counts accumulate as a dense ``[V, L]`` table by scatter-add, so
multiple data shards combine with a single ``psum`` over the data axis and the
table itself can shard over a model axis (`parallel/fit_sharded.py` wires the
mesh; this module is mesh-agnostic math).

Dense tables want a bounded id space: hashed vocabs (any gram lengths) or
exact vocabs with max length ≤ 2 use this path end-to-end; exact trigram
(V ≈ 16.8M) still works on a real chip but tests keep V small.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ..telemetry import REGISTRY, span
from ..telemetry.gauges import note_donation_reuse
from .vocab import VocabSpec, partial_window_ids, window_ids


@partial(jax.jit, static_argnames=("spec", "num_langs"))
def gram_counts_dense(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    lang_ids: jnp.ndarray,
    mult: jnp.ndarray | None = None,
    *,
    spec: VocabSpec,
    num_langs: int,
) -> jnp.ndarray:
    """Count windows per (gram id, language) for one padded batch.

    Args:
      batch: uint8 [B, S]; lengths: int32 [B]; lang_ids: int32 [B];
      mult: optional int32 [B] per-row multiplicity — a deduplicated row
        (docs/PERFORMANCE.md §10) counts exactly as many times as its
        duplicates did, so dedup stays bit-preserving: integer window
        counts scaled by an integer weight equal the duplicated sum.
        ``None`` compiles the historical weightless program.
    Returns:
      int32 [V, L] occurrence counts (dense; V = spec.id_space_size).
    """
    B, S = batch.shape
    V = spec.id_space_size
    counts = jnp.zeros((V, num_langs), dtype=jnp.int32)
    for n in spec.gram_lengths:
        W = max(S - n + 1, 1)
        ids = window_ids(batch, n, spec)
        starts = jnp.arange(W, dtype=jnp.int32)[None, :]
        mask = starts <= (lengths[:, None] - n)
        # Partial window of short docs (Scala sliding parity; shared helper).
        short_ids = partial_window_ids(batch, lengths, n, ids[:, 0], spec)
        is_short = lengths < n
        ids = ids.at[:, 0].set(jnp.where(is_short, short_ids, ids[:, 0]))
        mask = mask.at[:, 0].set(mask[:, 0] | (is_short & (lengths > 0)))

        # 2-D scatter (row = gram id, col = language) keeps indices int32-safe
        # for any V × L (a flattened V*L index overflows int32 at CLD2 scale).
        # Masked windows scatter a zero update into (0, lang) — harmless.
        rows = jnp.where(mask, ids, 0).reshape(-1)
        cols = jnp.broadcast_to(lang_ids[:, None], ids.shape).reshape(-1)
        updates = mask.astype(jnp.int32)
        if mult is not None:
            updates = updates * mult.astype(jnp.int32)[:, None]
        counts = counts.at[rows, cols].add(updates.reshape(-1))
    return counts


@partial(jax.jit, static_argnames=("weight_mode",))
def weights_from_counts(counts: jnp.ndarray, *, weight_mode: str = "parity") -> jnp.ndarray:
    """Dense [V, L] counts → dense [V, L] float32 weights.

    parity: log1p(present / #langs containing) — reference formula (Q1).
    counts: log1p(count / total occurrences of the gram).
    """
    present = counts > 0
    if weight_mode == "parity":
        nlangs = present.sum(axis=1, keepdims=True)
        ratio = jnp.where(nlangs > 0, present / jnp.maximum(nlangs, 1), 0.0)
    else:
        totals = counts.sum(axis=1, keepdims=True)
        ratio = jnp.where(totals > 0, counts / jnp.maximum(totals, 1), 0.0)
    return jnp.log1p(ratio.astype(jnp.float32))


@partial(jax.jit, static_argnames=("k",))
def top_k_rows(weights: jnp.ndarray, *, k: int) -> jnp.ndarray:
    """Per-language top-k row indices over the dense table: int32 [L, k].

    Tie rule: lowest gram id wins (this framework's documented rule; the
    reference's tie order is partition-dependent, SURVEY.md §2.9). The
    parity weight formula produces huge equal-weight plateaus, and the TPU
    lowering of ``lax.top_k`` does NOT honor the lowest-index-first tie
    order its CPU lowering exhibits (found by on-chip fit fuzzing — host
    and device fits picked different plateau members). So the boundary
    plateau is re-ranked explicitly:

    1. value top-k: the k-th value ``w*`` is the boundary; entries with
       value > w* are winners outright (they occupy a sorted-descending
       prefix of the result, in whatever order — ties above the boundary
       are impossible to place wrongly since every strictly-above entry is
       selected).
    2. an int32 top-k over ``-id`` restricted to the ``== w*`` plateau
       yields its members lowest-id-first; the remaining ``k - n_above``
       slots are filled from it. The plateau always has at least that many
       members, so every filled slot is valid.

    Integer keys (not f32 -id) keep id order exact beyond 2^24. One
    implementation site: this is :func:`_block_top_k` over the whole table
    as a single block (ids == row indices at offset 0).
    """
    return _block_top_k(weights.T, k, 0)[1]


# Beyond this many dense-table elements the single-shot lax.top_k sort
# (whose TPU lowering materializes [L, V] f32 + s32 sort temps) would OOM a
# 16GB chip — config 3's exact-trigram table is 16.8M × 50 = 842M elements,
# ~13GB of sort temp. The blocked two-stage top-k below bounds the sort to
# [L, block] per step.
TOPK_SORT_BUDGET_ELEMS = 256 * 1024 * 1024


@partial(jax.jit, static_argnames=("weight_mode",))
def masked_candidate_weights(counts: jnp.ndarray, *, weight_mode: str):
    """Masked weights [V, L] in ONE compiled program, so the unmasked
    weight table never materializes as a separate buffer — at config-3
    scale each [V, L] f32 buffer is 3.4GB and the fit's HBM peak is what
    decides whether the single-chip device fit fits at all. Non-occurred
    rows mask to -inf (not candidates)."""
    w = weights_from_counts(counts, weight_mode=weight_mode)
    occurred = counts.sum(axis=1) > 0
    return jnp.where(occurred[:, None], w, -jnp.inf)


def _block_top_k(blk: jnp.ndarray, k: int, id_offset: int):
    """(values [L, k], global ids [L, k]) for one vocab block under the
    (value desc, id asc) total order — the same boundary-plateau re-ranking
    as :func:`top_k_rows`, with ids offset into the global vocab axis."""
    L, W = blk.shape
    vals, idx = jax.lax.top_k(blk, k)
    w_star = vals[:, k - 1 : k]
    n_above = (blk > w_star).sum(axis=1, keepdims=True)
    iota = jnp.arange(W, dtype=jnp.int32)[None, :]
    plateau_key = jnp.where(blk == w_star, -iota, jnp.iinfo(jnp.int32).min)
    _, pidx = jax.lax.top_k(plateau_key, k)
    j = jnp.arange(k, dtype=jnp.int32)[None, :]
    shifted = jnp.clip(j - n_above, 0, k - 1)
    rows = jnp.where(
        j < n_above, idx, jnp.take_along_axis(pidx, shifted, axis=1)
    )
    gvals = jnp.take_along_axis(blk, rows, axis=1)
    # id_offset: python int (unrolled path) or traced int32 (scan path).
    return gvals, rows.astype(jnp.int32) + id_offset


def _candidates_top_k(cv: jnp.ndarray, ci: jnp.ndarray, k: int):
    """Top-k over (value, real-id) candidate pairs under the (value desc,
    id asc) total order: value top-k for the strictly-above entries, then
    the boundary plateau re-ranked by the candidates' REAL ids (not
    positions) so global tie order holds. Returns (values [L, k],
    ids [L, k]) — the values ride along so the selection composes: a
    shard's candidates can themselves be merged by a further
    ``_candidates_top_k`` (the cross-shard collective merge) without
    re-deriving them."""
    fvals, fidx = jax.lax.top_k(cv, k)
    w_star = fvals[:, k - 1 : k]
    n_above = (cv > w_star).sum(axis=1, keepdims=True)
    plateau_key = jnp.where(cv == w_star, -ci, jnp.iinfo(jnp.int32).min)
    pvals, _ = jax.lax.top_k(plateau_key, k)
    plateau_ids = -pvals  # ascending real ids; slots past the plateau are
    j = jnp.arange(k, dtype=jnp.int32)[None, :]  # never selected (see proof
    shifted = jnp.clip(j - n_above, 0, k - 1)  # in top_k_rows_blocked)
    above_ids = jnp.take_along_axis(ci, fidx, axis=1)
    ids = jnp.where(
        j < n_above,
        above_ids,
        jnp.take_along_axis(plateau_ids, shifted, axis=1),
    ).astype(jnp.int32)
    # Selected plateau slots all sit exactly at the boundary value.
    vals = jnp.where(j < n_above, fvals, jnp.broadcast_to(w_star, fvals.shape))
    return vals, ids


def _final_candidates_top_k(cv: jnp.ndarray, ci: jnp.ndarray, k: int):
    return _candidates_top_k(cv, ci, k)[1]


def shard_topk_candidates(
    masked: jnp.ndarray, k: int, id_offset, *, block: int = 1 << 21
):
    """One vocab shard's top-k candidates (values [L, k], GLOBAL ids [L, k])
    under the (value desc, id asc) total order — the per-shard half of the
    distributed finalize (``parallel.sharded.make_sharded_finalize_topk``).
    ``id_offset`` (python int or traced int32 — inside shard_map it is
    ``axis_index * rows_per_shard``) lifts local row indices to global gram
    ids, so the cross-shard merge ranks ties by REAL id and the collective
    finalize keeps the host fit's lowest-index tie order. Shards wider than
    ``block`` walk in blocks to bound the lax.top_k sort temp, exactly like
    :func:`top_k_rows_blocked`."""
    wT = masked.T  # [L, Vs]
    L, Vs = wT.shape
    if Vs <= block:
        return _block_top_k(wT, k, id_offset)
    cand_v, cand_i = [], []
    for s in range(0, Vs, block):
        blk = wT[:, s : s + block]
        bk = min(k, blk.shape[1])
        bv, bi = _block_top_k(blk, bk, id_offset + s)
        cand_v.append(bv)
        cand_i.append(bi)
    cv = jnp.concatenate(cand_v, axis=1)
    ci = jnp.concatenate(cand_i, axis=1)
    return _candidates_top_k(cv, ci, k)


@partial(jax.jit, static_argnames=("k", "block"))
def top_k_rows_blocked(
    weights: jnp.ndarray, *, k: int, block: int = 1 << 21
) -> jnp.ndarray:
    """Two-stage top-k over the vocab axis: per-block winners under the
    (value desc, id asc) total order, then a final selection over the
    gathered candidates — SURVEY §7.4's "sharded top_k + merge",
    single-device edition (the mesh path gets the same effect from GSPMD's
    local-top-k + cross-shard merge over the vocab sharding).

    Exact: any member of the global top-k has at most k-1 entries ahead of
    it under the total order, hence at most k-1 within its own block, so it
    survives its block's top-k; and a block's plateau contribution (lowest
    ids first) always covers the global selection's need from that block
    (needed-from-block ≤ k − that block's above-boundary count). Bounds the
    lax.top_k sort temp to [L, block] instead of [L, V].
    """
    wT = weights.T  # [L, V]
    L, V = wT.shape
    if V <= block:
        return top_k_rows(weights, k=k)
    cand_v, cand_i = [], []
    for s in range(0, V, block):
        blk = wT[:, s : s + block]
        bk = min(k, blk.shape[1])
        bv, bi = _block_top_k(blk, bk, s)
        cand_v.append(bv)
        cand_i.append(bi)
    cv = jnp.concatenate(cand_v, axis=1)
    ci = jnp.concatenate(cand_i, axis=1)
    return _final_candidates_top_k(cv, ci, k)


@partial(jax.jit, static_argnames=("weight_mode", "k", "block"))
def finalize_topk_blocked(
    counts: jnp.ndarray,
    *,
    weight_mode: str,
    k: int,
    block: int = 1 << 21,
) -> jnp.ndarray:
    """Count table → top-k rows WITHOUT ever materializing the full [V, L]
    weight table: a lax.scan walks the vocab axis block by block, computing
    each block's weights + candidate mask from its COUNT slice and keeping
    only its top-k (value desc, id asc) candidates; a final selection over
    the gathered candidates finishes the job.

    This is the memory shape that actually fits config-3 scale on one chip
    (V=16.8M × L=50): the naive finalize needs counts (3.4GB) + weights
    (3.4GB) + masked (3.4GB) + a [L, V] transpose (3.4GB) + an [L, V] sort
    temp (~13GB); this program's working set is counts + one
    [block, L]/[L, block] slice pipeline (~5GB — even a padded copy of
    counts proved too much for the compile-time budget, so the tail block
    slides BACK to stay in bounds instead of padding). Lanes a tail block
    re-reads from its predecessor are masked to -inf and their ids set to
    the sentinel V; -inf candidates can only surface for a language with
    fewer than k real candidates, and the caller filters both by id < V
    and by occurrence, so the final profile is unaffected.
    """
    V, L = counts.shape
    block = min(block, V)
    nb = -(-V // block)

    def body(carry, i):
        start = jnp.minimum(i * block, V - block)
        cblk = jax.lax.dynamic_slice_in_dim(counts, start, block, 0)
        w = weights_from_counts(cblk, weight_mode=weight_mode)
        occ = cblk.sum(axis=1) > 0
        # Tail block: lanes before i*block were already owned by the
        # previous block — exclude them from this block's candidates.
        lane = jnp.arange(block, dtype=jnp.int32)
        owned = (start + lane) >= i * block
        blk = jnp.where((occ & owned)[:, None], w, -jnp.inf).T  # [L, block]
        bv, bi = _block_top_k(blk, min(k, block), start)
        bi = jnp.where(bi >= i * block, bi, jnp.int32(V))  # unowned → V
        return carry, (bv, bi)

    _, (vals, ids) = jax.lax.scan(
        body, None, jnp.arange(nb, dtype=jnp.int32)
    )
    cv = vals.transpose(1, 0, 2).reshape(L, -1)
    ci = ids.transpose(1, 0, 2).reshape(L, -1)
    return _final_candidates_top_k(cv, ci, k)


def fit_dense_step(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    lang_ids: jnp.ndarray,
    counts_acc: jnp.ndarray,
    mult: jnp.ndarray | None = None,
    *,
    spec: VocabSpec,
    num_langs: int,
) -> jnp.ndarray:
    """One accumulation step: counts_acc += counts(batch). Streaming fit over
    micro-batches keeps HBM bounded regardless of corpus size. ``mult`` is
    the optional per-row dedup multiplicity (see :func:`gram_counts_dense`);
    duplicate-free batches pass None and compile the historical program."""
    return counts_acc + gram_counts_dense(
        batch, lengths, lang_ids, mult, spec=spec, num_langs=num_langs
    )


# Donating accumulation step for the single-device fit loop: the [V, L]
# accumulator is the fit's dominant buffer (3.4GB at config-3 scale), and
# the loop never reads the pre-step value again — donating it lets XLA
# update in place instead of double-buffering. Accelerators only: the CPU
# backend can't consume donations and would warn per batch. One body, two
# compilations — the math can never diverge between the two step modes.
_fit_dense_step_donated = partial(
    jax.jit, static_argnames=("spec", "num_langs"), donate_argnums=(3,)
)(fit_dense_step)


@dataclass
class DeviceFitContext:
    """How one device fit (or incremental refit) runs: the zero accumulator,
    the count step, batch placement, and whether the [V, L] table is sharded
    over the mesh's table axis. Built once per fit by
    :func:`device_fit_context` and shared by ``fit_profile_device`` and the
    incremental ``models.refit.FitAccumulator`` so the two paths can never
    drift."""

    counts: jnp.ndarray
    step: object
    placement: object
    ndata: int
    donate: bool
    table_sharded: bool
    mesh: object


def device_fit_context(
    spec: VocabSpec, num_langs: int, mesh=None
) -> DeviceFitContext:
    """Resolve the count-step machinery for a (spec, mesh) pair.

    ``mesh``: batches shard over its data axis; the count accumulator
    shards over the TABLE axis (``parallel.mesh.table_axis`` — the vocab
    axis when it has devices, else the data axis) whenever the id space
    divides evenly and the mesh is single-process, which turns the
    per-step GSPMD count reduction into a reduce-scatter and bounds each
    device's finalize to V/shards rows. Multi-process meshes (and
    non-dividing id spaces) keep the replicated accumulator — every
    process must enqueue identical collectives, and the replicated form
    is the one whose schedule is pinned by the lockstep story.
    """
    V = spec.id_space_size
    counts = jnp.zeros((V, num_langs), dtype=jnp.int32)
    step = fit_dense_step
    ndata = 1
    donate = False
    placement = None
    table_sharded = False
    if mesh is not None:
        from ..parallel.mesh import (
            DATA_AXIS,
            batch_sharding,
            replicated,
            table_shards,
            table_sharding,
        )
        from ..parallel.sharded import make_sharded_fit_step

        ndata = int(mesh.shape[DATA_AXIS])
        nshards = table_shards(mesh)
        table_sharded = (
            nshards > 1 and V % nshards == 0 and jax.process_count() == 1
        )
        acc_sharding = table_sharding(mesh) if table_sharded else replicated(mesh)
        counts = jax.device_put(counts, acc_sharding)
        placement = batch_sharding(mesh)
        sharded = make_sharded_fit_step(
            mesh, spec, num_langs, shard_table=table_sharded
        )

        def step(batch, lengths, lang_ids, acc, mult=None, **_):
            return sharded(batch, lengths, lang_ids, acc, mult=mult)

    elif jax.devices()[0].platform != "cpu":
        step = _fit_dense_step_donated
        donate = True
    return DeviceFitContext(
        counts, step, placement, ndata, donate, table_sharded, mesh
    )


def accumulate_counts(
    ctx: DeviceFitContext,
    counts,
    byte_docs,
    lang_arr,
    *,
    spec: VocabSpec,
    num_langs: int,
    batch_rows: int | None = None,
    extra_counts=None,
):
    """One pipelined counting pass: ``counts += counts(byte_docs)``.

    The count half of the device fit, factored out so the incremental
    refit engine updates its persisted accumulator through the *same*
    plan/pack/put/count pipeline (``ops.fit_pipeline``) the from-scratch
    fit uses — int32 scatter-add is order- and batching-independent, which
    is what makes refit ≡ from-scratch bit-exact. Chunk-split straddle
    windows and caller ``extra_counts`` ride the one-shot scatter at the
    end of the pass.
    """
    import numpy as np

    from .fit_pipeline import (
        iter_device_batches,
        plan_fit_batches,
        resolve_fit_batching,
    )

    fixed_rows, byte_budget = resolve_fit_batching(batch_rows)
    items, item_langs, plan, straddle, item_mult = plan_fit_batches(
        byte_docs, lang_arr, spec,
        batch_rows=fixed_rows, byte_budget=byte_budget,
    )
    # (rows, pad_to) -> dispatch count: exactly the compiled-shape set, so
    # the roofline gauges below bill the loop's true cost (billing every
    # step at the largest shape overstates small/tail steps by orders of
    # magnitude).
    step_shapes: dict[tuple[int, int], int] = {}
    with span(
        "fit/count", docs=len(byte_docs), backend="device", shards=ctx.ndata,
        batches=len(plan),
    ) as count_span:
        from ..resilience import faults

        # Pipelined ingest (ops.fit_pipeline): the packer thread keeps ≥2
        # packed-and-transferring batches ahead of this loop; ragged
        # transfer applies on single-device dispatch only (a mesh shards
        # the padded batch itself — same rule as the scoring runner).
        batches = iter_device_batches(
            items, item_langs, plan, item_mult=item_mult,
            placement=ctx.placement, ragged=ctx.mesh is None, ndata=ctx.ndata,
            parent=count_span.parent,
        )
        try:
            for batch, lengths, langs, mult, rows, pad_to in batches:
                faults.inject("fit/count")  # chaos: one call per count step
                key = (rows, pad_to)
                step_shapes[key] = step_shapes.get(key, 0) + 1
                prev = counts
                counts = ctx.step(
                    batch, lengths, langs, counts, mult=mult,
                    spec=spec, num_langs=num_langs,
                )
                if ctx.donate:
                    note_donation_reuse(prev)
        finally:
            # Deterministic teardown: an injected/count-step failure stops
            # the packer thread before the error leaves this frame, so the
            # estimator-level replay starts from a clean slate.
            batches.close()
        # Count dispatch is async: fencing (opt-in) bills the span the
        # device_s through the last batch's completion.
        count_span.fence(counts)

    # Boundary windows severed by oversized-doc chunk-splitting ride the
    # same one-shot scatter as caller-provided extra counts (duplicate
    # (id, lang) pairs accumulate — scatter-add semantics).
    if straddle is not None:
        if extra_counts is None:
            extra_counts = straddle
        else:
            extra_counts = tuple(
                np.concatenate(
                    [np.asarray(a, dtype=np.int64), np.asarray(b, dtype=np.int64)]
                )
                for a, b in zip(extra_counts, straddle)
            )

    # Roofline gauges for the count loop (single-device only — the GSPMD
    # program's cost model is per-process): summed per-shape program cost
    # over the shapes the loop actually dispatched, in the same units as
    # the fit/count span. Diagnostics; never fatal.
    if ctx.mesh is None and step_shapes:
        try:
            from ..telemetry import cost as cost_mod

            cost_mod.record_fit_count_cost(spec, num_langs, step_shapes)
        except Exception:
            pass

    if extra_counts is not None:
        e_ids, e_langs, e_counts = (
            jnp.asarray(np.asarray(a, dtype=np.int32)) for a in extra_counts
        )
        if e_ids.size:
            counts = counts.at[e_ids, e_langs].add(e_counts)
    return counts


def finalize_counts(
    counts,
    *,
    num_langs: int,
    profile_size: int,
    weight_mode: str = "parity",
    mesh=None,
    table_sharded: bool = False,
):
    """Count table → (sorted gram ids [G], float64 weights [G, L]) without
    the full ``[V, L]`` table ever crossing the device→host wire.

    The reduce half of the fit, entirely on device: weighting + per-language
    top-k — vocab-sharded per-shard blocked top-k with a cross-shard
    collective candidate merge when ``table_sharded`` (ids stay REAL through
    the merge, so the host fit's lowest-index tie order is preserved across
    any shard geometry), the single-program blocked/naive selection
    otherwise. Only the compact winner rows (ids + their exact int32
    counts — ``k·L`` rows, not ``V``) are then fetched in ``fit/collect``,
    measured as the ``fit/collect_bytes`` counter and the
    ``langdetect_fit_collect_bytes`` gauge (``telemetry/compare.py`` tracks
    the gauge as an upward-regressing contract metric: a silent fall-back
    to a full-table collect fails the guard). Winner weights are recomputed
    on host in float64 from the exact integer counts, same as the
    historical path — bit-identical to the host fit.
    """
    import numpy as np

    V = int(counts.shape[0])
    k = min(profile_size, V)
    nshards = 1
    topk_fn = None
    if mesh is not None and table_sharded:
        from ..parallel.mesh import table_shards
        from ..parallel.sharded import make_sharded_finalize_topk

        nshards = table_shards(mesh)
        topk_fn = make_sharded_finalize_topk(
            mesh, profile_size=k, weight_mode=weight_mode
        )
    # Non-occurred rows are not candidates (the reference's table only holds
    # grams seen in training); they mask below any real weight for top-k.
    with span(
        "fit/finalize", backend="device", k=k, vocab=V, shards=nshards
    ) as fin_span:
        if topk_fn is not None:
            top = topk_fn(counts)
        elif V * num_langs > TOPK_SORT_BUDGET_ELEMS:
            # Big tables (config-3 scale): the scanned finalize never
            # materializes the [V, L] weight table and bounds the top-k sort
            # per vocab block; ties → lowest id either way.
            top = finalize_topk_blocked(counts, weight_mode=weight_mode, k=k)
        else:
            masked = masked_candidate_weights(counts, weight_mode=weight_mode)
            top = top_k_rows(masked, k=k)  # ties → lowest id (re-ranked)
        fin_span.fence(top)

    top_np = np.unique(np.asarray(top).reshape(-1))
    top_np = top_np[top_np < V]  # blocked-path pad rows carry ids >= V
    # Recompute winner weights on host in float64 from the exact integer
    # counts (see docstring) instead of fetching the device's float32 table;
    # the same gathered rows decide occurrence (non-occurred candidates
    # surface only for languages with fewer than k real grams).
    with span("fit/collect", winners=int(top_np.size)) as col_span:
        counts_sel_dev = counts[jnp.asarray(top_np)]
        counts_sel = np.asarray(counts_sel_dev, dtype=np.int64)
        # Bytes that actually cross to the host: the [L, k] winner ids and
        # the [winners, L] int32 count rows — vs the V·L·4 full table the
        # pre-device-finalize fit pulled back.
        collect_bytes = int(top.nbytes) + int(counts_sel_dev.nbytes)
        table_bytes = V * num_langs * 4
        col_span.set(bytes=collect_bytes, table_bytes=table_bytes)
        REGISTRY.incr("fit/collect_bytes", collect_bytes)
        REGISTRY.set_gauge(
            "langdetect_fit_collect_bytes", float(collect_bytes),
            program="fit/collect",
        )
        occurred_np = counts_sel.sum(axis=1) > 0
        rows = top_np[occurred_np]  # dense row index == gram id
        counts_rows = counts_sel[occurred_np]
        if weight_mode == "parity":
            present = counts_rows > 0
            nlangs = present.sum(axis=1, keepdims=True)
            ratio = np.where(present, 1.0 / np.maximum(nlangs, 1), 0.0)
        else:
            totals = counts_rows.sum(axis=1, keepdims=True)
            ratio = counts_rows / np.maximum(totals, 1)
        weights = np.log1p(ratio.astype(np.float64))
    return rows.astype(np.int64), weights


def fit_profile_device(
    byte_docs,
    lang_indices,
    num_langs: int,
    spec: VocabSpec,
    profile_size: int,
    weight_mode: str = "parity",
    batch_rows: int | None = None,
    mesh=None,
    extra_counts=None,
):
    """Full single-device fit: returns (sorted gram ids [G], weights [G, L]).

    Mirrors :func:`ops.fit.fit_profile_numpy` — candidate set = grams
    occurring anywhere in the corpus; per language, top-k by (weight desc,
    id asc); union of winners with full weight vectors — but streams
    micro-batches through the jit-compiled dense counting step, so the corpus
    never has to fit in memory at once and the count/weight/top-k math runs
    on the accelerator. Only the compact winner rows come back to the host
    (the reference's collect-to-driver step, LanguageDetector.scala:252-254).

    Ingest is pipelined (``ops.fit_pipeline``): a background packer thread
    packs length-sorted micro-batches with the native packer, ships them
    ragged when that is smaller than padded, and overlaps async
    ``device_put`` with the count dispatches — ≥2 batches stay in flight
    while the jit step consumes the previous one. ``batch_rows`` None (the
    default) sizes rows adaptively per length bucket under a byte budget
    (``LANGDETECT_FIT_BATCH_BYTES``; ``LANGDETECT_FIT_BATCH_ROWS`` forces a
    fixed count); documents longer than the largest length bucket are
    chunk-split onto bucketed widths — never a per-width recompile — with
    the severed boundary windows injected exactly via ``extra_counts``.

    Precision: counts accumulate in int32 on device — exact up to 2^31-1
    occurrences per (gram, language) per fit; corpora beyond that need the
    host fit (int64 throughout). Winner *weights* are recomputed on host in
    float64 from the exact integer counts, so the returned weights match the
    host fit bit-for-bit; only the top-k *selection* happens at float32
    precision, which can pick a different winner when two grams' weights
    differ by less than one f32 ulp (only possible in 'counts' mode — parity
    weights take |L|+1 discrete values).

    ``mesh``: optional ``jax.sharding.Mesh`` — batches shard over its "data"
    axis and the count table stripes over the TABLE axis
    (``device_fit_context``: single-process meshes whose id space divides
    the shard count — the per-step GSPMD reduction is then a
    reduce-scatter, each device finalizes its own V/shards stripe through
    the collective top-k merge, and only winner rows reach the host).
    Multi-process meshes and non-dividing id spaces keep the replicated
    table + unsharded finalize (the lockstep collective schedule). Either
    way the collectives are what GSPMD derives — the TPU-native analog of
    the reference's groupByKey shuffles (LanguageDetector.scala:52-66).
    Pad rows (empty docs) contribute nothing.

    ``extra_counts``: optional (ids [E], langs [E], counts [E]) arrays
    scatter-added into the dense table once — the split long-gram fit uses
    it to inject short-doc partial-window contributions owned by this part
    (:func:`fit_profile_device_split`).
    """
    import numpy as np

    ctx = device_fit_context(spec, num_langs, mesh)
    lang_arr = np.asarray(lang_indices, dtype=np.int32)
    counts = accumulate_counts(
        ctx, ctx.counts, byte_docs, lang_arr,
        spec=spec, num_langs=num_langs, batch_rows=batch_rows,
        extra_counts=extra_counts,
    )
    return finalize_counts(
        counts,
        num_langs=num_langs,
        profile_size=profile_size,
        weight_mode=weight_mode,
        mesh=mesh,
        table_sharded=ctx.table_sharded,
    )


def fit_profile_device_split(
    byte_docs,
    lang_indices,
    num_langs: int,
    spec: VocabSpec,
    profile_size: int,
    weight_mode: str = "parity",
    batch_rows: int | None = None,
    mesh=None,
):
    """Device fit for exact vocabs with gram lengths > 3 (VERDICT r2 #9).

    No dense device table can hold the 256^4..256^5 long-gram id space, so
    the corpus is counted in two disjoint parts, split by the RESULTING
    gram's length (not the window class — a 2-byte doc's partial window for
    n=5 is a 2-gram):

      * gram length <= 3 -> the device dense fit over the (1..3)-length
        sub-spec (ids identical to the full spec's — exact offsets stack
        lengths ascending), with short docs' extra partial windows for the
        long classes injected via ``extra_counts``;
      * gram length >= 4 -> the exact host counting path, restricted to the
        long window classes with short-gram partials excluded
        (``min_partial_gram_len=4``).

    The id sets are disjoint, and a gram's weight depends only on its own
    per-language counts, so per-part weighting is exact; the final profile
    is the joint per-language top-k over the union of both parts' top-k
    (top-k of a union is contained in the union of top-k's under the total
    (-weight, id) order). Cross-checked bit-for-bit against the pure host
    fit in tests/test_fit_device.py.
    """
    import numpy as np

    from . import fit as fit_ops

    low_lengths = tuple(n for n in spec.gram_lengths if n <= 3)
    long_lengths = tuple(n for n in spec.gram_lengths if n > 3)
    if not long_lengths:
        raise ValueError("split fit is for specs with gram lengths > 3")
    if not low_lengths:
        # Nothing is device-countable: the exact host path is the fit.
        return fit_ops.fit_profile_numpy(
            byte_docs, lang_indices, num_langs, spec, profile_size,
            weight_mode,
        )
    from .vocab import EXACT

    spec_low = VocabSpec(EXACT, low_lengths)

    # Short docs' partial windows for the long classes whose gram (the whole
    # doc) is <= 3 bytes: owned by the device part, injected as extra counts.
    lang_arr = np.asarray(lang_indices, dtype=np.int64)
    corr: dict[tuple[int, int], int] = {}
    for doc, lang in zip(byte_docs, lang_arr):
        n_doc = len(doc)
        if 0 < n_doc <= 3:
            reps = sum(1 for n in long_lengths if n > n_doc)
            if reps:
                key = (spec_low.gram_to_id(bytes(doc)), int(lang))
                corr[key] = corr.get(key, 0) + reps
    extra = None
    if corr:
        e = np.asarray(
            [(i, l, c) for (i, l), c in corr.items()], dtype=np.int64
        )
        extra = (e[:, 0], e[:, 1], e[:, 2])

    ids_low, w_low = fit_profile_device(
        byte_docs, lang_arr, num_langs, spec_low, profile_size,
        weight_mode, batch_rows=batch_rows, mesh=mesh, extra_counts=extra,
    )

    # The host long-gram half is often the split fit's dominant cost —
    # record it under the same stage paths the pure-host fit uses so the
    # breakdown stays attributable (attrs distinguish the halves).
    with span(
        "fit/count", docs=len(byte_docs), backend="host", grams="long"
    ):
        gc = fit_ops.extract_gram_counts(
            byte_docs, lang_arr, num_langs, spec,
            gram_lengths_subset=long_lengths, min_partial_gram_len=4,
        )
    with span("fit/weights", pairs=len(gc.ids), backend="host"):
        ids_high, w_high = fit_ops.compute_weights(gc, weight_mode)
    with span("fit/topk", backend="host", k=profile_size):
        ids_high, w_high = fit_ops.select_top_grams(
            ids_high, w_high, profile_size
        )

    with span("fit/merge", k=profile_size):
        all_ids = np.concatenate([np.asarray(ids_low, np.int64), ids_high])
        all_w = np.concatenate(
            [np.asarray(w_low, np.float64), np.asarray(w_high, np.float64)]
        )
        ids, weights = fit_ops.select_top_grams(all_ids, all_w, profile_size)
        order = np.argsort(ids)
        return ids[order], np.ascontiguousarray(weights[order])
