"""Batch scoring: padded byte batches → per-language scores → argmax.

Replaces the reference's per-row hot loop — per-window JVM hash-map lookup +
``BLAS.axpy`` accumulate + Breeze argmax
(``/root/reference/src/main/.../LanguageDetectorModel.scala:131-156``) — with
fixed-shape, jit-compiled pipelines. The XLA strategies here, picked by the
profile's device view (``models.profile.GramProfile.device_membership``):

* **dense gather** (``lut=None``): the weight table covers the whole id space
  ``[V, L]`` and window ids index it directly — one gather per window.
* **LUT gather** (``lut`` int32 ``[V]``): a dense id→row lookup table maps
  window ids into a compact ``[G+1, L]`` table (row G = zeros miss row).
  Replaces binary-search membership — ``jnp.searchsorted`` lowers to a
  serial scan on TPU and measured ~40ms per [256, 2048] batch, vs ~4ms for
  the LUT gather.
* **cuckoo gather** (:func:`score_batch_cuckoo`): exact gram lengths 4..5
  overflow the int32 id space, so membership resolves through packed
  ``(lo, hi)`` key pairs and a two-choice cuckoo table (``ops.cuckoo``) —
  two wide gathers + verification per window.
* **one-hot MXU** (:func:`score_batch_onehot`): for exact vocabularies with
  gram lengths ⊆ {1, 2}, scoring needs no gathers at all — the bigram
  histogram of a window block is the outer product of the two byte one-hots,
  a ``[W, 256]ᵀ @ [W, 256]`` batched matmul on the MXU, and scores are
  ``hist @ W``. This is the north star's "histogram × log-prob matrix as one
  matmul" (BASELINE.json) in its purest form.

The pallas strategies (fused kernel, per-doc histogram kernel, and the
hybrid composition with these gathers) live in :mod:`ops.score_pallas` and
:mod:`api.runner`.

The window axis is processed in blocks under ``lax.scan`` so peak memory is
``B·block·L`` (gather) or ``B·block·256`` (one-hot) regardless of document
length, and XLA fuses the compare/gather + mask + reduce per block.

Semantics parity (SURVEY.md §2.9): unknown grams contribute zero; an all-miss
document scores all-zeros and argmax resolves to index 0 — the reference's Q6
behavior; ties resolve to the lowest index (Breeze and ``jnp.argmax`` both
return the first maximum). Documents shorter than a gram length contribute one
partial window per configured length, exactly like Scala ``sliding``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import (
    EXACT,
    HASHED,
    VocabSpec,
    mix32,
    partial_window_ids,
    partial_window_keys,
    window_ids,
    window_keys,
)

# Default window-axis block for the scan; multiple of 128 lanes.
DEFAULT_BLOCK = 1024


def _partial_window_rows(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    n: int,
    window0_ids: jnp.ndarray,
    spec: VocabSpec,
    lut: jnp.ndarray | None,
    miss_row: int,
) -> jnp.ndarray:
    """Row indices for the single partial window of docs with len < n.
    Docs with len == 0 get the miss row (Scala ``sliding`` over an empty
    collection emits nothing)."""
    short_ids = partial_window_ids(batch, lengths, n, window0_ids, spec)
    rows = short_ids if lut is None else lut[short_ids]
    return jnp.where(lengths > 0, rows, miss_row)


def _splice_partial_windows(
    rows: jnp.ndarray,
    partial_rows: jnp.ndarray,
    lengths: jnp.ndarray,
    n: int,
    window_limit: jnp.ndarray | None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Shared window-mask + Scala-``sliding`` partial-window splice.

    Full windows are those with start ≤ len - n (AND start < window_limit
    when chunk-ownership limits apply); a doc shorter than n contributes its
    single partial window in column 0 regardless of the limit (chunking
    never produces short rows, so the limit cannot apply to them). Both the
    id scorer and the cuckoo scorer resolve rows their own way, then apply
    exactly this rule — keep it in one place so they cannot drift.
    """
    B, W = rows.shape
    starts = jnp.arange(W, dtype=jnp.int32)[None, :]
    mask = starts <= (lengths[:, None] - n)
    if window_limit is not None:
        mask = mask & (starts < window_limit[:, None])
    is_short = lengths < n
    rows = rows.at[:, 0].set(jnp.where(is_short, partial_rows, rows[:, 0]))
    mask = mask.at[:, 0].set(mask[:, 0] | (is_short & (lengths > 0)))
    return rows, mask


def _block_accumulate(
    weights: jnp.ndarray, rows: jnp.ndarray, mask: jnp.ndarray, block: int
) -> jnp.ndarray:
    """Σ_w weights[rows[b, w]] · mask[b, w] → [B, L], scanned in window blocks."""
    B, W = rows.shape
    L = weights.shape[1]
    pad = (-W) % block
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    nblk = rows.shape[1] // block
    rows = rows.reshape(B, nblk, block).transpose(1, 0, 2)
    mask = mask.reshape(B, nblk, block).transpose(1, 0, 2)

    def body(acc, blk):
        r, m = blk
        contrib = weights[r] * m[..., None].astype(weights.dtype)
        return acc + contrib.sum(axis=1).astype(jnp.float32), None

    init = jnp.zeros((B, L), dtype=jnp.float32)
    acc, _ = jax.lax.scan(body, init, (rows, mask))
    return acc


@partial(jax.jit, static_argnames=("spec", "block", "gram_lengths_subset"))
def score_batch(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    weights: jnp.ndarray,
    lut: jnp.ndarray | None,
    *,
    spec: VocabSpec,
    block: int = DEFAULT_BLOCK,
    window_limit: jnp.ndarray | None = None,
    gram_lengths_subset: tuple[int, ...] | None = None,
) -> jnp.ndarray:
    """Scores for a padded batch (gather strategies).

    Args:
      batch: uint8 [B, S] zero-padded document bytes.
      lengths: int32 [B] true byte lengths (≤ S).
      weights: float [V, L] dense over the id space (``lut`` None) or
        [G+1, L] compact with a zeros miss row at G (``lut`` given).
      lut: optional int32 [V] id→row table; unlearned ids map to row G.
        A size-0 array is treated like None (dense direct indexing) so the
        sharded callers can pass a sentinel instead of a None pytree leaf.
      spec: vocabulary spec (static — hashable frozen dataclass).
      block: window-axis scan block size.
      window_limit: optional int32 [B] — row i only counts window starts
        < window_limit[i]. Used for long-document chunking: a non-final chunk
        owns starts [0, chunk_size - overlap); the final chunk owns all
        (see ``ops.encoding.chunk_document``). None ⇒ no limit.
      gram_lengths_subset: optional subset of ``spec.gram_lengths`` to score
        (ids/partial-window rules unchanged — shorter-length id spaces stay
        addressable). The hybrid strategy scores n ≤ 2 through the pallas
        histogram kernel and passes the remaining lengths here.

    Returns:
      float32 [B, L] accumulated per-language scores.
    """
    if lut is not None and lut.size == 0:
        lut = None
    B, S = batch.shape
    L = weights.shape[1]
    # Dense strategy has no dedicated miss row; masked windows are zeroed by
    # the mask multiply inside the block scan, so any in-range row is safe.
    miss_row = weights.shape[0] - 1 if lut is not None else 0
    total = jnp.zeros((B, L), dtype=jnp.float32)
    lengths_to_score = (
        gram_lengths_subset if gram_lengths_subset is not None
        else spec.gram_lengths
    )
    for n in lengths_to_score:
        ids = window_ids(batch, n, spec)  # [B, W]
        rows = ids if lut is None else lut[ids]
        partial_rows = _partial_window_rows(
            batch, lengths, n, ids[:, 0], spec, lut, miss_row
        )
        rows, mask = _splice_partial_windows(
            rows, partial_rows, lengths, n, window_limit
        )
        total = total + _block_accumulate(weights, rows, mask, block)
    return total


# ------------------------------------------------ cuckoo-membership scorer ---


def _cuckoo_rows(
    lo: jnp.ndarray,
    hi: jnp.ndarray,
    entries: jnp.ndarray,
    miss_row: int,
    seed1: int,
    seed2: int,
) -> jnp.ndarray:
    """Two-probe verified lookup: packed keys → compact weight rows (or the
    miss row G). ``entries`` is the int32 [M, 4] packed table
    (``ops.cuckoo.CuckooTable.entries``): each probe is one wide gather
    carrying key halves + row. M is a power of two, so ``% M`` is a mask."""
    M = entries.shape[0]
    h1 = (mix32(lo, hi, seed1, xp=jnp) & jnp.uint32(M - 1)).astype(jnp.int32)
    h2 = (mix32(lo, hi, seed2, xp=jnp) & jnp.uint32(M - 1)).astype(jnp.int32)
    e1 = entries[h1]
    e2 = entries[h2]
    hit1 = (e1[..., 0] == lo) & (e1[..., 1] == hi)
    hit2 = (e2[..., 0] == lo) & (e2[..., 1] == hi)
    return jnp.where(
        hit1, e1[..., 2], jnp.where(hit2, e2[..., 2], miss_row)
    )


@partial(
    jax.jit,
    static_argnames=("seed1", "seed2", "spec", "block", "gram_lengths_subset"),
)
def score_batch_cuckoo(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    weights: jnp.ndarray,
    entries: jnp.ndarray,
    *,
    seed1: int,
    seed2: int,
    spec: VocabSpec,
    block: int = DEFAULT_BLOCK,
    window_limit: jnp.ndarray | None = None,
    gram_lengths_subset: tuple[int, ...] | None = None,
) -> jnp.ndarray:
    """Scores via cuckoo membership — exact vocabs whose gram lengths exceed
    the int32 id space (n = 4..5), where no dense LUT can exist.

    Same contract as :func:`score_batch` (masking, partial-window rule,
    window_limit, subset), but membership is resolved by packed-key lookup
    (``ops.cuckoo``) instead of integer ids: per window, two wide gathers
    into the packed [M, 4] entry table + key verification. ``weights`` is
    the compact [G+1, L] table with the zeros miss row at G.
    """
    if spec.mode != EXACT:
        raise ValueError(
            "score_batch_cuckoo needs an exact vocab spec — hashed specs "
            "use integer-id scoring (score_batch), not packed-key membership"
        )
    B, S = batch.shape
    L = weights.shape[1]
    G = weights.shape[0] - 1
    total = jnp.zeros((B, L), dtype=jnp.float32)
    lengths_to_score = (
        gram_lengths_subset if gram_lengths_subset is not None
        else spec.gram_lengths
    )
    for n in lengths_to_score:
        lo, hi = window_keys(batch, n)
        rows = _cuckoo_rows(lo, hi, entries, G, seed1, seed2)
        plo, phi = partial_window_keys(batch, lengths, n)
        prows = _cuckoo_rows(plo, phi, entries, G, seed1, seed2)
        prows = jnp.where(lengths > 0, prows, G)
        rows, mask = _splice_partial_windows(
            rows, prows, lengths, n, window_limit
        )
        total = total + _block_accumulate(weights, rows, mask, block)
    return total


# --------------------------------------------------- one-hot MXU strategy ----

# Max gram length the one-hot factorization covers: an n-gram histogram is an
# order-n tensor of byte one-hots; n=2 is a single [256, 256] outer product
# (one MXU matmul), n=3 would need a [B, 256, 65536] intermediate.
ONEHOT_MAX_N = 2


def onehot_supported(spec: VocabSpec, num_rows: int) -> bool:
    """True when :func:`score_batch_onehot` applies: exact vocab, grams ⊆
    {1, 2}, dense weight table over the full id space."""
    return (
        spec.mode == EXACT
        and max(spec.gram_lengths) <= ONEHOT_MAX_N
        and num_rows == spec.id_space_size
    )


@partial(jax.jit, static_argnames=("spec", "block"))
def score_batch_onehot(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    weights: jnp.ndarray,
    *,
    spec: VocabSpec,
    block: int = 512,
    window_limit: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Gather-free scoring for exact vocabularies with gram lengths ⊆ {1, 2}.

    Builds the per-document unigram histogram ``[B, 256]`` and bigram
    histogram ``[B, 256, 256]`` from byte one-hots — the bigram histogram of
    a window block is ``einsum('bwi,bwj->bij', onehot(byte0)·mask,
    onehot(byte1))``, a batched MXU matmul — then multiplies by the dense
    weight table: ``scores = hist1 @ W[:256] + hist2 @ W[256:]``. One-hot
    entries are exactly 0/1 in bf16 and counts accumulate in f32, so the
    histograms are exact.

    ``weights`` must be the dense [id_space, L] table (length-1 rows first,
    then length-2 rows — the ``VocabSpec.offsets`` layout).
    """
    if spec.mode != EXACT or max(spec.gram_lengths) > ONEHOT_MAX_N:
        raise ValueError(
            "score_batch_onehot needs an exact vocab with gram lengths <= "
            f"{ONEHOT_MAX_N} (got mode={spec.mode!r}, "
            f"lengths={spec.gram_lengths})"
        )
    B, S = batch.shape
    max_n = max(spec.gram_lengths)
    if S < max_n:  # batch narrower than the largest window: zero-extend
        batch = jnp.pad(batch, ((0, 0), (0, max_n - S)))
        S = max_n
    L = weights.shape[1]
    iota = jnp.arange(256, dtype=jnp.int32)
    w1 = weights[:256]

    def masked_counts(vals: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        """Σ_w onehot(vals[b, w]) · mask[b, w] → [B, 256] (f32)."""
        oh = (vals[..., None] == iota) & mask[..., None]
        return oh.astype(jnp.float32).sum(axis=1)

    total = jnp.zeros((B, L), dtype=jnp.float32)
    for n in spec.gram_lengths:
        W = max(S - n + 1, 1)
        starts = jnp.arange(W, dtype=jnp.int32)[None, :]
        mask = starts <= (lengths[:, None] - n)
        if window_limit is not None:
            mask = mask & (starts < window_limit[:, None])
        pad = (-W) % block
        b_pad = jnp.pad(batch[:, : W + n - 1], ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
        nblk = (W + pad) // block

        if n == 1:
            vals = b_pad.astype(jnp.int32).reshape(B, nblk, block).transpose(1, 0, 2)
            m = mask.reshape(B, nblk, block).transpose(1, 0, 2)

            def body1(acc, blk):
                v, mm = blk
                return acc + masked_counts(v, mm), None

            hist1, _ = jax.lax.scan(
                body1, jnp.zeros((B, 256), jnp.float32), (vals, m)
            )
            # HIGHEST: the TPU default for f32 dots is bf16 passes, which
            # truncates histogram counts and weights (~1e-2 score error —
            # enough to flip argmax ties; caught by on-chip fuzzing).
            total = total + jax.lax.dot(
                hist1, w1.astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,
            )
        else:
            b0 = b_pad[:, : W + pad] if pad else b_pad[:, :W]
            b1 = jnp.pad(batch[:, 1 : W + 1], ((0, 0), (0, (-W) % block)))
            b0 = b0.astype(jnp.int32).reshape(B, nblk, block).transpose(1, 0, 2)
            b1 = b1.astype(jnp.int32).reshape(B, nblk, block).transpose(1, 0, 2)
            m = mask.reshape(B, nblk, block).transpose(1, 0, 2)

            def body2(acc, blk):
                v0, v1, mm = blk
                oh0 = ((v0[..., None] == iota) & mm[..., None]).astype(jnp.bfloat16)
                oh1 = (v1[..., None] == iota).astype(jnp.bfloat16)
                h = jnp.einsum(
                    "bwi,bwj->bij", oh0, oh1,
                    preferred_element_type=jnp.float32,
                )
                return acc + h, None

            hist2, _ = jax.lax.scan(
                body2, jnp.zeros((B, 256, 256), jnp.float32), (b0, b1, m)
            )
            w2 = weights[spec.offsets[2] : spec.offsets[2] + 65536]
            total = total + jax.lax.dot(
                hist2.reshape(B, 65536), w2.astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,
            )

        # Partial-window rule (Scala sliding parity): a doc shorter than n
        # contributes its whole-byte prefix once, in the prefix's own length
        # class — here only len==1 docs under n==2 (len==0 emits nothing).
        if n == 2:
            is_short = lengths == 1
            short_oh = (
                (batch[:, 0].astype(jnp.int32)[:, None] == iota)
                & is_short[:, None]
            )
            total = total + jax.lax.dot(
                short_oh.astype(jnp.float32), w1.astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,
            )
    return total


# ------------------------------------------- per-window (cell) scoring ------
#
# The segmentation output mode (docs/SEGMENTATION.md): instead of folding
# every window's contribution into one [B, L] document score, contributions
# are kept per CELL — a fixed span of `cell` consecutive window start
# positions. A window starting at byte s belongs to cell s // cell,
# regardless of gram length, so the per-cell tensors of all lengths align
# and sum. Summing a document's cells restores the whole-doc score exactly
# up to f32 reduction order; the whole-doc paths above are untouched (the
# bit-identical pre-segmentation contract is pinned by tests/test_segment).


def _cell_accumulate(
    weights: jnp.ndarray,
    rows: jnp.ndarray,
    mask: jnp.ndarray,
    cell: int,
    n_cells: int,
    block: int,
) -> jnp.ndarray:
    """Σ_w weights[rows[b, w]] · mask[b, w] scattered by window cell →
    [B, n_cells, L], scanned in window blocks (block rounded to a multiple
    of ``cell`` so no block straddles a cell boundary)."""
    B, W = rows.shape
    L = weights.shape[1]
    blk = max(cell, (block // cell) * cell)
    m = blk // cell  # cells per scanned block
    full = -(-max(W, n_cells * cell) // blk) * blk
    if full != W:
        rows = jnp.pad(rows, ((0, 0), (0, full - W)))
        mask = jnp.pad(mask, ((0, 0), (0, full - W)))
    nblk = full // blk
    rows = rows.reshape(B, nblk, m, cell).transpose(1, 0, 2, 3)
    mask = mask.reshape(B, nblk, m, cell).transpose(1, 0, 2, 3)

    def body(acc, xs):
        r, mm, k = xs  # [B, m, cell] (+ scalar block index)
        contrib = weights[r] * mm[..., None].astype(weights.dtype)
        cells = contrib.sum(axis=2).astype(jnp.float32)  # [B, m, L]
        cur = jax.lax.dynamic_slice(acc, (0, k * m, 0), (B, m, L))
        return jax.lax.dynamic_update_slice(
            acc, cur + cells, (0, k * m, 0)
        ), None

    init = jnp.zeros((B, nblk * m, L), dtype=jnp.float32)
    acc, _ = jax.lax.scan(
        body, init, (rows, mask, jnp.arange(nblk, dtype=jnp.int32))
    )
    return acc[:, :n_cells]


@partial(jax.jit, static_argnames=("spec", "cell", "block"))
def window_scores_batch(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    weights: jnp.ndarray,
    lut: jnp.ndarray | None,
    *,
    spec: VocabSpec,
    cell: int,
    block: int = DEFAULT_BLOCK,
    window_limit: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Per-cell scores for a padded batch (gather strategies): float32
    [B, ceil(S / cell), L] where entry ``[b, c]`` sums every window of
    every gram length whose start position lies in ``[c·cell, (c+1)·cell)``
    (masking, the Scala ``sliding`` partial-window splice into window 0,
    and ``window_limit`` chunk ownership all exactly as
    :func:`score_batch` — the partial window of a short doc lands in cell
    0). The gather formulation is the segmentation mode's exactness
    oracle, the same role it plays for whole-doc scoring."""
    if lut is not None and lut.size == 0:
        lut = None
    B, S = batch.shape
    n_cells = -(-S // cell)
    miss_row = weights.shape[0] - 1 if lut is not None else 0
    total = jnp.zeros((B, n_cells, weights.shape[1]), dtype=jnp.float32)
    for n in spec.gram_lengths:
        ids = window_ids(batch, n, spec)
        rows = ids if lut is None else lut[ids]
        partial_rows = _partial_window_rows(
            batch, lengths, n, ids[:, 0], spec, lut, miss_row
        )
        rows, mask = _splice_partial_windows(
            rows, partial_rows, lengths, n, window_limit
        )
        total = total + _cell_accumulate(
            weights, rows, mask, cell, n_cells, block
        )
    return total


@partial(
    jax.jit,
    static_argnames=("seed1", "seed2", "spec", "cell", "block"),
)
def window_scores_batch_cuckoo(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    weights: jnp.ndarray,
    entries: jnp.ndarray,
    *,
    seed1: int,
    seed2: int,
    spec: VocabSpec,
    cell: int,
    block: int = DEFAULT_BLOCK,
    window_limit: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """:func:`window_scores_batch` for packed-key cuckoo membership (exact
    gram lengths 4..5) — the same two-probe row resolution as
    :func:`score_batch_cuckoo`, scattered per cell."""
    if spec.mode != EXACT:
        raise ValueError(
            "window_scores_batch_cuckoo needs an exact vocab spec — hashed "
            "specs use integer-id scoring (window_scores_batch)"
        )
    B, S = batch.shape
    n_cells = -(-S // cell)
    G = weights.shape[0] - 1
    total = jnp.zeros((B, n_cells, weights.shape[1]), dtype=jnp.float32)
    for n in spec.gram_lengths:
        lo, hi = window_keys(batch, n)
        rows = _cuckoo_rows(lo, hi, entries, G, seed1, seed2)
        plo, phi = partial_window_keys(batch, lengths, n)
        prows = _cuckoo_rows(plo, phi, entries, G, seed1, seed2)
        prows = jnp.where(lengths > 0, prows, G)
        rows, mask = _splice_partial_windows(
            rows, prows, lengths, n, window_limit
        )
        total = total + _cell_accumulate(
            weights, rows, mask, cell, n_cells, block
        )
    return total


def window_scores_numpy(
    byte_docs: list[bytes],
    weights: np.ndarray,
    sorted_ids: np.ndarray | None,
    spec: VocabSpec,
    cell: int,
) -> list[np.ndarray]:
    """Host mirror of :func:`window_scores_batch` (float64 test oracle):
    per document a ``[max(1, ceil(len / cell)), L]`` array; window start →
    cell ``start // cell``; a short doc's partial windows land in cell 0."""
    from .vocab import short_doc_ids_numpy, window_ids_numpy

    L = weights.shape[1]

    def row_of(ids: np.ndarray) -> np.ndarray:
        if sorted_ids is None:
            return weights[ids]
        if len(sorted_ids) == 0:
            return np.zeros((len(ids), L), dtype=weights.dtype)
        pos = np.searchsorted(sorted_ids, ids)
        pos_c = np.minimum(pos, len(sorted_ids) - 1)
        hit = sorted_ids[pos_c] == ids
        rows = np.where(hit, pos_c, weights.shape[0] - 1)
        return weights[rows]

    out = []
    for doc in byte_docs:
        n_cells = max(1, -(-len(doc) // cell))
        acc = np.zeros((n_cells, L), dtype=np.float64)
        arr = np.frombuffer(doc, dtype=np.uint8)[None, :]
        for n in spec.gram_lengths:
            if len(doc) >= n:
                ids = window_ids_numpy(arr, n, spec)[0]
                starts = np.arange(len(ids)) // cell
                np.add.at(acc, starts, row_of(np.asarray(ids, np.int64)))
        short = short_doc_ids_numpy(doc, spec)
        if short:
            acc[0] += row_of(np.asarray(short, dtype=np.int64)).sum(axis=0)
        out.append(acc)
    return out


def argmax_language(scores: jnp.ndarray) -> jnp.ndarray:
    """[B, L] → int32 [B]; first maximum wins (reference tie/zero behavior)."""
    return jnp.argmax(scores, axis=1).astype(jnp.int32)


# --- numpy mirror (used by the CPU backend and as a test oracle bridge) ------


def score_batch_numpy(
    byte_docs: list[bytes],
    weights: np.ndarray,
    sorted_ids: np.ndarray | None,
    spec: VocabSpec,
) -> np.ndarray:
    """Vectorized host scorer with identical semantics (no padding needed).

    ``weights``/``sorted_ids`` are the *profile* arrays (compact [G, L] +
    ascending ids for exact mode; dense [V, L] + None for hashed) — the host
    mirror keeps the binary-search membership formulation since numpy's
    searchsorted is fast on CPU.
    """
    from .vocab import short_doc_ids_numpy, window_ids_numpy

    L = weights.shape[1]
    out = np.zeros((len(byte_docs), L), dtype=np.float64)
    for i, doc in enumerate(byte_docs):
        arr = np.frombuffer(doc, dtype=np.uint8)[None, :]
        acc = np.zeros((L,), dtype=np.float64)
        ids_all = []
        for n in spec.gram_lengths:
            if len(doc) >= n:
                ids_all.append(window_ids_numpy(arr, n, spec)[0])
        short = short_doc_ids_numpy(doc, spec)
        if short:
            ids_all.append(np.asarray(short, dtype=np.int64))
        if ids_all:
            ids = np.concatenate(ids_all)
            if sorted_ids is not None:
                if len(sorted_ids) == 0:
                    rows = np.full(len(ids), weights.shape[0] - 1)
                else:
                    pos = np.searchsorted(sorted_ids, ids)
                    pos_c = np.minimum(pos, len(sorted_ids) - 1)
                    hit = sorted_ids[pos_c] == ids
                    rows = np.where(hit, pos_c, weights.shape[0] - 1)
                acc += weights[rows].sum(axis=0)
            else:
                acc += weights[ids].sum(axis=0)
        out[i] = acc
    return out
