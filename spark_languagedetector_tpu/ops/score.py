"""Batch scoring: padded byte batches → per-language scores → argmax.

Replaces the reference's per-row hot loop — per-window JVM hash-map lookup +
``BLAS.axpy`` accumulate + Breeze argmax
(``/root/reference/src/main/.../LanguageDetectorModel.scala:131-156``) — with a
fixed-shape, jit-compiled pipeline:

    bytes [B, S] ──window_ids──▶ ids [B, W] ──membership──▶ rows [B, W]
      ──gather W[rows] · mask, block-scan──▶ scores [B, L] ──argmax──▶ [B]

Exact mode resolves membership with a branchless binary search against the
model's sorted id vector (misses hit a zeros row). Hashed mode indexes the
dense ``[V, L]`` weight table directly. The window axis is processed in
blocks under ``lax.scan`` so peak memory is ``B·block·L`` regardless of
document length, and XLA fuses the gather+mask+reduce per block.

Semantics parity (SURVEY.md §2.9): unknown grams contribute zero; an all-miss
document scores all-zeros and argmax resolves to index 0 — the reference's Q6
behavior; ties resolve to the lowest index (Breeze and ``jnp.argmax`` both
return the first maximum). Documents shorter than a gram length contribute one
partial window per configured length, exactly like Scala ``sliding``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import EXACT, HASHED, VocabSpec, partial_window_ids, window_ids

# Default window-axis block for the scan; multiple of 128 lanes.
DEFAULT_BLOCK = 1024


def _lookup_rows_exact(ids: jnp.ndarray, sorted_ids: jnp.ndarray) -> jnp.ndarray:
    """ids [B, W] int32 → row indices into the weight matrix [G+1, L].

    Binary search + equality check; misses map to row G (the zeros row).
    An empty profile (G == 0) maps everything to the miss row.
    """
    G = sorted_ids.shape[0]
    if G == 0:
        return jnp.zeros_like(ids)
    pos = jnp.searchsorted(sorted_ids, ids, side="left").astype(jnp.int32)
    pos_c = jnp.minimum(pos, G - 1)
    hit = sorted_ids[pos_c] == ids
    return jnp.where(hit, pos_c, G)


def _partial_window_rows(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    n: int,
    window0_ids: jnp.ndarray,
    spec: VocabSpec,
    sorted_ids: jnp.ndarray | None,
    miss_row: int,
) -> jnp.ndarray:
    """Row indices for the single partial window of docs with len < n.
    Docs with len == 0 get the miss row (Scala ``sliding`` over an empty
    collection emits nothing)."""
    short_ids = partial_window_ids(batch, lengths, n, window0_ids, spec)
    if spec.mode == EXACT:
        rows = _lookup_rows_exact(short_ids[:, None], sorted_ids)[:, 0]
    else:
        rows = short_ids
    return jnp.where(lengths > 0, rows, miss_row)


def _block_accumulate(
    weights: jnp.ndarray, rows: jnp.ndarray, mask: jnp.ndarray, block: int
) -> jnp.ndarray:
    """Σ_w weights[rows[b, w]] · mask[b, w] → [B, L], scanned in window blocks."""
    B, W = rows.shape
    L = weights.shape[1]
    pad = (-W) % block
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    nblk = rows.shape[1] // block
    rows = rows.reshape(B, nblk, block).transpose(1, 0, 2)
    mask = mask.reshape(B, nblk, block).transpose(1, 0, 2)

    def body(acc, blk):
        r, m = blk
        contrib = weights[r] * m[..., None].astype(weights.dtype)
        return acc + contrib.sum(axis=1).astype(jnp.float32), None

    init = jnp.zeros((B, L), dtype=jnp.float32)
    acc, _ = jax.lax.scan(body, init, (rows, mask))
    return acc


@partial(jax.jit, static_argnames=("spec", "block"))
def score_batch(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    weights: jnp.ndarray,
    sorted_ids: jnp.ndarray | None,
    *,
    spec: VocabSpec,
    block: int = DEFAULT_BLOCK,
    window_limit: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Scores for a padded batch.

    Args:
      batch: uint8 [B, S] zero-padded document bytes.
      lengths: int32 [B] true byte lengths (≤ S).
      weights: float [G+1, L] (exact; row G zeros) or [V, L] (hashed).
      sorted_ids: int32 [G] ascending gram ids (exact mode) or None.
      spec: vocabulary spec (static — hashable frozen dataclass).
      block: window-axis scan block size.
      window_limit: optional int32 [B] — row i only counts window starts
        < window_limit[i]. Used for long-document chunking: a non-final chunk
        owns starts [0, chunk_size - overlap); the final chunk owns all
        (see ``ops.encoding.chunk_document``). None ⇒ no limit.

    Returns:
      float32 [B, L] accumulated per-language scores.
    """
    B, S = batch.shape
    L = weights.shape[1]
    miss_row = weights.shape[0] - 1 if spec.mode == EXACT else 0
    total = jnp.zeros((B, L), dtype=jnp.float32)
    for n in spec.gram_lengths:
        W = max(S - n + 1, 1)
        ids = window_ids(batch, n, spec)  # [B, W]
        if spec.mode == EXACT:
            rows = _lookup_rows_exact(ids, sorted_ids)
        else:
            rows = ids
        starts = jnp.arange(W, dtype=jnp.int32)[None, :]
        mask = starts <= (lengths[:, None] - n)  # full windows only
        if window_limit is not None:
            mask = mask & (starts < window_limit[:, None])
        # Partial-window rule for docs shorter than n (Scala sliding parity).
        partial_rows = _partial_window_rows(
            batch, lengths, n, ids[:, 0], spec, sorted_ids, miss_row
        )
        is_short = lengths < n
        rows = rows.at[:, 0].set(jnp.where(is_short, partial_rows, rows[:, 0]))
        mask = mask.at[:, 0].set(mask[:, 0] | (is_short & (lengths > 0)))
        if spec.mode == HASHED:
            # Hashed mode has no zeros row; masked gathers still index row 0,
            # so the mask multiply inside the block scan is what zeroes them.
            rows = jnp.where(mask, rows, 0)
        total = total + _block_accumulate(weights, rows, mask, block)
    return total


def argmax_language(scores: jnp.ndarray) -> jnp.ndarray:
    """[B, L] → int32 [B]; first maximum wins (reference tie/zero behavior)."""
    return jnp.argmax(scores, axis=1).astype(jnp.int32)


# --- numpy mirror (used by the CPU backend and as a test oracle bridge) ------


def score_batch_numpy(
    byte_docs: list[bytes],
    weights: np.ndarray,
    sorted_ids: np.ndarray | None,
    spec: VocabSpec,
) -> np.ndarray:
    """Vectorized host scorer with identical semantics (no padding needed)."""
    from .vocab import short_doc_ids_numpy, window_ids_numpy

    L = weights.shape[1]
    out = np.zeros((len(byte_docs), L), dtype=np.float64)
    for i, doc in enumerate(byte_docs):
        arr = np.frombuffer(doc, dtype=np.uint8)[None, :]
        acc = np.zeros((L,), dtype=np.float64)
        ids_all = []
        for n in spec.gram_lengths:
            if len(doc) >= n:
                ids_all.append(window_ids_numpy(arr, n, spec)[0])
        short = short_doc_ids_numpy(doc, spec)
        if short:
            ids_all.append(np.asarray(short, dtype=np.int64))
        if ids_all:
            ids = np.concatenate(ids_all)
            if spec.mode == EXACT:
                if len(sorted_ids) == 0:
                    rows = np.full(len(ids), weights.shape[0] - 1)
                else:
                    pos = np.searchsorted(sorted_ids, ids)
                    pos_c = np.minimum(pos, len(sorted_ids) - 1)
                    hit = sorted_ids[pos_c] == ids
                    rows = np.where(hit, pos_c, weights.shape[0] - 1)
                acc += weights[rows].sum(axis=0)
            else:
                acc += weights[ids].sum(axis=0)
        out[i] = acc
    return out
