"""Text → fixed-shape byte tensors (host side, vectorized numpy).

The reference streams each document's bytes through Scala iterators
(``/root/reference/src/main/.../LanguageDetector.scala:36-43``,
``LanguageDetectorModel.scala:139-152``). XLA needs static shapes, so the
TPU-native front door is: encode each text to bytes, then pack a micro-batch
into a zero-padded ``uint8 [B, S]`` array plus an ``int32 [B]`` length vector
(SURVEY.md §7.4 "fixed shapes vs ragged text"). Padding is 0x00; validity is
carried by the length vector, never by sentinel bytes.

Two string→bytes encodings exist because the reference has a train/predict
encoding mismatch (SURVEY.md §2.9 Q2): fit uses UTF-8 while predict truncates
UTF-16 code units to their low byte. ``utf8`` is this framework's default for
both paths; ``low_byte`` exists so parity mode can reproduce the reference's
predict path bit-for-bit.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

UTF8 = "utf8"
LOW_BYTE = "low_byte"
ENCODINGS = (UTF8, LOW_BYTE)


def text_to_bytes(text: str, encoding: str = UTF8) -> bytes:
    if encoding == UTF8:
        return text.encode("utf-8")
    if encoding == LOW_BYTE:
        # Reference predict path: text.toCharArray.map(_.toByte)
        # (LanguageDetectorModel.scala:161) — low byte of each UTF-16 unit.
        units = text.encode("utf-16-le")
        return units[::2]
    raise ValueError(f"unknown encoding {encoding!r}; expected one of {ENCODINGS}")


def texts_to_bytes(texts: Sequence[str], encoding: str = UTF8) -> list[bytes]:
    return [text_to_bytes(t, encoding) for t in texts]


def bucket_length(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ length; buckets sorted asc.

    Bucketed padded shapes keep XLA compile counts bounded: every micro-batch
    compiles at one of a small set of [B, S] shapes. A document longer than
    the largest bucket gets a power-of-two bucket that covers it — padding
    never silently truncates (explicit ``pad_to`` is the only truncating
    path, used by the runner after chunking long docs).
    """
    for b in buckets:
        if length <= b:
            return b
    width = buckets[-1]
    while width < length:
        width *= 2
    return width


# ~1.5× growth bounds padding waste at 50% worst-case (the old 4×-growth set
# paid up to 4× transfer + compute on docs just past a bucket edge); all
# values are multiples of 128 so Mosaic lane tiling never re-pads short
# buckets. More buckets = more compiled shapes, but only shapes actually seen
# compile, and each is cached for the process lifetime.
DEFAULT_LENGTH_BUCKETS: tuple[int, ...] = (
    128, 256, 512, 768, 1024, 1536, 2048, 3072, 4096, 6144, 8192
)


def rows_under_byte_budget(
    pad_to: int, byte_budget: int, max_rows: int, floor: int = 64
) -> int:
    """Back-compat alias: the byte-budget row-sizing policy moved to the
    execution core (``exec.core.rows_under_byte_budget`` — one policy under
    the scoring runner, the fit pipeline, and the autotuner). Lazy import:
    the core imports this module for :func:`bucket_length`."""
    from ..exec.core import rows_under_byte_budget as _core

    return _core(pad_to, byte_budget, max_rows, floor)


def pad_batch(
    byte_docs: Sequence[bytes],
    pad_to: int | None = None,
    length_buckets: Sequence[int] | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Pack variable-length byte strings into (uint8 [B, S], int32 lengths [B]).

    Documents longer than the padded width are truncated (callers that need
    unbounded documents chunk first — see ``parallel/sequence.py``).
    """
    lengths = np.fromiter((len(d) for d in byte_docs), dtype=np.int32, count=len(byte_docs))
    max_len = int(lengths.max()) if len(byte_docs) else 1
    max_len = max(max_len, 1)
    if pad_to is None:
        buckets = length_buckets or DEFAULT_LENGTH_BUCKETS
        pad_to = bucket_length(max_len, buckets)
    batch = np.zeros((len(byte_docs), pad_to), dtype=np.uint8)
    for i, doc in enumerate(byte_docs):
        n = min(len(doc), pad_to)
        if n:
            batch[i, :n] = np.frombuffer(doc, dtype=np.uint8, count=n)
    np.minimum(lengths, pad_to, out=lengths)
    return batch, lengths


# --- ragged (wire-efficient) packing -----------------------------------
#
# The padded [B, S] form moves bucket-width rows over the host→device wire,
# paying for padding bytes that carry no information (~15-20% of the
# transfer at bucketed fill factors, and up to ~50% for short docs in a
# wide batch). The ragged form ships each document 128-byte-chunk-aligned
# in one flat [C, 128] uint8 buffer plus an int32 chunk offset per doc;
# the device reconstructs the exact padded batch with one 128-byte-row
# gather (see ``unpack_ragged``), so everything downstream of the transfer
# is bit-identical to the padded path. Chunk row 0 is reserved all-zeros:
# out-of-range chunk indices gather it, which is what restores the padded
# form's zero tail. 128 bytes = one TPU lane tile, so gathered rows are
# exactly lane-width (no relayout) and alignment waste averages 64B/doc.
RAGGED_CHUNK = 128

# Flat-size buckets bound the number of compiled (C, B, S) shapes the
# ragged path introduces. Rounding to 1/16 of the batch's padded chunk
# count keeps mean bucket waste ~3% of the padded size (vs the ~15-20%
# padding the ragged form removes) while batches of stable fill land on
# 1-3 distinct C values per (B, S) geometry.
_FLAT_BUCKET_BASE = 256


def round_chunks(c: int, step: int | None = None) -> int:
    """Smallest multiple of ``step`` >= max(c, 256) (``step`` defaults to
    256; the runner passes padded_chunks/16 for its batch geometry)."""
    step = max(int(step or 0), _FLAT_BUCKET_BASE)
    return -(-max(c, 1) // step) * step


def ragged_layout(
    byte_docs: Sequence[bytes], pad_to: int, flat_step: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Shared layout bookkeeping for the ragged packers: → (zeroed flat
    uint8 [C, 128], offs int32 [B], lengths int32 [B] clamped to pad_to).

    Single owner of the layout invariants (reserved zero row 0,
    ``offs[i] = 1 + cumsum(chunks)``, truncation matching ``pad_batch``,
    ``round_chunks`` bucketing) — the numpy and native packers differ only
    in the per-document copy loop that fills ``flat``.
    """
    n = len(byte_docs)
    lengths = np.fromiter(
        (min(len(d), pad_to) for d in byte_docs), dtype=np.int32, count=n
    )
    nchunks = -(-lengths // RAGGED_CHUNK)  # ceil; 0 for empty docs
    # offs[i] = 1 + chunks of all earlier docs (row 0 = reserved zero chunk)
    offs = np.empty(n, dtype=np.int32)
    if n:
        offs[0] = 1
        np.cumsum(nchunks[:-1], dtype=np.int32, out=offs[1:])
        offs[1:] += 1
    total = int(1 + nchunks.sum())
    flat = np.zeros((round_chunks(total, flat_step), RAGGED_CHUNK), dtype=np.uint8)
    return flat, offs, lengths


def pack_ragged_numpy(
    byte_docs: Sequence[bytes], pad_to: int, flat_step: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """list[bytes] → (flat uint8 [C, 128], offs int32 [B], lengths int32 [B]).

    Host mirror of the native ``pack_ragged`` loader. ``offs[i]`` is doc
    i's first chunk row in ``flat`` (row 0 is the reserved zero chunk);
    docs longer than ``pad_to`` are truncated, matching ``pad_batch``.
    A :class:`~.encode_device.DocBlock` fills via one vectorized scatter
    instead of the per-document loop (docs/PERFORMANCE.md §11).
    """
    from .encode_device import DocBlock, ragged_block

    if isinstance(byte_docs, DocBlock):
        return ragged_block(byte_docs, pad_to, flat_step)
    flat, offs, lengths = ragged_layout(byte_docs, pad_to, flat_step)
    view = flat.reshape(-1)
    for i, doc in enumerate(byte_docs):
        ln = int(lengths[i])
        if ln:
            start = int(offs[i]) * RAGGED_CHUNK
            view[start : start + ln] = np.frombuffer(doc, np.uint8, count=ln)
    return flat, offs, lengths


def unpack_ragged(flat, offs, lengths, pad_to: int):
    """Device-side inverse of ``pack_ragged``: → uint8 [B, pad_to].

    Bit-identical to ``pad_batch``'s output: valid chunks gather the doc's
    bytes, chunks past ``ceil(len/128)`` gather the reserved zero row. One
    lane-width row gather — ~free next to the h2d transfer it shrinks.
    Written against ``jnp`` (jit-traceable); callers jit it per (C, B, S)
    shape triple.
    """
    import jax
    import jax.numpy as jnp

    nch = pad_to // RAGGED_CHUNK
    j = jax.lax.broadcasted_iota(jnp.int32, (1, nch), 1)
    valid = j < -(-lengths[:, None] // RAGGED_CHUNK)
    idx = jnp.where(valid, offs[:, None] + j, 0)
    return flat[idx].reshape(lengths.shape[0], pad_to)


# Shared jitted unpack: one compile cache per (C, B, S) shape triple for
# every ragged consumer (the scoring runner's dispatch and the fit
# pipeline's ingest), built lazily so importing this module never touches
# jax. All three shapes are bucketed by the packers, so the compile count
# stays bounded.
_UNPACK_JIT = None


def unpack_ragged_jit(flat, offs, lengths, pad_to: int):
    """jit-compiled :func:`unpack_ragged` (``pad_to`` static), cached across
    callers so the runner and the fit pipeline share compilations."""
    global _UNPACK_JIT
    if _UNPACK_JIT is None:
        from functools import partial

        import jax

        _UNPACK_JIT = partial(jax.jit, static_argnames=("pad_to",))(
            unpack_ragged
        )
    return _UNPACK_JIT(flat, offs, lengths, pad_to)


def truncate_utf8(doc: bytes, cap: int) -> bytes:
    """First ``cap`` bytes of a document, never splitting a UTF-8 character:
    if byte ``cap`` is a continuation byte, the cut backs up to the char
    boundary (at most 3 bytes). Non-UTF-8 input falls back to the hard cap
    when backtracking would consume the whole prefix.

    This is the ``maxScoreBytes`` primitive (fastText-style scoring cap):
    language identity saturates within a few hundred bytes, so scoring only
    a prefix preserves accuracy while shipping ~len/cap× fewer bytes to the
    device — the wire, not the MXU, bounds short-gram configs
    (docs/PERFORMANCE.md §1)."""
    if cap <= 0 or len(doc) <= cap:
        return doc
    k = cap
    while k > 0 and (doc[k] & 0xC0) == 0x80:
        k -= 1
    return doc[:k] if k > 0 else doc[:cap]


def chunk_document(
    doc: bytes, chunk_size: int, overlap: int
) -> list[bytes]:
    """Split one long document into fixed-size chunks with ``overlap`` shared
    bytes between consecutive chunks (``overlap = max(gram_lengths) - 1``), so
    every sliding window of the original document is fully contained in some
    chunk (SURVEY.md §5.7). To count each window exactly once, a non-final
    chunk owns window starts ``[0, chunk_size - overlap)`` and the final chunk
    owns all of its window starts — enforced by the scorer's per-chunk window
    masks. The doc's gram histogram is then the sum of per-chunk histograms
    (associative ⇒ chunks may land on different devices and combine with a
    psum — the ring-attention analog for bag-of-grams scoring).
    """
    if chunk_size <= overlap:
        raise ValueError(f"chunk_size {chunk_size} must exceed overlap {overlap}")
    if len(doc) <= chunk_size:
        return [doc]
    stride = chunk_size - overlap
    return [doc[start : start + chunk_size] for start in range(0, len(doc) - overlap, stride)]
