"""Device-side encode: score straight from raw concatenated bytes.

The padded and ragged transfer forms both re-materialize documents on the
host — per-doc copies into a padded ``[B, S]`` plane, or chunk-aligned
rows in a flat buffer — before anything ships. On all-unique traffic that
host freight is the end-to-end wall (docs/PERFORMANCE.md §11): compute
sustains ~165k docs/s while the pipeline delivers ~107k, and every fleet
replica pays its own copy of the bill. This module moves the remaining
encode work into the compiled program: the wire carries raw document
bytes concatenated once (uint8 byte plane) plus one int32 offset and one
int32 length per document, and the padded batch every scoring strategy
consumes is rebuilt *inside the same jit* as the scorer by one XLA
gather (:func:`encode_batch`). Nothing downstream changes — the rebuilt
batch is bit-identical to ``ops.encoding.pad_batch``'s output, so
gather/onehot/hist/fused all score it unchanged.

Host-side helpers keep the producer zero-copy: a :class:`DocBlock` views
numpy- or Arrow-backed corpora (data buffer + offsets) without ever
materializing per-document Python ``bytes``; :func:`utf8_safe_lengths`
applies the ``max_score_bytes`` cap to the whole block with vectorized
numpy, matching ``ops.encoding.truncate_utf8`` byte-for-byte; and
:func:`gather_wire` / :func:`wire_from_docs` assemble one batch's wire
buffer with a single fancy gather / single concat.

Wire sizes are bucketed (:func:`wire_capacity`) so the encode jit sees a
bounded set of ``(wire, B, S)`` shapes, mirroring the ragged path's
``round_chunks`` discipline.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class DocBlock:
    """A corpus as one flat uint8 byte plane + per-doc offsets — the
    zero-copy input form for :meth:`api.runner.BatchRunner.score`.

    ``flat`` is a 1-D uint8 view of the concatenated document bytes;
    ``offs`` is int64 ``[B + 1]`` with doc ``i`` occupying
    ``flat[offs[i]:offs[i+1]]``. Offsets are absolute positions into
    ``flat`` (an Arrow slice's offsets ride through unrebased), and
    ``owners`` pins whatever object backs the views so an Arrow buffer
    cannot be freed while a scoring call still reads it.
    """

    __slots__ = ("flat", "offs", "owners")

    def __init__(self, flat: np.ndarray, offs: np.ndarray, owners=()):
        flat = np.asarray(flat)
        if flat.dtype != np.uint8 or flat.ndim != 1:
            raise ValueError("DocBlock.flat must be a 1-D uint8 array")
        offs = np.asarray(offs)
        if offs.ndim != 1 or offs.size < 1:
            raise ValueError("DocBlock.offs must be 1-D with >= 1 entries")
        offs = offs.astype(np.int64, copy=False)
        if offs.size > 1:
            if int(offs[0]) < 0 or int(offs[-1]) > flat.size:
                raise ValueError("DocBlock.offs out of range for flat")
            if np.any(np.diff(offs) < 0):
                raise ValueError("DocBlock.offs must be non-decreasing")
        self.flat = flat
        self.offs = offs
        self.owners = tuple(owners)

    # ------------------------------------------------------ constructors ----
    @classmethod
    def from_bytes(cls, docs: Sequence[bytes]) -> "DocBlock":
        """One concat of the whole corpus — the list[bytes] on-ramp (per-doc
        Python objects already exist; the win is everything after)."""
        lens = np.fromiter(
            (len(d) for d in docs), dtype=np.int64, count=len(docs)
        )
        offs = np.zeros(len(docs) + 1, dtype=np.int64)
        np.cumsum(lens, out=offs[1:])
        joined = b"".join(docs)
        flat = np.frombuffer(joined, dtype=np.uint8)
        return cls(flat, offs, owners=(joined,))

    @classmethod
    def from_arrow(cls, arr) -> "DocBlock":
        """View a pyarrow Binary/String (or Large*) array's buffers without
        copying the data plane; the array itself is retained as the owner.
        Raises ImportError when pyarrow is absent (the dep stays optional)."""
        import pyarrow as pa  # gated: zero-copy Arrow ingest is opt-in

        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        off_dtype = np.int64
        if pa.types.is_string(arr.type) or pa.types.is_binary(arr.type):
            off_dtype = np.int32
        elif not (
            pa.types.is_large_string(arr.type)
            or pa.types.is_large_binary(arr.type)
        ):
            raise TypeError(
                f"DocBlock.from_arrow needs a (large_)binary/string array, "
                f"got {arr.type}"
            )
        if arr.null_count:
            raise ValueError("DocBlock.from_arrow: nulls not supported")
        bufs = arr.buffers()  # [validity, offsets, data]
        offs_all = np.frombuffer(bufs[1], dtype=off_dtype)
        offs = offs_all[arr.offset : arr.offset + len(arr) + 1]
        data = bufs[2]
        flat = (
            np.frombuffer(data, dtype=np.uint8)
            if data is not None
            else np.zeros(0, dtype=np.uint8)
        )
        return cls(flat, offs.astype(np.int64, copy=False), owners=(arr,))

    # ------------------------------------------------------------ views ----
    def __len__(self) -> int:
        return self.offs.size - 1

    def starts(self) -> np.ndarray:
        return self.offs[:-1]

    def lengths(self) -> np.ndarray:
        return self.offs[1:] - self.offs[:-1]

    @property
    def total_bytes(self) -> int:
        return int(self.offs[-1] - self.offs[0])

    def doc(self, i: int) -> bytes:
        """Materialize one document (fallback/degraded paths only)."""
        return self.flat[int(self.offs[i]) : int(self.offs[i + 1])].tobytes()


def utf8_safe_lengths(
    flat: np.ndarray,
    starts: np.ndarray,
    lengths: np.ndarray,
    cap: int,
) -> np.ndarray:
    """Vectorized ``max_score_bytes`` cap over a byte plane: per-doc
    truncated lengths matching ``ops.encoding.truncate_utf8`` exactly —
    a cut landing on a UTF-8 continuation byte backs up to the character
    boundary, and a backtrack that would consume the whole prefix falls
    back to the hard cap (non-UTF-8 input). ``cap <= 0`` is a no-op.
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    if cap <= 0:
        return lengths
    out = np.minimum(lengths, cap)
    over = np.flatnonzero(lengths > cap)
    if over.size == 0:
        return out
    starts = np.asarray(starts, dtype=np.int64)
    # Gather bytes [0..cap] of each over-cap doc in bounded slabs: the
    # backtrack loop can in principle walk to position 0 on malformed
    # input, so the whole prefix participates.
    span_cols = cap + 1
    rows_per_slab = max(1, (4 << 20) // span_cols)
    col = np.arange(span_cols, dtype=np.int64)
    for lo in range(0, over.size, rows_per_slab):
        sel = over[lo : lo + rows_per_slab]
        b = flat[starts[sel, None] + col]
        noncont = (b & 0xC0) != 0x80
        # Position 0 is a stop regardless of its byte class (the loop's
        # ``k > 0`` guard); scanning down from ``cap``, the first
        # non-continuation position is where the cut lands.
        noncont[:, 0] = True
        k = span_cols - 1 - np.argmax(noncont[:, ::-1], axis=1)
        out[sel] = np.where(k > 0, k, cap)
    return out


def chunk_table(
    starts: np.ndarray,
    lengths: np.ndarray,
    chunk_size: int,
    overlap: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized ``ops.encoding.chunk_document`` over a byte plane:
    ``(doc_of, chunk_starts, chunk_lengths, window_limits)`` arrays, one
    row per chunk, in (doc, chunk-rank) order — the same expansion the
    runner's per-doc loop produces, without materializing chunk bytes.
    Non-final chunks own window starts ``[0, chunk_size - overlap)``;
    the final chunk owns all of its starts (limit = ``chunk_size``).
    """
    if chunk_size <= overlap:
        raise ValueError(
            f"chunk_size {chunk_size} must exceed overlap {overlap}"
        )
    starts = np.asarray(starts, dtype=np.int64)
    lengths = np.asarray(lengths, dtype=np.int64)
    stride = chunk_size - overlap
    m = np.where(
        lengths <= chunk_size, 1, -(-(lengths - overlap) // stride)
    ).astype(np.int64)
    total = int(m.sum())
    n = lengths.size
    doc_of = np.repeat(np.arange(n, dtype=np.int64), m)
    first = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(m[:-1], out=first[1:])
    rank = np.arange(total, dtype=np.int64) - np.repeat(first, m)
    chunk_starts = starts[doc_of] + rank * stride
    chunk_lengths = np.minimum(chunk_size, lengths[doc_of] - rank * stride)
    is_final = rank == m[doc_of] - 1
    limits = np.where(is_final, chunk_size, stride).astype(np.int64)
    return doc_of, chunk_starts, chunk_lengths, limits


# Wire-size buckets: the encode jit compiles per (wire, B, S) shape, so
# raw totals are rounded up to 1/16 of the batch's padded byte size
# (floor 256) — at most ~17 wire variants per (B, S) geometry, and the
# wire never exceeds the padded form it replaces.
_WIRE_BUCKET_BASE = 256


def wire_capacity(total: int, rows: int, pad_to: int) -> int:
    """Bucketed wire-buffer size for ``total`` real bytes in a
    ``rows × pad_to`` batch geometry."""
    padded = max(rows * pad_to, 1)
    step = max(_WIRE_BUCKET_BASE, padded // 16)
    return min(-(-max(total, 1) // step) * step, padded)


def gather_wire(
    flat: np.ndarray,
    starts: np.ndarray,
    lengths: np.ndarray,
    capacity: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One batch's wire form off a byte plane: ``(wire uint8 [capacity],
    starts int32 [B], lengths int32 [B])`` via a single fancy gather —
    no per-document copies, overlapping source ranges (chunk overlap)
    welcome. Returned starts are exclusive length cumsums into ``wire``.
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    starts = np.asarray(starts, dtype=np.int64)
    n = lengths.size
    wstarts = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(lengths[:-1], out=wstarts[1:])
    total = int(lengths.sum())
    cap = total if capacity is None else int(capacity)
    if cap < total:
        raise ValueError(f"wire capacity {cap} < real bytes {total}")
    wire = np.zeros(cap, dtype=np.uint8)
    if total:
        delta = np.repeat(starts - wstarts, lengths)
        wire[:total] = flat[delta + np.arange(total, dtype=np.int64)]
    return wire, wstarts.astype(np.int32), lengths.astype(np.int32)


def wire_from_docs(
    byte_docs: Sequence[bytes], capacity: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One batch's wire form from materialized docs: a single ``join``
    (one memcpy per doc inside CPython, no padded-plane scatter) plus the
    int32 index arrays — the list[bytes] tier of the device-encode path.
    """
    n = len(byte_docs)
    lengths = np.fromiter((len(d) for d in byte_docs), np.int64, count=n)
    wstarts = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(lengths[:-1], out=wstarts[1:])
    total = int(lengths.sum())
    cap = total if capacity is None else int(capacity)
    if cap < total:
        raise ValueError(f"wire capacity {cap} < real bytes {total}")
    wire = np.zeros(cap, dtype=np.uint8)
    if total:
        wire[:total] = np.frombuffer(b"".join(byte_docs), dtype=np.uint8)
    return wire, wstarts.astype(np.int32), lengths.astype(np.int32)


def encode_batch(wire, starts, lengths, pad_to: int):
    """Device-side inverse of the wire form: → uint8 ``[B, pad_to]``,
    bit-identical to ``ops.encoding.pad_batch``. One row gather plus a
    validity mask — position 0 of the wire is real data (unlike the
    ragged form's reserved zero row), so out-of-range lanes must be
    zeroed after the gather, restoring the padded form's zero tail.
    Written against ``jnp``; callers jit it per (wire, B, S) shape.
    """
    import jax
    import jax.numpy as jnp

    j = jax.lax.broadcasted_iota(jnp.int32, (lengths.shape[0], pad_to), 1)
    valid = j < lengths[:, None]
    idx = jnp.where(valid, starts[:, None] + j, 0)
    return jnp.where(valid, wire[idx], jnp.uint8(0))


# Shared jitted encode: one compile cache per (wire, B, S) shape triple
# for every device-encode consumer (the scoring runner's dispatch and the
# fit pipeline's ingest), built lazily so importing this module never
# touches jax. All three shapes are bucketed, so compile counts stay
# bounded — exactly the ``unpack_ragged_jit`` discipline.
_ENCODE_JIT = None


def encode_batch_jit(wire, starts, lengths, pad_to: int):
    """jit-compiled :func:`encode_batch` (``pad_to`` static), cached across
    callers so the runner and the fit pipeline share compilations."""
    global _ENCODE_JIT
    if _ENCODE_JIT is None:
        from functools import partial

        import jax

        _ENCODE_JIT = partial(jax.jit, static_argnames=("pad_to",))(
            encode_batch
        )
    return _ENCODE_JIT(wire, starts, lengths, pad_to)


# ------------------------------------------------ host packers over a block -
def pad_block(block: DocBlock, pad_to: int) -> tuple[np.ndarray, np.ndarray]:
    """``ops.encoding.pad_batch`` over a :class:`DocBlock`: one vectorized
    scatter instead of a per-document copy loop, bit-identical output.
    The host-pack fallback (degraded ladder, native unavailable) stays
    exact for block-fed calls without materializing Python bytes."""
    starts = block.starts()
    lengths = np.minimum(block.lengths(), pad_to)
    n = lengths.size
    batch = np.zeros((n, pad_to), dtype=np.uint8)
    total = int(lengths.sum())
    if total:
        wstarts = np.zeros(n, dtype=np.int64)
        np.cumsum(lengths[:-1], out=wstarts[1:])
        pos = np.arange(total, dtype=np.int64)
        src = np.repeat(starts - wstarts, lengths) + pos
        row = np.repeat(np.arange(n, dtype=np.int64), lengths)
        dst = row * pad_to + (pos - np.repeat(wstarts, lengths))
        batch.reshape(-1)[dst] = block.flat[src]
    return batch, lengths.astype(np.int32)


def ragged_block(
    block: DocBlock, pad_to: int, flat_step: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """``ops.encoding.pack_ragged_numpy`` over a :class:`DocBlock`: the
    chunk-aligned flat layout filled by one vectorized scatter."""
    from .encoding import RAGGED_CHUNK, round_chunks

    starts = block.starts()
    lengths = np.minimum(block.lengths(), pad_to).astype(np.int64)
    n = lengths.size
    nchunks = -(-lengths // RAGGED_CHUNK)
    offs = np.empty(n, dtype=np.int32)
    if n:
        offs[0] = 1
        np.cumsum(nchunks[:-1], dtype=np.int32, out=offs[1:])
        offs[1:] += 1
    total_chunks = int(1 + nchunks.sum())
    flat = np.zeros(
        (round_chunks(total_chunks, flat_step), RAGGED_CHUNK), dtype=np.uint8
    )
    total = int(lengths.sum())
    if total:
        wstarts = np.zeros(n, dtype=np.int64)
        np.cumsum(lengths[:-1], out=wstarts[1:])
        pos = np.arange(total, dtype=np.int64)
        src = np.repeat(starts - wstarts, lengths) + pos
        dst = (
            np.repeat(offs.astype(np.int64) * RAGGED_CHUNK - wstarts, lengths)
            + pos
        )
        flat.reshape(-1)[dst] = block.flat[src]
    return flat, offs, lengths.astype(np.int32)
