"""ops subpackage."""
