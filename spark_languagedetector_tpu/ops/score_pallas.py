"""Fused one-hot scoring as a single Pallas TPU kernel.

The XLA one-hot strategy (:func:`ops.score.score_batch_onehot`) materializes
per-block one-hots and a per-document ``[B, 256, 256]`` histogram accumulator
in HBM — ~600MB of HBM traffic per [256, 2048] batch for ~50 GFLOP of MXU
work, and O(B·65536) memory that caps the micro-batch size. This kernel fuses
the whole pipeline in VMEM:

    bytes block → one-hot (VPU, registers) → [256, BLK]ᵀ·[256, BLK] bigram
    histogram accumulate (MXU, VMEM scratch) → ⟨hist, W_l⟩ contraction (VPU)

Per document the only HBM traffic is the byte row in and L floats out, and
per-document state is a constant 256KB VMEM scratch — so micro-batches can be
thousands of documents, amortizing the per-dispatch host/tunnel overhead that
dominates the XLA path (measured: ~0.4ms vs ~1.25ms per [256, 2048] batch,
and 8×+ fewer dispatches end-to-end).

Replaces the reference's per-window JVM hash-map + ``BLAS.axpy`` hot loop
(``/root/reference/src/main/.../LanguageDetectorModel.scala:139-152``) for
exact vocabularies with gram lengths ⊆ {1, 2}; other configs use the gather
strategies in :mod:`ops.score`.

Mosaic constraints shaping the code (all found empirically):
  * every intermediate is kept 2-D (rank-1 values crash the lowering);
  * lane-dimension dynamic slices must be 128-aligned, so the "next byte"
    plane is a pre-shifted copy of the batch prepared by XLA outside the
    kernel rather than an off-by-one slice inside it;
  * one-hots are built lane-major ``[256, BLK]`` (windows on lanes) so no
    transposes are needed: the bigram histogram is an NT contraction over
    the shared lane axis.

Semantics parity with :func:`ops.score.score_batch` (SURVEY.md §2.9): unknown
grams contribute zero, all-miss documents argmax to index 0, a document
shorter than a configured gram length contributes its whole-byte prefix once
per such length (Scala ``sliding`` partial-window rule — applied in the XLA
wrapper, not the kernel, since it touches only ``lengths < 2`` rows).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .vocab import EXACT, VocabSpec

# jax renamed TPUCompilerParams -> CompilerParams between 0.4.x and 0.5;
# alias once so the kernels lower (and interpret) on both.
COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

# Documents per grid step: the sublane tile height of the batch block.
DB = 8

# Window-axis block (lane dimension of the one-hots). Larger blocks mean a
# deeper MXU contraction (K = block) and fewer scratch read-modify-writes;
# 2048 measured ~30% faster than 512 on v5e for [4096, 2048] batches. Padded
# widths below the block shrink it to the (128-aligned) width, so short
# length buckets still run single-step.
DEFAULT_BLOCK = 2048

# Language-count ceiling for the *fused* kernel: its bigram weight view is
# L × 256KB resident in VMEM per dispatch and its contraction loop is
# per-language. Larger L switches to the histogram kernel + XLA matmul
# (``weight_views`` picks the shape; ``score_batch_pallas`` dispatches on it)
# — per-doc [256, 256] histograms written to HBM, then one MXU contraction
# ``hist @ W`` over all languages at once, so L is unbounded.
MAX_PALLAS_LANGS = 16


def pallas_supported(spec: VocabSpec, num_rows: int, num_langs: int) -> bool:
    """True when a pallas strategy applies: exact vocab, gram lengths ⊆
    {1, 2}, dense weight table over the full id space (any language count —
    small L runs the fused kernel, large L the histogram kernel)."""
    if num_langs > MAX_PALLAS_LANGS and 2 not in spec.gram_lengths:
        # Unigram-only vocabs beyond the fused kernel's L cap would pay for
        # full [256, 256] histograms just to row-sum them — the XLA one-hot
        # strategy handles that case with a [B, 256] histogram directly.
        return False
    return (
        spec.mode == EXACT
        and max(spec.gram_lengths) <= 2
        and num_rows == spec.id_space_size
    )


def weight_views(
    weights: np.ndarray | jnp.ndarray, spec: VocabSpec
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dense [V, L] table → kernel views: w1 [256, L] plus the bigram view.

    For L ≤ MAX_PALLAS_LANGS the bigram view is [L, 256, 256] (VMEM-resident
    operand of the fused kernel); for larger L it stays [65536, L] (operand
    of the post-histogram XLA matmul). Call once per profile (the reshape/
    transpose is a real relayout — don't re-do it per batch). For
    gram_lengths == (1,) the bigram view is zeros.
    """
    w = jnp.asarray(weights, dtype=jnp.float32)
    L = w.shape[1]
    w1 = w[:256]
    fused = L <= MAX_PALLAS_LANGS
    if 2 in spec.gram_lengths:
        off = spec.offsets[2]
        w2 = w[off : off + 65536]
        if fused:
            w2 = w2.reshape(256, 256, L).transpose(2, 0, 1)
    elif fused:
        w2 = jnp.zeros((L, 256, 256), dtype=jnp.float32)
    else:
        w2 = jnp.zeros((65536, L), dtype=jnp.float32)
    return w1, w2


def _build_kernel(S: int, L: int, blk: int, has1: bool, has2: bool):
    n_steps = S // blk

    def kernel(b0_ref, b1_ref, len_ref, lim_ref, w1_ref, w2_ref, o_ref,
               acc2_ref, acc1_ref):
        base = pl.program_id(0) * DB
        for d in range(DB):
            dlen = len_ref[base + d]
            dlim = lim_ref[base + d]
            if has2:
                acc2_ref[:, :] = jnp.zeros((256, 256), jnp.float32)
            if has1:
                acc1_ref[:, :] = jnp.zeros((256, 128), jnp.float32)
            for k in range(n_steps):
                off = k * blk

                def step(off=off):
                    vals = b0_ref[pl.dslice(d, 1), pl.dslice(off, blk)]  # [1, blk]
                    iota = jax.lax.broadcasted_iota(jnp.int32, (256, blk), 0)
                    starts = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1) + off
                    lim_ok = starts < dlim
                    if has2:
                        nxt = b1_ref[pl.dslice(d, 1), pl.dslice(off, blk)]
                        mask2 = (starts <= dlen - 2) & lim_ok
                        oh0 = jnp.where(
                            (vals == iota) & mask2, 1.0, 0.0
                        ).astype(jnp.bfloat16)
                        oh1 = jnp.where(nxt == iota, 1.0, 0.0).astype(jnp.bfloat16)
                        acc2_ref[:, :] += jax.lax.dot_general(
                            oh0, oh1, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
                    if has1:
                        mask1 = (starts <= dlen - 1) & lim_ok
                        ohu = jnp.where((vals == iota) & mask1, 1.0, 0.0)
                        acc1_ref[:, 0:1] += ohu.sum(axis=1, keepdims=True)

                # A block holds no windows when the doc (or its owned chunk
                # range) ends before it — skip the one-hot build and matmul
                # entirely. Skipped blocks leave the pre-zeroed accumulators
                # intact, so empty docs (and mesh pad rows) correctly score
                # zero without paying for a single block.
                pl.when((off < dlen) & (off < dlim))(step)
            for l in range(L):
                s = jnp.zeros((1, 1), jnp.float32)
                if has2:
                    t2 = acc2_ref[:, :] * w2_ref[l]
                    s = s + t2.sum(axis=0, keepdims=True).sum(
                        axis=1, keepdims=True
                    )
                if has1:
                    t1 = acc1_ref[:, 0:1] * w1_ref[:, pl.dslice(l, 1)]
                    s = s + t1.sum(axis=0, keepdims=True)
                o_ref[pl.dslice(d, 1), pl.dslice(l, 1)] = s

    return kernel


def _build_hist_kernel(S: int, blk: int, mask_n: int):
    """Per-document bigram-pair histogram kernel: out[d] = Σ_w oh(b0_w)ᵀ oh(b1_w)
    over windows with start ≤ dlen - mask_n (and < dlim). With mask_n == 2
    the [256, 256] histogram counts full bigrams; with mask_n == 1 (unigram-
    only vocabs) each masked window still contributes exactly one count to
    row b0_w (oh(b1) sums to 1 per window), so a row-sum recovers the
    unigram histogram."""
    n_steps = S // blk

    def kernel(b0_ref, b1_ref, len_ref, lim_ref, o_ref, acc_ref):
        base = pl.program_id(0) * DB
        for d in range(DB):
            dlen = len_ref[base + d]
            dlim = lim_ref[base + d]
            acc_ref[:, :] = jnp.zeros((256, 256), jnp.float32)
            for k in range(n_steps):
                off = k * blk

                def step(off=off):
                    vals = b0_ref[pl.dslice(d, 1), pl.dslice(off, blk)]
                    nxt = b1_ref[pl.dslice(d, 1), pl.dslice(off, blk)]
                    iota = jax.lax.broadcasted_iota(jnp.int32, (256, blk), 0)
                    starts = (
                        jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1) + off
                    )
                    mask = (starts <= dlen - mask_n) & (starts < dlim)
                    oh0 = jnp.where(
                        (vals == iota) & mask, 1.0, 0.0
                    ).astype(jnp.bfloat16)
                    oh1 = jnp.where(nxt == iota, 1.0, 0.0).astype(jnp.bfloat16)
                    acc_ref[:, :] += jax.lax.dot_general(
                        oh0, oh1, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )

                pl.when((off < dlen) & (off < dlim))(step)
            o_ref[pl.dslice(d * 256, 256), :] = acc_ref[:, :]

    return kernel


def _hist_batch(
    b0: jnp.ndarray,
    b1: jnp.ndarray,
    lengths: jnp.ndarray,
    lim: jnp.ndarray,
    *,
    blk: int,
    mask_n: int,
    interpret: bool,
) -> jnp.ndarray:
    """float32 [B, 256, 256] per-document histograms via the pallas kernel."""
    B, S = b0.shape
    out = pl.pallas_call(
        _build_hist_kernel(S, blk, mask_n),
        grid=(B // DB,),
        in_specs=[
            pl.BlockSpec((DB, S), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((DB, S), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (DB * 256, 256), lambda b: (b, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B * 256, 256), jnp.float32),
        scratch_shapes=[pltpu.VMEM((256, 256), jnp.float32)],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(b0, b1, lengths, lim)
    return out.reshape(B, 256, 256)


def _score_from_hist(
    hist: jnp.ndarray,
    batch_i32: jnp.ndarray,
    lengths: jnp.ndarray,
    lim: jnp.ndarray,
    w1: jnp.ndarray,
    w2: jnp.ndarray,
    has1: bool,
    has2: bool,
) -> jnp.ndarray:
    """Histogram → scores: one MXU contraction over all languages.

    HIGHEST matmul precision keeps the count × log-weight products exact
    enough for argmax parity with the float64 host scorer (counts are exact
    integers in f32; bf16 passes would round them past 256).
    """
    B = hist.shape[0]
    scores = jnp.zeros((B, w1.shape[1]), jnp.float32)
    if has2:
        scores = scores + jax.lax.dot(
            hist.reshape(B, 65536), w2,
            precision=jax.lax.Precision.HIGHEST,
        )
    if has1:
        # Unigram histogram = bigram row-sum + the last byte's n=1 window
        # (start dlen-1 passes the n=1 mask but not the bigram mask), when
        # that start is owned by this chunk.
        h1 = hist.sum(axis=2)
        if has2:
            last = batch_i32[
                jnp.arange(B), jnp.clip(lengths - 1, 0, batch_i32.shape[1] - 1)
            ]
            ok = (lengths >= 1) & (lengths - 1 < lim)
            h1 = h1 + jnp.where(
                ok[:, None],
                (last[:, None] == jnp.arange(256, dtype=jnp.int32)).astype(
                    jnp.float32
                ),
                0.0,
            )
        scores = scores + jax.lax.dot(
            h1, w1, precision=jax.lax.Precision.HIGHEST
        )
    return scores


@partial(jax.jit, static_argnames=("spec", "block", "interpret"))
def score_batch_pallas(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    w1: jnp.ndarray,
    w2: jnp.ndarray,
    window_limit: jnp.ndarray | None = None,
    *,
    spec: VocabSpec,
    block: int = DEFAULT_BLOCK,
    interpret: bool = False,
) -> jnp.ndarray:
    """float32 [B, L] scores for a padded uint8 batch via the fused kernel.

    Args mirror :func:`ops.score.score_batch` except the weight table arrives
    pre-shaped by :func:`weight_views`. ``interpret=True`` runs the kernel in
    Pallas interpret mode (any backend — used by the CPU tests).
    """
    if spec.mode != EXACT or max(spec.gram_lengths) > 2:
        raise ValueError(
            "score_batch_pallas supports exact-mode vocabularies with gram "
            f"lengths <= 2 only; got mode={spec.mode!r} "
            f"gram_lengths={spec.gram_lengths!r}"
        )
    has1 = 1 in spec.gram_lengths
    has2 = 2 in spec.gram_lengths
    B0, S0 = batch.shape
    L = w1.shape[1]

    # Lane padding: S must be a multiple of the window block.
    blk = min(block, -(-S0 // 128) * 128)
    S = -(-S0 // blk) * blk
    if S != S0:
        batch = jnp.pad(batch, ((0, 0), (0, S - S0)))
    # Sublane padding: whole DB-document grid steps (padded rows: length 0).
    B = -(-B0 // DB) * DB
    if B != B0:
        batch = jnp.pad(batch, ((0, B - B0), (0, 0)))
        lengths = jnp.pad(lengths, (0, B - B0))
        if window_limit is not None:
            window_limit = jnp.pad(window_limit, (0, B - B0))

    b0 = batch.astype(jnp.int32)
    # Pre-shifted "next byte" plane (Mosaic needs 128-aligned lane slices).
    b1 = jnp.pad(b0[:, 1:], ((0, 0), (0, 1))) if has2 else b0
    lim = (
        jnp.full((B,), S, dtype=jnp.int32)
        if window_limit is None
        else window_limit.astype(jnp.int32)
    )

    if w2.ndim == 2:
        # Histogram path (L > MAX_PALLAS_LANGS): per-doc [256, 256]
        # histograms from the kernel, then one XLA MXU contraction over all
        # languages — hist @ W, the north star's matmul, with unbounded L.
        hist = _hist_batch(
            b0, b1, lengths.astype(jnp.int32), lim,
            blk=blk, mask_n=2 if has2 else 1, interpret=interpret,
        )
        out = _score_from_hist(
            hist, b0, lengths.astype(jnp.int32), lim, w1, w2, has1, has2
        )
        if has2:
            out = out + jnp.where(
                (lengths == 1)[:, None], w1[b0[:, 0]], 0.0
            )
        return out[:B0]

    out = pl.pallas_call(
        _build_kernel(S, L, blk, has1, has2),
        grid=(B // DB,),
        in_specs=[
            pl.BlockSpec((DB, S), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((DB, S), lambda b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((256, L), lambda b: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (L, 256, 256), lambda b: (0, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec((DB, L), lambda b: (b, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, L), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((256, 256), jnp.float32),
            pltpu.VMEM((256, 128), jnp.float32),
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(b0, b1, lengths.astype(jnp.int32), lim, w1, w2)

    if has2:
        # Partial-window rule: a 1-byte document under gram length 2
        # contributes its single byte once, in the length-1 id space. Chunking
        # never produces 1-byte rows, so window_limit cannot apply here.
        corr = jnp.where(
            (lengths == 1)[:, None], w1[b0[:, 0]], 0.0
        )
        out = out + corr
    return out[:B0]
