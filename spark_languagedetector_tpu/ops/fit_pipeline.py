"""Pipelined ingest for the device fit: plan → pack → put → count.

Before this module, ``fit_profile_device`` walked the corpus with a fully
serial host loop — Python slice, host ``pad_batch``, synchronous
``jnp.asarray`` transfer, dispatch — using none of the wire machinery the
scoring runner already had. BENCH_r05 measured the consequence:
``fit_docs_per_s_device`` 666 vs 669 on host, on the same link where scoring
runs 34k–165k docs/s. This module is the fit half catching up to the scoring
half (docs/PERFORMANCE.md §6): the same data-parallel-counting shape DrJAX
(arXiv:2403.07128) builds MapReduce primitives around, with the count-table
reduction left to GSPMD (arXiv:2105.04663) exactly as the sharded fit step
already does.

Three pieces, all host-side policy (the count math stays in ``fit_tpu``):

  * :func:`plan_fit_batches` — the deterministic micro-batch plan: oversized
    documents (longer than the largest length bucket) are chunk-split onto
    bucketed widths instead of rounding the padded width up per document
    (which recompiled the count step per distinct width); the boundary
    windows a split severs are counted on host and injected once through the
    fit's ``extra_counts`` scatter, so the split is exactly count-preserving.
    Items are length-sorted and grouped per length bucket with adaptive row
    counts under a byte budget — the scoring runner's ``MAX_BATCH_BYTES``
    discipline applied to fit, replacing the old hard-coded
    ``batch_rows=512``.
  * :func:`iter_device_batches` — a bounded producer/consumer pipeline: a
    background packer thread packs each planned batch with the native packer
    (``native/pack_batch`` / ``pack_ragged``), ships it ragged when the
    chunk-aligned flat buffer is smaller than the padded form, and starts its
    async ``device_put``, keeping :data:`FIT_PIPELINE_DEPTH` transferred
    batches queued ahead of the jit count step that consumes them. The
    consumer (the fit loop) therefore always has the next micro-batch
    resident by the time the previous count dispatch returns.
  * :func:`resolve_fit_batching` — the knob resolution: an explicit
    ``batch_rows`` (estimator param ``fitBatchRows``) wins, then the
    ``LANGDETECT_FIT_BATCH_ROWS`` env override, else adaptive sizing under
    ``LANGDETECT_FIT_BATCH_BYTES`` (default 8MB per padded transfer).

Exactness: the packed batches are bit-identical to ``pad_batch`` output (the
ragged unpack reconstructs the same padded array on device), chunk-split plus
the host-counted straddle windows reproduce every sliding window of every
oversized document exactly once, and int32 count accumulation is
order-independent — so the fitted profile stays bit-identical to the host
fit (pinned by tests/test_fit_pipeline.py across single-device, split, and
mesh paths).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..exec import config as exec_config
from ..exec.core import (
    dedup_counted,
    ordered_prefetch,
    plan_micro_batches,
    rows_under_byte_budget,
)
from ..telemetry import REGISTRY, span
from ..utils.logging import get_logger
from .encoding import RAGGED_CHUNK, bucket_length, round_chunks
from .vocab import VocabSpec

_log = get_logger("ops.fit_pipeline")

ROWS_ENV = "LANGDETECT_FIT_BATCH_ROWS"
BYTES_ENV = "LANGDETECT_FIT_BATCH_BYTES"

# Byte budget for one micro-batch's padded transfer — the same wall the
# scoring runner's MAX_BATCH_BYTES encodes (8MB batches beat both many
# smaller puts and coarser-overlap 16MB ones on the tunneled link;
# api/runner.py). Rows halve from the cap until the padded bytes fit, so
# the compiled (rows, pad_to) set stays a small fixed lattice.
DEFAULT_FIT_BATCH_BYTES = 8 << 20
DEFAULT_FIT_MAX_ROWS = 4096
MIN_FIT_ROWS = 64

# Packed-and-transferring batches the producer keeps queued ahead of the
# consumer. 2 keeps one batch packing and one in transfer while the count
# step consumes a third — deeper buys nothing (the wire is serial) and
# holds more device memory.
FIT_PIPELINE_DEPTH = 2


def resolve_fit_batching(batch_rows: int | None = None) -> tuple[int | None, int]:
    """(fixed_rows | None, byte_budget) for the fit's micro-batch plan.

    An explicit ``batch_rows`` (the estimator's ``fitBatchRows`` param or a
    direct ``fit_profile_device`` argument) wins; otherwise the
    ``LANGDETECT_FIT_BATCH_ROWS`` env var forces a fixed row count; otherwise
    rows adapt per length bucket under the byte budget — env
    ``LANGDETECT_FIT_BATCH_BYTES``, else the tuning profile's
    ``fit_batch_bytes``, else :data:`DEFAULT_FIT_BATCH_BYTES` (the full
    precedence lives in ``exec.config``).
    """
    budget = int(exec_config.resolve("fit_batch_bytes"))
    if batch_rows is not None:
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be positive, got {batch_rows}")
        return int(batch_rows), budget
    rows = exec_config.resolve("fit_batch_rows")
    return (None if rows is None else int(rows)), budget


def rows_for_fit_bucket(
    pad_to: int,
    byte_budget: int = DEFAULT_FIT_BATCH_BYTES,
    max_rows: int = DEFAULT_FIT_MAX_ROWS,
) -> int:
    """Adaptive rows for a padded width — the fit twin of
    ``api.runner.rows_for_bucket``, parameterized by budget so the env knob
    reaches it. Both delegate to the shared halving policy."""
    return rows_under_byte_budget(pad_to, byte_budget, max_rows, MIN_FIT_ROWS)


def split_bounds(doc_len: int, max_len: int, min_tail: int) -> list[int]:
    """Split positions for one oversized document: chunks of ``max_len``
    with the final boundary pulled back so the tail chunk keeps at least
    ``min_tail`` bytes (= the max gram length — a tail shorter than a gram
    would trigger the partial-window rule the original long document never
    takes). Every chunk length stays in [min_tail, max_len]."""
    if doc_len <= max_len:
        return []
    bounds = list(range(max_len, doc_len, max_len))
    if doc_len - bounds[-1] < min_tail:
        bounds[-1] = doc_len - min_tail
    return bounds


def plan_fit_batches(
    byte_docs: Sequence[bytes],
    lang_indices,
    spec: VocabSpec,
    *,
    batch_rows: int | None = None,
    byte_budget: int = DEFAULT_FIT_BATCH_BYTES,
    length_buckets: Sequence[int] | None = None,
    dedup: bool | None = None,
):
    """Deterministic micro-batch plan for the device fit's ingest.

    Returns ``(items, item_langs, plan, straddle, item_mult)``:

      * ``items`` / ``item_langs`` — the work rows: every document ≤ the
        largest bucket verbatim, oversized documents chunk-split
        (:func:`split_bounds`) with the chunk inheriting the doc's language;
      * ``plan`` — ``[(sel int ndarray, pad_to), ...]``: row indices into
        ``items`` plus the bucketed padded width. With ``batch_rows`` fixed,
        sequential ``batch_rows``-row slices of the length-sorted order
        (the historical shapes); adaptive mode groups per bucket with
        :func:`rows_for_fit_bucket` rows, carrying each bucket's remainder
        into the next wider bucket so the whole fit has at most one ragged
        tail batch (the scoring planner's discipline). Every ``pad_to`` is a
        member of ``length_buckets`` — chunk-splitting guarantees no
        per-width recompiles.
      * ``straddle`` — ``(ids, langs, counts)`` int64 arrays for the
        boundary windows severed by chunk-splitting (host-computed via
        ``spec.gram_to_id``), or None. Scatter-added once through the fit's
        ``extra_counts`` path, they make the split exactly count-preserving.
      * ``item_mult`` — int32 per-item dedup multiplicity, or None when
        every (doc, lang) pair is distinct (or ``dedup`` is off — env
        ``LANGDETECT_DEDUP``). A duplicated source batch is counted once on
        device with its windows weighted by the duplicate count — integer
        counts × integer weight equals the duplicated sum exactly, so the
        fitted profile stays bit-identical to the undeduped fit
        (docs/PERFORMANCE.md §10).
    """
    if length_buckets is None:
        # The tuned lattice (exec.config: env > tuning profile > default) —
        # fit and score share one bucket set so the compiled shapes overlap.
        length_buckets = exec_config.resolve("length_buckets")
    if dedup is None:
        dedup = bool(exec_config.resolve("dedup"))
    max_len = length_buckets[-1]
    max_gram = max(spec.gram_lengths)
    lang_arr = np.asarray(lang_indices)
    docs = [
        d if isinstance(d, bytes) else bytes(d)  # native packer wants bytes
        for d in byte_docs
    ]
    doc_mult = None
    if dedup and len(docs) > 1:
        d = dedup_counted(
            [(doc, int(lang)) for doc, lang in zip(docs, lang_arr)],
            size_of=lambda key: len(key[0]),
        )
        if d is not None:
            first_idx, _, doc_mult = d
            docs = [docs[int(i)] for i in first_idx]
            lang_arr = np.asarray(lang_arr)[first_idx]
    items: list[bytes] = []
    item_langs: list[int] = []
    item_mult: list[int] = []
    corr: dict[tuple[int, int], int] = {}
    for j, (doc, lang) in enumerate(zip(docs, lang_arr)):
        lang = int(lang)
        m = 1 if doc_mult is None else int(doc_mult[j])
        if len(doc) <= max_len:
            items.append(doc)
            item_langs.append(lang)
            item_mult.append(m)
            continue
        prev = 0
        for p in split_bounds(len(doc), max_len, max_gram):
            items.append(doc[prev:p])
            item_langs.append(lang)
            item_mult.append(m)
            prev = p
            # Windows straddling this boundary (start in (p-n, p)) exist in
            # no chunk; count them here (× the dedup multiplicity — the
            # duplicates' severed windows are the same windows). n = 1
            # windows never straddle.
            for n in spec.gram_lengths:
                for s in range(p - n + 1, p):
                    key = (spec.gram_to_id(doc[s : s + n]), lang)
                    corr[key] = corr.get(key, 0) + m
        items.append(doc[prev:])
        item_langs.append(lang)
        item_mult.append(m)

    langs_np = np.asarray(item_langs, dtype=np.int32)
    mult_np = (
        None if doc_mult is None
        else np.asarray(item_mult, dtype=np.int32)
    )
    order = np.argsort([len(d) for d in items], kind="stable")
    plan: list[tuple[np.ndarray, int]] = []
    if batch_rows is not None:
        for start in range(0, len(order), batch_rows):
            sel = order[start : start + batch_rows]
            longest = max((len(items[i]) for i in sel), default=1)
            plan.append(
                (np.asarray(sel), bucket_length(max(longest, 1), length_buckets))
            )
    else:
        # The shared core planner (exec.core): per-bucket grouping with the
        # remainder carried into the next wider bucket — the same plan the
        # scoring runner emits, in the fit's length-sorted order.
        plan = plan_micro_batches(
            [len(d) for d in items],
            length_buckets=length_buckets,
            rows_for=lambda b: rows_for_fit_bucket(b, byte_budget),
            order=order,
        )

    straddle = None
    if corr:
        e = np.asarray(
            [(i, l, c) for (i, l), c in sorted(corr.items())], dtype=np.int64
        )
        straddle = (e[:, 0], e[:, 1], e[:, 2])
    return items, langs_np, plan, straddle, mult_np


def iter_device_batches(
    items: Sequence[bytes],
    item_langs: np.ndarray,
    plan,
    *,
    item_mult: np.ndarray | None = None,
    placement=None,
    ragged: bool = True,
    device_encode: bool | None = None,
    ndata: int = 1,
    parent=None,
    depth: int = FIT_PIPELINE_DEPTH,
):
    """Yield ``(batch, lengths, lang_ids, mult, rows, pad_to)`` device
    operands for every planned micro-batch, with packing and transfer
    pipelined ahead. ``mult`` is the per-row dedup multiplicity slice of
    ``item_mult`` (None rides through when the plan carries no duplicates,
    so duplicate-free fits dispatch the historical program unchanged).

    A background packer (the execution core's :func:`ordered_prefetch`
    pipeline, one worker so packs stay plan-ordered) walks ``plan`` in
    order: native pack (ragged when the chunk-aligned flat buffer beats the
    padded form — size precheck identical to the scoring runner's; or the
    device-encode wire form — raw bytes + int32 offsets, no host padding,
    docs/PERFORMANCE.md §11 — when ``device_encode`` or the
    ``LANGDETECT_DEVICE_ENCODE`` knob enables it on a single-process
    direct-put geometry), mesh row padding (``ndata`` > 1), async
    ``device_put`` to ``placement``,
    then an ordered hand-off — up to ``depth`` batches sit
    transferred-or-transferring beyond the one the consumer holds, so the
    count step never waits on the host. Ragged batches are rebuilt into the
    exact padded form on device by the shared ``unpack_ragged_jit`` gather
    in the *consumer* thread, keeping every compiled-program dispatch in
    deterministic plan order (multi-process meshes require identical
    collective enqueue order on every process; ``device_put`` of
    addressable shards is not a collective, but the puts are plan-ordered
    too).

    ``parent`` is the span the cross-thread ``fit/pack`` / ``fit/put`` spans
    attach under (pass the ``fit/count`` span's parent so they become
    siblings of ``fit/count``). Per-batch fill/padding-waste histograms and
    the ``fit/wire_bytes`` counter are observed against the capacity that
    actually rides the wire, mirroring the scoring path's bookkeeping.

    Closing the generator (or a consumer exception) stops the producer and
    drains the queue — a chaos-injected count fault leaves no packer thread
    behind, so the estimator-level replay starts from a clean slate.
    """
    if not plan:
        return
    import jax

    from .. import native
    from .encode_device import encode_batch_jit, wire_capacity, wire_from_docs
    from .encoding import unpack_ragged_jit

    native.available()  # one-time native build outside the pipelined loop
    # Multi-process meshes: device_put of a NamedSharding spanning other
    # processes' devices is not portable on this jax version — ship host
    # arrays and let the pjit in_shardings place them at dispatch.
    explicit_put = placement is None or jax.process_count() == 1
    if device_encode is None:
        device_encode = bool(exec_config.resolve("device_encode"))
    # The wire rung (docs/PERFORMANCE.md §11) ships raw bytes + int32
    # offsets and rebuilds the padded plane on device; it needs a direct
    # put and row counts the mesh padder hasn't reshaped.
    device_encode = device_encode and ndata == 1 and explicit_put

    def pack_one(planned):
        sel, pad_to = planned
        batch_docs = [items[k] for k in sel]
        blangs = item_langs[sel]
        bmult = None if item_mult is None else item_mult[sel]
        if ndata > 1:
            from ..parallel.mesh import pad_rows_for_mesh

            if bmult is None:
                batch_docs, blangs = pad_rows_for_mesh(
                    batch_docs, ndata, (blangs, 0)
                )
            else:
                # Pad rows are empty docs — zero windows either way — so
                # their multiplicity value is inert; 1 keeps them shaped
                # like real rows.
                batch_docs, blangs, bmult = pad_rows_for_mesh(
                    batch_docs, ndata, (blangs, 0), (bmult, 1)
                )
        rows = len(batch_docs)
        real_bytes = sum(len(d) for d in batch_docs)
        form = "padded"
        flat_step = 0
        total = 0
        if device_encode:
            # Wire rung: raw bytes + int32 offsets, no host padding at all
            # (the planner's chunk-split already bounds every doc ≤ pad_to,
            # so the join is the exact truncated content).
            form = "wire"
        elif ragged and pad_to % RAGGED_CHUNK == 0:
            # Same precheck as the scoring runner: ragged only wins when the
            # bucketed flat buffer is actually smaller than the padded batch.
            flat_step = (rows * pad_to // RAGGED_CHUNK) // 16
            total = 1 + sum(
                -(-min(len(d), pad_to) // RAGGED_CHUNK) for d in batch_docs
            )
            if round_chunks(total, flat_step) * RAGGED_CHUNK < rows * pad_to:
                form = "ragged"
        if form == "wire":
            capacity = wire_capacity(real_bytes, rows, pad_to)
            with span("fit/pack", parent=parent, rows=rows, pad_to=pad_to,
                      wire=True):
                host = wire_from_docs(batch_docs, capacity)
            REGISTRY.incr("fit/encoded_batches")
        elif form == "ragged":
            capacity = round_chunks(total, flat_step) * RAGGED_CHUNK
            with span("fit/pack", parent=parent, rows=rows, pad_to=pad_to,
                      ragged=True):
                host = native.pack_ragged(batch_docs, pad_to, flat_step=flat_step)
            REGISTRY.incr("fit/ragged_batches")
        else:
            capacity = rows * pad_to
            with span("fit/pack", parent=parent, rows=rows, pad_to=pad_to,
                      ragged=False):
                host = native.pack_batch(batch_docs, pad_to)
        fill = real_bytes / capacity if capacity else 1.0
        REGISTRY.observe("fit/batch_fill_ratio", fill)
        REGISTRY.observe("fit/padding_waste", 1.0 - fill)
        # Aggregate padding-tax counters: exact whole-run fill is
        # real/capacity (the per-batch histogram is a sampled reservoir);
        # the tuner's smoke gate and the compare guard read these.
        REGISTRY.incr("fit/real_bytes", real_bytes)
        REGISTRY.incr("fit/capacity_bytes", capacity)
        blangs = np.ascontiguousarray(blangs, dtype=np.int32)
        if bmult is not None:
            bmult = np.ascontiguousarray(bmult, dtype=np.int32)
        REGISTRY.incr(
            "fit/wire_bytes",
            sum(a.nbytes for a in host) + blangs.nbytes
            + (0 if bmult is None else bmult.nbytes),
        )
        if explicit_put:
            # Async puts: they return immediately and the copies overlap the
            # next batch's packing (and the consumer's count dispatch); the
            # span fences them only under LANGDETECT_TELEMETRY_FENCE.
            with span("fit/put", parent=parent, rows=rows, pad_to=pad_to) as sp:
                dev = tuple(jax.device_put(a, placement) for a in host)
                blangs_dev = jax.device_put(blangs, placement)
                bmult_dev = (
                    None if bmult is None
                    else jax.device_put(bmult, placement)
                )
                sp.fence(*dev)
        else:
            dev, blangs_dev, bmult_dev = host, blangs, bmult
        return (form, dev, blangs_dev, bmult_dev, rows, pad_to)

    # The core's bounded ordered pipeline, one packer worker: packs (and
    # their async puts) stay in deterministic plan order, up to ``depth``
    # packed batches run ahead of the consumer. Closing this generator
    # closes the pipeline; abort_wait=False so a pack wedged on a stuck
    # h2d link can't turn a fit abort into a hang (the historical
    # daemon-packer semantics — chaos replay still starts clean because
    # pending packs are cancelled and a straggler only writes telemetry).
    pipeline = ordered_prefetch(
        plan, pack_one, depth=max(1, depth), workers=1, abort_wait=False
    )
    try:
        for _, packed, _, _ in pipeline:
            form, dev, blangs_dev, bmult_dev, rows, pad_to = packed()
            if form == "wire":
                wire, wstarts, lengths = dev
                batch = encode_batch_jit(wire, wstarts, lengths, pad_to)
            elif form == "ragged":
                flat, offs, lengths = dev
                batch = unpack_ragged_jit(flat, offs, lengths, pad_to)
            else:
                batch, lengths = dev
            yield batch, lengths, blangs_dev, bmult_dev, rows, pad_to
    finally:
        pipeline.close()
