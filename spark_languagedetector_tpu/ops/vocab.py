"""Gram vocabulary: byte n-grams ↔ integer ids, exact and hashed modes.

The reference keys its model on raw byte sequences in a JVM hash map
(``Map[Seq[Byte], Array[Double]]``, ``LanguageDetectorModel.scala:132``) and
looks every sliding window up per-row (``:139-152``). There is no TPU analog of
a pointer-chasing hash map (SURVEY.md §7.4 "vocab on device"), so grams become
integers:

- **EXACT** mode (parity): a gram of length n maps bijectively to
  ``offset(n) + poly(bytes)`` where ``poly`` is the big-endian base-256
  polynomial value and ``offset(n)`` stacks the id spaces of the configured
  gram lengths disjointly. Device-side membership: lengths ≤ 3 keep int32
  polynomial ids resolved through a dense table or id→row LUT; lengths 4..5
  overflow int32 ids, so they resolve through packed ``(lo, hi)`` int32 key
  pairs and a cuckoo hash table (``ops.cuckoo``) — exact membership in O(1)
  gathers at any supported length. The cap ``max(gram_lengths) <= 5`` is the
  packed-key width (4 bytes + fifth byte + length tag in two int32 halves);
  longer grams use hashed mode.

- **HASHED** mode (fastText-lid-style): window bytes folded into ``2**bits``
  buckets. Collisions merge grams (accuracy impact measured by the parity
  benchmarks, not assumed); scale is unbounded. Two bucket schemes:

  * ``exact12`` (default for ``hash_bits >= 17``): grams of length ≤ 2 keep
    their exact polynomial ids in ``[0, 65792)`` — collision-free — and only
    grams of length ≥ 3 FNV-fold into the remaining buckets. Short grams are
    the bulk of the window count, so this both removes their collisions and
    lets the pallas histogram kernel score them without gathers (the hybrid
    strategy in ``api.runner``).
  * ``fnv1a``: FNV-1a over all lengths into the full bucket range — the
    scheme of models persisted before ``exact12`` existed; the loader
    defaults to it when metadata carries no scheme.

All id arithmetic is vectorized numpy on host and jnp on device; the two
implementations are kept in lockstep by tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax.numpy as jnp
import numpy as np

EXACT = "exact"
HASHED = "hashed"

# Hashed-mode bucket schemes (VocabSpec.hash_scheme).
FNV1A = "fnv1a"
EXACT12 = "exact12"

# Exact mode supports any gram length up to the packed-key limit. Lengths
# <= 3 keep int32 polynomial ids on device (dense/LUT membership); lengths
# 4..5 exceed int32 id space, so the device resolves them with packed
# (lo, hi) int32 key pairs through a cuckoo hash table (ops/cuckoo.py) —
# the TPU-native replacement for the reference's JVM byte-sequence map
# (LanguageDetectorModel.scala:139-152) at any gram length. Host-side ids
# stay int64 polynomials for every length (fit, persistence, oracle).
MAX_EXACT_GRAM_LEN = 5
# Largest gram length device ids (int32 polynomial) can represent.
MAX_DEVICE_ID_GRAM_LEN = 3

# exact12: grams of length <= 2 own buckets [0, _EXACT12_BASE); longer grams
# fold into the rest.
_EXACT12_BASE = 256 + 65536

# FNV-1a 32-bit constants.
_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)


def exact_offsets(gram_lengths: Sequence[int]) -> dict[int, int]:
    """Disjoint id-space offsets for every gram length 1..max(gram_lengths).

    All lengths below the max get a slot (not just the configured ones)
    because the reference's ``sliding`` emits a *partial* window for documents
    shorter than the gram length — in fit (LanguageDetector.scala:39) and in
    predict (LanguageDetectorModel.scala:143) — so grams shorter than any
    configured length can be learned and matched.
    """
    offsets: dict[int, int] = {}
    acc = 0
    for n in range(1, max(gram_lengths) + 1):
        offsets[n] = acc
        acc += 256**n
    return offsets


def exact_space_size(gram_lengths: Sequence[int]) -> int:
    return sum(256**n for n in range(1, max(gram_lengths) + 1))


# Single source of truth for the exact12 short-gram region: its layout IS the
# exact layout for gram lengths <= 2 (1-grams at offset 0, 2-grams at 256,
# fold region starting at the combined space size). Every id-computation site
# (gram_to_id, window_ids, window_ids_numpy, prefix_hashes) reads these.
_SHORT_GRAM_OFFSETS = exact_offsets((1, 2))
if _EXACT12_BASE != exact_space_size((1, 2)):  # pragma: no cover
    raise AssertionError("exact12 layout constant drifted from exact layout")


@dataclass(frozen=True)
class VocabSpec:
    """How window bytes become integer gram ids.

    ``mode``: EXACT or HASHED.
    ``gram_lengths``: window sizes, ascending, deduplicated.
    ``hash_bits``: log2 of bucket count (HASHED only).
    """

    mode: str
    gram_lengths: tuple[int, ...]
    hash_bits: int = 20
    # "auto" resolves at construction: exact12 when the bucket space can hold
    # the collision-free short-gram region (hash_bits >= 17), fnv1a below.
    hash_scheme: str = "auto"

    def __post_init__(self):
        if self.mode not in (EXACT, HASHED):
            raise ValueError(f"unknown vocab mode {self.mode!r}")
        glens = tuple(sorted(set(int(n) for n in self.gram_lengths)))
        if not glens or glens[0] < 1:
            raise ValueError(f"gram lengths must be >= 1, got {self.gram_lengths}")
        object.__setattr__(self, "gram_lengths", glens)
        if self.mode == EXACT and glens[-1] > MAX_EXACT_GRAM_LEN:
            raise ValueError(
                f"exact vocab supports gram lengths <= {MAX_EXACT_GRAM_LEN} "
                f"(the packed-key width for device membership); got {glens}. "
                "Use mode='hashed'."
            )
        if self.mode == HASHED and not (1 <= self.hash_bits <= 30):
            raise ValueError(f"hash_bits must be in [1, 30], got {self.hash_bits}")
        if self.hash_scheme not in ("auto", FNV1A, EXACT12):
            raise ValueError(
                f"unknown hash scheme {self.hash_scheme!r}; expected 'auto', "
                f"{FNV1A!r}, or {EXACT12!r}"
            )
        if self.mode == EXACT:
            # Irrelevant for exact vocabs; normalize so spec equality/hashing
            # never depends on it.
            object.__setattr__(self, "hash_scheme", FNV1A)
        elif self.hash_scheme == "auto":
            object.__setattr__(
                self,
                "hash_scheme",
                EXACT12 if (1 << self.hash_bits) > _EXACT12_BASE else FNV1A,
            )
        elif self.hash_scheme == EXACT12 and (1 << self.hash_bits) <= _EXACT12_BASE:
            raise ValueError(
                f"hash_scheme='exact12' needs hash_bits >= 17 (bucket space "
                f"must exceed {_EXACT12_BASE}); got {self.hash_bits}"
            )

    @property
    def _fold_modulus(self) -> int:
        """Bucket count available to FNV-folded (length >= 3) grams."""
        if self.hash_scheme == EXACT12:
            return (1 << self.hash_bits) - _EXACT12_BASE
        return 1 << self.hash_bits

    @property
    def id_space_size(self) -> int:
        """Total dense id space (exact) or bucket count (hashed)."""
        if self.mode == EXACT:
            return exact_space_size(self.gram_lengths)
        return 1 << self.hash_bits

    @property
    def offsets(self) -> dict[int, int]:
        if self.mode != EXACT:
            raise ValueError("offsets only exist for exact vocabs")
        return exact_offsets(self.gram_lengths)

    # -- host-side gram ↔ id (exact mode) -------------------------------------
    def gram_to_id(self, gram: bytes) -> int:
        if self.mode == EXACT:
            n = len(gram)
            if n not in self.offsets:
                raise ValueError(
                    f"gram length {n} outside 1..{max(self.gram_lengths)}"
                )
            value = 0
            for b in gram:
                value = value * 256 + b
            return self.offsets[n] + value
        if self.hash_scheme == EXACT12 and 1 <= len(gram) <= 2:
            value = 0
            for b in gram:
                value = value * 256 + b
            return _SHORT_GRAM_OFFSETS[len(gram)] + value
        h = int(_FNV_OFFSET)
        for b in gram:
            h = ((h ^ b) * int(_FNV_PRIME)) & 0xFFFFFFFF
        if self.hash_scheme == EXACT12:
            return _EXACT12_BASE + h % self._fold_modulus
        return h & ((1 << self.hash_bits) - 1)

    def id_to_gram(self, gram_id: int) -> bytes:
        """Inverse mapping — exact mode only (hashed buckets are lossy)."""
        if self.mode != EXACT:
            raise ValueError("hashed vocab ids cannot be decoded to bytes")
        offsets = self.offsets
        for n in sorted(offsets, reverse=True):
            if gram_id >= offsets[n]:
                value = gram_id - offsets[n]
                out = bytearray(n)
                for i in range(n - 1, -1, -1):
                    out[i] = value % 256
                    value //= 256
                return bytes(out)
        raise ValueError(f"gram id {gram_id} below all offsets")


# --- window id computation (numpy host / jnp device, kept in lockstep) -------


def window_ids_numpy(batch: np.ndarray, n: int, spec: VocabSpec) -> np.ndarray:
    """Ids of all n-windows of ``batch`` (uint8 [B, S]) → int64/uint32 [B, S-n+1].

    Host mirror of :func:`window_ids` used by the numpy fit path and tests.
    Validity masking is the caller's job.
    """
    B, S = batch.shape
    if S < n:  # batch narrower than the window: zero-extend (padding bytes)
        batch = np.pad(batch, ((0, 0), (0, n - S)))
        S = n
    W = S - n + 1
    if spec.mode == EXACT or (spec.hash_scheme == EXACT12 and n <= 2):
        ids = np.zeros((B, W), dtype=np.int64)
        for i in range(n):
            ids = ids * 256 + batch[:, i : i + W].astype(np.int64)
        off = spec.offsets[n] if spec.mode == EXACT else _SHORT_GRAM_OFFSETS[n]
        return ids + off
    h = np.full((B, W), _FNV_OFFSET, dtype=np.uint32)
    for i in range(n):
        h = (h ^ batch[:, i : i + W].astype(np.uint32)) * _FNV_PRIME
    if spec.hash_scheme == EXACT12:
        return (h % np.uint32(spec._fold_modulus)).astype(np.int64) + _EXACT12_BASE
    return (h & np.uint32((1 << spec.hash_bits) - 1)).astype(np.int64)


def window_ids(batch: jnp.ndarray, n: int, spec: VocabSpec) -> jnp.ndarray:
    """Device-side window ids: uint8 [B, S] → int32 [B, S-n+1].

    Shifted-slice formulation (no gather): the n byte planes of each window are
    just n static slices of the batch, combined with the per-mode mixing
    arithmetic. XLA fuses this to a handful of vector ops — this op replaces
    the reference's per-window ``Map.get`` (LanguageDetectorModel.scala:145).
    """
    B, S = batch.shape
    if S < n:  # batch narrower than the window: zero-extend (padding bytes)
        batch = jnp.pad(batch, ((0, 0), (0, n - S)))
        S = n
    W = S - n + 1
    if spec.mode == EXACT or (spec.hash_scheme == EXACT12 and n <= 2):
        if spec.mode == EXACT and n > MAX_DEVICE_ID_GRAM_LEN:
            raise ValueError(
                f"exact {n}-gram ids overflow int32; device membership for "
                "gram lengths > 3 goes through packed keys (window_keys) "
                "and the cuckoo scorer"
            )
        ids = jnp.zeros((B, W), dtype=jnp.int32)
        for i in range(n):
            ids = ids * 256 + batch[:, i : i + W].astype(jnp.int32)
        off = spec.offsets[n] if spec.mode == EXACT else _SHORT_GRAM_OFFSETS[n]
        return ids + off
    h = jnp.full((B, W), _FNV_OFFSET, dtype=jnp.uint32)
    for i in range(n):
        h = (h ^ batch[:, i : i + W].astype(jnp.uint32)) * _FNV_PRIME
    if spec.hash_scheme == EXACT12:
        return (h % jnp.uint32(spec._fold_modulus)).astype(jnp.int32) + _EXACT12_BASE
    return (h & ((1 << spec.hash_bits) - 1)).astype(jnp.int32)


def prefix_hashes(batch: jnp.ndarray, max_len: int, spec: "VocabSpec") -> jnp.ndarray:
    """Hashed-mode bucket of the k-byte prefix for k = 1..max_len →
    int32 [B, max_len+1].

    Column k holds the bucket of the k-byte prefix per the spec's scheme
    (column 0 is zeros/unused). Only needed for hashed-mode partial windows,
    where max_len < max gram length, so this is a handful of vector ops.
    """
    B, S = batch.shape
    if S < max_len:
        batch = jnp.pad(batch, ((0, 0), (0, max_len - S)))
    h = jnp.full((B,), _FNV_OFFSET, dtype=jnp.uint32)
    cols = [jnp.zeros((B,), dtype=jnp.int32)]
    exact12 = spec.hash_scheme == EXACT12
    mask = jnp.uint32((1 << spec.hash_bits) - 1)
    fold = jnp.uint32(spec._fold_modulus)
    for i in range(max_len):
        h = (h ^ batch[:, i].astype(jnp.uint32)) * _FNV_PRIME
        k = i + 1
        if exact12 and k == 1:
            cols.append(_SHORT_GRAM_OFFSETS[1] + batch[:, 0].astype(jnp.int32))
        elif exact12 and k == 2:
            cols.append(
                _SHORT_GRAM_OFFSETS[2]
                + batch[:, 0].astype(jnp.int32) * 256
                + batch[:, 1].astype(jnp.int32)
            )
        elif exact12:
            cols.append((h % fold).astype(jnp.int32) + _EXACT12_BASE)
        else:
            cols.append((h & mask).astype(jnp.int32))
    return jnp.stack(cols, axis=1)


def partial_window_ids(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    n: int,
    window0_ids: jnp.ndarray,
    spec: "VocabSpec",
) -> jnp.ndarray:
    """Gram id of the single partial window of each doc with len < n: int32 [B].

    Shared by the scorer and the device fit so the Scala-``sliding`` parity
    rule lives in exactly one place. Values are only meaningful where
    ``lengths < n`` and ``lengths > 0``; callers mask everything else.

    Exact mode: window 0's padded polynomial is ``poly(prefix) * 256**(n-len)``
    (padding bytes are zero), so the prefix id is recovered with a shift into
    the length-``len`` id space. Hashed mode: FNV prefix buckets.
    """
    if spec.mode == EXACT:
        offsets = spec.offsets
        pow256 = jnp.array([256**k for k in range(n + 1)], dtype=jnp.int32)
        off_by_len = jnp.array(
            [0] + [offsets[k] for k in range(1, n + 1)], dtype=jnp.int32
        )
        len_c = jnp.clip(lengths, 0, n)
        return off_by_len[len_c] + (window0_ids - offsets[n]) // pow256[n - len_c]
    prefixes = prefix_hashes(batch, n - 1, spec)
    len_c = jnp.clip(lengths, 0, n - 1)
    return prefixes[jnp.arange(batch.shape[0]), len_c]


# --- packed gram keys (device membership for exact gram lengths > 3) --------
#
# A gram of length n <= 5 packs bijectively into two int32 halves:
#   lo = first four bytes big-endian (missing bytes are zero)
#   hi = fifth byte | (n << 8)
# The length tag in ``hi`` keeps different-length prefixes distinct (b"ab\0"
# vs b"ab"), mirroring the disjoint per-length id spaces of exact mode.


def gram_key(gram: bytes) -> tuple[int, int]:
    """Host scalar: gram bytes (1..5) → (lo, hi) packed key."""
    n = len(gram)
    if not 1 <= n <= MAX_EXACT_GRAM_LEN:
        raise ValueError(f"gram length {n} outside 1..{MAX_EXACT_GRAM_LEN}")
    lo = 0
    for i in range(4):
        lo = (lo << 8) | (gram[i] if i < n else 0)
    if lo >= 1 << 31:  # match the device's wrapped int32 representation
        lo -= 1 << 32
    hi = (gram[4] if n == 5 else 0) | (n << 8)
    return lo, hi


def window_keys(batch: jnp.ndarray, n: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device window keys: uint8 [B, S] → (lo, hi) int32 [B, S-n+1] each.

    Shifted-slice formulation like :func:`window_ids`; no gathers.
    """
    B, S = batch.shape
    if S < n:
        batch = jnp.pad(batch, ((0, 0), (0, n - S)))
        S = n
    W = S - n + 1
    lo = jnp.zeros((B, W), dtype=jnp.int32)
    for i in range(4):
        plane = (
            batch[:, i : i + W].astype(jnp.int32)
            if i < n
            else jnp.zeros((B, W), jnp.int32)
        )
        lo = (lo << 8) | plane
    hi = (
        batch[:, 4 : 4 + W].astype(jnp.int32)
        if n == 5
        else jnp.zeros((B, W), jnp.int32)
    ) | (n << 8)
    return lo, hi


def window_keys_numpy(batch: np.ndarray, n: int) -> tuple[np.ndarray, np.ndarray]:
    """Host mirror of :func:`window_keys` (lockstep-tested)."""
    B, S = batch.shape
    if S < n:
        batch = np.pad(batch, ((0, 0), (0, n - S)))
        S = n
    W = S - n + 1
    lo = np.zeros((B, W), dtype=np.int64)
    for i in range(4):
        plane = (
            batch[:, i : i + W].astype(np.int64)
            if i < n
            else np.zeros((B, W), np.int64)
        )
        lo = (lo << 8) | plane
    hi = (
        batch[:, 4 : 4 + W].astype(np.int64) if n == 5 else np.zeros((B, W), np.int64)
    ) | (n << 8)
    return lo.astype(np.int32), hi.astype(np.int32)


def partial_window_keys(
    batch: jnp.ndarray, lengths: jnp.ndarray, n: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Packed key of each doc's single partial window (len < n): (lo, hi) [B].

    The partial window is the whole document, a gram of its own length k
    (Scala ``sliding`` parity — same rule as :func:`partial_window_ids`).
    Values are only meaningful where ``0 < lengths < n``; callers mask."""
    B, S = batch.shape
    if S < 4:
        batch = jnp.pad(batch, ((0, 0), (0, 4 - S)))
    # The partial window's length k <= n - 1 <= 4, so the fifth byte never
    # participates and hi is just the length tag.
    k = jnp.clip(lengths, 0, n - 1)
    lo = jnp.zeros((B,), dtype=jnp.int32)
    for i in range(4):
        plane = jnp.where(i < k, batch[:, i].astype(jnp.int32), 0)
        lo = (lo << 8) | plane
    hi = k << 8
    return lo, hi


# Murmur3-style 32-bit mixer over a packed key + seed; host and device
# versions share constants and are lockstep-tested. Used by the cuckoo
# table's two bucket hashes.
_MIX_C1 = 0x85EB_CA6B
_MIX_C2 = 0xC2B2_AE35
_GOLDEN = 0x9E37_79B9


def mix32(lo, hi, seed: int, xp=np):
    """uint32 mix of int32 (lo, hi) arrays + seed. ``xp``: numpy or jax.numpy.
    int32 → uint32 casts wrap two's-complement identically in both."""
    u = xp.uint32
    h = xp.asarray(lo).astype(u) ^ u((seed * _GOLDEN) & 0xFFFFFFFF)
    h = (h ^ (h >> u(16))) * u(_MIX_C1)
    h = h ^ xp.asarray(hi).astype(u) * u(_MIX_C2)
    h = (h ^ (h >> u(13))) * u(_MIX_C1)
    return h ^ (h >> u(16))


def short_doc_ids_numpy(
    doc: bytes, spec: VocabSpec
) -> list[int]:
    """Reference partial-window rule (host): a document shorter than a gram
    length contributes ONE window of the whole document for that length
    (Scala ``sliding`` emits a partial final group — SURVEY.md §3.2 hot loop).
    That partial gram matches learned grams of its own (shorter) length, so it
    maps into the id space of ``len(doc)``. Returns one id per configured gram
    length > len(doc) — NOT deduplicated, because the reference looks the
    partial window up once per gram length, accumulating its weights once each.
    """
    n_doc = len(doc)
    if n_doc == 0 or n_doc >= max(spec.gram_lengths):
        return []
    short_id = spec.gram_to_id(bytes(doc))
    return [short_id for n in spec.gram_lengths if n > n_doc]
