"""Gram vocabulary: byte n-grams ↔ integer ids, exact and hashed modes.

The reference keys its model on raw byte sequences in a JVM hash map
(``Map[Seq[Byte], Array[Double]]``, ``LanguageDetectorModel.scala:132``) and
looks every sliding window up per-row (``:139-152``). There is no TPU analog of
a pointer-chasing hash map (SURVEY.md §7.4 "vocab on device"), so grams become
integers:

- **EXACT** mode (parity): a gram of length n maps bijectively to
  ``offset(n) + poly(bytes)`` where ``poly`` is the big-endian base-256
  polynomial value and ``offset(n)`` stacks the id spaces of the configured
  gram lengths disjointly. Device-side membership is a binary search over the
  model's sorted id vector. Exact mode supports ``max(gram_lengths) <= 3``
  (id space must fit int32 for TPU-friendly integer ops); longer grams use
  hashed mode, matching BASELINE's configs (exact n≤3, hashed n=1..5).

- **HASHED** mode (fastText-lid-style): FNV-1a over the window bytes folded
  into ``2**bits`` buckets. Collisions merge grams (accuracy impact measured
  by the parity benchmarks, not assumed); scale is unbounded.

All id arithmetic is vectorized numpy on host and jnp on device; the two
implementations are kept in lockstep by tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax.numpy as jnp
import numpy as np

EXACT = "exact"
HASHED = "hashed"

MAX_EXACT_GRAM_LEN = 3

# FNV-1a 32-bit constants.
_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)


def exact_offsets(gram_lengths: Sequence[int]) -> dict[int, int]:
    """Disjoint id-space offsets for every gram length 1..max(gram_lengths).

    All lengths below the max get a slot (not just the configured ones)
    because the reference's ``sliding`` emits a *partial* window for documents
    shorter than the gram length — in fit (LanguageDetector.scala:39) and in
    predict (LanguageDetectorModel.scala:143) — so grams shorter than any
    configured length can be learned and matched.
    """
    offsets: dict[int, int] = {}
    acc = 0
    for n in range(1, max(gram_lengths) + 1):
        offsets[n] = acc
        acc += 256**n
    return offsets


def exact_space_size(gram_lengths: Sequence[int]) -> int:
    return sum(256**n for n in range(1, max(gram_lengths) + 1))


@dataclass(frozen=True)
class VocabSpec:
    """How window bytes become integer gram ids.

    ``mode``: EXACT or HASHED.
    ``gram_lengths``: window sizes, ascending, deduplicated.
    ``hash_bits``: log2 of bucket count (HASHED only).
    """

    mode: str
    gram_lengths: tuple[int, ...]
    hash_bits: int = 20

    def __post_init__(self):
        if self.mode not in (EXACT, HASHED):
            raise ValueError(f"unknown vocab mode {self.mode!r}")
        glens = tuple(sorted(set(int(n) for n in self.gram_lengths)))
        if not glens or glens[0] < 1:
            raise ValueError(f"gram lengths must be >= 1, got {self.gram_lengths}")
        object.__setattr__(self, "gram_lengths", glens)
        if self.mode == EXACT and glens[-1] > MAX_EXACT_GRAM_LEN:
            raise ValueError(
                f"exact vocab supports gram lengths <= {MAX_EXACT_GRAM_LEN} "
                f"(id space must fit int32); got {glens}. Use mode='hashed'."
            )
        if self.mode == HASHED and not (1 <= self.hash_bits <= 30):
            raise ValueError(f"hash_bits must be in [1, 30], got {self.hash_bits}")

    @property
    def id_space_size(self) -> int:
        """Total dense id space (exact) or bucket count (hashed)."""
        if self.mode == EXACT:
            return exact_space_size(self.gram_lengths)
        return 1 << self.hash_bits

    @property
    def offsets(self) -> dict[int, int]:
        if self.mode != EXACT:
            raise ValueError("offsets only exist for exact vocabs")
        return exact_offsets(self.gram_lengths)

    # -- host-side gram ↔ id (exact mode) -------------------------------------
    def gram_to_id(self, gram: bytes) -> int:
        if self.mode == EXACT:
            n = len(gram)
            if n not in self.offsets:
                raise ValueError(
                    f"gram length {n} outside 1..{max(self.gram_lengths)}"
                )
            value = 0
            for b in gram:
                value = value * 256 + b
            return self.offsets[n] + value
        h = int(_FNV_OFFSET)
        for b in gram:
            h = ((h ^ b) * int(_FNV_PRIME)) & 0xFFFFFFFF
        return h & ((1 << self.hash_bits) - 1)

    def id_to_gram(self, gram_id: int) -> bytes:
        """Inverse mapping — exact mode only (hashed buckets are lossy)."""
        if self.mode != EXACT:
            raise ValueError("hashed vocab ids cannot be decoded to bytes")
        offsets = self.offsets
        for n in sorted(offsets, reverse=True):
            if gram_id >= offsets[n]:
                value = gram_id - offsets[n]
                out = bytearray(n)
                for i in range(n - 1, -1, -1):
                    out[i] = value % 256
                    value //= 256
                return bytes(out)
        raise ValueError(f"gram id {gram_id} below all offsets")


# --- window id computation (numpy host / jnp device, kept in lockstep) -------


def window_ids_numpy(batch: np.ndarray, n: int, spec: VocabSpec) -> np.ndarray:
    """Ids of all n-windows of ``batch`` (uint8 [B, S]) → int64/uint32 [B, S-n+1].

    Host mirror of :func:`window_ids` used by the numpy fit path and tests.
    Validity masking is the caller's job.
    """
    B, S = batch.shape
    if S < n:  # batch narrower than the window: zero-extend (padding bytes)
        batch = np.pad(batch, ((0, 0), (0, n - S)))
        S = n
    W = S - n + 1
    if spec.mode == EXACT:
        ids = np.zeros((B, W), dtype=np.int64)
        for i in range(n):
            ids = ids * 256 + batch[:, i : i + W].astype(np.int64)
        return ids + spec.offsets[n]
    h = np.full((B, W), _FNV_OFFSET, dtype=np.uint32)
    for i in range(n):
        h = (h ^ batch[:, i : i + W].astype(np.uint32)) * _FNV_PRIME
    return (h & np.uint32((1 << spec.hash_bits) - 1)).astype(np.int64)


def window_ids(batch: jnp.ndarray, n: int, spec: VocabSpec) -> jnp.ndarray:
    """Device-side window ids: uint8 [B, S] → int32 [B, S-n+1].

    Shifted-slice formulation (no gather): the n byte planes of each window are
    just n static slices of the batch, combined with the per-mode mixing
    arithmetic. XLA fuses this to a handful of vector ops — this op replaces
    the reference's per-window ``Map.get`` (LanguageDetectorModel.scala:145).
    """
    B, S = batch.shape
    if S < n:  # batch narrower than the window: zero-extend (padding bytes)
        batch = jnp.pad(batch, ((0, 0), (0, n - S)))
        S = n
    W = S - n + 1
    if spec.mode == EXACT:
        ids = jnp.zeros((B, W), dtype=jnp.int32)
        for i in range(n):
            ids = ids * 256 + batch[:, i : i + W].astype(jnp.int32)
        return ids + spec.offsets[n]
    h = jnp.full((B, W), _FNV_OFFSET, dtype=jnp.uint32)
    for i in range(n):
        h = (h ^ batch[:, i : i + W].astype(jnp.uint32)) * _FNV_PRIME
    return (h & ((1 << spec.hash_bits) - 1)).astype(jnp.int32)


def prefix_hashes(batch: jnp.ndarray, max_len: int, hash_bits: int) -> jnp.ndarray:
    """FNV-1a bucket of batch[:, :k] for k = 1..max_len → int32 [B, max_len+1].

    Column k holds the bucket of the k-byte prefix (column 0 is zeros/unused).
    Only needed for hashed-mode partial windows, where max_len < max gram
    length, so this is a handful of vector ops.
    """
    B, S = batch.shape
    if S < max_len:
        batch = jnp.pad(batch, ((0, 0), (0, max_len - S)))
    h = jnp.full((B,), _FNV_OFFSET, dtype=jnp.uint32)
    cols = [jnp.zeros((B,), dtype=jnp.int32)]
    mask = jnp.uint32((1 << hash_bits) - 1)
    for i in range(max_len):
        h = (h ^ batch[:, i].astype(jnp.uint32)) * _FNV_PRIME
        cols.append((h & mask).astype(jnp.int32))
    return jnp.stack(cols, axis=1)


def partial_window_ids(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    n: int,
    window0_ids: jnp.ndarray,
    spec: "VocabSpec",
) -> jnp.ndarray:
    """Gram id of the single partial window of each doc with len < n: int32 [B].

    Shared by the scorer and the device fit so the Scala-``sliding`` parity
    rule lives in exactly one place. Values are only meaningful where
    ``lengths < n`` and ``lengths > 0``; callers mask everything else.

    Exact mode: window 0's padded polynomial is ``poly(prefix) * 256**(n-len)``
    (padding bytes are zero), so the prefix id is recovered with a shift into
    the length-``len`` id space. Hashed mode: FNV prefix buckets.
    """
    if spec.mode == EXACT:
        offsets = spec.offsets
        pow256 = jnp.array([256**k for k in range(n + 1)], dtype=jnp.int32)
        off_by_len = jnp.array(
            [0] + [offsets[k] for k in range(1, n + 1)], dtype=jnp.int32
        )
        len_c = jnp.clip(lengths, 0, n)
        return off_by_len[len_c] + (window0_ids - offsets[n]) // pow256[n - len_c]
    prefixes = prefix_hashes(batch, n - 1, spec.hash_bits)
    len_c = jnp.clip(lengths, 0, n - 1)
    return prefixes[jnp.arange(batch.shape[0]), len_c]


def short_doc_ids_numpy(
    doc: bytes, spec: VocabSpec
) -> list[int]:
    """Reference partial-window rule (host): a document shorter than a gram
    length contributes ONE window of the whole document for that length
    (Scala ``sliding`` emits a partial final group — SURVEY.md §3.2 hot loop).
    That partial gram matches learned grams of its own (shorter) length, so it
    maps into the id space of ``len(doc)``. Returns one id per configured gram
    length > len(doc) — NOT deduplicated, because the reference looks the
    partial window up once per gram length, accumulating its weights once each.
    """
    n_doc = len(doc)
    if n_doc == 0 or n_doc >= max(spec.gram_lengths):
        return []
    short_id = spec.gram_to_id(bytes(doc))
    return [short_id for n in spec.gram_lengths if n > n_doc]
