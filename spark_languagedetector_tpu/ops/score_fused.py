"""One fused detect megakernel: window → hash → lookup → accumulate → argmax.

ROADMAP item 3 ("close the compute ceiling"). The strategies this replaces
split the per-document pipeline across several XLA programs with HBM
round-trips between them: the gather path reads an L-wide table row per
window (~7MB/doc of table traffic at the hashed-2^20 / 176-language config —
the roofline gauges say that program is memory-bound on table reads), and
the hist path (:mod:`ops.score_hist`) fixes the gather but writes a ~287KB
per-document row histogram to HBM and reads it back for the ``hist @ W``
contraction. This kernel runs the whole chain in ONE ``pallas_call``:

  * **window → hash in-kernel**: window ids are computed on the VPU from
    pre-shifted byte planes — exact/exact12 polynomial ids for short grams,
    and the FNV-1a fold for hashed vocabs (the same host-side hash in
    :mod:`ops.vocab`, wrapping int32 arithmetic; the non-power-of-two
    ``exact12`` fold modulus is reduced with a float-reciprocal quotient +
    two correction steps, exact for 32-bit inputs);
  * **table lookup + accumulate on the MXU**: per document a digit-decomposed
    row histogram (``r = hi*256 + lo``; two one-hots, one NT matmul per
    window block — the :mod:`ops.score_hist` formulation) is built in VMEM
    scratch *per table tile* and immediately contracted with that tile of
    the (quantized) weight table. The table streams through VMEM in
    ``[tile_hi*256, Lpad]`` tiles on the inner grid axis, so Pallas's
    pipeline machinery double-buffers the HBM→VMEM tile fetches behind the
    compute; the histogram never exists in HBM;
  * **quantized weights, f32 accumulation**: int8/int16 tables with
    per-language f32 scales (:func:`models.profile.quantize_weights`) cut
    the streamed table bytes 4×/2×; counts and integer weights are exact in
    f32, the scale multiplies once per (doc, language) at the end;
  * **argmax in-kernel**: the detect variant emits one (label, best-score)
    pair per document — first-maximum tie-breaking, all-miss docs argmax to
    0 (SURVEY.md §2.9) — so per document the only HBM traffic is the byte
    row in, the streamed table tiles, and 8 bytes out.

The one stage Mosaic cannot fuse is compact-row *membership*: an id→row
gather does not lower in-kernel (the same constraint documented in
:mod:`ops.score_hist`), so profiles that ship a LUT resolve window rows in
XLA inside the same jit and pass them as an int32 plane. The ``exact12``
hashed scheme splits the difference: its short-gram buckets [0, 65792) ARE
exact polynomial ids, so the fused table is laid out [dense short-gram
region ∥ compact long-gram rows] and only gram lengths ≥ 3 need the row
plane — the bulk of the window count hashes fully in-kernel even for the
hashed-2^20 production config.

Parity contract (docs/ARCHITECTURE.md §tolerance classes): unquantized
scores match the gather reference up to f32 reduction order with exact
argmax on the bench suites; quantized scores carry the per-language scale
rounding (bench gates: int16 argmax parity 1.0, int8 agreement ≥ 0.999).
CPU substrates run the kernel in Pallas interpret mode (tier-1 pins the
semantics without hardware).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.profile import QUANT_DTYPES, quantize_weights
from .score import _splice_partial_windows
from .score_pallas import COMPILER_PARAMS
from .vocab import (
    _EXACT12_BASE,
    _FNV_OFFSET,
    _FNV_PRIME,
    _SHORT_GRAM_OFFSETS,
    EXACT,
    EXACT12,
    HASHED,
    MAX_DEVICE_ID_GRAM_LEN,
    VocabSpec,
    partial_window_ids,
    window_ids,
)

# Documents per grid step (sublane tile height of the byte/row planes).
DB = 8

# Window-axis block: lane dimension of the one-hots (MXU contraction depth).
DEFAULT_BLOCK = 2048

# Streamed table tile budget. The tile is [tile_hi*256, Lpad] rows of the
# quantized table resident in VMEM; Pallas double-buffers it, so the live
# footprint is 2x this. 2MB keeps the whole kernel (planes + one-hots +
# tile pair + histogram scratch) under ~6MB of VMEM at the production
# shapes — see docs/PERFORMANCE.md §7 for the knob table.
DEFAULT_TILE_BYTES = 2 << 20

# Kernel-side FNV-1a constants as wrapping int32 (bit-identical to the
# uint32 host arithmetic in ops.vocab for xor/multiply/shift).
_FNV_OFFSET_I32 = int(np.int32(np.uint32(_FNV_OFFSET)))
_FNV_PRIME_I32 = int(_FNV_PRIME)

# Inline window-id kinds (FusedLayout.inline entries).
POLY = "poly"  # p1 = id-space offset of the length's region
FNV_MASK = "fnv_mask"  # p1 = 2^hash_bits - 1
FNV_FOLD = "fnv_fold"  # p1 = fold base (_EXACT12_BASE), p2 = fold modulus


@dataclass(frozen=True)
class FusedLayout:
    """Static shape/plan of one fused program (hashable — a jit static).

    ``inline``: per gram length scored from byte planes in-kernel:
    ``(n, kind, p1, p2)``. ``rows_lengths``: gram lengths whose compact rows
    are resolved by XLA membership and passed as an int32 plane. ``rows`` is
    the real row count of the fused table (pre-padding); the table streams
    in ``tiles`` tiles of ``tile_hi`` hi-digits (256 rows each).
    """

    inline: tuple[tuple[int, str, int, int], ...]
    rows_lengths: tuple[int, ...]
    rows: int
    tile_hi: int
    tiles: int
    lpad: int
    n_langs: int
    quant: str | None

    @property
    def rows_padded(self) -> int:
        return self.tiles * self.tile_hi * 256

    @property
    def max_inline(self) -> int:
        return max((n for n, _, _, _ in self.inline), default=0)


@dataclass(frozen=True)
class FusedTables:
    """Host-built operands of the fused program (one per profile form).

    ``wq`` [rows_padded, lpad] quantized (or f32) table; ``scales``
    [8, lpad] f32 per-language scales (row-replicated for the sublane
    tile); ``lut`` int32 [id_space] fused-row membership or None when every
    length is inline; ``table_bytes`` counts the real (unpadded) quantized
    rows, ``f32_bytes`` the same rows at f32 — the bench's table_bytes
    ratio.
    """

    layout: FusedLayout
    wq: np.ndarray
    scales: np.ndarray
    lut: np.ndarray | None
    table_bytes: int
    f32_bytes: int


def fused_supported(
    spec: VocabSpec, num_rows: int, num_langs: int, *, lut, cuckoo
) -> bool:
    """True when the fused kernel covers this profile form: dense tables
    (exact gram lengths ≤ 3 / hashed any scheme) and LUT-compact profiles.
    Packed-key cuckoo membership (exact gram lengths 4..5) stays on the
    hybrid/hist strategies — its two-probe verify has no in-kernel analog
    and no int32 id plane exists for those lengths."""
    if cuckoo is not None:
        return False
    if spec.mode == EXACT and max(spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN:
        return False
    return True


def _hashed_inline_entry(spec: VocabSpec, n: int) -> tuple[int, str, int, int]:
    """Inline plan entry for gram length ``n`` of a hashed vocab whose
    buckets index the fused table directly."""
    if spec.hash_scheme == EXACT12 and n <= 2:
        return (n, POLY, _SHORT_GRAM_OFFSETS[n], 0)
    if spec.hash_scheme == EXACT12:
        return (n, FNV_FOLD, _EXACT12_BASE, spec._fold_modulus)
    return (n, FNV_MASK, (1 << spec.hash_bits) - 1, 0)


def _tile_hi(lpad: int, itemsize: int, tile_bytes: int) -> int:
    """Hi-digits per streamed table tile under the VMEM tile budget,
    sublane-friendly (multiple of 8, at least 8)."""
    ht = tile_bytes // (256 * lpad * itemsize)
    return max(8, (ht // 8) * 8)


def build_fused_tables(
    weights,
    lut,
    spec: VocabSpec,
    quantization: str | None = None,
    tile_bytes: int = DEFAULT_TILE_BYTES,
) -> FusedTables:
    """Fused-table layout + operands from a runner's device view.

    Dense tables (``lut`` None): every window id is its own row — all gram
    lengths inline. LUT-compact exact12 profiles: the short-gram bucket
    region [0, 65792) is re-materialized dense (rows = bucket ids, the
    hybrid strategy's ``dense12`` trick) so gram lengths ≤ 2 stay inline,
    and the long-gram buckets remap into compact rows appended after it.
    Everything else resolves every length through the (re-based) LUT in
    XLA. Call once per profile — the quantize + relayout is real work.
    """
    w = np.asarray(weights, dtype=np.float32)
    R0, L = w.shape
    lut_np = None if lut is None else np.asarray(lut)
    if lut_np is not None and lut_np.size == 0:
        lut_np = None

    if lut_np is None:
        if spec.mode == EXACT:
            if max(spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN:
                raise ValueError(
                    "fused kernel: exact gram lengths > "
                    f"{MAX_DEVICE_ID_GRAM_LEN} have no int32 id plane"
                )
            if R0 != spec.id_space_size:
                raise ValueError(
                    "fused kernel: dense exact table must cover the id "
                    f"space ({spec.id_space_size} rows, got {R0})"
                )
            inline = tuple(
                (n, POLY, spec.offsets[n], 0) for n in spec.gram_lengths
            )
        else:
            if R0 != spec.id_space_size:
                raise ValueError(
                    "fused kernel: dense hashed table must cover the bucket "
                    f"space ({spec.id_space_size} rows, got {R0})"
                )
            inline = tuple(
                _hashed_inline_entry(spec, n) for n in spec.gram_lengths
            )
        rows_lengths: tuple[int, ...] = ()
        table = w
        lut_fused = None
    elif (
        spec.mode == HASHED
        and spec.hash_scheme == EXACT12
        and any(n <= 2 for n in spec.gram_lengths)
    ):
        short = tuple(n for n in spec.gram_lengths if n <= 2)
        long = tuple(n for n in spec.gram_lengths if n > 2)
        # Rows = [dense short-gram region | compact long-gram rows]: short
        # buckets become their own row index (in-kernel polynomial ids, no
        # membership), long buckets remap into the rows they actually hit.
        dense12 = w[lut_np[:_EXACT12_BASE]]
        inline = tuple((n, POLY, _SHORT_GRAM_OFFSETS[n], 0) for n in short)
        rows_lengths = long
        if long:
            long_refs = lut_np[_EXACT12_BASE:]
            long_rows = np.unique(long_refs)
            rank = np.zeros(R0, dtype=np.int64)
            rank[long_rows] = np.arange(len(long_rows))
            lut_fused = np.empty(spec.id_space_size, dtype=np.int32)
            # Short buckets stay identity: long-gram *partial* windows are
            # 1-2 byte prefixes whose buckets land in the short region.
            lut_fused[:_EXACT12_BASE] = np.arange(_EXACT12_BASE)
            lut_fused[_EXACT12_BASE:] = (
                _EXACT12_BASE + rank[long_refs]
            ).astype(np.int32)
            table = np.concatenate([dense12, w[long_rows]])
        else:
            lut_fused = None
            table = dense12
    else:
        inline = ()
        rows_lengths = spec.gram_lengths
        table = w
        lut_fused = lut_np.astype(np.int32)

    R, _ = table.shape
    f32_bytes = R * L * 4
    if quantization is not None:
        q, scales_l = quantize_weights(table, quantization)
        np_dtype, _ = QUANT_DTYPES[quantization]
        itemsize = np.dtype(np_dtype).itemsize
    else:
        q, scales_l = table, np.ones(L, dtype=np.float32)
        itemsize = 4
    table_bytes = R * L * itemsize

    lpad = max(128, -(-L // 128) * 128)
    ht = _tile_hi(lpad, itemsize, tile_bytes)
    rhi = -(-R // 256)
    tiles = max(1, -(-rhi // ht))
    rpad = tiles * ht * 256
    wq = np.zeros((rpad, lpad), dtype=q.dtype)
    wq[:R, :L] = q
    scales = np.zeros((8, lpad), dtype=np.float32)
    scales[:, :L] = scales_l

    layout = FusedLayout(
        inline=inline,
        rows_lengths=rows_lengths,
        rows=R,
        tile_hi=ht,
        tiles=tiles,
        lpad=lpad,
        n_langs=L,
        quant=quantization,
    )
    return FusedTables(layout, wq, scales, lut_fused, table_bytes, f32_bytes)


# --------------------------------------------------------------- kernel ----


def _build_fused_kernel(
    S: int,
    KW: int,
    wseg: int,
    blk: int,
    layout: FusedLayout,
    want_labels: bool,
):
    """Kernel over grid (doc blocks, table tiles); table tiles stream on
    the inner axis (Pallas double-buffers the HBM→VMEM fetch), byte/row
    planes stay resident across a doc block's tiles (their block index is
    tile-invariant)."""
    HT, T = layout.tile_hi, layout.tiles
    Lpad, n_langs = layout.lpad, layout.n_langs
    has_inline = bool(layout.inline)
    has_rows = bool(layout.rows_lengths)
    n_steps = S // blk if has_inline else 0
    n_rsteps = KW // blk if has_rows else 0

    def kernel(*refs):
        it = iter(refs)
        bytes_ref = next(it) if has_inline else None
        rows_ref = next(it) if has_rows else None
        len_ref = next(it)
        lim_ref = next(it)
        prow_ref = next(it) if has_inline else None
        wq_ref = next(it)
        scale_ref = next(it)
        out_ref = next(it)
        if want_labels:
            label_ref = next(it)
            best_ref = next(it)
        hist_ref = next(it)
        acc_ref = next(it)

        b = pl.program_id(0)
        t = pl.program_id(1)
        base = b * DB
        tile_base = t * HT  # first hi-digit this tile covers

        @pl.when(t == 0)
        def _init():
            acc_ref[:, :] = jnp.zeros((DB, Lpad), jnp.float32)

        for d in range(DB):
            dlen = len_ref[base + d]
            dlim = lim_ref[base + d]
            hist_ref[:, :] = jnp.zeros((HT, 256), jnp.float32)

            def accumulate(ids, mask):
                """One window block's [HT, 256] histogram contribution:
                tile-local hi one-hot (masked) × lo one-hot, NT matmul."""
                iota_hi = jax.lax.broadcasted_iota(jnp.int32, (HT, blk), 0)
                iota_lo = jax.lax.broadcasted_iota(jnp.int32, (256, blk), 0)
                hi_loc = (ids >> 8) - tile_base
                lo = ids & 255
                oh_hi = jnp.where(
                    (hi_loc == iota_hi) & mask, 1.0, 0.0
                ).astype(jnp.bfloat16)
                oh_lo = jnp.where(lo == iota_lo, 1.0, 0.0).astype(
                    jnp.bfloat16
                )
                hist_ref[:, :] += jax.lax.dot_general(
                    oh_hi, oh_lo, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

            if has_inline:
                for j, (n, kind, p1, p2) in enumerate(layout.inline):
                    for k in range(n_steps):
                        off = k * blk

                        def step(off=off, k=k, n=n, kind=kind, p1=p1,
                                 p2=p2, j=j):
                            # window → id, in-kernel. Byte plane i of this
                            # block lives at lanes [i*S + off, +blk).
                            def plane(i):
                                return bytes_ref[
                                    pl.dslice(d, 1),
                                    pl.dslice(i * S + off, blk),
                                ]

                            if kind == POLY:
                                ids = jnp.zeros((1, blk), jnp.int32)
                                for i in range(n):
                                    ids = ids * 256 + plane(i)
                                ids = ids + p1
                            else:
                                # FNV-1a, wrapping int32 == uint32 bits.
                                h = jnp.full(
                                    (1, blk), _FNV_OFFSET_I32, jnp.int32
                                )
                                for i in range(n):
                                    h = (h ^ plane(i)) * _FNV_PRIME_I32
                                if kind == FNV_MASK:
                                    ids = h & p1
                                else:
                                    # h mod p2 (p2 not a power of two):
                                    # float quotient + correction steps.
                                    # f32(h) carries ≤2^-24 relative error
                                    # (≤256 absolute at 2^32), so q is off
                                    # by at most ~1; two bidirectional
                                    # corrections restore the exact
                                    # remainder. h - q*p2 wraps in int32
                                    # but the true value fits, so the low
                                    # 32 bits are the answer.
                                    hf = h.astype(jnp.float32)
                                    hf = jnp.where(
                                        h < 0, hf + jnp.float32(2.0**32), hf
                                    )
                                    q = jnp.floor(
                                        hf / jnp.float32(p2)
                                    ).astype(jnp.int32)
                                    r = h - q * p2
                                    r = jnp.where(r < 0, r + p2, r)
                                    r = jnp.where(r < 0, r + p2, r)
                                    r = jnp.where(r >= p2, r - p2, r)
                                    r = jnp.where(r >= p2, r - p2, r)
                                    ids = p1 + r
                            starts = jax.lax.broadcasted_iota(
                                jnp.int32, (1, blk), 1
                            ) + off
                            mask = (starts <= dlen - n) & (starts < dlim)
                            if k == 0:
                                # Scala ``sliding`` partial window: a doc
                                # shorter than n contributes its whole-byte
                                # prefix once, spliced into window 0.
                                short = dlen < n
                                lane0 = starts == 0
                                ids = jnp.where(
                                    lane0 & short, prow_ref[base + d, j], ids
                                )
                                mask = mask | (lane0 & short & (dlen > 0))
                            accumulate(ids, mask)

                        # No window of this block starts inside the doc's
                        # owned range: skip the hash + matmul entirely.
                        pl.when((off < dlen) & (off < dlim))(step)

            if has_rows:
                for k in range(n_rsteps):
                    off = k * blk
                    local = off % wseg  # segment-local start (static)

                    def step(off=off):
                        r = rows_ref[pl.dslice(d, 1), pl.dslice(off, blk)]
                        # Masked windows arrive as row -1: hi -1 one-hots
                        # to nothing, so no extra mask plane is needed.
                        accumulate(r, jnp.full((1, blk), True))

                    pl.when((local < dlen) & (local < dlim))(step)

            # Contract this doc's tile histogram with the resident table
            # tile: HT small matmuls [1, 256] @ [256, Lpad], f32 over
            # exact integer counts × integer (quantized) weights.
            def h_body(h, carry):
                hrow = hist_ref[pl.dslice(h, 1), :]
                wrow = wq_ref[
                    pl.dslice(pl.multiple_of(h * 256, 256), 256), :
                ].astype(jnp.float32)
                acc_ref[pl.dslice(d, 1), :] += jax.lax.dot_general(
                    hrow, wrow, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return carry

            jax.lax.fori_loop(0, HT, h_body, 0)

        @pl.when(t == T - 1)
        def _emit():
            scaled = acc_ref[:, :] * scale_ref[0:1, :]
            out_ref[:, :] = scaled
            if want_labels:
                lane = jax.lax.broadcasted_iota(jnp.int32, (DB, Lpad), 1)
                masked = jnp.where(lane < n_langs, scaled, -jnp.inf)
                best = jnp.max(masked, axis=1, keepdims=True)
                # First maximum wins (reference tie/zero behavior); an
                # all-miss doc is all-zero scores -> label 0.
                label_ref[:, :] = jnp.min(
                    jnp.where(masked == best, lane, Lpad),
                    axis=1, keepdims=True,
                )
                best_ref[:, :] = best

    return kernel


# ------------------------------------------------- segment-mode kernel -----
#
# The per-window output mode (docs/SEGMENTATION.md): the whole-doc kernel
# above folds every window block's histogram into ONE per-doc accumulator,
# throwing the position axis away at the first contraction. The segment
# variant keeps it at CELL granularity — the window block size is set equal
# to the cell width, each block's histogram is contracted into its own
# accumulator column, and the kernel emits [B, C, Lpad] per-cell scores
# (C = S / cell). Everything else — in-kernel window ids, the FNV folds,
# the partial-window splice, the streamed table tiles, quantized scales —
# is identical to the whole-doc kernel, which is untouched (the
# bit-identical whole-doc contract is pinned by tests/test_segment.py).


def _build_fused_segment_kernel(S: int, wseg: int, cell: int,
                                layout: FusedLayout):
    """Kernel over grid (doc blocks, table tiles) emitting per-cell scores.

    One histogram scratch per (doc, cell): window block k == cell k, so the
    [HT, 256] scratch is rebuilt per cell and contracted into the cell's
    own slice of the [DB, C*Lpad] accumulator. The byte/row planes stay
    resident across a doc block's tiles exactly like the whole-doc kernel.
    """
    HT, T = layout.tile_hi, layout.tiles
    Lpad, n_langs = layout.lpad, layout.n_langs
    has_inline = bool(layout.inline)
    has_rows = bool(layout.rows_lengths)
    C = S // cell

    def kernel(*refs):
        it = iter(refs)
        bytes_ref = next(it) if has_inline else None
        rows_ref = next(it) if has_rows else None
        len_ref = next(it)
        lim_ref = next(it)
        prow_ref = next(it) if has_inline else None
        wq_ref = next(it)
        scale_ref = next(it)
        out_ref = next(it)
        hist_ref = next(it)
        acc_ref = next(it)

        b = pl.program_id(0)
        t = pl.program_id(1)
        base = b * DB
        tile_base = t * HT

        @pl.when(t == 0)
        def _init():
            acc_ref[:, :] = jnp.zeros((DB, C * Lpad), jnp.float32)

        for d in range(DB):
            dlen = len_ref[base + d]
            dlim = lim_ref[base + d]

            def accumulate(ids, mask):
                iota_hi = jax.lax.broadcasted_iota(jnp.int32, (HT, cell), 0)
                iota_lo = jax.lax.broadcasted_iota(jnp.int32, (256, cell), 0)
                hi_loc = (ids >> 8) - tile_base
                lo = ids & 255
                oh_hi = jnp.where(
                    (hi_loc == iota_hi) & mask, 1.0, 0.0
                ).astype(jnp.bfloat16)
                oh_lo = jnp.where(lo == iota_lo, 1.0, 0.0).astype(
                    jnp.bfloat16
                )
                hist_ref[:, :] += jax.lax.dot_general(
                    oh_hi, oh_lo, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

            for k in range(C):
                off = k * cell

                def cell_step(k=k, off=off):
                    hist_ref[:, :] = jnp.zeros((HT, 256), jnp.float32)
                    if has_inline:
                        for j, (n, kind, p1, p2) in enumerate(layout.inline):

                            def plane(i, off=off):
                                return bytes_ref[
                                    pl.dslice(d, 1),
                                    pl.dslice(i * S + off, cell),
                                ]

                            if kind == POLY:
                                ids = jnp.zeros((1, cell), jnp.int32)
                                for i in range(n):
                                    ids = ids * 256 + plane(i)
                                ids = ids + p1
                            else:
                                h = jnp.full(
                                    (1, cell), _FNV_OFFSET_I32, jnp.int32
                                )
                                for i in range(n):
                                    h = (h ^ plane(i)) * _FNV_PRIME_I32
                                if kind == FNV_MASK:
                                    ids = h & p1
                                else:
                                    # Same exact float-quotient fold as the
                                    # whole-doc kernel (see its comment).
                                    hf = h.astype(jnp.float32)
                                    hf = jnp.where(
                                        h < 0, hf + jnp.float32(2.0**32), hf
                                    )
                                    q = jnp.floor(
                                        hf / jnp.float32(p2)
                                    ).astype(jnp.int32)
                                    r = h - q * p2
                                    r = jnp.where(r < 0, r + p2, r)
                                    r = jnp.where(r < 0, r + p2, r)
                                    r = jnp.where(r >= p2, r - p2, r)
                                    r = jnp.where(r >= p2, r - p2, r)
                                    ids = p1 + r
                            starts = jax.lax.broadcasted_iota(
                                jnp.int32, (1, cell), 1
                            ) + off
                            mask = (starts <= dlen - n) & (starts < dlim)
                            if k == 0:
                                short = dlen < n
                                lane0 = starts == 0
                                ids = jnp.where(
                                    lane0 & short, prow_ref[base + d, j], ids
                                )
                                mask = mask | (lane0 & short & (dlen > 0))
                            accumulate(ids, mask)
                    if has_rows:
                        for j in range(len(layout.rows_lengths)):
                            r = rows_ref[
                                pl.dslice(d, 1),
                                pl.dslice(j * wseg + off, cell),
                            ]
                            # Masked windows are row -1: hi -1 one-hots to
                            # nothing, no extra mask plane needed.
                            accumulate(r, jnp.full((1, cell), True))

                    def h_body(h, carry):
                        hrow = hist_ref[pl.dslice(h, 1), :]
                        wrow = wq_ref[
                            pl.dslice(pl.multiple_of(h * 256, 256), 256), :
                        ].astype(jnp.float32)
                        acc_ref[
                            pl.dslice(d, 1), pl.dslice(k * Lpad, Lpad)
                        ] += jax.lax.dot_general(
                            hrow, wrow, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
                        return carry

                    jax.lax.fori_loop(0, HT, h_body, 0)

                # No window of this cell starts inside the doc's owned
                # range: skip the hash + matmuls entirely.
                pl.when((off < dlen) & (off < dlim))(cell_step)

        @pl.when(t == T - 1)
        def _emit():
            for c in range(C):
                sl = pl.dslice(c * Lpad, Lpad)
                out_ref[:, sl] = acc_ref[:, sl] * scale_ref[0:1, :]

    return kernel


def _fused_segment_call(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    wq: jnp.ndarray,
    scales: jnp.ndarray,
    lut: jnp.ndarray | None,
    window_limit: jnp.ndarray | None,
    spec: VocabSpec,
    layout: FusedLayout,
    cell: int,
    interpret: bool,
):
    if cell < 128 or cell % 128:
        raise ValueError(
            f"fused segment cell width must be a positive multiple of 128 "
            f"(lane tiling), got {cell}"
        )
    B0, S0 = batch.shape
    if layout.rows and wq.shape != (layout.rows_padded, layout.lpad):
        raise ValueError(
            f"fused table shape {wq.shape} disagrees with layout "
            f"({layout.rows_padded}, {layout.lpad})"
        )
    # Lane padding: S a whole number of cells (the cell IS the window
    # block, so no extra block rounding exists in this variant).
    S = -(-S0 // cell) * cell
    if S != S0:
        batch = jnp.pad(batch, ((0, 0), (0, S - S0)))
    B = -(-B0 // DB) * DB
    if B != B0:
        batch = jnp.pad(batch, ((0, B - B0), (0, 0)))
        lengths = jnp.pad(lengths, (0, B - B0))
        if window_limit is not None:
            window_limit = jnp.pad(window_limit, (0, B - B0))
    lengths = lengths.astype(jnp.int32)
    lim = (
        jnp.full((B,), S, dtype=jnp.int32)
        if window_limit is None
        else window_limit.astype(jnp.int32)
    )
    b32 = batch.astype(jnp.int32)

    has_inline = bool(layout.inline)
    has_rows = bool(layout.rows_lengths)

    operands = []
    in_specs = []
    if has_inline:
        P = layout.max_inline
        planes = [
            jnp.pad(b32[:, i:], ((0, 0), (0, i))) if i else b32
            for i in range(P)
        ]
        operands.append(jnp.concatenate(planes, axis=1))
        in_specs.append(
            pl.BlockSpec(
                (DB, P * S), lambda b, t: (b, 0), memory_space=pltpu.VMEM
            )
        )
    wseg = 0
    if has_rows:
        wmax = max(max(S - n + 1, 1) for n in layout.rows_lengths)
        wseg = -(-wmax // cell) * cell
        operands.append(
            _rows_plane(batch, lengths, lut, window_limit, spec, layout, wseg)
        )
        KW = wseg * len(layout.rows_lengths)
        in_specs.append(
            pl.BlockSpec(
                (DB, KW), lambda b, t: (b, 0), memory_space=pltpu.VMEM
            )
        )
    operands += [lengths, lim]
    in_specs += [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    if has_inline:
        operands.append(_inline_partial_rows(batch, lengths, spec, layout))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    HT, T, Lpad = layout.tile_hi, layout.tiles, layout.lpad
    operands.append(wq)
    in_specs.append(
        pl.BlockSpec(
            (HT * 256, Lpad), lambda b, t: (t, 0), memory_space=pltpu.VMEM
        )
    )
    operands.append(scales.astype(jnp.float32))
    in_specs.append(
        pl.BlockSpec((8, Lpad), lambda b, t: (0, 0), memory_space=pltpu.VMEM)
    )

    C = S // cell
    out = pl.pallas_call(
        _build_fused_segment_kernel(S, wseg, cell, layout),
        grid=(B // DB, T),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (DB, C * Lpad), lambda b, t: (b, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, C * Lpad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((HT, 256), jnp.float32),
            pltpu.VMEM((DB, C * Lpad), jnp.float32),
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, C, Lpad)[:B0, :, : layout.n_langs]


@partial(
    jax.jit,
    static_argnames=("spec", "layout", "cell", "interpret"),
)
def segment_batch_fused(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    wq: jnp.ndarray,
    scales: jnp.ndarray,
    lut: jnp.ndarray | None = None,
    window_limit: jnp.ndarray | None = None,
    *,
    spec: VocabSpec,
    layout: FusedLayout,
    cell: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """float32 [B, ceil(S / cell), L] per-cell scores via the fused kernel.

    The segmentation-mode twin of :func:`score_batch_fused`: same window
    ids, masking, partial-window splice, ``window_limit`` chunk ownership,
    and quantized scales — but window contributions land in the cell of
    their start position (``start // cell``) instead of one doc total, so
    the host span decoder (:mod:`...segment.spans`) can see where each
    language lives. Summing a row's cells restores the whole-doc score up
    to f32 reduction order. Exact integer histogram counts × (quantized)
    weights per cell, like the whole-doc kernel.
    """
    return _fused_segment_call(
        batch, lengths, wq, scales, lut, window_limit,
        spec, layout, cell, interpret,
    )


# ------------------------------------------------------------- wrapper -----


def _window0_ids(batch: jnp.ndarray, n: int, spec: VocabSpec) -> jnp.ndarray:
    """Exact-mode id of window 0 only (the partial-window helper's seed) —
    O(B) instead of materializing every window id in XLA."""
    B, S = batch.shape
    if S < n:
        batch = jnp.pad(batch, ((0, 0), (0, n - S)))
    ids = jnp.zeros((B,), jnp.int32)
    for i in range(n):
        ids = ids * 256 + batch[:, i].astype(jnp.int32)
    return ids + spec.offsets[n]


def _inline_partial_rows(
    batch: jnp.ndarray, lengths: jnp.ndarray, spec: VocabSpec,
    layout: FusedLayout,
) -> jnp.ndarray:
    """int32 [B, max(1, n_inline)] partial-window rows per inline length
    (meaningful only where 0 < len < n; the kernel masks the rest)."""
    cols = []
    for n, _, _, _ in layout.inline:
        if spec.mode == EXACT:
            w0 = _window0_ids(batch, n, spec)
        else:
            w0 = jnp.zeros((batch.shape[0],), jnp.int32)  # hashed: unused
        cols.append(partial_window_ids(batch, lengths, n, w0, spec))
    if not cols:
        cols = [jnp.zeros((batch.shape[0],), jnp.int32)]
    return jnp.stack(cols, axis=1).astype(jnp.int32)


def _rows_plane(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    lut: jnp.ndarray | None,
    window_limit: jnp.ndarray | None,
    spec: VocabSpec,
    layout: FusedLayout,
    wseg: int,
) -> jnp.ndarray:
    """int32 [B, K*wseg] concatenated fused-row segments for the lengths
    whose membership lives in XLA (masked/padded windows are -1: the
    kernel's hi one-hot matches nothing there)."""
    B = batch.shape[0]
    segs = []
    for n in layout.rows_lengths:
        ids = window_ids(batch, n, spec)
        rows = ids if lut is None else lut[ids]
        pids = partial_window_ids(batch, lengths, n, ids[:, 0], spec)
        prow = pids if lut is None else lut[pids]
        prow = jnp.where(lengths > 0, prow, -1)
        rows, mask = _splice_partial_windows(
            rows, prow, lengths, n, window_limit
        )
        rows = jnp.where(mask, rows, -1)
        pad = wseg - rows.shape[1]
        if pad:
            rows = jnp.pad(rows, ((0, 0), (0, pad)), constant_values=-1)
        segs.append(rows)
    return (
        jnp.concatenate(segs, axis=1) if len(segs) > 1 else segs[0]
    ).astype(jnp.int32)


def _fused_call(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    wq: jnp.ndarray,
    scales: jnp.ndarray,
    lut: jnp.ndarray | None,
    window_limit: jnp.ndarray | None,
    spec: VocabSpec,
    layout: FusedLayout,
    block: int,
    interpret: bool,
    want_labels: bool,
):
    B0, S0 = batch.shape
    if layout.rows and wq.shape != (layout.rows_padded, layout.lpad):
        raise ValueError(
            f"fused table shape {wq.shape} disagrees with layout "
            f"({layout.rows_padded}, {layout.lpad})"
        )
    # Lane padding: S a multiple of the window block.
    blk = min(block, -(-S0 // 128) * 128)
    S = -(-S0 // blk) * blk
    if S != S0:
        batch = jnp.pad(batch, ((0, 0), (0, S - S0)))
    # Sublane padding: whole DB-document grid steps (pad rows: length 0).
    B = -(-B0 // DB) * DB
    if B != B0:
        batch = jnp.pad(batch, ((0, B - B0), (0, 0)))
        lengths = jnp.pad(lengths, (0, B - B0))
        if window_limit is not None:
            window_limit = jnp.pad(window_limit, (0, B - B0))
    lengths = lengths.astype(jnp.int32)
    lim = (
        jnp.full((B,), S, dtype=jnp.int32)
        if window_limit is None
        else window_limit.astype(jnp.int32)
    )
    b32 = batch.astype(jnp.int32)

    has_inline = bool(layout.inline)
    has_rows = bool(layout.rows_lengths)

    operands = []
    in_specs = []
    if has_inline:
        # Pre-shifted byte planes on the lane axis (Mosaic needs
        # 128-aligned lane slices — same workaround as score_pallas's b1).
        P = layout.max_inline
        planes = [
            jnp.pad(b32[:, i:], ((0, 0), (0, i))) if i else b32
            for i in range(P)
        ]
        operands.append(jnp.concatenate(planes, axis=1))
        in_specs.append(
            pl.BlockSpec(
                (DB, P * S), lambda b, t: (b, 0), memory_space=pltpu.VMEM
            )
        )
    wseg = 0
    if has_rows:
        wmax = max(
            max(S - n + 1, 1) for n in layout.rows_lengths
        )
        wseg = -(-wmax // blk) * blk
        operands.append(
            _rows_plane(batch, lengths, lut, window_limit, spec, layout, wseg)
        )
        KW = wseg * len(layout.rows_lengths)
        in_specs.append(
            pl.BlockSpec(
                (DB, KW), lambda b, t: (b, 0), memory_space=pltpu.VMEM
            )
        )
    operands += [lengths, lim]
    in_specs += [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    if has_inline:
        operands.append(_inline_partial_rows(batch, lengths, spec, layout))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    HT, T, Lpad = layout.tile_hi, layout.tiles, layout.lpad
    operands.append(wq)
    in_specs.append(
        pl.BlockSpec(
            (HT * 256, Lpad), lambda b, t: (t, 0), memory_space=pltpu.VMEM
        )
    )
    operands.append(scales.astype(jnp.float32))
    in_specs.append(
        pl.BlockSpec((8, Lpad), lambda b, t: (0, 0), memory_space=pltpu.VMEM)
    )

    out_shape = [jax.ShapeDtypeStruct((B, Lpad), jnp.float32)]
    out_specs = [
        pl.BlockSpec((DB, Lpad), lambda b, t: (b, 0), memory_space=pltpu.VMEM)
    ]
    if want_labels:
        out_shape += [
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec(
                (DB, 1), lambda b, t: (b, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (DB, 1), lambda b, t: (b, 0), memory_space=pltpu.VMEM
            ),
        ]

    out = pl.pallas_call(
        _build_fused_kernel(
            S if has_inline else 0,
            wseg * len(layout.rows_lengths),
            wseg,
            blk,
            layout,
            want_labels,
        ),
        grid=(B // DB, T),
        in_specs=in_specs,
        out_specs=out_specs if want_labels else out_specs[0],
        out_shape=out_shape if want_labels else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((HT, 256), jnp.float32),
            pltpu.VMEM((DB, Lpad), jnp.float32),
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(*operands)
    if want_labels:
        scores, labels, best = out
        return (
            scores[:B0, : layout.n_langs],
            labels[:B0, 0],
            best[:B0, 0],
        )
    return out[:B0, : layout.n_langs]


@partial(
    jax.jit,
    static_argnames=("spec", "layout", "block", "interpret"),
)
def score_batch_fused(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    wq: jnp.ndarray,
    scales: jnp.ndarray,
    lut: jnp.ndarray | None = None,
    window_limit: jnp.ndarray | None = None,
    *,
    spec: VocabSpec,
    layout: FusedLayout,
    block: int = DEFAULT_BLOCK,
    interpret: bool = False,
) -> jnp.ndarray:
    """float32 [B, L] scores via the fused megakernel.

    Same contract as :func:`ops.score.score_batch` (masking, Scala
    ``sliding`` partial-window rule, ``window_limit`` chunk ownership) with
    the table pre-built by :func:`build_fused_tables`. Scores carry the
    per-language dequantize scale, so chunked long documents sum across
    dispatches exactly like every other strategy.
    """
    return _fused_call(
        batch, lengths, wq, scales, lut, window_limit,
        spec, layout, block, interpret, want_labels=False,
    )


@partial(
    jax.jit,
    static_argnames=("spec", "layout", "block", "interpret"),
)
def detect_batch_fused(
    batch: jnp.ndarray,
    lengths: jnp.ndarray,
    wq: jnp.ndarray,
    scales: jnp.ndarray,
    lut: jnp.ndarray | None = None,
    window_limit: jnp.ndarray | None = None,
    *,
    spec: VocabSpec,
    layout: FusedLayout,
    block: int = DEFAULT_BLOCK,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(labels int32 [B], best float32 [B]) — argmax in-kernel.

    The serving-path variant of :func:`score_batch_fused`: per document
    only the label/score pair leaves the chip. First-maximum ties, all-miss
    docs label 0 (the scores themselves never reach HBM).
    """
    _, labels, best = _fused_call(
        batch, lengths, wq, scales, lut, window_limit,
        spec, layout, block, interpret, want_labels=True,
    )
    return labels, best
