"""Profile fitting: gram counting → weighting → per-language top-k.

Host (vectorized numpy) implementation of the reference's four training stages
(``/root/reference/src/main/.../LanguageDetector.scala``):

  computeGrams (:25-46)  → :func:`extract_gram_counts` — one padded-batch pass
  reduceGrams (:52-66)   →   (same pass; np.unique over (id, lang) replaces
                              |langs| shuffles — fixes SURVEY.md §2.9 Q9)
  computeProbabilities (:75-92) → :func:`compute_weights`
  filterTopGrams (:100-132)     → :func:`select_top_grams`

Weighting has two modes (SURVEY.md §2.9 Q1):
  * ``parity``: the reference's actual formula — occurrence counts are
    discarded and weight_l = log(1 + present_l / #langs_containing_gram),
    a cross-language uniqueness weight.
  * ``counts``: the formula the reference's README/docstrings *claim* —
    weight_l = log(1 + count_l / total_count) — behind an explicit flag.

The device-side (TPU, mesh-sharded) fit lives in ``fit_tpu.py``; both produce
the same profile arrays and are cross-checked by tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..telemetry import span
from .encoding import pad_batch
from .vocab import EXACT, VocabSpec, window_ids_numpy

PARITY = "parity"
COUNTS = "counts"
WEIGHT_MODES = (PARITY, COUNTS)

_FIT_BATCH = 1024  # docs per padded counting batch
# Pending (unmerged per-batch unique) elements before an LSM-style merge into
# the running accumulator — ~128MB of ids+counts. Module-level so tests can
# shrink it to exercise flush boundaries.
_PENDING_MERGE_LIMIT = 8_000_000


@dataclass(frozen=True)
class GramCounts:
    """Sparse per-(gram, language) totals: the reduceGrams output."""

    ids: np.ndarray  # int64 [M] gram ids, ascending
    langs: np.ndarray  # int32 [M] language indices
    counts: np.ndarray  # int64 [M] total occurrences
    num_langs: int


def extract_gram_counts(
    byte_docs: Sequence[bytes],
    lang_indices: np.ndarray,
    num_langs: int,
    spec: VocabSpec,
    batch_size: int = _FIT_BATCH,
    gram_lengths_subset: tuple[int, ...] | None = None,
    min_partial_gram_len: int = 1,
) -> GramCounts:
    """Count every window occurrence per (gram id, language).

    One padded-batch sweep over the corpus; all languages aggregate in a single
    pass (the reference launches per-language Spark jobs — Q9). Partial windows
    of short documents are included, mirroring Scala ``sliding``.

    ``gram_lengths_subset`` counts only those window classes (ids stay in the
    full spec's id space); ``min_partial_gram_len`` additionally drops partial
    windows whose *gram* (the whole short doc) is shorter than the bound. The
    split device fit uses both to partition contributions by resulting gram
    length with no overlap (``ops.fit_tpu.fit_profile_device_split``).
    """
    lang_indices = np.asarray(lang_indices, dtype=np.int64)
    lengths_to_count = (
        tuple(gram_lengths_subset)
        if gram_lengths_subset is not None
        else spec.gram_lengths
    )
    max_n = max(lengths_to_count)

    # Streaming reduction with bounded memory (the reference streams this
    # through Spark shuffles, LanguageDetector.scala:52-66): each batch's
    # raw window-id array is reduced to (unique pair, count) immediately,
    # and the per-batch uniques merge LSM-style — deferred until the pending
    # set is large enough to amortize the sort — so peak RSS is
    # O(batch windows + distinct pairs), not O(total corpus windows).
    acc_ids = np.zeros(0, np.int64)
    acc_counts = np.zeros(0, np.int64)
    pending: list[tuple[np.ndarray, np.ndarray]] = []
    pending_elems = 0

    def flush():
        nonlocal acc_ids, acc_counts, pending, pending_elems
        if not pending:
            return
        all_ids = np.concatenate([acc_ids] + [u for u, _ in pending])
        all_counts = np.concatenate([acc_counts] + [c for _, c in pending])
        acc_ids, inv = np.unique(all_ids, return_inverse=True)
        # bincount sums in float64 — exact for counts below 2^53.
        acc_counts = np.bincount(
            inv, weights=all_counts.astype(np.float64)
        ).astype(np.int64)
        pending = []
        pending_elems = 0

    for start in range(0, len(byte_docs), batch_size):
        docs = byte_docs[start : start + batch_size]
        langs = lang_indices[start : start + batch_size]
        batch, lengths = pad_batch(docs, pad_to=max(max(len(d) for d in docs), 1))
        batch_chunks: list[np.ndarray] = []
        for n in lengths_to_count:
            if batch.shape[1] < n:
                continue  # no full windows of this class in the batch
            ids = window_ids_numpy(batch, n, spec)  # [B, W]
            W = ids.shape[1]
            mask = np.arange(W)[None, :] <= (lengths[:, None] - n)
            lang_grid = np.broadcast_to(langs[:, None], ids.shape)
            batch_chunks.append(ids[mask] * num_langs + lang_grid[mask])
        # Partial windows for docs shorter than some gram length: one window
        # of the whole doc per class it falls short of (Scala ``sliding``),
        # id in the doc's own length class.
        for i, doc in enumerate(docs):
            n_doc = len(doc)
            if min_partial_gram_len <= n_doc < max_n:
                reps = sum(1 for n in lengths_to_count if n > n_doc)
                if reps:
                    short_id = spec.gram_to_id(bytes(doc))
                    batch_chunks.append(
                        np.full(reps, short_id, dtype=np.int64) * num_langs
                        + langs[i]
                    )
        if batch_chunks:
            u, c = np.unique(np.concatenate(batch_chunks), return_counts=True)
            pending.append((u, c.astype(np.int64)))
            pending_elems += len(u)
            # Gate on the pending size alone: once the accumulator itself
            # outgrows the limit, including it in the test would force a
            # full re-sort after every batch (quadratic in corpus size).
            if pending_elems > _PENDING_MERGE_LIMIT:
                flush()

    flush()
    return GramCounts(
        ids=acc_ids // num_langs,
        langs=(acc_ids % num_langs).astype(np.int32),
        counts=acc_counts,
        num_langs=num_langs,
    )


def compute_weights(
    gram_counts: GramCounts, weight_mode: str = PARITY
) -> tuple[np.ndarray, np.ndarray]:
    """Per-gram weight vectors.

    Returns (unique_ids [U] ascending, weights [U, L] float64).
    """
    if weight_mode not in WEIGHT_MODES:
        raise ValueError(f"weight_mode must be one of {WEIGHT_MODES}")
    L = gram_counts.num_langs
    unique_ids, row_index = np.unique(gram_counts.ids, return_inverse=True)
    U = len(unique_ids)
    weights = np.zeros((U, L), dtype=np.float64)
    if weight_mode == PARITY:
        # #langs containing each gram; each (id, lang) appears exactly once.
        nlangs = np.bincount(row_index, minlength=U).astype(np.float64)
        weights[row_index, gram_counts.langs] = np.log1p(1.0 / nlangs[row_index])
    else:
        totals = np.zeros(U, dtype=np.float64)
        np.add.at(totals, row_index, gram_counts.counts.astype(np.float64))
        weights[row_index, gram_counts.langs] = np.log1p(
            gram_counts.counts / totals[row_index]
        )
    return unique_ids, weights


def select_top_grams(
    unique_ids: np.ndarray,
    weights: np.ndarray,
    profile_size: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Keep the union over languages of each language's top-k grams.

    Reference semantics (LanguageDetector.scala:100-132): per language, sort
    *all* grams by that language's weight descending, take k, union the winner
    sets, and keep the full weight vector of every winner. Ties break by gram
    id ascending (deterministic; the reference's order under Spark is
    partition-dependent). Duplicate winners collapse (Q7's implicit dedupe).
    """
    L = weights.shape[1]
    k = min(profile_size, len(unique_ids))
    winner_rows: list[np.ndarray] = []
    for l in range(L):
        # lexsort: last key primary → primary -weight, secondary id ascending.
        order = np.lexsort((unique_ids, -weights[:, l]))[:k]
        winner_rows.append(order)
    rows = np.unique(np.concatenate(winner_rows)) if winner_rows else np.zeros(0, np.int64)
    return unique_ids[rows], np.ascontiguousarray(weights[rows])


def fit_profile_numpy(
    byte_docs: Sequence[bytes],
    lang_indices: np.ndarray,
    num_langs: int,
    spec: VocabSpec,
    profile_size: int,
    weight_mode: str = PARITY,
) -> tuple[np.ndarray, np.ndarray]:
    """Full host fit: returns (sorted gram ids [G], weights [G, L] float64)."""
    with span("fit/count", docs=len(byte_docs), backend="cpu"):
        from ..resilience import faults

        faults.inject("fit/count")  # chaos hook: one count pass per attempt
        gram_counts = extract_gram_counts(
            byte_docs, lang_indices, num_langs, spec
        )
    with span("fit/weights", pairs=len(gram_counts.ids)):
        unique_ids, weights = compute_weights(gram_counts, weight_mode)
    with span("fit/topk", k=profile_size):
        return select_top_grams(unique_ids, weights, profile_size)
