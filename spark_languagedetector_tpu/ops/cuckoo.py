"""Device-side cuckoo hash table: exact gram membership at any length.

The reference resolves gram membership with a JVM hash map keyed on byte
sequences (``/root/reference/src/main/.../LanguageDetectorModel.scala:139-152``).
For exact gram lengths ≤ 3 this framework uses integer ids small enough for a
dense id→row LUT; lengths 4..5 overflow int32 ids and a dense LUT over the
256^5 id space is impossible, so membership becomes a **two-choice cuckoo
table** over packed ``(lo, hi)`` int32 keys (``ops.vocab.gram_key``):

* host build (here): every profile gram is placed at one of its two bucket
  positions ``mix32(key, seed1) % M`` / ``mix32(key, seed2) % M`` via the
  classic eviction loop; a cycle triggers a rebuild with fresh seeds. M is a
  power of two at ≤ 50% load, where two-choice cuckoo succeeds with high
  probability.
* device lookup (``ops.score.score_batch_cuckoo``): two slot gathers + key
  verification against the stored halves — exact membership in O(1) gathers,
  no serial binary search (``searchsorted`` lowers to a scan on TPU).

The miss row G carries sentinel keys (hi = -1) that no real gram can produce
(real ``hi`` is ``byte | (n << 8)`` ≥ 256), so unverified probes fall through
to the zero-weight row.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .vocab import mix32

# Slots-per-gram factor; 2-choice cuckoo at ≤ 50% load succeeds w.h.p.
_LOAD_FACTOR_INV = 2.5
_MAX_EVICTIONS = 500
_MAX_REBUILDS = 20


@dataclass(frozen=True)
class CuckooTable:
    """Host-built table, ready to ship to device.

    ``slots``: int32 [M] — row index into the compact weight table, or G
    (miss row) for empty slots. ``keys_lo``/``keys_hi``: int32 [G+1] packed
    keys per row; row G holds the non-matching sentinel.
    """

    slots: np.ndarray
    keys_lo: np.ndarray
    keys_hi: np.ndarray
    seed1: int
    seed2: int

    @property
    def num_slots(self) -> int:
        return int(self.slots.shape[0])

    def entries(self) -> np.ndarray:
        """Device form: int32 [M, 4] rows ``[key_lo, key_hi, row, 0]``.

        One wide gather resolves a whole probe (key halves + row) instead of
        three narrow ones — measured ~2× on the device lookup. Empty slots
        carry the miss row and the sentinel ``key_hi = -1``.
        """
        M = self.num_slots
        out = np.zeros((M, 4), dtype=np.int32)
        out[:, 0] = self.keys_lo[self.slots]
        out[:, 1] = self.keys_hi[self.slots]
        out[:, 2] = self.slots
        return out


def build_cuckoo(keys_lo: np.ndarray, keys_hi: np.ndarray) -> CuckooTable:
    """Place G packed keys into a two-choice cuckoo table.

    Args are int32 [G] arrays (row order = compact weight-table row order).
    Raises RuntimeError only if every rebuild fails — practically unreachable
    at this load factor.
    """
    G = int(keys_lo.shape[0])
    M = 1 << max(4, int(np.ceil(np.log2(max(G, 1) * _LOAD_FACTOR_INV))))
    keys_lo = np.ascontiguousarray(keys_lo, dtype=np.int32)
    keys_hi = np.ascontiguousarray(keys_hi, dtype=np.int32)

    rng = np.random.default_rng(0xC0C0)
    for _ in range(_MAX_REBUILDS):
        seed1, seed2 = (int(s) for s in rng.integers(1, 2**31 - 1, size=2))
        h1 = (mix32(keys_lo, keys_hi, seed1) % np.uint32(M)).astype(np.int64)
        h2 = (mix32(keys_lo, keys_hi, seed2) % np.uint32(M)).astype(np.int64)
        slots = np.full(M, G, dtype=np.int32)
        ok = True
        for row in range(G):
            cur, bucket = row, int(h1[row])
            placed = False
            for _ in range(_MAX_EVICTIONS):
                if slots[bucket] == G:
                    slots[bucket] = cur
                    placed = True
                    break
                # Evict the occupant to its alternate bucket.
                cur, slots[bucket] = int(slots[bucket]), cur
                b1, b2 = int(h1[cur]), int(h2[cur])
                bucket = b2 if bucket == b1 else b1
            if not placed:
                ok = False
                break
        if ok:
            lo = np.concatenate([keys_lo, np.zeros(1, np.int32)])
            hi = np.concatenate([keys_hi, np.full(1, -1, np.int32)])
            return CuckooTable(
                slots=slots, keys_lo=lo, keys_hi=hi, seed1=seed1, seed2=seed2
            )
    raise RuntimeError(
        f"cuckoo build failed after {_MAX_REBUILDS} rebuilds "
        f"(G={G}, M={M}) — table pathologically unlucky"
    )


def lookup_numpy(
    table: CuckooTable, lo: np.ndarray, hi: np.ndarray
) -> np.ndarray:
    """Host mirror of the device lookup (``ops.score._cuckoo_rows``):
    packed keys → compact rows (miss → G). Lockstep-tested."""
    M = table.num_slots
    G = table.keys_lo.shape[0] - 1
    lo = np.ascontiguousarray(lo, dtype=np.int32)
    hi = np.ascontiguousarray(hi, dtype=np.int32)
    h1 = (mix32(lo, hi, table.seed1) % np.uint32(M)).astype(np.int64)
    h2 = (mix32(lo, hi, table.seed2) % np.uint32(M)).astype(np.int64)
    r1 = table.slots[h1]
    r2 = table.slots[h2]
    hit1 = (table.keys_lo[r1] == lo) & (table.keys_hi[r1] == hi)
    hit2 = (table.keys_lo[r2] == lo) & (table.keys_hi[r2] == hi)
    return np.where(hit1, r1, np.where(hit2, r2, G))
