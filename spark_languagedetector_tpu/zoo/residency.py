"""LRU residency accounting for the model zoo (docs/SERVING.md §12).

Hundreds of tenant profiles cannot all keep their quantized weight tables
(and compiled-program handles) resident at once; this module owns the
bookkeeping half of paging: which tenants are resident, how many table
bytes each one holds, and — when a new load pushes the zoo past its byte
or model budget — which least-recently-used tenants to page out.

Policy, not mechanism: the :class:`~.zoo.ModelZoo` supplies ``evictable``
(a tenant is untouchable while any of its registry versions holds a lease
or its batcher has queued/in-flight work — "evictions never touch a
leased version" is structural, via :meth:`~..serve.registry.ModelRegistry
.busy`) and ``evict`` (the actual teardown). When every candidate is
busy, the zoo runs transiently over budget rather than blocking or
corrupting a dispatch — logged, never silent.

Budgets resolve through ``exec/config``'s audited table
(``LANGDETECT_ZOO_RESIDENT_BYTES`` / ``LANGDETECT_ZOO_RESIDENT_MODELS``;
unset ⇒ unlimited). Occupancy is surfaced as the
``langdetect_zoo_resident_bytes`` / ``langdetect_zoo_resident_models``
gauges and every page-out increments ``zoo/evictions`` (tracked
informationally by ``telemetry/compare`` — evictions are normal life
under a budget, not a regression).

Not thread-safe on its own: the owning zoo calls every method under its
control-plane lock.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

from ..exec import config as exec_config
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("zoo.residency")


class ResidencyManager:
    """LRU map of resident tenants → table bytes, under two budgets."""

    def __init__(
        self,
        *,
        max_bytes: int | None = None,
        max_models: int | None = None,
    ):
        mb = exec_config.resolve("zoo_resident_bytes", max_bytes)
        mm = exec_config.resolve("zoo_resident_models", max_models)
        self.max_bytes = None if mb is None else int(mb)
        self.max_models = None if mm is None else int(mm)
        self._resident: OrderedDict[str, int] = OrderedDict()

    # ------------------------------------------------------------ access ----
    @property
    def bytes(self) -> int:
        return sum(self._resident.values())

    @property
    def models(self) -> int:
        return len(self._resident)

    def resident(self) -> dict[str, int]:
        """{tenant: table bytes} in LRU order (oldest first)."""
        return dict(self._resident)

    def is_resident(self, name: str) -> bool:
        return name in self._resident

    # ----------------------------------------------------------- updates ----
    def touch(self, name: str) -> None:
        """Mark one resident tenant most-recently-used."""
        if name in self._resident:
            self._resident.move_to_end(name)

    def drop(self, name: str) -> None:
        """Forget a tenant the zoo tore down outside the admit loop."""
        if self._resident.pop(name, None) is not None:
            self._gauges()

    def _over_budget(self) -> bool:
        if self.max_models is not None and self.models > self.max_models:
            return True
        return self.max_bytes is not None and self.bytes > self.max_bytes

    def admit(
        self,
        name: str,
        nbytes: int,
        *,
        evictable: Callable[[str], bool],
        evict: Callable[[str], None],
    ) -> list[str]:
        """Record ``name`` resident at ``nbytes`` (MRU), then page out
        LRU tenants while over either budget. The just-admitted tenant is
        never its own victim; an unevictable candidate (leased / queued
        work) is skipped. Returns the evicted tenant names in order."""
        self._resident.pop(name, None)
        self._resident[name] = int(nbytes)
        evicted: list[str] = []
        while self._over_budget():
            victim = next(
                (
                    n for n in self._resident
                    if n != name and n not in evicted and evictable(n)
                ),
                None,
            )
            if victim is None:
                # Every candidate is mid-dispatch or leased: run over
                # budget until the next admit rather than evicting under
                # a live lease or blocking the serving path.
                log_event(
                    _log, "zoo.residency.over_budget", tenant=name,
                    resident_bytes=self.bytes, resident_models=self.models,
                    max_bytes=self.max_bytes, max_models=self.max_models,
                )
                break
            evict(victim)
            del self._resident[victim]
            evicted.append(victim)
            REGISTRY.incr("zoo/evictions")
            log_event(
                _log, "zoo.residency.evicted", tenant=victim, for_=name,
                resident_bytes=self.bytes, resident_models=self.models,
            )
        self._gauges()
        return evicted

    def _gauges(self) -> None:
        REGISTRY.set_gauge(
            "langdetect_zoo_resident_bytes", float(self.bytes)
        )
        REGISTRY.set_gauge(
            "langdetect_zoo_resident_models", float(self.models)
        )

    def describe(self) -> dict:
        return {
            "resident_models": self.models,
            "resident_bytes": self.bytes,
            "max_models": self.max_models,
            "max_bytes": self.max_bytes,
            "lru": list(self._resident),
        }
