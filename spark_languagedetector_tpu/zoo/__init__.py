"""Multi-tenant model zoo: named tenants, bounded residency, isolation.

The control plane that turns the single-model serving stack into a
many-profile one (docs/SERVING.md §12):

  * :class:`~.zoo.ModelZoo` — tenant → versioned registry + dedicated
    batcher routing, tenant-scoped installs/rollbacks, and per-tenant
    auto-refit scoping;
  * :class:`~.residency.ResidencyManager` — LRU paging of resident
    weight tables under the ``LANGDETECT_ZOO_RESIDENT_BYTES`` /
    ``LANGDETECT_ZOO_RESIDENT_MODELS`` budgets, never evicting a leased
    version;
  * :class:`~.zoo.TenantQuota` — per-tenant admission-queue overrides
    (the quota lane that keeps a noisy tenant's burst on that tenant);
  * :class:`~.zoo.TenantLoadShed` / :class:`~.zoo.UnknownTenant` — the
    explicit per-tenant failure surface (503 + Retry-After / 400).

Importing this package never initializes jax — runners are built lazily
by the models each tenant's cold load installs.
"""

from __future__ import annotations

from .residency import ResidencyManager
from .zoo import (
    DEFAULT_TENANT,
    ModelZoo,
    TenantEntry,
    TenantLoadShed,
    TenantQuota,
    TenantRuntime,
    UnknownTenant,
    ZooError,
)

__all__ = [
    "DEFAULT_TENANT",
    "ModelZoo",
    "ResidencyManager",
    "TenantEntry",
    "TenantLoadShed",
    "TenantQuota",
    "TenantRuntime",
    "UnknownTenant",
    "ZooError",
]
