"""Multi-tenant model zoo: hundreds of profiles behind one serving fleet.

The serving stack below this module is single-model: one
:class:`~..serve.registry.ModelRegistry`, one
:class:`~..serve.batcher.ContinuousBatcher`, one set of knobs. Serving
millions of users means many *domains* — per-customer, per-script,
per-domain profile variants — and GSPMD / pjit portability (PAPERS.md:
arXiv:2105.04663, arXiv:2204.06514) makes that a pure control-plane
problem: every tenant's compiled program is the same geometry-portable
artifact, so multi-tenancy is routing + residency + isolation, which is
exactly what this module owns (docs/SERVING.md §12):

  * **Tenant routing** — a named map tenant → versioned registry +
    dedicated batcher. ``runtime(None)`` resolves the default tenant, so
    every pre-zoo single-model call keeps its exact behavior.
  * **Bounded residency** — tenants page in on first use (cold load:
    the registry's ``prepare``/``commit`` split, so the build + pre-warm
    happen off the serving path and the pointer flip is the only
    serving-visible step) and page out LRU under the
    ``LANGDETECT_ZOO_RESIDENT_BYTES`` / ``_MODELS`` budgets
    (:mod:`.residency`). Eviction drops the compiled runner and device
    tables — and, for disk-backed tenants with no unsaved installs, the
    host-side model too — but never touches a tenant whose registry
    holds a lease or whose batcher has queued work.
  * **Isolation** — each tenant's batcher is its own admission queue
    (its own quota lane: a noisy tenant's burst fills and sheds *that*
    queue, with per-queue shed tallies and a ``zoo/shed/<tenant>``
    counter — neighbors never pay), and the shared score cache is
    partitioned per tenant by key prefix. A bookkeeping mismatch between
    the requested tenant and the runtime that would answer is rejected
    and counted (``zoo/cross_tenant_rejects`` — a reliability counter
    whose very appearance regresses the compare guard).
  * **Tenant-scoped refit** — :meth:`ModelZoo.auto_refit` hands the
    continuous-learning driver an install proxy bound to ONE tenant, so
    a refit can only ever move that tenant's serving pointer.

A cold-load failure (including an injected ``zoo/load`` fault) degrades
to :class:`TenantLoadShed` — HTTP 503 + Retry-After *for that tenant
only*, never a wrong-tenant answer and never an outage for its
neighbors.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass

from ..resilience import faults
from ..serve.batcher import (
    ContinuousBatcher,
    ServeClosed,
    ServeError,
    ServeOverloaded,
)
from ..serve.registry import ModelRegistry
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event
from .residency import ResidencyManager

_log = get_logger("zoo.zoo")

# Tenant names ride metric names (`zoo/shed/<tenant>`), cache-key scopes,
# and log fields: keep them in the same lowercase grammar as every other
# telemetry segment so the observability surface stays parseable.
_TENANT_RE = re.compile(r"[a-z0-9_]{1,64}")

_VERSION_RE = re.compile(r"v(\d+)")

DEFAULT_TENANT = "default"


class ZooError(ServeError):
    """Base class for model-zoo control-plane failures."""


class UnknownTenant(ZooError, ValueError):
    """Request named a tenant the zoo does not know (a ValueError, so the
    HTTP front end answers 400 — a caller bug, never retried)."""


class TenantLoadShed(ServeOverloaded):
    """A tenant's cold load failed (injected ``zoo/load`` fault, bad
    model directory, OOM): that tenant's request is shed explicitly —
    HTTP 503 + Retry-After — and every other tenant keeps serving."""

    def __init__(self, tenant: str, *, retry_after_s: float):
        super().__init__(
            f"tenant {tenant!r} cold load failed; retry shortly",
            reason="cold_load",
            retry_after_s=retry_after_s,
        )
        self.tenant = tenant


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant overrides for the tenant's admission queue (its quota
    lane). ``None`` fields fall through to the zoo-wide batcher defaults
    (which resolve env > tuning profile > built-in like every knob)."""

    max_rows: int | None = None
    max_wait_ms: float | None = None
    max_queue_rows: int | None = None
    slo_ms: float | None = None

    def describe(self) -> dict:
        return {
            "max_rows": self.max_rows,
            "max_wait_ms": self.max_wait_ms,
            "max_queue_rows": self.max_queue_rows,
            "slo_ms": self.slo_ms,
        }


class TenantRuntime:
    """One resident tenant's serving half: registry + batcher + cost."""

    __slots__ = ("tenant", "registry", "batcher", "table_bytes", "loaded_at")

    def __init__(self, tenant, registry, batcher, table_bytes):
        self.tenant = tenant
        self.registry = registry
        self.batcher = batcher
        self.table_bytes = int(table_bytes)
        self.loaded_at = time.time()


class TenantEntry:
    """One registered tenant: identity, current model/version, quota, and
    (while resident) its runtime."""

    __slots__ = (
        "name", "model", "version", "seq", "source", "quota", "dirty",
        "loads", "runtime", "_load_lock",
    )

    def __init__(self, name, model, version, seq, source, quota):
        self.name = name
        self.model = model
        self.version = version
        self.seq = seq
        self.source = source
        self.quota = quota or TenantQuota()
        # True once an in-memory install (refit/admin swap by object)
        # diverged this tenant from its on-disk source: eviction must
        # then keep the host-side model (nothing on disk has it).
        self.dirty = source is None
        self.loads = 0
        self.runtime: TenantRuntime | None = None
        self._load_lock = threading.Lock()

    def describe(self) -> dict:
        rt = self.runtime
        return {
            "tenant": self.name,
            "version": self.version,
            "resident": rt is not None,
            "loads": self.loads,
            "source": self.source,
            "dirty": self.dirty,
            "table_bytes": rt.table_bytes if rt is not None else None,
            "quota": self.quota.describe(),
        }


def _table_bytes(runner) -> int:
    """Resident cost of one tenant's device tables: the (possibly
    quantized) weight table plus whichever membership form the profile
    chose (dense LUT or cuckoo arrays)."""
    total = 0
    for attr in ("weights", "lut"):
        nb = getattr(getattr(runner, attr, None), "nbytes", None)
        if nb:
            total += int(nb)
    cuckoo = getattr(runner, "cuckoo", None)
    if cuckoo is not None:
        for attr in ("slots", "keys_lo", "keys_hi"):
            nb = getattr(getattr(cuckoo, attr, None), "nbytes", None)
            if nb:
                total += int(nb)
    return total


class _TenantInstaller:
    """Registry-shaped install proxy bound to one tenant: the only
    surface :class:`~..stream.refit.AutoRefit` needs, routed through
    :meth:`ModelZoo.install` so a refit lands on the tenant's *current*
    registry even across an eviction/reload cycle — and can never land
    anywhere else."""

    def __init__(self, zoo: "ModelZoo", tenant: str):
        self._zoo = zoo
        self._tenant = tenant

    def install(self, model, **kw) -> str:
        return self._zoo.install(self._tenant, model, **kw)


class ModelZoo:
    """Named-tenant control plane in front of the serving stack.

    ``batcher_kw`` are zoo-wide defaults for every tenant's
    :class:`~..serve.batcher.ContinuousBatcher` (a tenant's
    :class:`TenantQuota` overrides them per lane knob). One score cache
    is shared across all tenants — entries are tenant-partitioned by key
    prefix, so sharing is a memory win, never a leak (pinned by
    ``tests/test_cache.py``).
    """

    def __init__(
        self,
        *,
        default_tenant: str = DEFAULT_TENANT,
        resident_bytes: int | None = None,
        resident_models: int | None = None,
        prewarm: bool = True,
        cache=None,
        cache_enable: bool | None = None,
        retry_after_s: float = 0.25,
        drain_timeout_s: float = 5.0,
        **batcher_kw,
    ):
        from ..exec import config as exec_config

        self.default_tenant = self._valid_name(default_tenant)
        self.prewarm = prewarm
        self.retry_after_s = float(retry_after_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._batcher_kw = dict(batcher_kw)
        if cache is None and bool(
            exec_config.resolve("cache_enable", cache_enable)
        ):
            from ..serve.cache import ScoreCache

            cache = ScoreCache()
        self.cache = cache
        self._entries: dict[str, TenantEntry] = {}
        self._residency = ResidencyManager(
            max_bytes=resident_bytes, max_models=resident_models
        )
        self._lock = threading.Lock()
        # Runtimes detached by _evict_locked, awaiting their (possibly
        # slow) drain — torn down by _finish_evictions AFTER the
        # control-plane lock drops, so a page-out never stalls routing.
        self._evicting: list[TenantRuntime] = []
        self._closed = False
        log_event(
            _log, "zoo.start", default_tenant=self.default_tenant,
            max_bytes=self._residency.max_bytes,
            max_models=self._residency.max_models,
        )

    # ------------------------------------------------------- registration ---
    @staticmethod
    def _valid_name(name) -> str:
        if not isinstance(name, str) or not _TENANT_RE.fullmatch(name):
            raise UnknownTenant(
                f"tenant names are [a-z0-9_]{{1,64}}, got {name!r}"
            )
        return name

    def add_tenant(
        self,
        name: str,
        model=None,
        *,
        path: str | None = None,
        version: str = "v1",
        quota: TenantQuota | None = None,
        resident: bool = False,
    ) -> TenantEntry:
        """Register a tenant from a fitted model object or a persisted
        model directory (``path`` tenants page fully to disk: eviction
        can drop even the host-side model and reload it cold). Nothing
        is built until the tenant's first request — or now, with
        ``resident=True`` (pre-warming off the serving path)."""
        name = self._valid_name(name)
        if (model is None) == (path is None):
            raise ValueError("pass exactly one of model or path")
        m = _VERSION_RE.fullmatch(version)
        seq = int(m.group(1)) if m else 1
        entry = TenantEntry(name, model, version, seq, path, quota)
        with self._lock:
            if self._closed:
                raise ZooError("model zoo is closed")
            if name in self._entries:
                raise ValueError(f"tenant {name!r} already registered")
            self._entries[name] = entry
        REGISTRY.incr("zoo/tenants_added")
        log_event(
            _log, "zoo.tenant_added", tenant=name, version=version,
            source=path, resident=resident,
        )
        if resident:
            self._load(entry)
        return entry

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def version(self, tenant: str | None = None) -> str:
        return self._entry(tenant).version

    def _entry(self, tenant: str | None) -> TenantEntry:
        name = self.default_tenant if tenant is None else tenant
        if not isinstance(name, str):
            raise UnknownTenant(f'"tenant" must be a string, got {name!r}')
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise UnknownTenant(f"unknown tenant {name!r}")
        return entry

    # ------------------------------------------------------------ routing ---
    def runtime(self, tenant: str | None = None) -> tuple[TenantEntry, TenantRuntime]:
        """Resolve a request's tenant (None ⇒ the default tenant) to its
        live runtime, cold-loading if paged out. The returned runtime is
        guaranteed to BE the named tenant's — a bookkeeping mismatch is
        rejected and counted (``zoo/cross_tenant_rejects``), never
        answered from the wrong model."""
        entry = self._entry(tenant)
        with self._lock:
            rt = entry.runtime
            if rt is not None:
                self._guard_tenant(entry, rt)
                self._residency.touch(entry.name)
                return entry, rt
        rt = self._load(entry)
        return entry, rt

    @staticmethod
    def _guard_tenant(entry: TenantEntry, rt: TenantRuntime) -> None:
        if rt.tenant != entry.name:
            REGISTRY.incr("zoo/cross_tenant_rejects")
            log_event(
                _log, "zoo.cross_tenant_reject", tenant=entry.name,
                runtime=rt.tenant,
            )
            raise ZooError(
                f"tenant {entry.name!r} resolved runtime {rt.tenant!r}; "
                "rejecting rather than answering from the wrong tenant"
            )

    # ---------------------------------------------------------- cold load ---
    def _load(self, entry: TenantEntry) -> TenantRuntime:
        """Page one tenant in: build + pre-warm its runner entirely off
        the serving path (the registry ``prepare``/``commit`` split),
        publish the runtime, then page out LRU tenants over budget."""
        with entry._load_lock:
            return self._load_locked(entry)

    def _load_locked(self, entry: TenantEntry) -> TenantRuntime:
        """:meth:`_load` body; the caller holds ``entry._load_lock``."""
        with self._lock:
            if self._closed:
                # ServeClosed, not ZooError: a request racing server
                # shutdown must surface as the retryable 503 the rest
                # of the serving stack speaks, never a 500.
                raise ServeClosed("model zoo is closed")
            rt = entry.runtime
            if rt is not None:  # raced: another caller loaded it
                self._guard_tenant(entry, rt)
                self._residency.touch(entry.name)
                return rt
        t0 = time.perf_counter()
        try:
            faults.inject("zoo/load")
            model = entry.model
            if model is None:
                # Cold-start plane: a baked artifact for the tenant's
                # source tree turns the parquet parse into an mmap page-in
                # — and N tenants baked into one artifact dir share page
                # cache across repeated load/evict cycles
                # (docs/PERFORMANCE.md §12). Parquet stays the fallback.
                from ..artifacts.bake import maybe_load_baked

                model = maybe_load_baked(entry.source)
            if model is None:
                from ..models.estimator import LanguageDetectorModel

                model = LanguageDetectorModel.load(entry.source)
            registry = ModelRegistry(
                drain_timeout_s=self.drain_timeout_s
            )
            prepared = registry.prepare(
                model, version=entry.version, prewarm=self.prewarm,
                source=entry.source, metadata={"tenant": entry.name},
            )
            registry.commit(prepared)
        except Exception as e:
            REGISTRY.incr("zoo/load_errors")
            log_event(
                _log, "zoo.load_failed", tenant=entry.name,
                error=repr(e),
            )
            raise TenantLoadShed(
                entry.name, retry_after_s=self.retry_after_s
            ) from e
        batcher = self._make_batcher(entry, registry)
        rt = TenantRuntime(
            entry.name, registry, batcher,
            _table_bytes(prepared.runner),
        )
        with self._lock:
            entry.model = model
            entry.runtime = rt
            entry.loads += 1
            evicted = self._residency.admit(
                entry.name, rt.table_bytes,
                evictable=self._evictable_locked,
                evict=self._evict_locked,
            )
        self._finish_evictions()
        REGISTRY.incr("zoo/cold_loads")
        # Latency next to the count: the cold-start wall per tenant, a
        # tracked regression metric (telemetry/compare diffs its p50).
        REGISTRY.observe("zoo/cold_load_s", time.perf_counter() - t0)
        log_event(
            _log, "zoo.cold_load", tenant=entry.name,
            version=entry.version, loads=entry.loads,
            table_bytes=rt.table_bytes, evicted=evicted,
            wall_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        return rt

    def _make_batcher(self, entry: TenantEntry, registry) -> ContinuousBatcher:
        q = entry.quota
        kw = dict(self._batcher_kw)
        for knob, value in (
            ("max_rows", q.max_rows),
            ("max_wait_ms", q.max_wait_ms),
            ("max_queue_rows", q.max_queue_rows),
            ("slo_ms", q.slo_ms),
        ):
            if value is not None:
                kw[knob] = value
        # The shared cache is passed explicitly (tenant-partitioned by
        # the batcher's key scope); cache_enable=False keeps a cache-less
        # zoo from growing one private cache per tenant.
        return ContinuousBatcher(
            registry, cache=self.cache, cache_enable=False,
            tenant=entry.name, name=f"zoo-{entry.name}", **kw,
        )

    # ------------------------------------------------------------ paging ----
    def _evictable_locked(self, name: str) -> bool:
        entry = self._entries.get(name)
        rt = entry.runtime if entry is not None else None
        if rt is None:
            return False
        stats = rt.batcher.stats()
        if stats["queued_rows"] or stats["inflight_rows"]:
            return False
        return not rt.registry.busy()

    def _evict_locked(self, name: str) -> None:
        """Detach one tenant under the control-plane lock (cheap
        pointer work only). The batcher drain — which can run a whole
        raced-in dispatch — happens in :meth:`_finish_evictions` after
        the lock drops, so one page-out never stalls every other
        tenant's routing."""
        entry = self._entries[name]
        rt = entry.runtime
        entry.runtime = None
        if rt is None:
            return
        self._evicting.append(rt)
        model = entry.model
        if model is not None and hasattr(model, "_runner"):
            # The registry's runner refs die with rt; the model's cached
            # runner is the last pin on the device tables.
            model._runner = None
        if entry.source is not None and not entry.dirty:
            entry.model = None  # disk-backed and clean: page out fully

    def _finish_evictions(self) -> None:
        """Drain + tear down detached runtimes outside the zoo lock
        (idle by the evictable check; the drain still answers — never
        drops — an admit that raced the detach)."""
        while True:
            with self._lock:
                if not self._evicting:
                    return
                rt = self._evicting.pop()
            rt.batcher.close(drain=True)

    def preload(self, tenants=None) -> list[str]:
        """Make the named tenants (default: all) resident ahead of
        traffic — the operator-facing pre-warm, off the serving path."""
        names = list(tenants) if tenants is not None else self.tenants()
        loaded = []
        for name in names:
            entry = self._entry(name)
            if entry.runtime is None:
                self._load(entry)
                loaded.append(name)
        return loaded

    def resident(self) -> dict[str, int]:
        with self._lock:
            return self._residency.resident()

    # ----------------------------------------------------------- installs ---
    def install(
        self,
        tenant: str | None,
        model,
        *,
        version: str | None = None,
        prewarm: bool | None = None,
        source: str | None = None,
        from_path: str | None = None,
        metadata: dict | None = None,
    ) -> str:
        """Tenant-scoped hot-swap: install ``model`` as the tenant's new
        serving version. A resident tenant goes through its registry's
        pre-warmed atomic flip; a paged-out tenant just updates its
        paged state (the next cold load builds the new version
        directly). No other tenant's pointer moves.

        ``source`` is provenance (registry metadata); ``from_path``
        additionally asserts the model is bit-identical to that saved
        directory, so eviction may page the tenant fully back to disk.
        An in-memory install (refit) clears the on-disk source — the old
        path no longer describes what this tenant serves."""
        entry = self._entry(tenant)
        with entry._load_lock:
            seq = entry.seq + 1
            vname = version or f"v{seq}"
            meta = dict(metadata or {})
            meta.setdefault("tenant", entry.name)
            with self._lock:
                rt = entry.runtime
            if rt is not None:
                vname = rt.registry.install(
                    model,
                    version=vname,
                    prewarm=self.prewarm if prewarm is None else prewarm,
                    source=source,
                    metadata=meta,
                )
            with self._lock:
                entry.model = model
                entry.version = vname
                entry.source = from_path
                entry.dirty = from_path is None
                m = _VERSION_RE.fullmatch(vname)
                entry.seq = max(entry.seq, int(m.group(1))) if m else seq
                if rt is not None and entry.runtime is rt:
                    rt.table_bytes = _table_bytes(
                        rt.registry.peek().runner
                    )
                    self._residency.admit(
                        entry.name, rt.table_bytes,
                        evictable=self._evictable_locked,
                        evict=self._evict_locked,
                    )
            self._finish_evictions()
        REGISTRY.incr("zoo/installs")
        log_event(
            _log, "zoo.install", tenant=entry.name, version=vname,
            resident=rt is not None, source=source,
        )
        return vname

    def load(
        self, tenant: str | None, path: str, *, version: str | None = None
    ) -> str:
        """Install-from-disk for one tenant (the zoo's ``/admin/swap``)."""
        from ..models.estimator import LanguageDetectorModel

        return self.install(
            tenant, LanguageDetectorModel.load(path),
            version=version, source=str(path), from_path=str(path),
        )

    def rollback(self, tenant: str | None = None) -> str:
        """Tenant-scoped rollback through the tenant's live registry
        (requires residency: history does not survive paging). Serialized
        against installs/loads on the same tenant, and the paged state is
        resynced from the registry — model AND version — so an eviction
        right after a rollback reloads exactly what the registry served."""
        entry = self._entry(tenant)
        with entry._load_lock:
            with self._lock:
                rt = entry.runtime
            if rt is None:
                rt = self._load_locked(entry)
            version = rt.registry.rollback()
            served = rt.registry.peek()
            with self._lock:
                entry.version = version
                entry.model = served.model
                entry.source = None
                entry.dirty = True
                m = _VERSION_RE.fullmatch(version)
                if m:
                    entry.seq = max(entry.seq, int(m.group(1)))
        return version

    def auto_refit(self, tenant: str | None, estimator, **kw):
        """A continuous-learning driver scoped to ONE tenant: its
        install proxy routes every refit hot-swap through
        :meth:`install` for that tenant's registry only
        (docs/SERVING.md §7a, §12)."""
        from ..stream.refit import AutoRefit

        entry = self._entry(tenant)
        kw.setdefault("source_name", f"auto-refit:{entry.name}")
        return AutoRefit(
            estimator, _TenantInstaller(self, entry.name),
            tenant=entry.name, **kw,
        )

    # ------------------------------------------------------------- status ---
    def healthz(self) -> dict:
        with self._lock:
            entries = list(self._entries.values())
            residency = self._residency.describe()
        tenants = {}
        for entry in entries:
            block = entry.describe()
            rt = entry.runtime
            block["batcher"] = rt.batcher.stats() if rt is not None else None
            tenants[entry.name] = block
        return {
            "default_tenant": self.default_tenant,
            "tenants": tenants,
            "residency": residency,
        }

    def varz(self) -> dict:
        out = self.healthz()
        out["cache"] = None if self.cache is None else self.cache.stats()
        for name, block in out["tenants"].items():
            entry = self._entries.get(name)
            rt = entry.runtime if entry is not None else None
            block["versions"] = (
                rt.registry.versions() if rt is not None else None
            )
        return out

    # ---------------------------------------------------------- lifecycle ---
    def close(self, drain: bool = True) -> None:
        """Tear down every resident tenant. With ``drain`` (default) no
        accepted request is dropped; ``drain=False`` is the abrupt path —
        queued requests fail explicitly with ServeClosed, never hang."""
        with self._lock:
            self._closed = True
            names = list(self._entries)
        for name in names:
            entry = self._entries[name]
            with entry._load_lock:
                with self._lock:
                    rt = entry.runtime
                    entry.runtime = None
                    self._residency.drop(name)
                if rt is not None:
                    rt.batcher.close(drain=drain)
        log_event(_log, "zoo.close", tenants=len(names), drained=drain)
