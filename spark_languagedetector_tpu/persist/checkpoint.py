"""Streaming resume tokens: tiny atomic JSON checkpoints.

The streaming engine commits one record per sunk batch (the TPU-native
analog of Structured Streaming's offset log): ``{"committed": N, ...}``
means source batches ``[0, N)`` are fully sunk and must not be re-emitted
after a restart. Writes are write-temp-then-rename atomic, so a process
killed mid-commit leaves either the previous checkpoint or the new one —
never a torn file. (Same-directory rename: POSIX guarantees atomicity
only within a filesystem.)
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

CHECKPOINT_VERSION = 1


def save_checkpoint(path: str | Path, state: dict) -> None:
    """Atomically persist ``state`` (plus version + timestamp) to ``path``."""
    target = Path(path)
    target.parent.mkdir(parents=True, exist_ok=True)
    record = {"version": CHECKPOINT_VERSION, "ts": time.time(), **state}
    tmp = target.with_name(target.name + ".tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(record, default=str) + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, target)


def load_checkpoint(path: str | Path) -> dict | None:
    """Read a checkpoint; ``None`` when absent.

    A malformed file raises: the atomic writer cannot produce one, so
    corruption means something external touched the resume token — losing
    exactly-once silently would be worse than failing loudly.
    """
    target = Path(path)
    if not target.exists():
        return None
    text = target.read_text(encoding="utf-8").strip()
    if not text:
        raise ValueError(f"empty checkpoint file {target}")
    record = json.loads(text.splitlines()[0])
    if not isinstance(record, dict):
        raise ValueError(f"checkpoint {target} is not a JSON object")
    return record
