"""persist subpackage."""
