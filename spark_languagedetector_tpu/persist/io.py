"""Model persistence: parquet layout compatible with the reference's format.

The reference writes (``/root/reference/src/main/.../LanguageDetectorModel.scala:27-105``):

    <path>/metadata/            Spark DefaultParamsWriter JSON
    <path>/probabilities/       parquet of (gram bytes, weight vector)
    <path>/supportedLanguages/  parquet of language strings
    <path>/gramLengths/         parquet of ints

This writer produces the same directory layout with pyarrow parquet files
(readable by Spark), plus a ``metadata/part-00000`` JSON line carrying the
class name, uid, params, and the TPU-native extras the reference doesn't have
(vocab mode, hash bits, weight mode). Hashed profiles have no gram bytes, so
``probabilities/`` stores bucket ids; the metadata records which flavor was
written and the reader reconstructs accordingly.
"""

from __future__ import annotations

import json
import shutil
import time
from pathlib import Path

import numpy as np

from ..models.profile import GramProfile
from ..ops.vocab import EXACT, HASHED, VocabSpec
from ..utils.logging import get_logger, log_event

_log = get_logger("persist.io")

_CLASS_NAME = "spark_languagedetector_tpu.models.estimator.LanguageDetectorModel"


def _write_parquet(path: Path, table) -> None:
    import pyarrow.parquet as pq

    path.mkdir(parents=True, exist_ok=True)
    pq.write_table(table, path / "part-00000.parquet")


def _read_parquet(path: Path):
    import pyarrow.parquet as pq

    files = sorted(path.glob("*.parquet"))
    if not files:
        raise FileNotFoundError(f"no parquet files under {path}")
    import pyarrow as pa

    return pa.concat_tables([pq.read_table(f) for f in files])


def save_model(
    path: str | Path,
    profile: GramProfile,
    uid: str,
    params: dict,
    overwrite: bool = True,
) -> None:
    """Write the model directory (SaveMode.Overwrite semantics)."""
    import pyarrow as pa

    root = Path(path)
    if root.exists():
        if not overwrite:
            raise FileExistsError(f"{root} already exists")
        shutil.rmtree(root)
    root.mkdir(parents=True)

    # metadata/ — single JSON line, Spark DefaultParamsWriter-style fields.
    meta = {
        "class": _CLASS_NAME,
        "timestamp": int(time.time() * 1000),
        "uid": uid,
        "paramMap": params,
        "vocab": {
            "mode": profile.spec.mode,
            "gramLengths": list(profile.spec.gram_lengths),
            "hashBits": profile.spec.hash_bits,
            "hashScheme": profile.spec.hash_scheme,
        },
        "languages": list(profile.languages),
    }
    meta_dir = root / "metadata"
    meta_dir.mkdir()
    (meta_dir / "part-00000").write_text(json.dumps(meta) + "\n")

    # probabilities/ — gram bytes (exact) or bucket ids (hashed) + weights.
    if profile.spec.mode == EXACT:
        grams = [profile.spec.id_to_gram(int(i)) for i in profile.ids]
        prob_table = pa.table(
            {
                "gram": pa.array(grams, type=pa.binary()),
                "probabilities": pa.array(
                    [row.tolist() for row in profile.weights],
                    type=pa.list_(pa.float64()),
                ),
            }
        )
    else:
        compact = profile.compacted()
        prob_table = pa.table(
            {
                "bucket": pa.array(compact.ids.tolist(), type=pa.int64()),
                "probabilities": pa.array(
                    [row.tolist() for row in compact.weights],
                    type=pa.list_(pa.float64()),
                ),
            }
        )
    _write_parquet(root / "probabilities", prob_table)

    # supportedLanguages/ and gramLengths/ — mirroring the reference layout.
    _write_parquet(
        root / "supportedLanguages",
        pa.table({"value": pa.array(list(profile.languages), type=pa.string())}),
    )
    _write_parquet(
        root / "gramLengths",
        pa.table({"value": pa.array(list(profile.spec.gram_lengths), type=pa.int32())}),
    )
    log_event(_log, "model.saved", path=str(root), grams=profile.num_grams)


def load_model(path: str | Path) -> tuple[GramProfile, str, dict]:
    """Read a model directory → (profile, uid, params).

    Checks the stored class name like the reference reader
    (LanguageDetectorModel.scala:66,72).
    """
    root = Path(path)
    meta_file = root / "metadata" / "part-00000"
    meta = json.loads(meta_file.read_text().splitlines()[0])
    if meta.get("class") != _CLASS_NAME:
        raise ValueError(
            f"metadata class mismatch: expected {_CLASS_NAME}, got {meta.get('class')}"
        )

    languages = tuple(
        _read_parquet(root / "supportedLanguages")["value"].to_pylist()
    )
    gram_lengths = tuple(
        int(v) for v in _read_parquet(root / "gramLengths")["value"].to_pylist()
    )
    vocab_meta = meta.get("vocab", {})
    mode = vocab_meta.get("mode", EXACT)
    # Models persisted before bucket schemes existed used pure FNV-1a; the
    # scheme must round-trip exactly or every hashed id changes meaning.
    spec = VocabSpec(
        mode,
        gram_lengths,
        hash_bits=vocab_meta.get("hashBits", 20),
        hash_scheme=vocab_meta.get("hashScheme", "fnv1a"),
    )

    prob = _read_parquet(root / "probabilities")
    weights_rows = prob["probabilities"].to_pylist()
    L = len(languages)
    if mode == EXACT:
        grams = prob["gram"].to_pylist()
        pairs = sorted(
            ((spec.gram_to_id(bytes(g)), np.asarray(w, dtype=np.float64))
             for g, w in zip(grams, weights_rows)),
            key=lambda p: p[0],
        )
        ids = np.asarray([p[0] for p in pairs], dtype=np.int64)
        weights = (
            np.stack([p[1] for p in pairs])
            if pairs
            else np.zeros((0, L), dtype=np.float64)
        )
    else:
        pairs = sorted(
            ((int(b), np.asarray(w, dtype=np.float64))
             for b, w in zip(prob["bucket"].to_pylist(), weights_rows)),
            key=lambda p: p[0],
        )
        ids = np.asarray([p[0] for p in pairs], dtype=np.int64)
        weights = (
            np.stack([p[1] for p in pairs])
            if pairs
            else np.zeros((0, L), dtype=np.float64)
        )

    profile = GramProfile(spec=spec, languages=languages, ids=ids, weights=weights)
    return profile, meta["uid"], meta.get("paramMap", {})


def save_gram_dump(path: str | Path, profile: GramProfile) -> None:
    """The reference's ``saveGramsToHDFS`` artifact
    (LanguageDetector.scala:167-171): the fitted gram-probability dataset as
    parquet, overwrite mode."""
    import pyarrow as pa

    root = Path(path)
    if root.exists():
        shutil.rmtree(root)
    if profile.spec.mode == EXACT:
        grams = [profile.spec.id_to_gram(int(i)) for i in profile.ids]
        table = pa.table(
            {
                "gram": pa.array(grams, type=pa.binary()),
                "probabilities": pa.array(
                    [row.tolist() for row in profile.weights],
                    type=pa.list_(pa.float64()),
                ),
            }
        )
    else:
        compact = profile.compacted()
        table = pa.table(
            {
                "bucket": pa.array(compact.ids.tolist(), type=pa.int64()),
                "probabilities": pa.array(
                    [row.tolist() for row in compact.weights],
                    type=pa.list_(pa.float64()),
                ),
            }
        )
    _write_parquet(root, table)
    log_event(_log, "grams.saved", path=str(root))
