"""Model persistence: parquet layout compatible with the reference's format.

The reference writes (``/root/reference/src/main/.../LanguageDetectorModel.scala:27-105``):

    <path>/metadata/            Spark DefaultParamsWriter JSON
    <path>/probabilities/       parquet of (gram bytes, weight vector)
    <path>/supportedLanguages/  parquet of language strings
    <path>/gramLengths/         parquet of ints

This writer produces the same directory layout with pyarrow parquet files
(readable by Spark), plus a ``metadata/part-00000`` JSON line carrying the
class name, uid, params, and the TPU-native extras the reference doesn't have
(vocab mode, hash bits, weight mode). Hashed profiles have no gram bytes, so
``probabilities/`` stores bucket ids; the metadata records which flavor was
written and the reader reconstructs accordingly.

Cross-implementation interop: the reference's writer emits ``probabilities/``
as a Spark ``Dataset[(Seq[Byte], Array[Double])]`` — tuple columns ``_1``
(list<int8>, signed JVM bytes) and ``_2`` (list<double>)
(LanguageDetectorModel.scala:37-43; reader :73-78) — under the Scala class
name. :func:`load_model` reads BOTH layouts (column names decide), and
``save_model(..., layout="reference")`` writes the Scala layout so a model
trained here loads in the Spark implementation (exact vocabs only — the
reference has no hashed mode).

Model/pipeline persistence lives here; the streaming engine's per-batch
resume tokens (the Structured-Streaming-offset-log analog) are the
sibling :mod:`.checkpoint` module — tiny atomic JSON, not parquet.
"""

from __future__ import annotations

import json
import shutil
import time
from pathlib import Path

import numpy as np

from ..models.profile import GramProfile
from ..ops.vocab import EXACT, HASHED, VocabSpec
from ..utils.logging import get_logger, log_event

_log = get_logger("persist.io")

_CLASS_NAME = "spark_languagedetector_tpu.models.estimator.LanguageDetectorModel"
# The reference implementation's writer records its JVM class
# (LanguageDetectorModel.scala:66 — DefaultParamsReader checks it on load).
_SPARK_CLASS_NAME = (
    "org.apache.spark.ml.feature.languagedetection.LanguageDetectorModel"
)


def _write_parquet(path: Path, table) -> None:
    import pyarrow.parquet as pq

    path.mkdir(parents=True, exist_ok=True)
    pq.write_table(table, path / "part-00000.parquet")


def _read_parquet(path: Path):
    import pyarrow.parquet as pq

    files = sorted(path.glob("*.parquet"))
    if not files:
        raise FileNotFoundError(f"no parquet files under {path}")
    import pyarrow as pa

    return pa.concat_tables([pq.read_table(f) for f in files])


def save_model(
    path: str | Path,
    profile: GramProfile,
    uid: str,
    params: dict,
    overwrite: bool = True,
    layout: str = "native",
    quantize: str | None = None,
    calibration: dict | None = None,
) -> None:
    """Write the model directory (SaveMode.Overwrite semantics).

    The write is crash-atomic the same way :func:`save_fit_state` and
    ``api.pipeline`` saves are: the tree is built under a temp sibling and
    swapped in with renames, so a process killed mid-save leaves either
    the previous model or the new one at ``path`` — never a torn tree
    (segmentation hot-swaps load models this writer produced mid-traffic,
    docs/SEGMENTATION.md).

    ``layout="reference"`` writes the Scala implementation's exact on-disk
    shape — tuple-column probabilities parquet under the JVM class name,
    paramMap limited to the params the reference model declares
    (HasInputCol/HasOutputCol) — so the Spark reader can load it. Exact
    vocabs only: the reference has no hashed mode to round-trip into.

    ``quantize`` ('int8' | 'int16') stores the weight matrix quantized:
    integer parquet columns plus per-language f32 scales in the metadata
    (``models.profile.quantize_weights``). A lossy codec — the loader
    reconstructs ``q * scale`` f32 weights — but a fixed point of
    quantize∘dequantize, so a model served through the fused quantized
    strategy round-trips to bit-identical quantized scores, at 4x/2x less
    disk than float64 rows. Native layout only.

    ``calibration`` is the segmentation temperature state
    (``segment.calibrate.Calibration.to_dict()``): one float per language
    plus the held-out fit provenance, embedded in the metadata JSON so
    temperatures and profile commit atomically together. JSON ``repr``
    round-trips doubles exactly, so the loaded temperatures — and
    therefore the calibration content version the serve cache keys on —
    are bit-identical to the saved ones. Reference layout has nowhere to
    put it: the state is dropped with a logged event, and the loaded
    model serves segmentation uncalibrated with an explicit
    ``calibrated: false`` flag, never silently wrong.
    """
    import os

    import pyarrow as pa

    if layout not in ("native", "reference"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "reference" and profile.spec.mode != EXACT:
        raise ValueError(
            "layout='reference' requires an exact vocab — the reference "
            "implementation stores gram bytes and has no hashed mode"
        )
    if quantize is not None:
        from ..models.profile import QUANT_DTYPES

        if quantize not in QUANT_DTYPES:
            raise ValueError(
                f"unknown quantize dtype {quantize!r}; expected one of "
                f"{tuple(QUANT_DTYPES)}"
            )
        if layout == "reference":
            raise ValueError(
                "quantize is a native-layout extension — the reference "
                "format stores float64 rows only"
            )
    root = Path(path)
    if root.exists() and not overwrite:
        raise FileExistsError(f"{root} already exists")
    if calibration is not None and layout == "reference":
        log_event(
            _log, "model.calibration_dropped", path=str(root),
            reason="reference layout has no calibration field; the loaded "
            "model serves segmentation with calibrated=false provenance",
        )
        calibration = None
    # Build the whole tree under a temp sibling; the swap at the end is
    # the only destructive step.
    tmp = root.parent / f".{root.name}.tmp.{os.getpid()}"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)

    # metadata/ — single JSON line, Spark DefaultParamsWriter-style fields.
    if layout == "reference":
        # Flatten our nested Params metadata to Spark's flat paramMap,
        # restricted to params the reference model declares
        # (HasInputCol/HasOutputCol — LanguageDetectorModel.scala:183-184).
        flat = {
            **params.get("defaultParams", {}),
            **params.get("params", {}),
        }
        meta = {
            "class": _SPARK_CLASS_NAME,
            "timestamp": int(time.time() * 1000),
            "sparkVersion": "2.2.0",
            "uid": uid,
            "paramMap": {
                k: v for k, v in flat.items()
                if k in ("inputCol", "outputCol")
            },
        }
    else:
        meta = {
            "class": _CLASS_NAME,
            "timestamp": int(time.time() * 1000),
            "uid": uid,
            "paramMap": params,
            "vocab": {
                "mode": profile.spec.mode,
                "gramLengths": list(profile.spec.gram_lengths),
                "hashBits": profile.spec.hash_bits,
                "hashScheme": profile.spec.hash_scheme,
            },
            "languages": list(profile.languages),
        }
        if calibration is not None:
            if len(calibration.get("temperatures", ())) != len(
                profile.languages
            ):
                raise ValueError(
                    "calibration covers "
                    f"{len(calibration.get('temperatures', ()))} languages, "
                    f"profile has {len(profile.languages)}"
                )
            meta["calibration"] = calibration
    # Quantized storage: the integer rows go into probabilities/, the
    # per-language scales (the other half of the codec) into metadata.
    # One compaction pass serves both the quantizer and the bucket/gram
    # columns below (a no-op for already-compact profiles; for the dense
    # hashed form it is a full-table scan worth doing once).
    try:
        compact = profile.compacted()
        quant_rows = None
        if quantize is not None:
            from ..models.profile import quantize_weights

            quant_rows, quant_scales = quantize_weights(
                compact.weights, quantize
            )
            meta["quantization"] = {
                "dtype": quantize,
                "scales": [float(s) for s in quant_scales],
            }
        meta_dir = tmp / "metadata"
        meta_dir.mkdir()
        (meta_dir / "part-00000").write_text(json.dumps(meta) + "\n")

        # probabilities/ — gram bytes (exact) or bucket ids (hashed) + weights.
        if layout == "reference":
            # Spark tuple encoding of Dataset[(Seq[Byte], Array[Double])]:
            # _1 = list<int8> (JVM bytes are signed), _2 = list<double>.
            grams = [profile.spec.id_to_gram(int(i)) for i in profile.ids]
            prob_table = pa.table(
                {
                    "_1": pa.array(
                        [
                            np.frombuffer(g, np.uint8).astype(np.int8).tolist()
                            for g in grams
                        ],
                        type=pa.list_(pa.int8()),
                    ),
                    "_2": pa.array(
                        [row.tolist() for row in profile.weights],
                        type=pa.list_(pa.float64()),
                    ),
                }
            )
        elif profile.spec.mode == EXACT:
            grams = [profile.spec.id_to_gram(int(i)) for i in profile.ids]
            rows = (
                quant_rows if quant_rows is not None else profile.weights
            )
            value_type = pa.int32() if quant_rows is not None else pa.float64()
            prob_table = pa.table(
                {
                    "gram": pa.array(grams, type=pa.binary()),
                    "probabilities": pa.array(
                        [row.tolist() for row in rows],
                        type=pa.list_(value_type),
                    ),
                }
            )
        else:
            rows = quant_rows if quant_rows is not None else compact.weights
            value_type = pa.int32() if quant_rows is not None else pa.float64()
            prob_table = pa.table(
                {
                    "bucket": pa.array(compact.ids.tolist(), type=pa.int64()),
                    "probabilities": pa.array(
                        [row.tolist() for row in rows],
                        type=pa.list_(value_type),
                    ),
                }
            )
        _write_parquet(tmp / "probabilities", prob_table)

        # supportedLanguages/ and gramLengths/ — mirroring the reference
        # layout.
        _write_parquet(
            tmp / "supportedLanguages",
            pa.table(
                {"value": pa.array(list(profile.languages), type=pa.string())}
            ),
        )
        _write_parquet(
            tmp / "gramLengths",
            pa.table(
                {
                    "value": pa.array(
                        list(profile.spec.gram_lengths), type=pa.int32()
                    )
                }
            ),
        )
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise

    # The two-rename swap (same protocol as save_fit_state): old root
    # renamed aside, tmp renamed in, failure restores the old root. A
    # crash between the renames leaves the complete tree in a sibling —
    # nothing here ever deletes the only good copy.
    backup = None
    if root.exists():
        backup = root.parent / f".{root.name}.old.{os.getpid()}"
        if backup.exists():
            shutil.rmtree(backup)
        os.replace(root, backup)
    try:
        os.replace(tmp, root)
    except BaseException:
        if backup is not None:
            os.replace(backup, root)
        raise
    if backup is not None:
        shutil.rmtree(backup)
    # A crashed EARLIER save (different pid) may have left .tmp/.old
    # siblings behind; with a good tree now at root they are garbage —
    # clean them so crashed saves don't leak model-sized trees
    # (save_fit_state does the same).
    for stale in list(root.parent.glob(f".{root.name}.tmp.*")) + list(
        root.parent.glob(f".{root.name}.old.*")
    ):
        shutil.rmtree(stale, ignore_errors=True)
    log_event(
        _log, "model.saved", path=str(root), grams=profile.num_grams,
        calibrated=calibration is not None,
    )
    # Cold-start plane (docs/PERFORMANCE.md §12): with LANGDETECT_BAKE_ON_SAVE
    # on, every successful native save also bakes the mmap-ready artifact —
    # same quantization codec, same calibration — so later cold loads page
    # in instead of parsing this parquet tree. The bake is an optimization
    # layered on a save that already committed: its failure is logged, never
    # raised.
    from ..exec import config as exec_config

    if layout == "native" and exec_config.resolve("bake_on_save"):
        from ..artifacts.bake import artifact_path_for, bake_artifact

        try:
            bake_artifact(
                artifact_path_for(root), profile, uid, params,
                calibration=calibration, quantize=quantize,
            )
        except Exception as e:
            log_event(
                _log, "model.bake_failed", path=str(root), error=repr(e)
            )


def load_model(path: str | Path) -> tuple[GramProfile, str, dict, dict | None]:
    """Read a model directory → (profile, uid, params, calibration).

    ``calibration`` is the segmentation temperature state saved with the
    model (``Calibration.to_dict()`` shape), or None for models saved
    without one — the loader never invents a calibration, so an
    uncalibrated model stays explicitly uncalibrated
    (docs/SEGMENTATION.md). Checks the stored class name like the
    reference reader (LanguageDetectorModel.scala:66,72).
    """
    root = Path(path)
    meta_file = root / "metadata" / "part-00000"
    meta = json.loads(meta_file.read_text().splitlines()[0])
    if meta.get("class") not in (_CLASS_NAME, _SPARK_CLASS_NAME):
        raise ValueError(
            f"metadata class mismatch: expected {_CLASS_NAME} or "
            f"{_SPARK_CLASS_NAME}, got {meta.get('class')}"
        )

    languages = tuple(
        _read_parquet(root / "supportedLanguages")["value"].to_pylist()
    )
    gram_lengths = tuple(
        int(v) for v in _read_parquet(root / "gramLengths")["value"].to_pylist()
    )
    vocab_meta = meta.get("vocab", {})
    mode = vocab_meta.get("mode", EXACT)
    # Models persisted before bucket schemes existed used pure FNV-1a; the
    # scheme must round-trip exactly or every hashed id changes meaning.
    spec = VocabSpec(
        mode,
        gram_lengths,
        hash_bits=vocab_meta.get("hashBits", 20),
        hash_scheme=vocab_meta.get("hashScheme", "fnv1a"),
    )

    prob = _read_parquet(root / "probabilities")
    L = len(languages)
    if "_1" in prob.column_names:
        # Reference tuple layout (Dataset[(Seq[Byte], Array[Double])]):
        # _1 holds signed JVM bytes — wrap back to raw gram bytes.
        if mode != EXACT:
            raise ValueError(
                "reference-layout probabilities imply an exact vocab, but "
                f"metadata says mode={mode!r}"
            )
        grams = [
            np.asarray(g, dtype=np.int8).astype(np.uint8).tobytes()
            for g in prob["_1"].to_pylist()
        ]
        weights_rows = prob["_2"].to_pylist()
    else:
        grams = None
        weights_rows = prob["probabilities"].to_pylist()
    if mode == EXACT:
        if grams is None:
            grams = prob["gram"].to_pylist()
        pairs = sorted(
            ((spec.gram_to_id(bytes(g)), np.asarray(w, dtype=np.float64))
             for g, w in zip(grams, weights_rows)),
            key=lambda p: p[0],
        )
        ids = np.asarray([p[0] for p in pairs], dtype=np.int64)
        weights = (
            np.stack([p[1] for p in pairs])
            if pairs
            else np.zeros((0, L), dtype=np.float64)
        )
    else:
        pairs = sorted(
            ((int(b), np.asarray(w, dtype=np.float64))
             for b, w in zip(prob["bucket"].to_pylist(), weights_rows)),
            key=lambda p: p[0],
        )
        ids = np.asarray([p[0] for p in pairs], dtype=np.int64)
        weights = (
            np.stack([p[1] for p in pairs])
            if pairs
            else np.zeros((0, L), dtype=np.float64)
        )

    quant_meta = meta.get("quantization")
    if quant_meta and len(weights):
        # Quantized storage codec: rows are exact integers (read back as
        # float64), scales per language. The float64 product q*scale is
        # exact, so the f32 device cast matches models.profile.
        # dequantize_weights bit-for-bit — and requantizing returns the
        # stored integers, making fused quantized scores save/load-stable.
        weights = weights * np.asarray(
            quant_meta["scales"], dtype=np.float64
        )

    profile = GramProfile(spec=spec, languages=languages, ids=ids, weights=weights)
    params = meta.get("paramMap", {})
    if meta.get("class") == _SPARK_CLASS_NAME:
        # Spark's DefaultParamsWriter stores explicitly-set params as a flat
        # name->value map; our Params metadata nests them under "params".
        params = {"params": params}
    return profile, meta["uid"], params, meta.get("calibration")


_FIT_STATE_CLASS = "spark_languagedetector_tpu.models.refit.FitAccumulator"
FIT_STATE_VERSION = 1


def save_fit_state(
    path: str | Path,
    *,
    spec: VocabSpec,
    languages,
    weight_mode: str,
    profile_size: int,
    train_encoding: str,
    label_col: str,
    input_col: str,
    batch_rows: int | None,
    committed: int,
    docs_seen: int,
    lang_docs,
    ids: np.ndarray,
    rows: np.ndarray,
) -> None:
    """Persist an incremental-fit count accumulator (the fit's sufficient
    statistic) as a checkpoint directory.

    Layout mirrors the model codec: ``metadata/part-00000`` one JSON line
    (spec, languages, weight mode, profile size, per-language doc coverage,
    and the RESUME TOKEN ``committed`` — the number of source batches whose
    counts this table already contains), plus ``counts/`` parquet of the
    NONZERO table rows (``id`` int64, ``counts`` list<int64> per language).
    Sparse row storage: a 2^20×176 table with a few hundred thousand
    occurring grams stores those rows, not the 738MB dense form.

    The write is crash-atomic the same way ``api.pipeline`` saves are: the
    whole tree is built under a temp sibling and swapped in with renames,
    so a process killed mid-checkpoint leaves either the previous
    accumulator state or the new one — never a torn directory. The token
    travels INSIDE the state (not a side file), so counts and token can
    never commit separately: a resumed stream replays exactly the batches
    the table does not contain (docs/SERVING.md §7).
    """
    import os

    import pyarrow as pa

    root = Path(path)
    meta = {
        "class": _FIT_STATE_CLASS,
        "version": FIT_STATE_VERSION,
        "timestamp": int(time.time() * 1000),
        "vocab": {
            "mode": spec.mode,
            "gramLengths": list(spec.gram_lengths),
            "hashBits": spec.hash_bits,
            "hashScheme": spec.hash_scheme,
        },
        "languages": list(languages),
        "weightMode": weight_mode,
        "profileSize": int(profile_size),
        # Part of the statistic, not plumbing: the same corpus under a
        # different text→bytes encoding counts different grams, so a
        # resumed accumulator must keep the encoding its counts were
        # built under.
        "trainEncoding": train_encoding,
        # Plumbing that must survive a restart all the same: a restored
        # accumulator keeps reading the columns (and micro-batch rows)
        # its updates were configured with.
        "labelCol": label_col,
        "inputCol": input_col,
        "fitBatchRows": batch_rows,
        "committed": int(committed),
        "docsSeen": int(docs_seen),
        "langDocs": [int(c) for c in lang_docs],
    }
    tmp = root.parent / f".{root.name}.tmp.{os.getpid()}"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    try:
        meta_dir = tmp / "metadata"
        meta_dir.mkdir()
        (meta_dir / "part-00000").write_text(json.dumps(meta) + "\n")
        # Numpy-native arrow columns: this codec runs once per STREAMED
        # batch (the auto-refit driver checkpoints after every consumed
        # batch), and round-tripping a few-hundred-thousand-row × L table
        # through Python lists would dominate the per-batch commit. The
        # flat values zero-copy; offsets are a cheap arange.
        ids_np = np.ascontiguousarray(np.asarray(ids, dtype=np.int64))
        rows_np = np.ascontiguousarray(np.asarray(rows, dtype=np.int64))
        n, L = rows_np.shape
        offsets = pa.array(np.arange(0, (n + 1) * L, L, dtype=np.int32))
        counts_col = pa.ListArray.from_arrays(
            offsets, pa.array(rows_np.reshape(-1))
        )
        _write_parquet(
            tmp / "counts",
            pa.table({"id": pa.array(ids_np), "counts": counts_col}),
        )
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    backup = None
    if root.exists():
        backup = root.parent / f".{root.name}.old.{os.getpid()}"
        if backup.exists():
            shutil.rmtree(backup)
        os.replace(root, backup)
    try:
        os.replace(tmp, root)
    except BaseException:
        if backup is not None:
            os.replace(backup, root)
        raise
    if backup is not None:
        shutil.rmtree(backup)
    # A crashed EARLIER run (different pid) may have left .tmp/.old
    # siblings behind; with a good state now at root they are garbage —
    # clean them so crashed runs don't leak checkpoint-sized trees.
    for stale in list(root.parent.glob(f".{root.name}.tmp.*")) + list(
        root.parent.glob(f".{root.name}.old.*")
    ):
        shutil.rmtree(stale, ignore_errors=True)
    log_event(
        _log, "fit_state.saved", path=str(root), committed=int(committed),
        nonzero_rows=int(len(ids)),
    )


def recover_fit_state(path: str | Path) -> bool:
    """Finish a checkpoint swap a crash interrupted; True when recovered.

    The save's two-rename swap has one unavoidable window (POSIX has no
    directory exchange): killed between "root renamed aside" and "tmp
    renamed in", the path holds NO state — the data lives complete in a
    ``.<name>.tmp.<pid>`` (new) or ``.<name>.old.<pid>`` (previous)
    sibling. When ``path`` is missing, this promotes the newest candidate
    (by mtime) that FULLY loads — a SIGKILL mid-build can leave a torn
    tmp whose metadata parses but whose counts parquet is missing or
    truncated, so a metadata check alone would promote garbage; full
    validation (:func:`load_fit_state`) is the guard. Other siblings are
    deleted only AFTER a candidate was successfully promoted, so a torn
    candidate can never cost a complete one. Call before checking
    existence of a resumable state (the auto-refit driver does). No-op
    when ``path`` exists.
    """
    import os

    root = Path(path)
    if root.exists():
        return False
    candidates = list(root.parent.glob(f".{root.name}.tmp.*")) + list(
        root.parent.glob(f".{root.name}.old.*")
    )
    candidates.sort(key=lambda p: p.stat().st_mtime, reverse=True)
    for cand in candidates:
        try:
            state = load_fit_state(cand)  # full validation, counts included
        except Exception:
            continue  # torn/foreign candidate: never promote it
        os.replace(cand, root)
        for stale in list(root.parent.glob(f".{root.name}.tmp.*")) + list(
            root.parent.glob(f".{root.name}.old.*")
        ):
            shutil.rmtree(stale, ignore_errors=True)
        log_event(
            _log, "fit_state.recovered", path=str(root), source=cand.name,
            committed=state["committed"],
        )
        return True
    return False


def load_fit_state(path: str | Path) -> dict:
    """Read a persisted fit accumulator → dict with the metadata fields of
    :func:`save_fit_state` plus ``spec`` (a reconstructed VocabSpec),
    ``ids`` (int64 [R]) and ``rows`` (int64 [R, L]) sparse count rows."""
    root = Path(path)
    meta = json.loads(
        (root / "metadata" / "part-00000").read_text().splitlines()[0]
    )
    if meta.get("class") != _FIT_STATE_CLASS:
        raise ValueError(
            f"metadata class mismatch: expected {_FIT_STATE_CLASS}, got "
            f"{meta.get('class')}"
        )
    vocab = meta["vocab"]
    spec = VocabSpec(
        vocab["mode"],
        tuple(int(n) for n in vocab["gramLengths"]),
        hash_bits=vocab.get("hashBits", 20),
        hash_scheme=vocab.get("hashScheme", "fnv1a"),
    )
    table = _read_parquet(root / "counts")
    L = len(meta["languages"])
    ids = table["id"].combine_chunks().to_numpy(
        zero_copy_only=False
    ).astype(np.int64, copy=False)
    counts_col = table["counts"].combine_chunks()
    flat = counts_col.flatten().to_numpy(zero_copy_only=False)
    if len(flat) != len(ids) * L:
        raise ValueError(
            f"count rows carry {len(flat)} values for {len(ids)} grams, "
            f"metadata says {L} languages"
        )
    rows = (
        flat.astype(np.int64, copy=False).reshape(len(ids), L)
        if len(ids)
        else np.zeros((0, L), dtype=np.int64)
    )
    return {
        "spec": spec,
        "languages": tuple(meta["languages"]),
        "weight_mode": meta["weightMode"],
        "profile_size": int(meta["profileSize"]),
        "train_encoding": meta.get("trainEncoding", "utf8"),
        "label_col": meta.get("labelCol", "lang"),
        "input_col": meta.get("inputCol", "fulltext"),
        "batch_rows": meta.get("fitBatchRows"),
        "committed": int(meta["committed"]),
        "docs_seen": int(meta["docsSeen"]),
        "lang_docs": [int(c) for c in meta["langDocs"]],
        "ids": ids,
        "rows": rows,
    }


def save_gram_dump(path: str | Path, profile: GramProfile) -> None:
    """The reference's ``saveGramsToHDFS`` artifact
    (LanguageDetector.scala:167-171): the fitted gram-probability dataset as
    parquet, overwrite mode."""
    import pyarrow as pa

    root = Path(path)
    if root.exists():
        shutil.rmtree(root)
    if profile.spec.mode == EXACT:
        grams = [profile.spec.id_to_gram(int(i)) for i in profile.ids]
        table = pa.table(
            {
                "gram": pa.array(grams, type=pa.binary()),
                "probabilities": pa.array(
                    [row.tolist() for row in profile.weights],
                    type=pa.list_(pa.float64()),
                ),
            }
        )
    else:
        compact = profile.compacted()
        table = pa.table(
            {
                "bucket": pa.array(compact.ids.tolist(), type=pa.int64()),
                "probabilities": pa.array(
                    [row.tolist() for row in compact.weights],
                    type=pa.list_(pa.float64()),
                ),
            }
        )
    _write_parquet(root, table)
    log_event(_log, "grams.saved", path=str(root))
