"""Continuous micro-batcher: concurrent small requests → coalesced dispatches.

Every entry point before this module was offline: ``BatchRunner.score``
takes one pre-assembled list, ``run_stream`` pulls from one source. Online
serving is the inverse shape — many concurrent callers, each with a handful
of documents, all wanting low latency. The pjit/TPUv4 serving lesson
(PAPERS.md: Yoo et al., arXiv:2204.06514) is that throughput lives or dies
on keeping one resident compiled program fed with coalesced batches on a
closed shape lattice. The runner already maintains that lattice (bucketed
[B, S] shapes, ragged transfers); this module supplies the admission queue
in front of it:

  * requests are admitted into priority lanes (``interactive`` ahead of
    ``bulk``) and coalesced into one ``BatchRunner.score``/``predict_ids``
    call by a single dispatcher thread — a flush fires when the queue
    reaches ``max_rows`` or the oldest admitted request has waited
    ``max_wait_ms`` (env ``LANGDETECT_SERVE_MAX_ROWS`` /
    ``LANGDETECT_SERVE_MAX_WAIT_MS``);
  * demux is deterministic: each request's rows come back as a contiguous
    slice of the coalesced result — the batcher adds no numeric step of
    its own, so responses are bit-identical to calling the runner
    directly with the same documents on every batch-geometry-stable
    strategy (``gather``/the runner's A/B reference — pinned by
    ``tests/test_serve.py``; matmul-based strategies can differ in the
    final f32 bit across coalesce geometries, the reduction-order class
    documented in ARCHITECTURE.md, with labels exact throughout);
  * backpressure is explicit: the queue is bounded (rows), an estimated
    wait past the SLO sheds, and breaker-open / degraded-ladder states
    shed the bulk lane — shed requests fail fast with
    :class:`ServeOverloaded` (the HTTP front end maps it to 503), never
    hang. The ``serve/admit`` fault site lets chaos plans force sheds
    deterministically.

Model hot-swap composes through the source: the dispatcher leases the
serving runner per dispatch (see :mod:`.registry`), so a swap lands
between dispatches and every request is answered by exactly one version.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..resilience import faults
from ..telemetry import REGISTRY, current_trace_id, new_trace_id, span, trace_request
from ..utils.logging import get_logger, log_event

_log = get_logger("serve.batcher")

# Priority lanes, drained in this order: a bulk backlog must never add
# queueing delay to an interactive request.
INTERACTIVE = "interactive"
BULK = "bulk"
LANES = (INTERACTIVE, BULK)

# Env knobs (docs/SERVING.md §3); explicit ctor args win.
MAX_WAIT_ENV = "LANGDETECT_SERVE_MAX_WAIT_MS"
MAX_ROWS_ENV = "LANGDETECT_SERVE_MAX_ROWS"
QUEUE_ROWS_ENV = "LANGDETECT_SERVE_QUEUE_ROWS"
SLO_MS_ENV = "LANGDETECT_SERVE_SLO_MS"

DEFAULT_MAX_WAIT_MS = 10.0
DEFAULT_MAX_ROWS = 256
DEFAULT_QUEUE_ROWS = 4096
DEFAULT_SLO_MS = 0.0  # 0 ⇒ estimated-wait shedding off


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class ServeOverloaded(ServeError):
    """Request shed at admission (queue full, SLO blown, degraded bulk,
    or an injected ``serve/admit`` fault). Maps to HTTP 503."""

    def __init__(self, message: str, *, reason: str = "overloaded",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class ServeDeadlineExceeded(ServeError):
    """The request's deadline passed while it was still queued — rejected
    explicitly instead of burning device time on a dead response. Maps to
    HTTP 504."""


class ServeClosed(ServeError):
    """Submitted to a batcher that has been closed."""


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default


@dataclass
class ServeResult:
    """One request's demuxed response.

    ``values`` is the request's contiguous slice of the coalesced result:
    float32 ``[N, L]`` scores, or int32 ``[N]`` argmax ids in label mode.
    """

    values: np.ndarray
    version: str
    trace_id: str
    queue_wait_s: float
    dispatch_s: float
    languages: tuple[str, ...] | None = None

    @property
    def scores(self) -> np.ndarray:
        return self.values

    @property
    def labels(self) -> list[str]:
        if self.languages is None:
            raise ServeError("serving source carries no language names")
        return [self.languages[int(i)] for i in self.values]


@dataclass
class _Request:
    docs: list[bytes]
    want_labels: bool
    priority: str
    deadline: float | None  # absolute time.monotonic()
    trace_id: str
    admitted_at: float
    future: Future = field(default_factory=Future)


class _StaticSource:
    """Adapter presenting a bare :class:`~..api.runner.BatchRunner` through
    the registry's lease protocol (version pinned to ``"v0"``)."""

    class _Entry:
        __slots__ = ("runner", "version", "languages", "model")

        def __init__(self, runner):
            self.runner = runner
            self.version = "v0"
            self.languages = None
            self.model = None

    def __init__(self, runner):
        self._entry = self._Entry(runner)

    def peek(self):
        return self._entry

    def lease(self):
        from contextlib import nullcontext

        return nullcontext(self._entry)


class ContinuousBatcher:
    """SLO-aware continuous batcher in front of a runner (or registry).

    ``source`` is either a :class:`~..api.runner.BatchRunner` or anything
    with the registry lease protocol (``peek()`` and ``lease()`` yielding
    an entry with ``runner``/``version``/``languages`` — see
    :class:`~.registry.ModelRegistry`). One dispatcher thread owns all
    device work, so concurrent callers never race the runner.
    """

    def __init__(
        self,
        source,
        *,
        max_wait_ms: float | None = None,
        max_rows: int | None = None,
        max_queue_rows: int | None = None,
        slo_ms: float | None = None,
        shed_bulk_when_degraded: bool = True,
        name: str = "serve",
    ):
        if not hasattr(source, "lease"):
            source = _StaticSource(source)
        self._source = source
        self.max_wait_s = (
            max_wait_ms if max_wait_ms is not None
            else _env_float(MAX_WAIT_ENV, DEFAULT_MAX_WAIT_MS)
        ) / 1000.0
        self.max_rows = int(
            max_rows if max_rows is not None
            else _env_float(MAX_ROWS_ENV, DEFAULT_MAX_ROWS)
        )
        self.max_queue_rows = int(
            max_queue_rows if max_queue_rows is not None
            else _env_float(QUEUE_ROWS_ENV, DEFAULT_QUEUE_ROWS)
        )
        self.slo_s = (
            slo_ms if slo_ms is not None
            else _env_float(SLO_MS_ENV, DEFAULT_SLO_MS)
        ) / 1000.0
        if self.max_rows < 1 or self.max_queue_rows < 1:
            raise ValueError("max_rows and max_queue_rows must be >= 1")
        self.shed_bulk_when_degraded = shed_bulk_when_degraded
        self.name = name
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._lanes: dict[str, deque[_Request]] = {p: deque() for p in LANES}
        self._queued_rows = 0
        self._inflight_rows = 0
        # Rows/s over recent dispatches (EMA): the estimated-wait shed
        # signal. Zero until the first dispatch lands.
        self._ema_rows_per_s = 0.0
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=f"{name}-batcher", daemon=True
        )
        self._thread.start()
        log_event(
            _log, "serve.batcher.start", max_wait_ms=self.max_wait_s * 1e3,
            max_rows=self.max_rows, max_queue_rows=self.max_queue_rows,
            slo_ms=self.slo_s * 1e3,
        )

    # ------------------------------------------------------- admission ------
    def submit(
        self,
        byte_docs: Sequence[bytes],
        *,
        priority: str = INTERACTIVE,
        want_labels: bool = False,
        deadline_ms: float | None = None,
        trace_id: str | None = None,
    ) -> Future:
        """Admit one request; returns a Future resolving to a
        :class:`ServeResult` (or raising the dispatch error).

        Raises :class:`ServeOverloaded` immediately when the request is
        shed — admission control fails fast so callers can retry
        elsewhere instead of queueing into a blown SLO.
        """
        if priority not in LANES:
            raise ValueError(
                f"unknown priority {priority!r}; expected one of {LANES}"
            )
        docs = list(byte_docs)
        # Chaos gate: an injected error here IS a shed — same counters,
        # same exception shape — so chaos plans exercise the rejection
        # path deterministically (docs/RESILIENCE.md §4).
        try:
            faults.inject("serve/admit")
        except faults.InjectedFault as e:
            self._count_shed(len(docs), "injected", priority)
            raise ServeOverloaded(
                "admission rejected (injected fault)", reason="injected",
                retry_after_s=self.max_wait_s,
            ) from e
        tid = trace_id or current_trace_id() or new_trace_id()
        if not docs:
            if self._closed:
                raise ServeClosed(f"batcher {self.name!r} is closed")
            # Zero-row requests never wake the row-counting dispatcher;
            # answer them at admission with the empty result the runner
            # itself would return (score([]) is [0, L]).
            entry = self._source.peek()
            L = getattr(getattr(entry, "runner", None), "weights", None)
            L = 0 if L is None else int(L.shape[1])
            fut: Future = Future()
            fut.set_result(ServeResult(
                values=(
                    np.zeros(0, np.int32) if want_labels
                    else np.zeros((0, L), np.float32)
                ),
                version=entry.version,
                trace_id=tid,
                queue_wait_s=0.0,
                dispatch_s=0.0,
                languages=getattr(entry, "languages", None),
            ))
            REGISTRY.incr("serve/admitted_requests")
            REGISTRY.incr("serve/requests")
            return fut
        now = time.monotonic()
        req = _Request(
            docs=docs,
            want_labels=want_labels,
            priority=priority,
            deadline=None if deadline_ms is None else now + deadline_ms / 1e3,
            trace_id=tid,
            admitted_at=now,
        )
        with self._cv:
            if self._closed:
                raise ServeClosed(f"batcher {self.name!r} is closed")
            reason, wait_s = self._shed_reason_locked(len(docs), priority)
            if reason is not None:
                self._count_shed(len(docs), reason, priority)
                raise ServeOverloaded(
                    f"request shed ({reason}): {self._queued_rows} rows "
                    f"queued, estimated wait {wait_s * 1e3:.1f}ms",
                    reason=reason,
                    retry_after_s=max(wait_s, self.max_wait_s),
                )
            self._lanes[priority].append(req)
            self._queued_rows += len(docs)
            self._set_queue_gauges_locked()
            self._cv.notify_all()
        REGISTRY.incr("serve/admitted_requests")
        return req.future

    def score(self, byte_docs: Sequence[bytes], **kw) -> np.ndarray:
        """Blocking convenience: admit + wait; float32 [N, L] scores."""
        return self.submit(byte_docs, **kw).result().values

    def predict_ids(self, byte_docs: Sequence[bytes], **kw) -> np.ndarray:
        """Blocking convenience: admit + wait; int32 [N] argmax ids."""
        return self.submit(byte_docs, want_labels=True, **kw).result().values

    def _shed_reason_locked(
        self, rows: int, priority: str
    ) -> tuple[str | None, float]:
        """(shed reason or None, estimated wait seconds). Caller holds
        the lock. Reject-newest: the request being admitted is the one
        shed — already-queued work is never evicted."""
        backlog = self._queued_rows + self._inflight_rows
        wait_s = (
            backlog / self._ema_rows_per_s if self._ema_rows_per_s > 0 else 0.0
        )
        if self._queued_rows + rows > self.max_queue_rows:
            return "queue_full", wait_s
        if self.slo_s > 0 and wait_s > self.slo_s:
            return "slo", wait_s
        if priority == BULK and self.shed_bulk_when_degraded:
            entry = self._source.peek()
            runner = getattr(entry, "runner", None)
            breaker = getattr(runner, "breaker", None)
            state = breaker.state if breaker is not None else "closed"
            if state == "open" or getattr(runner, "_degraded_mode", False):
                return "degraded", wait_s
        return None, wait_s

    def _count_shed(self, rows: int, reason: str, priority: str) -> None:
        REGISTRY.incr("serve/shed_requests")
        REGISTRY.incr("serve/shed_rows", rows)
        REGISTRY.incr(f"serve/shed_{reason}")
        log_event(
            _log, "serve.shed", reason=reason, rows=rows, priority=priority,
            queued_rows=self._queued_rows, trace_id=current_trace_id(),
        )

    def _set_queue_gauges_locked(self) -> None:
        depth = sum(len(lane) for lane in self._lanes.values())
        REGISTRY.set_gauge("langdetect_serve_queue_depth", depth)
        REGISTRY.set_gauge("langdetect_serve_queue_rows", self._queued_rows)

    # ------------------------------------------------------- dispatcher -----
    @staticmethod
    def _complete(req: _Request, result=None, error: Exception | None = None):
        """Resolve one request's future, tolerating caller-side cancels.

        A client may cancel() its pending future (its own timeout) while
        the request is queued; set_result on a cancelled future raises
        InvalidStateError, and an exception here would kill the one
        dispatcher thread and hang every later request — the worst
        possible failure mode for this module. Cancelled requests are
        simply dropped (their caller stopped listening)."""
        try:
            if error is not None:
                req.future.set_exception(error)
            else:
                req.future.set_result(result)
        except BaseException:
            REGISTRY.incr("serve/cancelled_requests")

    def _oldest_locked(self) -> float | None:
        ages = [
            lane[0].admitted_at for lane in self._lanes.values() if lane
        ]
        return min(ages) if ages else None

    def _take_locked(self) -> list[_Request]:
        """Pop one coalesced batch: interactive lane first, then bulk,
        whole requests only, until ``max_rows`` is reached (the first
        request is always taken, even when larger than ``max_rows``).
        All requests in a batch share one result mode — a mode flip at a
        lane front ends the batch there (it leads the next one), so the
        demux below stays a pure offset walk."""
        batch: list[_Request] = []
        rows = 0
        want_labels: bool | None = None
        for lane in LANES:
            q = self._lanes[lane]
            while q and (rows < self.max_rows or not batch):
                if want_labels is not None and q[0].want_labels != want_labels:
                    break
                req = q.popleft()
                want_labels = req.want_labels
                batch.append(req)
                rows += len(req.docs)
        self._queued_rows -= rows
        self._inflight_rows = rows
        self._set_queue_gauges_locked()
        return batch

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._queued_rows == 0 and not self._closed:
                    self._cv.wait()
                if self._queued_rows == 0 and self._closed:
                    return
                # Coalescing window: hold the flush until max_rows are
                # queued or the oldest request has waited max_wait — the
                # micro-batch analog of Nagle, bounded by the SLO knob.
                while self._queued_rows < self.max_rows:
                    oldest = self._oldest_locked()
                    if oldest is None:
                        break
                    remaining = oldest + self.max_wait_s - time.monotonic()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(remaining)
                if self._queued_rows == 0:
                    continue
                batch = self._take_locked()
            try:
                self._dispatch(batch)
            except Exception as e:  # safety net: the thread must survive
                log_event(_log, "serve.dispatcher_error", error=repr(e))
                for req in batch:
                    self._complete(req, error=ServeError(
                        f"internal dispatcher error: {e!r}"
                    ))
            finally:
                with self._cv:
                    self._inflight_rows = 0
                    self._cv.notify_all()

    def _dispatch(self, batch: list[_Request]) -> None:
        t_start = time.monotonic()
        live: list[_Request] = []
        for req in batch:
            if req.future.cancelled():
                # The caller gave up while the request was queued: don't
                # spend device time on a response nobody will read.
                REGISTRY.incr("serve/cancelled_requests")
            elif req.deadline is not None and t_start > req.deadline:
                REGISTRY.incr("serve/deadline_rejects")
                log_event(
                    _log, "serve.deadline", trace_id=req.trace_id,
                    rows=len(req.docs),
                    waited_ms=(t_start - req.admitted_at) * 1e3,
                )
                self._complete(req, error=ServeDeadlineExceeded(
                    f"deadline passed after {t_start - req.admitted_at:.3f}s "
                    "in queue"
                ))
            else:
                live.append(req)
        if not live:
            return
        rows = sum(len(r.docs) for r in live)
        docs = [d for r in live for d in r.docs]
        want_labels = live[0].want_labels
        REGISTRY.set_gauge("langdetect_serve_inflight_rows", rows)
        try:
            with self._source.lease() as entry:
                # The lead request's trace id is the dispatch's ambient
                # trace (the runner's score span joins it); every
                # coalesced request keeps its own id on its result and in
                # the serve.dispatch event, so one slow request is
                # greppable end to end.
                with trace_request(live[0].trace_id), span(
                    "serve/dispatch", rows=rows, requests=len(live),
                    version=entry.version, labels=want_labels,
                ):
                    t0 = time.perf_counter()
                    if want_labels:
                        out = entry.runner.predict_ids(docs)
                    else:
                        out = entry.runner.score(docs)
                    dispatch_s = time.perf_counter() - t0
        except Exception as e:
            REGISTRY.incr("serve/dispatch_errors")
            log_event(
                _log, "serve.dispatch_error", rows=rows,
                requests=len(live), error=repr(e),
            )
            for req in live:
                self._complete(req, error=e)
            return
        finally:
            REGISTRY.set_gauge("langdetect_serve_inflight_rows", 0)
        # Telemetry: the coalescing evidence (counter + per-dispatch
        # distribution) and the three per-request latency legs.
        REGISTRY.incr("serve/dispatches")
        REGISTRY.incr("serve/requests", len(live))
        REGISTRY.incr("serve/coalesced_rows", rows)
        REGISTRY.observe("serve/rows_per_dispatch", rows)
        REGISTRY.observe("serve/requests_per_dispatch", len(live))
        REGISTRY.observe("serve/dispatch_s", dispatch_s)
        if dispatch_s > 0:
            rate = rows / dispatch_s
            self._ema_rows_per_s = (
                rate if self._ema_rows_per_s == 0.0
                else 0.7 * self._ema_rows_per_s + 0.3 * rate
            )
        done = time.monotonic()
        off = 0
        for req in live:
            sub = np.array(out[off:off + len(req.docs)])
            off += len(req.docs)
            queue_wait_s = t_start - req.admitted_at
            REGISTRY.observe("serve/queue_wait_s", queue_wait_s)
            REGISTRY.observe("serve/total_s", done - req.admitted_at)
            self._complete(req, ServeResult(
                values=sub,
                version=entry.version,
                trace_id=req.trace_id,
                queue_wait_s=queue_wait_s,
                dispatch_s=dispatch_s,
                languages=getattr(entry, "languages", None),
            ))
        log_event(
            _log, "serve.dispatch", rows=rows, requests=len(live),
            version=entry.version, dispatch_s=round(dispatch_s, 6),
            trace_ids=[r.trace_id for r in live],
        )

    # ------------------------------------------------------------ admin -----
    def stats(self) -> dict:
        """Queue/backpressure snapshot for /healthz."""
        with self._lock:
            return {
                "queue_depth": sum(len(q) for q in self._lanes.values()),
                "queued_rows": self._queued_rows,
                "inflight_rows": self._inflight_rows,
                "ema_rows_per_s": round(self._ema_rows_per_s, 3),
                "max_rows": self.max_rows,
                "max_wait_ms": self.max_wait_s * 1e3,
                "max_queue_rows": self.max_queue_rows,
                "slo_ms": self.slo_s * 1e3,
                "closed": self._closed,
            }

    def close(self, drain: bool = True) -> None:
        """Stop admitting; by default drain queued requests first so no
        admitted request is ever dropped. With ``drain=False`` queued
        requests fail with :class:`ServeClosed` (still never a hang)."""
        with self._cv:
            self._closed = True
            if not drain:
                for lane in self._lanes.values():
                    while lane:
                        req = lane.popleft()
                        self._queued_rows -= len(req.docs)
                        self._complete(req, error=ServeClosed(
                            f"batcher {self.name!r} closed"
                        ))
                self._set_queue_gauges_locked()
            self._cv.notify_all()
        self._thread.join(timeout=30.0)
        log_event(_log, "serve.batcher.close", drained=drain)

    def __enter__(self) -> "ContinuousBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
