"""Continuous micro-batcher: concurrent small requests → coalesced dispatches.

Every entry point before this module was offline: ``BatchRunner.score``
takes one pre-assembled list, ``run_stream`` pulls from one source. Online
serving is the inverse shape — many concurrent callers, each with a handful
of documents, all wanting low latency. The pjit/TPUv4 serving lesson
(PAPERS.md: Yoo et al., arXiv:2204.06514) is that throughput lives or dies
on keeping one resident compiled program fed with coalesced batches on a
closed shape lattice. The runner already maintains that lattice (bucketed
[B, S] shapes, ragged transfers); this module supplies the admission queue
in front of it:

  * requests are admitted into priority lanes (``interactive`` ahead of
    ``bulk``) and coalesced into one ``BatchRunner.score``/``predict_ids``
    call by a single dispatcher thread — a flush fires when the queue
    reaches ``max_rows`` or the oldest admitted request has waited
    ``max_wait_ms`` (env ``LANGDETECT_SERVE_MAX_ROWS`` /
    ``LANGDETECT_SERVE_MAX_WAIT_MS``);
  * demux is deterministic: each request's rows come back as a contiguous
    slice of the coalesced result — the batcher adds no numeric step of
    its own, so responses are bit-identical to calling the runner
    directly with the same documents on every batch-geometry-stable
    strategy (``gather``/the runner's A/B reference — pinned by
    ``tests/test_serve.py``; matmul-based strategies can differ in the
    final f32 bit across coalesce geometries, the reduction-order class
    documented in ARCHITECTURE.md, with labels exact throughout);
  * backpressure is explicit: the queue is bounded (rows), an estimated
    wait past the SLO sheds, and breaker-open / degraded-ladder states
    shed the bulk lane — shed requests fail fast with
    :class:`ServeOverloaded` (the HTTP front end maps it to 503), never
    hang. The ``serve/admit`` fault site lets chaos plans force sheds
    deterministically.

Model hot-swap composes through the source: the dispatcher leases the
serving runner per dispatch (see :mod:`.registry`), so a swap lands
between dispatches and every request is answered by exactly one version.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..exec import config as exec_config
from ..exec.core import AdmissionQueue
from ..ops.encoding import UTF8
from ..resilience import faults
from ..telemetry import REGISTRY, current_trace_id, new_trace_id, span, trace_request
from ..utils.logging import get_logger, log_event

_log = get_logger("serve.batcher")

# Process-unique tokens for _StaticSource cache scoping (in-process cache,
# so a simple counter is sufficient identity).
_STATIC_UIDS = itertools.count()

# Priority lanes, drained in this order: a bulk backlog must never add
# queueing delay to an interactive request.
INTERACTIVE = "interactive"
BULK = "bulk"
LANES = (INTERACTIVE, BULK)

# Env knobs (docs/SERVING.md §3), resolved through exec.config: explicit
# ctor args win, then the env spelling, then the tuning profile's measured
# flush window (docs/PERFORMANCE.md §9), then the defaults. The names and
# defaults below are views onto the one authoritative table
# (exec.config.KNOBS) — kept as module constants for the import surface.
MAX_WAIT_ENV = exec_config.KNOBS["serve_max_wait_ms"].env
MAX_ROWS_ENV = exec_config.KNOBS["serve_max_rows"].env
QUEUE_ROWS_ENV = exec_config.KNOBS["serve_queue_rows"].env
SLO_MS_ENV = exec_config.KNOBS["serve_slo_ms"].env

DEFAULT_MAX_WAIT_MS = exec_config.KNOBS["serve_max_wait_ms"].default
DEFAULT_MAX_ROWS = exec_config.KNOBS["serve_max_rows"].default
DEFAULT_QUEUE_ROWS = exec_config.KNOBS["serve_queue_rows"].default
DEFAULT_SLO_MS = exec_config.KNOBS["serve_slo_ms"].default  # 0 ⇒ shed off


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class ServeOverloaded(ServeError):
    """Request shed at admission (queue full, SLO blown, degraded bulk,
    or an injected ``serve/admit`` fault). Maps to HTTP 503."""

    def __init__(self, message: str, *, reason: str = "overloaded",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class ServeDeadlineExceeded(ServeError):
    """The request's deadline passed while it was still queued — rejected
    explicitly instead of burning device time on a dead response. Maps to
    HTTP 504."""


class ServeClosed(ServeError):
    """Submitted to a batcher that has been closed."""


@dataclass
class ServeResult:
    """One request's demuxed response.

    ``values`` is the request's contiguous slice of the coalesced result:
    float32 ``[N, L]`` scores, int32 ``[N]`` argmax ids in label mode, or
    a list of N result dicts in segment mode (docs/SEGMENTATION.md).
    """

    values: np.ndarray
    version: str
    trace_id: str
    queue_wait_s: float
    dispatch_s: float
    languages: tuple[str, ...] | None = None
    # How many rows the dispatch that served this request coalesced in
    # total (its own included) — the server_timing block's attribution
    # for "my latency was someone else's batch".
    rows_coalesced: int = 0

    @property
    def scores(self) -> np.ndarray:
        return self.values

    @property
    def labels(self) -> list[str]:
        if self.languages is None:
            raise ServeError("serving source carries no language names")
        return [self.languages[int(i)] for i in self.values]

    @property
    def results(self) -> list[dict]:
        """Segment-mode results (the ``values`` list, named)."""
        return list(self.values)


@dataclass
class _Request:
    docs: list[bytes]
    want_labels: bool
    priority: str
    deadline: float | None  # absolute time.monotonic()
    trace_id: str
    admitted_at: float
    # Segment mode: the full option set of the decode (None ⇒ label/score
    # mode). Requests only coalesce with requests whose options MATCH —
    # the key below — so one dispatched batch is one (mode, knobs) pair.
    segment_opts: object | None = None
    future: Future = field(default_factory=Future)

    def batch_key(self):
        """The coalescing key: result mode + every segment knob. Two
        requests with different knobs can never share a dispatch (and,
        downstream, never share cache entries — docs/SERVING.md §11)."""
        return (
            self.want_labels,
            None if self.segment_opts is None else self.segment_opts.key(),
        )


class _StaticSource:
    """Adapter presenting a bare :class:`~..api.runner.BatchRunner` through
    the registry's lease protocol (version pinned to ``"v0"``)."""

    class _Entry:
        __slots__ = ("runner", "version", "languages", "model", "uid")

        def __init__(self, runner):
            self.runner = runner
            self.version = "v0"
            self.languages = None
            self.model = None
            # Cache-scope token: bare runners have no model uid, and every
            # static source pins version "v0" — without a per-source token
            # two batchers wrapping DIFFERENT runners but sharing one
            # ScoreCache would collide on identical keys and serve one
            # model's scores for the other.
            self.uid = f"static_{next(_STATIC_UIDS)}"

    def __init__(self, runner):
        self._entry = self._Entry(runner)

    def peek(self):
        return self._entry

    def lease(self):
        from contextlib import nullcontext

        return nullcontext(self._entry)


class ContinuousBatcher:
    """SLO-aware continuous batcher in front of a runner (or registry).

    ``source`` is either a :class:`~..api.runner.BatchRunner` or anything
    with the registry lease protocol (``peek()`` and ``lease()`` yielding
    an entry with ``runner``/``version``/``languages`` — see
    :class:`~.registry.ModelRegistry`). One dispatcher thread owns all
    device work, so concurrent callers never race the runner.
    """

    def __init__(
        self,
        source,
        *,
        max_wait_ms: float | None = None,
        max_rows: int | None = None,
        max_queue_rows: int | None = None,
        slo_ms: float | None = None,
        shed_bulk_when_degraded: bool = True,
        cache=None,
        cache_enable: bool | None = None,
        name: str = "serve",
        tenant: str | None = None,
    ):
        if not hasattr(source, "lease"):
            source = _StaticSource(source)
        self._source = source
        # The version-keyed score cache (serve.cache, docs/SERVING.md §10):
        # consulted per document under the dispatch's registry lease, so a
        # hit is the bit-stored prior result of exactly the version this
        # dispatch serves — hot-swaps invalidate structurally (new version
        # ⇒ new keys). An explicit ``cache`` instance wins (shared across
        # batchers); otherwise one is built when the ``cache_enable`` knob
        # (env LANGDETECT_CACHE_ENABLE) resolves true.
        if cache is None and bool(
            exec_config.resolve("cache_enable", cache_enable)
        ):
            from .cache import ScoreCache

            cache = ScoreCache()
        self.cache = cache
        # Knob resolution through the audited config site: explicit ctor >
        # env > tuning profile (the autotuner's measured flush window) >
        # default. The batcher therefore loads the tuned profile at
        # startup with zero extra plumbing.
        self.shed_bulk_when_degraded = shed_bulk_when_degraded
        self.name = name
        # Tenant scope (docs/SERVING.md §12): set by the model zoo's
        # per-tenant runtime. Partitions the shared score cache's key
        # space per tenant (same-named versions across tenants can never
        # cross-answer, structurally) and attributes sheds to the tenant
        # (``zoo/shed/<tenant>``) on top of the global serve counters.
        self.tenant = tenant
        # The execution core's admission queue owns lanes, bounds, the
        # flush window, and the shed policy; the batcher supplies the
        # serving-specific pieces — the degraded-bulk probe and the gauge
        # names — and the dispatch itself. The knob attributes below are
        # live views onto the queue, so runtime mutation (tests, the shed
        # drill in bench --smoke-serve) keeps working.
        self._queue = AdmissionQueue(
            max_rows=int(exec_config.resolve("serve_max_rows", max_rows)),
            max_wait_s=float(
                exec_config.resolve("serve_max_wait_ms", max_wait_ms)
            ) / 1000.0,
            max_queue_rows=int(
                exec_config.resolve("serve_queue_rows", max_queue_rows)
            ),
            slo_s=float(exec_config.resolve("serve_slo_ms", slo_ms)) / 1000.0,
            lanes=LANES,
            shed_probe=self._degraded_probe,
            on_change=self._on_queue_change,
        )
        self._thread = threading.Thread(
            target=self._run, name=f"{name}-batcher", daemon=True
        )
        self._thread.start()
        log_event(
            _log, "serve.batcher.start", max_wait_ms=self.max_wait_s * 1e3,
            max_rows=self.max_rows, max_queue_rows=self.max_queue_rows,
            slo_ms=self.slo_s * 1e3,
        )

    def _degraded_probe(self, lane: str) -> str | None:
        """Admission-time health shed: while the serving runner's breaker
        is open (or its last dispatch rode the degraded ladder), the bulk
        lane sheds so remaining capacity serves interactive traffic."""
        if lane != BULK or not self.shed_bulk_when_degraded:
            return None
        entry = self._source.peek()
        runner = getattr(entry, "runner", None)
        breaker = getattr(runner, "breaker", None)
        state = breaker.state if breaker is not None else "closed"
        if state == "open" or getattr(runner, "_degraded_mode", False):
            return "degraded"
        return None

    def _on_queue_change(self, depth: int, queued_rows: int) -> None:
        REGISTRY.set_gauge("langdetect_serve_queue_depth", depth)
        REGISTRY.set_gauge("langdetect_serve_queue_rows", queued_rows)

    # Live knob views onto the core queue (settable at runtime: the next
    # admission / flush decision sees the new value).
    @property
    def max_rows(self) -> int:
        return self._queue.max_rows

    @max_rows.setter
    def max_rows(self, value: int) -> None:
        self._queue.max_rows = int(value)

    @property
    def max_wait_s(self) -> float:
        return self._queue.max_wait_s

    @max_wait_s.setter
    def max_wait_s(self, value: float) -> None:
        self._queue.max_wait_s = float(value)

    @property
    def max_queue_rows(self) -> int:
        return self._queue.max_queue_rows

    @max_queue_rows.setter
    def max_queue_rows(self, value: int) -> None:
        self._queue.max_queue_rows = int(value)

    @property
    def slo_s(self) -> float:
        return self._queue.slo_s

    @slo_s.setter
    def slo_s(self, value: float) -> None:
        self._queue.slo_s = float(value)

    @property
    def _ema_rows_per_s(self) -> float:
        return self._queue.ema_rows_per_s

    @_ema_rows_per_s.setter
    def _ema_rows_per_s(self, value: float) -> None:
        self._queue.ema_rows_per_s = float(value)

    # ------------------------------------------------------- admission ------
    def submit(
        self,
        byte_docs: Sequence[bytes],
        *,
        priority: str = INTERACTIVE,
        want_labels: bool = False,
        segment_options=None,
        deadline_ms: float | None = None,
        trace_id: str | None = None,
    ) -> Future:
        """Admit one request; returns a Future resolving to a
        :class:`ServeResult` (or raising the dispatch error).

        ``segment_options`` (a :class:`~..segment.SegmentOptions`)
        switches the request to the span-level segmentation result type;
        mutually exclusive with ``want_labels``. Raises
        :class:`ServeOverloaded` immediately when the request is shed —
        admission control fails fast so callers can retry elsewhere
        instead of queueing into a blown SLO.
        """
        if priority not in LANES:
            raise ValueError(
                f"unknown priority {priority!r}; expected one of {LANES}"
            )
        if segment_options is not None and want_labels:
            raise ValueError(
                "segment_options and want_labels are mutually exclusive"
            )
        docs = list(byte_docs)
        # Chaos gate: an injected error here IS a shed — same counters,
        # same exception shape — so chaos plans exercise the rejection
        # path deterministically (docs/RESILIENCE.md §4).
        try:
            faults.inject("serve/admit")
        except faults.InjectedFault as e:
            self._count_shed(len(docs), "injected", priority)
            raise ServeOverloaded(
                "admission rejected (injected fault)", reason="injected",
                retry_after_s=self.max_wait_s,
            ) from e
        tid = trace_id or current_trace_id() or new_trace_id()
        if not docs:
            if self._queue.closed:
                raise ServeClosed(f"batcher {self.name!r} is closed")
            # Zero-row requests never wake the row-counting dispatcher;
            # answer them at admission with the empty result the runner
            # itself would return (score([]) is [0, L]).
            entry = self._source.peek()
            L = getattr(getattr(entry, "runner", None), "weights", None)
            L = 0 if L is None else int(L.shape[1])
            fut: Future = Future()
            fut.set_result(ServeResult(
                values=(
                    [] if segment_options is not None
                    else np.zeros(0, np.int32) if want_labels
                    else np.zeros((0, L), np.float32)
                ),
                version=entry.version,
                trace_id=tid,
                queue_wait_s=0.0,
                dispatch_s=0.0,
                languages=getattr(entry, "languages", None),
            ))
            REGISTRY.incr("serve/admitted_requests")
            REGISTRY.incr("serve/requests")
            return fut
        now = time.monotonic()
        req = _Request(
            docs=docs,
            want_labels=want_labels,
            priority=priority,
            deadline=None if deadline_ms is None else now + deadline_ms / 1e3,
            trace_id=tid,
            admitted_at=now,
            segment_opts=segment_options,
        )
        # Admission is one atomic core call: closed check, queue bound,
        # SLO estimate, and the degraded-bulk probe all under the queue
        # lock (exec.core.AdmissionQueue) — reject-newest, never evict.
        reason, wait_s = self._queue.admit(req, len(docs), priority)
        if reason == "closed":
            raise ServeClosed(f"batcher {self.name!r} is closed")
        if reason is not None:
            self._count_shed(len(docs), reason, priority)
            raise ServeOverloaded(
                f"request shed ({reason}): {self._queue.queued_rows} rows "
                f"queued, estimated wait {wait_s * 1e3:.1f}ms",
                reason=reason,
                retry_after_s=max(wait_s, self.max_wait_s),
            )
        REGISTRY.incr("serve/admitted_requests")
        return req.future

    def score(self, byte_docs: Sequence[bytes], **kw) -> np.ndarray:
        """Blocking convenience: admit + wait; float32 [N, L] scores."""
        return self.submit(byte_docs, **kw).result().values

    def predict_ids(self, byte_docs: Sequence[bytes], **kw) -> np.ndarray:
        """Blocking convenience: admit + wait; int32 [N] argmax ids."""
        return self.submit(byte_docs, want_labels=True, **kw).result().values

    def segment(self, byte_docs: Sequence[bytes], options=None, **kw) -> list[dict]:
        """Blocking convenience: admit + wait; one segmentation result
        dict per document (docs/SEGMENTATION.md)."""
        if options is None:
            from ..segment import SegmentOptions

            options = SegmentOptions()
        return self.submit(
            byte_docs, segment_options=options, **kw
        ).result().values

    def _count_shed(self, rows: int, reason: str, priority: str) -> None:
        REGISTRY.incr("serve/shed_requests")
        REGISTRY.incr("serve/shed_rows", rows)
        REGISTRY.incr(f"serve/shed_{reason}")
        if self.tenant is not None:
            REGISTRY.incr(f"zoo/shed/{self.tenant}")
        log_event(
            _log, "serve.shed", reason=reason, rows=rows, priority=priority,
            queued_rows=self._queue.queued_rows, trace_id=current_trace_id(),
        )

    # ------------------------------------------------------- dispatcher -----
    @staticmethod
    def _complete(req: _Request, result=None, error: Exception | None = None):
        """Resolve one request's future, tolerating caller-side cancels.

        A client may cancel() its pending future (its own timeout) while
        the request is queued; set_result on a cancelled future raises
        InvalidStateError, and an exception here would kill the one
        dispatcher thread and hang every later request — the worst
        possible failure mode for this module. Cancelled requests are
        simply dropped (their caller stopped listening)."""
        try:
            if error is not None:
                req.future.set_exception(error)
            else:
                req.future.set_result(result)
        except BaseException:
            REGISTRY.incr("serve/cancelled_requests")

    def _run(self) -> None:
        # The flush-window wait, lane priority, and whole-request
        # coalescing all live in the core queue; requests in one batch
        # share a result mode AND its knobs (the key) — a mode or knob
        # flip at a lane front ends the batch there, so the demux below
        # stays a pure offset walk.
        while True:
            batch = self._queue.next_batch(key=lambda r: r.batch_key())
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except Exception as e:  # safety net: the thread must survive
                log_event(_log, "serve.dispatcher_error", error=repr(e))
                for req in batch:
                    self._complete(req, error=ServeError(
                        f"internal dispatcher error: {e!r}"
                    ))
            finally:
                self._queue.done()

    def _cache_scope(self, entry) -> str:
        """Cache key scope = tenant + model identity + version name.
        Version names alone repeat across independent sources (every
        registry auto-names "v1", "v2", ..., every static source pins
        "v0"), so a cache shared across batchers needs the model uid
        (persisted with the model — replicas loading one path share
        entries) or the static source's per-instance token in the key to
        make "never a wrong answer" structural rather than conventional.
        A tenant-scoped batcher (the model zoo's) additionally prefixes
        its tenant, partitioning the shared cache's namespace per tenant
        — two tenants with same-named versions (or even one shared model
        object) structurally address disjoint entries, across any number
        of eviction/reload cycles (docs/SERVING.md §12)."""
        scope = getattr(getattr(entry, "model", None), "uid", None) or (
            getattr(entry, "uid", None)
        )
        scope = f"{scope}:{entry.version}" if scope else entry.version
        if self.tenant is not None:
            scope = f"tenant:{self.tenant}|{scope}"
        return scope

    def _segmented(self, entry, docs: list[bytes], opts) -> list[dict]:
        """One coalesced segment-mode dispatch, through the score cache.

        The cache MODE string carries every decode knob (``opts.key()``:
        cell, smoothing, k, reject threshold, min-span) plus the
        calibration content version, so two segment requests with
        different knobs — or the same knobs across a recalibration — can
        never cross-answer; a knob change simply addresses different
        entries (docs/SERVING.md §11). Values are the canonical JSON
        encoding of the result dict (byte-stable: ``sort_keys`` + the
        decode's rounded floats), stored as uint8 arrays so the cache's
        byte accounting and copy-on-store semantics apply unchanged.
        """
        import json

        from ..segment import segment_documents

        model = getattr(entry, "model", None)
        languages = getattr(entry, "languages", None) or (
            model.profile.languages if model is not None else None
        )
        if not languages:
            raise ServeError(
                "serving source carries no language names for segment mode"
            )
        calibration = getattr(model, "calibration", None)
        cache = self.cache

        def decode(miss_docs):
            return segment_documents(
                entry.runner, miss_docs, languages,
                options=opts, calibration=calibration,
            )

        if cache is None:
            return decode(docs)
        cal_version = (
            calibration.version if calibration is not None else "uncal"
        )
        mode = f"segment[{opts.key()}][cal={cal_version}]"
        encoding = getattr(entry.runner, "score_encoding", UTF8)
        version = self._cache_scope(entry)
        cached = cache.get_many(version, mode, encoding, docs)
        miss = [i for i, c in enumerate(cached) if c is None]
        out: list = [
            None if c is None else json.loads(bytes(c)) for c in cached
        ]
        if miss:
            miss_docs = [docs[i] for i in miss]
            miss_out = decode(miss_docs)
            for j, i in enumerate(miss):
                out[i] = miss_out[j]
            cache.put_many(
                version, mode, encoding, miss_docs,
                [
                    np.frombuffer(
                        json.dumps(r, sort_keys=True).encode("utf-8"),
                        dtype=np.uint8,
                    )
                    for r in miss_out
                ],
            )
        return out

    def _scored(self, entry, docs: list[bytes], want_labels: bool):
        """One coalesced dispatch's results, through the score cache.

        Per-document lookup under the held lease: hits are answered from
        the leased version's stored results, misses ride the runner in
        one call (whose in-flight dedup still collapses duplicate misses),
        and every computed result is written back on fetch. Without a
        cache this is exactly the direct runner call.
        """
        runner = entry.runner
        cache = self.cache
        if cache is None:
            return (
                runner.predict_ids(docs) if want_labels
                else runner.score(docs)
            )
        mode = "labels" if want_labels else "scores"
        encoding = getattr(runner, "score_encoding", UTF8)
        version = self._cache_scope(entry)
        cached = cache.get_many(version, mode, encoding, docs)
        miss = [i for i, c in enumerate(cached) if c is None]
        if miss:
            miss_docs = [docs[i] for i in miss]
            miss_out = (
                runner.predict_ids(miss_docs) if want_labels
                else runner.score(miss_docs)
            )
        if len(miss) == len(docs):
            out = miss_out
        else:
            # L from the results themselves (never runner internals —
            # registry sources may wrap test doubles): any cached value
            # is an [L] row, any miss result a [rows, L] block.
            if want_labels:
                out = np.empty(len(docs), np.int32)
            else:
                L = (
                    np.asarray(miss_out).shape[1] if miss
                    else np.asarray(
                        next(c for c in cached if c is not None)
                    ).shape[0]
                )
                out = np.empty((len(docs), L), np.float32)
            for i, c in enumerate(cached):
                if c is not None:
                    out[i] = c
            for j, i in enumerate(miss):
                out[i] = miss_out[j]
        if miss:
            cache.put_many(version, mode, encoding, miss_docs, list(miss_out))
        return out

    def _dispatch(self, batch: list[_Request]) -> None:
        t_start = time.monotonic()
        live: list[_Request] = []
        for req in batch:
            if req.future.cancelled():
                # The caller gave up while the request was queued: don't
                # spend device time on a response nobody will read.
                REGISTRY.incr("serve/cancelled_requests")
            elif req.deadline is not None and t_start > req.deadline:
                REGISTRY.incr("serve/deadline_rejects")
                log_event(
                    _log, "serve.deadline", trace_id=req.trace_id,
                    rows=len(req.docs),
                    waited_ms=(t_start - req.admitted_at) * 1e3,
                )
                self._complete(req, error=ServeDeadlineExceeded(
                    f"deadline passed after {t_start - req.admitted_at:.3f}s "
                    "in queue"
                ))
            else:
                live.append(req)
        if not live:
            return
        rows = sum(len(r.docs) for r in live)
        docs = [d for r in live for d in r.docs]
        want_labels = live[0].want_labels
        # One batch = one batch_key (the queue coalesces on it), so the
        # lead request's options speak for every coalesced request.
        segment_opts = live[0].segment_opts
        REGISTRY.set_gauge("langdetect_serve_inflight_rows", rows)
        try:
            with self._source.lease() as entry:
                # The lead request's trace id is the dispatch's ambient
                # trace (the runner's score span joins it); every
                # coalesced request keeps its own id on its result and in
                # the serve.dispatch event, so one slow request is
                # greppable end to end.
                with trace_request(live[0].trace_id), span(
                    "serve/dispatch", rows=rows, requests=len(live),
                    version=entry.version, labels=want_labels,
                    segment=segment_opts is not None,
                ):
                    t0 = time.perf_counter()
                    out = (
                        self._segmented(entry, docs, segment_opts)
                        if segment_opts is not None
                        else self._scored(entry, docs, want_labels)
                    )
                    dispatch_s = time.perf_counter() - t0
        except Exception as e:
            REGISTRY.incr("serve/dispatch_errors")
            log_event(
                _log, "serve.dispatch_error", rows=rows,
                requests=len(live), error=repr(e),
            )
            for req in live:
                self._complete(req, error=e)
            return
        finally:
            REGISTRY.set_gauge("langdetect_serve_inflight_rows", 0)
        # Telemetry: the coalescing evidence (counter + per-dispatch
        # distribution) and the three per-request latency legs.
        REGISTRY.incr("serve/dispatches")
        REGISTRY.incr("serve/requests", len(live))
        REGISTRY.incr("serve/coalesced_rows", rows)
        REGISTRY.observe("serve/rows_per_dispatch", rows)
        REGISTRY.observe("serve/requests_per_dispatch", len(live))
        REGISTRY.observe("serve/dispatch_s", dispatch_s)
        # Serve-path fill: how full each dispatched batch ran against the
        # coalescing bound (the serving analog of score/batch_fill_ratio —
        # telemetry/compare regresses fill down / waste up, and the tuner
        # reads the aggregate counters). A single over-bound request
        # counts as full, never as negative waste.
        capacity = max(self.max_rows, rows)
        fill = rows / capacity if capacity else 1.0
        REGISTRY.observe("serve/fill_ratio", fill)
        REGISTRY.observe("serve/padding_waste", 1.0 - fill)
        REGISTRY.incr("serve/dispatch_capacity_rows", capacity)
        self._queue.record_rate(rows, dispatch_s)
        done = time.monotonic()
        off = 0
        for req in live:
            # Segment results are per-doc dicts: slice the list as-is
            # (an np.array of dicts would be an object array nobody
            # wants); numeric modes keep the contiguous array copy.
            if segment_opts is not None:
                sub = list(out[off:off + len(req.docs)])
            else:
                sub = np.array(out[off:off + len(req.docs)])
            off += len(req.docs)
            queue_wait_s = t_start - req.admitted_at
            REGISTRY.observe("serve/queue_wait_s", queue_wait_s)
            REGISTRY.observe("serve/total_s", done - req.admitted_at)
            self._complete(req, ServeResult(
                values=sub,
                version=entry.version,
                trace_id=req.trace_id,
                queue_wait_s=queue_wait_s,
                dispatch_s=dispatch_s,
                languages=getattr(entry, "languages", None),
                rows_coalesced=rows,
            ))
        log_event(
            _log, "serve.dispatch", rows=rows, requests=len(live),
            version=entry.version, dispatch_s=round(dispatch_s, 6),
            trace_ids=[r.trace_id for r in live],
        )

    # ------------------------------------------------------------ admin -----
    def stats(self) -> dict:
        """Queue/backpressure snapshot for /healthz. Includes the resolved
        ``device_encode`` knob (the runners this batcher dispatches into
        inherit it at construction), so "is this replica on the wire path"
        is a health-endpoint read, not log archaeology
        (docs/PERFORMANCE.md §11)."""
        out = self._queue.stats()
        out["device_encode"] = bool(exec_config.resolve("device_encode"))
        return out

    def close(self, drain: bool = True) -> None:
        """Stop admitting; by default drain queued requests first so no
        admitted request is ever dropped. With ``drain=False`` queued
        requests fail with :class:`ServeClosed` (still never a hang)."""
        for req in self._queue.close(drain=drain):
            self._complete(req, error=ServeClosed(
                f"batcher {self.name!r} closed"
            ))
        self._thread.join(timeout=30.0)
        log_event(_log, "serve.batcher.close", drained=drain)

    def __enter__(self) -> "ContinuousBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
