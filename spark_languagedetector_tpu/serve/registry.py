"""Versioned model registry: zero-downtime hot-swap for the serving path.

The offline flow rebuilds a runner whenever a model param changes; a
serving process cannot tear itself down to pick up a refitted profile.
GSPMD's compiled-program portability (PAPERS.md: Xu et al.,
arXiv:2105.04663) means a standby runner compiled off to the side is
exactly as fast as the live one the moment it is flipped in — so a swap
is: load the new :class:`~..models.profile.GramProfile` (via
``persist.load_model`` when given a path), build its runner on the
standby side, pre-warm the compile cache with probe docs, then atomically
flip the serving pointer. In-flight dispatches finish on the version they
leased (:meth:`ModelRegistry.lease` refcounts per entry); the old runner
is drained and retired, and stays cached for instant :meth:`rollback`.

Every request is answered by exactly one version: the dispatcher leases
the active entry per dispatch, the flip happens between leases, and a
lease pins its entry until released — no request ever observes half a
swap (pinned by ``tests/test_serve.py``).

The swap decomposes into explicit phases — :meth:`ModelRegistry.prepare`
(build + pre-warm the standby runner, nothing serving-visible) and
:meth:`ModelRegistry.commit` (the pointer flip) — so a *fleet* of
registries can run a coordinated two-phase flip: prepare on every
replica first, abort everywhere if any prepare fails, and only then
commit replica by replica (docs/SERVING.md §9). :meth:`install` is the
single-registry fusion of the two.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Iterator, Sequence

from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event
from .batcher import ServeError

_log = get_logger("serve.registry")

# Pre-warm probe: one short and one bucket-spanning doc so the common
# compile shapes exist before the first real request hits the new runner.
DEFAULT_PREWARM_DOCS = (b"serve warmup", b"x" * 300)


class ModelVersion:
    """One registered model: its runner, language names, and lease count."""

    __slots__ = (
        "version", "model", "runner", "languages", "source",
        "installed_at", "inflight", "retired", "metadata",
    )

    def __init__(self, version, model, runner, source, metadata=None):
        self.version = version
        self.model = model
        self.runner = runner
        self.languages = tuple(model.profile.languages)
        self.source = source
        self.installed_at = time.time()
        self.inflight = 0
        self.retired = False
        self.metadata = dict(metadata) if metadata else None

    def describe(self) -> dict:
        try:
            quant = self.model.get_or_default("quantization")
        except Exception:
            quant = None
        out = {
            "version": self.version,
            "uid": self.model.uid,
            "languages": len(self.languages),
            "grams": int(self.model.profile.num_grams),
            "source": self.source,
            "strategy": self.runner.strategy,
            "quantization": quant,
            "installed_at": self.installed_at,
            "inflight": self.inflight,
            "retired": self.retired,
        }
        if self.metadata:
            out["metadata"] = dict(self.metadata)
        return out


class PreparedVersion:
    """Phase-1 artifact of a two-phase swap: a standby runner, built and
    pre-warmed off the serving path, not yet serving-visible. Hand it to
    :meth:`ModelRegistry.commit` to flip it in, or drop it to abort —
    nothing was ever installed."""

    __slots__ = ("model", "runner", "version", "source", "metadata")

    def __init__(self, model, runner, version, source, metadata):
        self.model = model
        self.runner = runner
        self.version = version
        self.source = source
        self.metadata = metadata


class ModelRegistry:
    """Serving pointer + version history with atomic flips.

    ``install`` is the swap primitive (``load`` is install-from-disk):
    the standby runner is built and pre-warmed *before* the flip
    (``prepare``), so the pointer move (``commit``) is the only
    serving-visible step and takes a lock acquisition, not a compile.
    """

    def __init__(
        self,
        *,
        prewarm_docs: Sequence[bytes] = DEFAULT_PREWARM_DOCS,
        drain_timeout_s: float = 10.0,
    ):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._history: list[ModelVersion] = []
        self._active_idx: int | None = None
        self._counter = 0
        self._prewarm_docs = list(prewarm_docs)
        self._drain_timeout_s = drain_timeout_s

    # ------------------------------------------------------------ swaps -----
    def prepare(
        self,
        model,
        *,
        version: str | None = None,
        prewarm: bool = True,
        source: str | None = None,
        metadata: dict | None = None,
    ) -> PreparedVersion:
        """Phase 1 of a swap: build ``model``'s runner and pre-warm its
        compile cache, entirely off the serving path. Raises on any
        build/pre-warm failure — nothing serving-visible has happened, so
        a caller coordinating many registries can abort everywhere. The
        returned handle is flipped in by :meth:`commit` (version-name
        conflicts are checked there, at flip time)."""
        runner = model._get_runner()
        if prewarm and self._prewarm_docs:
            runner.score(list(self._prewarm_docs))
        return PreparedVersion(model, runner, version, source, metadata)

    def install(
        self,
        model,
        *,
        version: str | None = None,
        prewarm: bool = True,
        source: str | None = None,
        metadata: dict | None = None,
    ) -> str:
        """Register ``model`` and atomically make it the serving version.

        Returns the version name (auto ``v1``, ``v2``, … when not given).
        The runner is built and optionally pre-warmed on the standby side
        first (:meth:`prepare`); only then does the serving pointer flip
        (:meth:`commit`). The previously active version is drained
        (bounded by ``drain_timeout_s``) and retired — but kept in
        history for :meth:`rollback`.

        ``metadata``: optional provenance dict surfaced by ``describe()``/
        ``versions()`` (and thus ``/varz``) — the auto-refit driver stamps
        its refit token and doc coverage here so an operator can tell WHICH
        accumulated corpus a serving version was finalized from.
        """
        return self.commit(self.prepare(
            model, version=version, prewarm=prewarm, source=source,
            metadata=metadata,
        ))

    def commit(self, prepared: PreparedVersion) -> str:
        """Phase 2 of a swap: atomically flip the serving pointer to a
        :meth:`prepare`\\ d standby. Returns the version name."""
        model, runner = prepared.model, prepared.runner
        version, source = prepared.version, prepared.source
        metadata = prepared.metadata
        with self._cv:
            if version is None:
                # Auto names skip anything already registered (an explicit
                # install may have claimed a future "vN"), so an unrelated
                # swap can never collide with a hand-picked name.
                self._counter += 1
                while any(
                    e.version == f"v{self._counter}" for e in self._history
                ):
                    self._counter += 1
                version = f"v{self._counter}"
            if any(e.version == version for e in self._history):
                raise ServeError(f"version {version!r} already registered")
            entry = ModelVersion(version, model, runner, source, metadata)
            old = (
                None if self._active_idx is None
                else self._history[self._active_idx]
            )
            self._history.append(entry)
            self._active_idx = len(self._history) - 1
            idx = self._active_idx
        REGISTRY.incr("serve/swaps")
        REGISTRY.set_gauge(
            "langdetect_serve_model_version", float(idx), version=version
        )
        log_event(
            _log, "serve.swap", version=version, source=source,
            previous=old.version if old is not None else None,
        )
        if old is not None:
            self._retire(old)
        return version

    def load(self, path: str, *, artifact: str | None = None, **kw) -> str:
        """Load a persisted model directory (``persist.load_model`` layout)
        into a standby runner and swap it in.

        Cold-start fast path: when a baked artifact exists for ``path``
        (the explicit ``artifact`` path, else the ``.baked`` sibling /
        ``LANGDETECT_ARTIFACT_DIR`` resolution), the model is mmapped off
        it instead of parsed out of parquet — bit-identical scores, with
        the parquet tree as the fallback for a missing or torn artifact
        (docs/PERFORMANCE.md §12)."""
        from ..artifacts.bake import maybe_load_baked

        model = maybe_load_baked(path, artifact)
        if model is None:
            from ..models.estimator import LanguageDetectorModel

            model = LanguageDetectorModel.load(path)
        return self.install(model, source=str(path), **kw)

    def rollback(self) -> str:
        """Flip the serving pointer back to the previously installed
        version (instant — its runner is still cached). The rolled-back
        version stays in history, so repeated rollbacks walk backwards."""
        with self._cv:
            if self._active_idx is None or self._active_idx == 0:
                raise ServeError("no previous version to roll back to")
            old = self._history[self._active_idx]
            self._active_idx -= 1
            entry = self._history[self._active_idx]
            entry.retired = False
            idx = self._active_idx
        REGISTRY.incr("serve/rollbacks")
        REGISTRY.set_gauge(
            "langdetect_serve_model_version", float(idx),
            version=entry.version,
        )
        log_event(
            _log, "serve.rollback", version=entry.version, from_=old.version
        )
        self._retire(old)
        return entry.version

    def activate(self, version: str) -> str:
        """Flip the serving pointer to a *named* version already in
        history. This is the fleet swap's crash-recovery primitive:
        after an aborted fleet swap, plain :meth:`rollback` would walk
        one step back in history — which may be the just-retired standby
        of an *earlier* aborted swap, not the version that was actually
        serving. Naming the target makes convergence exact."""
        with self._cv:
            idx = next(
                (
                    i for i, e in enumerate(self._history)
                    if e.version == version
                ),
                None,
            )
            if idx is None:
                raise ServeError(f"version {version!r} not in history")
            if self._active_idx == idx:
                return version
            old = (
                None if self._active_idx is None
                else self._history[self._active_idx]
            )
            self._active_idx = idx
            entry = self._history[idx]
            entry.retired = False
        REGISTRY.incr("serve/activations")
        REGISTRY.set_gauge(
            "langdetect_serve_model_version", float(idx), version=version
        )
        log_event(
            _log, "serve.activate", version=version,
            from_=old.version if old is not None else None,
        )
        if old is not None:
            self._retire(old)
        return version

    def _retire(self, entry: ModelVersion) -> None:
        """Drain ``entry`` (wait for in-flight leases, bounded) and mark
        it retired. A drain timeout is logged, never raised — the old
        version finishes its dispatch and is released then."""
        deadline = time.monotonic() + self._drain_timeout_s
        with self._cv:
            while entry.inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(min(remaining, 0.1))
            drained = entry.inflight == 0
            entry.retired = True
        log_event(
            _log, "serve.retired", version=entry.version, drained=drained
        )

    # ----------------------------------------------------------- access -----
    def peek(self) -> ModelVersion:
        """The active entry without pinning it (shed checks, healthz)."""
        with self._lock:
            if self._active_idx is None:
                raise ServeError("no model installed in the serving registry")
            return self._history[self._active_idx]

    @contextmanager
    def lease(self) -> Iterator[ModelVersion]:
        """Pin the active version for one dispatch. The swap flips the
        pointer between leases; a held lease keeps its entry alive until
        released, which is what makes every request single-version."""
        with self._cv:
            if self._active_idx is None:
                raise ServeError("no model installed in the serving registry")
            entry = self._history[self._active_idx]
            entry.inflight += 1
        try:
            yield entry
        finally:
            with self._cv:
                entry.inflight -= 1
                self._cv.notify_all()

    def current_version(self) -> str:
        return self.peek().version

    def busy(self) -> bool:
        """True while ANY version in history holds an in-flight lease —
        the model zoo's residency manager refuses to page out a tenant
        whose registry reports busy, which is what makes "evictions never
        touch a leased version" structural (docs/SERVING.md §12)."""
        with self._lock:
            return any(e.inflight > 0 for e in self._history)

    def versions(self) -> list[dict]:
        with self._lock:
            active = self._active_idx
            return [
                {**e.describe(), "active": i == active}
                for i, e in enumerate(self._history)
            ]
