"""Version-keyed content-addressed score cache: level 2 of the redundancy
eliminator (docs/PERFORMANCE.md §10).

The in-flight dedup (level 1, ``exec.core.dedup_items``) eliminates
duplicate rows *within* one dispatch; this module eliminates them *across*
dispatches and requests: a bounded, sharded LRU in front of the serving
runner, keyed by ``(model version, result mode, score encoding, document
bytes)``. The batcher consults it per document under the registry lease it
already holds, so every answer — cached or computed — comes from exactly
the leased version:

  * **Parity** — a hit returns the bit-stored prior result of the *same*
    version, so per-version parity is exact by construction. (A
    *recomputed* duplicate under a matmul strategy may differ from the
    stored bits in the last f32 ulp across batch geometries — the
    reduction-order class in docs/ARCHITECTURE.md; gather/fused runners
    are bit-exact either way.)
  * **Staleness** — impossible structurally, not by invalidation
    callbacks: the version in the key is the leased entry's, and a
    hot-swap (single registry or the fleet's two-phase flip) moves the
    pointer *between* leases. A post-swap dispatch leases the new version
    and therefore can only read/write the new version's keys; every
    pre-swap entry is unreachable from it by construction and ages out of
    the LRU (docs/SERVING.md §10).
  * **Keys are the bytes themselves** — dict hashing + equality, so a
    "collision" is a true content match; there is no digest to get wrong.

Bounded by entries (``LANGDETECT_CACHE_ROWS``) and bytes
(``LANGDETECT_CACHE_BYTES`` — keys plus stored results), both resolved
through ``exec.config`` (a tuning profile may carry measured sizes —
``exec.tune`` solves them from a capture's observed duplicate mass).
Sharded to keep lock hold times tiny under concurrent front-end threads.

Chaos: every lookup/store passes the ``serve/cache`` fault site. An
injected failure degrades that operation to a miss (or skips the store) —
never a wrong answer, pinned by ``tests/test_cache.py``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ..exec import config as exec_config
from ..resilience import faults
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("serve.cache")

# Fixed per-entry accounting overhead (key tuple, OrderedDict node, numpy
# header) so a cache of tiny documents can't balloon unaccounted.
ENTRY_OVERHEAD_BYTES = 128


class ScoreCache:
    """Bounded, sharded, version-keyed LRU over per-document score results.

    ``get``/``put`` take the leased version plus the result mode
    (``"labels"`` / ``"scores"``), the runner's ``score_encoding``, and the
    raw document bytes; values are per-document numpy results (a ``[L]``
    float32 score row, or a 0-d int32 argmax id). Thread-safe; eviction is
    LRU per shard under the global row/byte bounds split evenly across
    shards.
    """

    def __init__(
        self,
        *,
        max_rows: int | None = None,
        max_bytes: int | None = None,
        shards: int = 8,
    ):
        self.max_rows = int(exec_config.resolve("cache_rows", max_rows))
        self.max_bytes = int(exec_config.resolve("cache_bytes", max_bytes))
        if self.max_rows < 1 or self.max_bytes < 1:
            raise ValueError("cache_rows and cache_bytes must be >= 1")
        n = max(1, int(shards))
        self._shards: list[OrderedDict] = [OrderedDict() for _ in range(n)]
        self._locks = [threading.Lock() for _ in range(n)]
        # Per-shard byte tallies; rows are len(shard). Shard bounds split
        # the global budget evenly (the content hash spreads keys).
        self._bytes = [0] * n
        self._shard_rows = max(1, self.max_rows // n)
        self._shard_bytes = max(1, self.max_bytes // n)
        # Lifetime tallies for stats() (/varz), per shard so every update
        # happens under the lock it already holds; the REGISTRY counters
        # are process-global and shared with any other cache instance.
        self._hits = [0] * n
        self._misses = [0] * n
        self._evictions = [0] * n
        log_event(
            _log, "serve.cache.start", max_rows=self.max_rows,
            max_bytes=self.max_bytes, shards=n,
        )

    # ------------------------------------------------------------ internals --
    def _shard_of(self, key) -> int:
        return hash(key) % len(self._shards)

    def _gauges(self) -> None:
        REGISTRY.set_gauge("langdetect_cache_rows", float(self.rows))
        REGISTRY.set_gauge("langdetect_cache_bytes", float(self.bytes))

    @property
    def rows(self) -> int:
        return sum(len(s) for s in self._shards)

    @property
    def bytes(self) -> int:
        return sum(self._bytes)

    # ------------------------------------------------------------- lookup ---
    def get(self, version: str, mode: str, encoding: str, doc: bytes):
        """The cached result for ``doc`` under ``version``, or None.

        A hit refreshes LRU order and is counted (``cache/hits``,
        ``cache/bytes_saved`` — the document bytes that now skip the
        wire). An injected ``serve/cache`` fault reads as a miss: the
        caller recomputes, losing only the saving.
        """
        return self.get_many(version, mode, encoding, (doc,))[0]

    def get_many(
        self, version: str, mode: str, encoding: str, docs
    ) -> list:
        """Batched :meth:`get` — one REGISTRY update per counter per call
        instead of per document, which is what keeps the serve dispatch
        loop off the global metrics lock at hundreds of rows per
        coalesce. Fault injection stays per document (the ``serve/cache``
        replay schedule is call-for-call identical to a loop of ``get``);
        per-doc LRU refresh and shard stats are unchanged.
        """
        out = []
        hits = misses = faulted = saved = 0
        for doc in docs:
            try:
                faults.inject("serve/cache")
            except faults.InjectedFault:
                faulted += 1
                misses += 1
                with self._locks[0]:
                    self._misses[0] += 1
                out.append(None)
                continue
            key = (version, mode, encoding, doc)
            i = self._shard_of(key)
            with self._locks[i]:
                shard = self._shards[i]
                hit = shard.get(key)
                if hit is None:
                    self._misses[i] += 1
                else:
                    shard.move_to_end(key)
                    self._hits[i] += 1
            if hit is None:
                misses += 1
                out.append(None)
            else:
                hits += 1
                saved += len(doc)
                out.append(hit[0])
        if faulted:
            REGISTRY.incr("cache/faults", faulted)
        if out:
            REGISTRY.incr("cache/lookups", len(out))
        if misses:
            REGISTRY.incr("cache/misses", misses)
        if hits:
            REGISTRY.incr("cache/hits", hits)
            REGISTRY.incr("cache/bytes_saved", saved)
        return out

    # -------------------------------------------------------------- store ---
    def put(
        self, version: str, mode: str, encoding: str, doc: bytes, value
    ) -> None:
        """Store one document's result (written on fetch, after a dispatch
        settles). Oversized single entries are refused rather than
        flushing a whole shard; injected faults skip the store."""
        self.put_many(version, mode, encoding, (doc,), (value,))

    def put_many(
        self, version: str, mode: str, encoding: str, docs, values
    ) -> None:
        """Batched :meth:`put`: the eviction counter and the occupancy
        gauges (an O(shards) sum each) update once per call rather than
        per stored document. Fault injection stays per document — the
        ``serve/cache`` replay schedule is call-for-call identical to a
        loop of ``put``."""
        evicted = 0
        for doc, value in zip(docs, values):
            try:
                faults.inject("serve/cache")
            except faults.InjectedFault:
                REGISTRY.incr("cache/faults")
                continue
            # Copy: callers hand in views of the dispatch's result array,
            # and a stored view would pin the whole [B, L] base buffer in
            # memory. Read-only: get() hands back the stored array itself,
            # so an in-place edit by a caller would otherwise corrupt
            # every future hit.
            value = np.array(value)
            value.setflags(write=False)
            cost = len(doc) + int(value.nbytes) + ENTRY_OVERHEAD_BYTES
            if cost > self._shard_bytes:
                continue
            key = (version, mode, encoding, doc)
            i = self._shard_of(key)
            with self._locks[i]:
                shard = self._shards[i]
                old = shard.pop(key, None)
                if old is not None:
                    self._bytes[i] -= old[1]
                shard[key] = (value, cost)
                self._bytes[i] += cost
                dropped = 0
                while len(shard) > self._shard_rows or (
                    self._bytes[i] > self._shard_bytes and shard
                ):
                    _, (_, old_cost) = shard.popitem(last=False)
                    self._bytes[i] -= old_cost
                    dropped += 1
                self._evictions[i] += dropped
            evicted += dropped
        if evicted:
            REGISTRY.incr("cache/evictions", evicted)
        self._gauges()

    # -------------------------------------------------------------- admin ---
    def clear(self) -> None:
        for i, lock in enumerate(self._locks):
            with lock:
                self._shards[i].clear()
                self._bytes[i] = 0
        self._gauges()

    def stats(self) -> dict:
        """Point-in-time snapshot for /varz and healthz."""
        hits, misses = sum(self._hits), sum(self._misses)
        lookups = hits + misses
        return {
            "rows": self.rows,
            "bytes": self.bytes,
            "max_rows": self.max_rows,
            "max_bytes": self.max_bytes,
            "hits": hits,
            "misses": misses,
            "evictions": sum(self._evictions),
            "hit_rate": round(hits / lookups, 6) if lookups else 0.0,
        }
