"""Health-checked routing front tier for a replicated serving fleet.

GSPMD's portability argument (PAPERS.md: arXiv:2105.04663) makes the
*data plane* of replication free — the same compiled program serves
identically on every replica. What is not free is the control plane this
module supplies: deciding, per request, which replica is healthy enough
and least loaded; noticing a replica die mid-flight and retrying the
(idempotent) request elsewhere; ejecting a flapping replica and
re-admitting it only after a half-open probe succeeds; and shedding
fleet-wide only when *every* ready replica is saturated. One router
thread-safe object owns all of it:

  * **Probing.** A background loop (or explicit :meth:`probe_once` —
    what the deterministic chaos tests drive) hits each replica's
    ``/healthz/ready``. Liveness is "the probe was answered at all";
    readiness is the replica's own report (breaker closed, not degraded,
    not draining — the server's split ``/healthz`` surface). Reachability
    feeds a per-replica :class:`~..resilience.policy.CircuitBreaker`:
    ``failure_threshold`` consecutive failed probes/dispatches eject the
    replica (breaker open — no traffic, no probes) until the cooldown
    elapses, then ONE half-open probe decides re-admission. The
    ``fleet/probe`` fault site injects probe failures deterministically.
  * **Routing.** Least outstanding rows among eligible replicas (ready,
    not draining, breaker closed, version matching the fleet pin when one
    is set), with the replica *index* as the deterministic tie-break —
    two routers fed the same sequence make the same choices.
  * **Failover.** A dispatch that dies mid-flight (connection refused or
    reset, HTTP 5xx, an injected ``fleet/dispatch`` fault) is classified
    by the same retryable taxonomy the serving layers use and retried on
    a different replica — a per-request exclusion set guarantees the
    retry never lands on the replica it just watched die. Scoring is
    idempotent (pure read), so replays are safe by construction. 400 and
    504 propagate untouched: the replica answered, the answer is final.
  * **Fleet-wide shed.** A 503-shed from a replica means "healthy but
    saturated": the router tries the remaining ready replicas and only
    when every one of them shed does it raise :class:`FleetSaturated`
    (HTTP 503 + the smallest ``Retry-After`` any replica offered).
  * **Storm defense** (docs/RESILIENCE.md §7). The *remaining* deadline
    budget decays into every failover attempt's ``deadline_ms`` (a
    nearly-expired request never occupies N replicas back-to-back), and
    below ``LANGDETECT_FLEET_DEADLINE_FLOOR_MS`` the router answers 504
    itself. Every extra attempt — failover or hedge — must withdraw a
    token from the shared :class:`~..resilience.policy.RetryBudget`, so
    a replica outage degrades to bounded goodput loss instead of a
    retry storm. With ``LANGDETECT_HEDGE_ENABLE`` the router issues one
    *hedge* to a different replica after the observed dispatch-latency
    quantile delay, first answer wins (sound: scoring is pure, leases
    pin versions). And a :class:`~.quarantine.QuarantineTable` remembers
    which content signatures keep coinciding with replica death — a
    query of death is answered 422 after at most K kills, never replayed
    onto the whole fleet serially.

  * **Dynamic membership.** :meth:`~FleetRouter.add_replica` admits a
    new endpoint mid-flight with a fresh breaker;
    :meth:`~FleetRouter.remove_replica` drains-then-detaches. Probing,
    routing, failover, ejection, and the swap's version pin all read the
    live handle table, so they compose unchanged on a changing replica
    set — the control-plane half of the elastic fleet
    (:mod:`..scale.elastic`, docs/SERVING.md §13).

The version pin is the router's half of the two-phase fleet hot-swap
(:mod:`.fleet`, docs/SERVING.md §9): while a swap is in flight, only
replicas serving the pinned version are eligible, which is what keeps a
client stream from ever interleaving two model versions.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from http.client import HTTPException

from ..exec import config as exec_config
from ..resilience import faults
from ..resilience.policy import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    RetryBudget,
    is_retryable,
)
from ..telemetry import REGISTRY, span
from ..telemetry.tracing import trace_request
from ..utils.logging import get_logger, log_event
from .batcher import (
    INTERACTIVE,
    LANES,
    ServeDeadlineExceeded,
    ServeError,
    ServeOverloaded,
)
from .client import ServeClient, ServeHTTPError
from .quarantine import QuarantineTable, QueryQuarantined, signature_of
from .server import JsonHTTPFront

_log = get_logger("serve.router")


class FleetSaturated(ServeOverloaded):
    """Every ready replica shed the request: the fleet as a whole is out
    of capacity. Maps to HTTP 503 + Retry-After like any other shed."""


class NoReadyReplica(ServeOverloaded):
    """No replica is currently eligible (all ejected, draining, or
    mid-swap): an explicit, retryable rejection — never a hang."""


class FleetSwapError(ServeError):
    """A fleet-wide two-phase swap aborted (phase 1) or rolled back
    (phase 2). The fleet is back on one consistent version."""


class ReplicaHandle:
    """Router-side view of one replica: address, health, load.

    Mutable state (``ready``/``reasons``/``draining``/``version``/
    ``outstanding_rows``) is guarded by the router's lock; the breaker
    has its own.
    """

    def __init__(
        self,
        name: str,
        host: str,
        port: int,
        *,
        breaker: CircuitBreaker,
        request_timeout_s: float,
        probe_timeout_s: float,
    ):
        self.name = name
        self.host, self.port = host, port
        self.client = ServeClient(host, port, timeout_s=request_timeout_s)
        self.probe_client = ServeClient(host, port, timeout_s=probe_timeout_s)
        self.breaker = breaker
        self.ready = False
        self.reasons: list[str] = ["unprobed"]
        self.draining = False  # router-side: the fleet swap's drain mark
        self.version: str | None = None
        self.outstanding_rows = 0

    def describe(self) -> dict:
        return {
            "replica": self.name,
            "address": f"{self.host}:{self.port}",
            "ready": self.ready,
            "reasons": list(self.reasons),
            "draining": self.draining,
            "version": self.version,
            "outstanding_rows": self.outstanding_rows,
            "breaker": self.breaker.state,
        }


def _as_endpoint(i: int, rep) -> tuple[str, str, int]:
    """(name, host, port) from a ServeReplica-like object or a tuple."""
    if hasattr(rep, "address"):
        host, port = rep.address
        return getattr(rep, "name", f"r{i}"), host, int(port)
    host, port = rep
    return f"r{i}", host, int(port)


class FleetRouter:
    """Routing front tier over N serve replicas (docs/SERVING.md §9).

    ``replicas``: :class:`~.fleet.ServeReplica` objects or bare
    ``(host, port)`` tuples — the router only ever talks HTTP, so a
    replica may live in this process, another process, or another host.
    Knobs resolve through the audited config precedence
    (``LANGDETECT_FLEET_*`` — exec/config.py).
    """

    def __init__(
        self,
        replicas,
        *,
        probe_interval_ms: float | None = None,
        probe_timeout_s: float | None = None,
        dispatch_attempts: int | None = None,
        breaker_threshold: int | None = None,
        breaker_cooldown_s: float | None = None,
        drain_timeout_s: float | None = None,
        request_timeout_s: float = 60.0,
        deadline_floor_ms: float | None = None,
        retry_budget: RetryBudget | None = None,
        hedge_enable: bool | None = None,
        hedge_quantile: float | None = None,
        hedge_min_ms: float | None = None,
        quarantine: QuarantineTable | None = None,
        name: str = "fleet",
    ):
        self.name = name
        self.probe_interval_s = float(exec_config.resolve(
            "fleet_probe_interval_ms", probe_interval_ms
        )) / 1000.0
        self.probe_timeout_s = float(exec_config.resolve(
            "fleet_probe_timeout_s", probe_timeout_s
        ))
        self.dispatch_attempts = int(exec_config.resolve(
            "fleet_dispatch_attempts", dispatch_attempts
        ))
        self.drain_timeout_s = float(exec_config.resolve(
            "fleet_drain_timeout_s", drain_timeout_s
        ))
        # Kept for dynamic membership: add_replica builds late handles
        # with the same breaker/timeout parameters the founders got.
        self._breaker_threshold = int(exec_config.resolve(
            "fleet_breaker_threshold", breaker_threshold
        ))
        self._breaker_cooldown_s = float(exec_config.resolve(
            "fleet_breaker_cooldown_s", breaker_cooldown_s
        ))
        self._request_timeout_s = float(request_timeout_s)
        # Storm defense (docs/RESILIENCE.md §7): deadline floor, shared
        # retry budget, hedging, and the query-of-death table. Defaults
        # resolve through the audited knob table; pass explicit instances
        # (or RetryBudget(fraction=0.0)) to share or disable.
        self.deadline_floor_ms = float(exec_config.resolve(
            "fleet_deadline_floor_ms", deadline_floor_ms
        ))
        self.retry_budget = (
            RetryBudget(name=name) if retry_budget is None else retry_budget
        )
        self.hedge_enable = bool(exec_config.resolve(
            "hedge_enable", hedge_enable
        ))
        self.hedge_quantile = float(exec_config.resolve(
            "hedge_quantile", hedge_quantile
        ))
        self.hedge_min_ms = float(exec_config.resolve(
            "hedge_min_ms", hedge_min_ms
        ))
        self.quarantine = (
            QuarantineTable(name=name) if quarantine is None else quarantine
        )
        # Recent *successful* dispatch latencies: the hedge timer's p9x
        # source (failures are usually fast and would shrink the delay).
        self._lat: deque[float] = deque(maxlen=256)
        self._lock = threading.Lock()
        self._pin: str | None = None
        self._handles: list[ReplicaHandle] = []
        for i, rep in enumerate(replicas):
            rname, host, port = _as_endpoint(i, rep)
            self._handles.append(self._new_handle(rname, host, port))
        if not self._handles:
            raise ValueError("a fleet router needs at least one replica")
        self._started = time.monotonic()
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        log_event(
            _log, "fleet.router.start", replicas=len(self._handles),
            probe_interval_ms=self.probe_interval_s * 1e3,
            dispatch_attempts=self.dispatch_attempts,
        )

    def _new_handle(self, rname: str, host: str, port: int) -> ReplicaHandle:
        return ReplicaHandle(
            rname, host, port,
            breaker=CircuitBreaker(
                failure_threshold=self._breaker_threshold,
                cooldown_s=self._breaker_cooldown_s,
                name=f"{self.name}:{rname}",
            ),
            request_timeout_s=self._request_timeout_s,
            probe_timeout_s=self.probe_timeout_s,
        )

    # ------------------------------------------------------ membership ------
    def add_replica(self, rep, *, name: str | None = None) -> str:
        """Admit a replica into routing (docs/SERVING.md §13): a fresh
        handle with a fresh CLOSED breaker — re-adding an address that
        was removed earlier must never inherit the removed member's
        ejection history. One immediate probe follows, so a healthy
        replica is eligible without waiting for the next probe round.
        Returns the member name; a duplicate name is a loud error."""
        with self._lock:
            idx = len(self._handles)
        rname, host, port = _as_endpoint(idx, rep)
        if name is not None:
            rname = name
        with self._lock:
            if any(h.name == rname for h in self._handles):
                raise ValueError(
                    f"replica name {rname!r} already routed; remove it "
                    "first or pick a fresh name"
                )
            h = self._new_handle(rname, host, port)
            self._handles.append(h)
        log_event(
            _log, "fleet.replica.added", replica=rname,
            address=f"{host}:{port}", replicas=idx + 1,
        )
        self._probe_replica(h)
        return rname

    def remove_replica(
        self, name: str, *, drain: bool = True, timeout_s: float | None = None
    ) -> bool:
        """Detach a replica from routing: drain-then-detach. The member
        is marked draining (no new picks), its outstanding routed
        requests are waited out (bounded), and only then does the handle
        leave the table — with its per-replica gauges zeroed so a
        removed member never freezes a stale series. Returns whether the
        drain completed inside the bound; on a timeout the handle still
        detaches, and a straggler's release simply updates the detached
        handle (the router's accounting can no longer be stranded by
        it). Unknown names raise ``ValueError``."""
        h = self._handle(name)
        self.set_draining(name, True)
        drained = True
        if drain:
            drained = self.wait_drained(name, timeout_s=timeout_s)
        with self._lock:
            if h in self._handles:
                self._handles.remove(h)
        REGISTRY.set_gauge(
            "langdetect_fleet_replica_ready", 0.0, replica=name
        )
        REGISTRY.set_gauge(
            "langdetect_fleet_outstanding_rows", 0.0, replica=name
        )
        log_event(
            _log, "fleet.replica.removed", replica=name, drained=drained,
            replicas=len(self._handles),
        )
        return drained

    # ---------------------------------------------------------- lifecycle ---
    def start(self, *, probe: bool = True) -> "FleetRouter":
        """Run one synchronous probe round (so routing works immediately),
        then start the background prober unless ``probe=False`` (tests
        drive :meth:`probe_once` explicitly for determinism)."""
        self.probe_once()
        if probe and self._probe_thread is None:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name=f"{self.name}-prober",
                daemon=True,
            )
            self._probe_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10.0)
            self._probe_thread = None

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception as e:  # the prober must survive anything
                log_event(_log, "fleet.probe_loop_error", error=repr(e))

    # ------------------------------------------------------------ probing ---
    def probe_once(self) -> list[str]:
        """One probe round over every replica, in index order (which is
        what makes chaos plans at ``fleet/probe`` replay exactly).

        Returns compact event strings (``"r1:unreachable:ejected"``,
        ``"r1:readmitted"``, …) — the deterministic-replay tests pin
        sequences of these.
        """
        # Snapshot under the lock: membership may change mid-round (a
        # scale-down detaching a handle must not break the iteration); a
        # just-removed member's last probe result lands on the detached
        # handle, harmlessly.
        with self._lock:
            handles = list(self._handles)
        events: list[str] = []
        with span("fleet/probe", replicas=len(handles)):
            for h in handles:
                evt = self._probe_replica(h)
                if evt:
                    events.append(evt)
        REGISTRY.incr("fleet/probe_rounds")
        REGISTRY.set_gauge(
            "langdetect_fleet_ready_replicas", float(len(self.eligible()))
        )
        return events

    def _probe_replica(self, h: ReplicaHandle) -> str | None:
        if not h.breaker.allow():
            # Open and still cooling down: stays ejected, unprobed. Once
            # the cooldown elapses allow() flips to half-open and the
            # probe below becomes the re-admission probe.
            with self._lock:
                h.ready = False
                h.reasons = ["ejected"]
            self._replica_gauges(h)
            return None
        before = h.breaker.state
        try:
            faults.inject("fleet/probe")
            payload = h.probe_client.readyz()
        except Exception as e:
            h.breaker.record_failure()
            # Only the CLOSED -> OPEN edge is an ejection *event*; a
            # failed half-open re-probe re-opens the breaker but is the
            # same outage continuing — counting it would make the
            # regression-guarded counter proportional to outage length.
            ejected = h.breaker.state == OPEN and before == CLOSED
            if ejected:
                REGISTRY.incr("fleet/ejections")
            with self._lock:
                h.ready = False
                h.reasons = ["unreachable"]
            self._replica_gauges(h)
            log_event(
                _log, "fleet.probe_failed", replica=h.name, error=repr(e),
                ejected=ejected,
            )
            return f"{h.name}:unreachable" + (":ejected" if ejected else "")
        # Reachable: liveness proven, which is what the router-side
        # breaker tracks. Readiness is the replica's own report and does
        # NOT trip the breaker — honest backpressure is not a crash.
        h.breaker.record_success()
        readmitted = before in (OPEN, HALF_OPEN) and h.breaker.state == CLOSED
        if readmitted:
            REGISTRY.incr("fleet/readmissions")
            log_event(_log, "fleet.readmitted", replica=h.name)
        ready = bool(payload.get("ready"))
        with self._lock:
            h.ready = ready
            h.reasons = list(
                payload.get("reasons") or ([] if ready else ["not_ready"])
            )
            h.version = payload.get("version") or h.version
        self._replica_gauges(h)
        if readmitted:
            return f"{h.name}:readmitted"
        return f"{h.name}:ready" if ready else f"{h.name}:not_ready"

    def _replica_gauges(self, h: ReplicaHandle) -> None:
        # Membership check and gauge write under ONE lock hold: checking,
        # releasing, then writing would let a concurrent remove_replica
        # zero the series in the gap and have this stale write resurrect
        # it forever. (Lock order router->registry matches _release.)
        with self._lock:
            if h not in self._handles:
                return  # detached mid-flight: its series is already zeroed
            REGISTRY.set_gauge(
                "langdetect_fleet_replica_ready",
                1.0 if (h.ready and h.breaker.state == CLOSED) else 0.0,
                replica=h.name,
            )

    # ------------------------------------------------------------ routing ---
    def _eligible_locked(self, h: ReplicaHandle) -> bool:
        return (
            h.ready
            and not h.draining
            and h.breaker.state == CLOSED
            and (self._pin is None or h.version == self._pin)
        )

    def eligible(self) -> list[str]:
        with self._lock:
            return [
                h.name for h in self._handles if self._eligible_locked(h)
            ]

    def _pick(self, rows: int, excluded: set) -> ReplicaHandle | None:
        """Least outstanding rows among eligible replicas; replica index
        breaks ties deterministically. Reserves ``rows`` on the winner."""
        with self._lock:
            best: tuple[tuple[int, int], ReplicaHandle] | None = None
            for idx, h in enumerate(self._handles):
                if h.name in excluded or not self._eligible_locked(h):
                    continue
                key = (h.outstanding_rows, idx)
                if best is None or key < best[0]:
                    best = (key, h)
            if best is None:
                return None
            h = best[1]
            h.outstanding_rows += rows
            REGISTRY.set_gauge(
                "langdetect_fleet_outstanding_rows",
                float(h.outstanding_rows), replica=h.name,
            )
            return h

    def _release(self, h: ReplicaHandle, rows: int) -> None:
        with self._lock:
            h.outstanding_rows = max(0, h.outstanding_rows - rows)
            # A straggler finishing after remove_replica's drain timeout
            # updates the detached handle but must not resurrect its
            # zeroed gauge series.
            if h not in self._handles:
                return
            REGISTRY.set_gauge(
                "langdetect_fleet_outstanding_rows",
                float(h.outstanding_rows), replica=h.name,
            )

    def _note_dispatch_failure(self, h: ReplicaHandle, exc: Exception) -> None:
        before = h.breaker.state
        h.breaker.record_failure()
        ejected = h.breaker.state == OPEN and before == CLOSED
        if ejected:
            REGISTRY.incr("fleet/ejections")
            with self._lock:
                h.ready = False
                h.reasons = ["dispatch_failures"]
            self._replica_gauges(h)
        REGISTRY.incr("fleet/failovers")
        log_event(
            _log, "fleet.failover", replica=h.name, error=repr(exc),
            ejected=ejected,
        )

    # ----------------------------------------------------- attempt/hedge ---
    def _call_one(
        self, h: ReplicaHandle, texts: list, *, rows: int, attempt: int,
        hedge: bool, want_labels: bool, segment_kw: dict | None,
        priority: str, deadline_ms: float | None, trace_id: str,
        tenant: str | None,
    ):
        """One wire dispatch to one replica. Releases its reservation and
        counts ``fleet/dispatches`` whatever happens; only successes feed
        the hedge timer's latency history."""
        t0 = time.perf_counter()
        try:
            with span(
                "fleet/dispatch", replica=h.name, rows=rows,
                attempt=attempt,
            ):
                if hedge:
                    faults.inject("fleet/hedge")
                else:
                    faults.inject("fleet/dispatch")
                # The tenant rides the request to whichever replica
                # wins: every replica fronts the same zoo surface, so
                # tenant routing is the replica's (SERVING.md §12) —
                # the fleet tier only has to carry the name.
                if segment_kw is not None:
                    out, meta = h.client.segment(
                        texts, priority=priority,
                        deadline_ms=deadline_ms, trace_id=trace_id,
                        tenant=tenant, **segment_kw,
                    )
                elif want_labels:
                    out, meta = h.client.detect(
                        texts, priority=priority,
                        deadline_ms=deadline_ms, trace_id=trace_id,
                        tenant=tenant,
                    )
                else:
                    out, meta = h.client.score(
                        texts, priority=priority,
                        deadline_ms=deadline_ms, trace_id=trace_id,
                        tenant=tenant,
                    )
            with self._lock:
                self._lat.append(time.perf_counter() - t0)
            return out, meta
        finally:
            REGISTRY.incr("fleet/dispatches")
            self._release(h, rows)

    def _hedge_delay_s(self) -> float:
        """Hedge-arm delay: the observed dispatch-latency quantile,
        floored by ``hedge_min_ms`` (which also covers cold history)."""
        floor = self.hedge_min_ms / 1e3
        with self._lock:
            lat = sorted(self._lat)
        if len(lat) < 8:
            return floor
        q = min(max(self.hedge_quantile, 0.0), 1.0)
        return max(floor, lat[min(len(lat) - 1, int(q * len(lat)))])

    def _note_side_failure(
        self, h: ReplicaHandle, exc: Exception, excluded: set,
        saturated: list, sig: str, texts: list,
    ) -> None:
        """Failure bookkeeping for a hedge leg that no longer decides the
        request (the other leg won or will): same breaker/exclusion/
        quarantine effects as the main loop, but never raises."""
        if isinstance(exc, ServeHTTPError):
            if exc.status == 503 and exc.shed:
                saturated.append(exc.retry_after_s)
                excluded.add(h.name)
                REGISTRY.incr("fleet/replica_saturated")
            elif exc.status == 503 or (
                exc.status >= 500 and exc.status != 504
            ):
                excluded.add(h.name)
                self._note_dispatch_failure(h, exc)
            # 400/404/504: the replica answered; nothing to eject.
            return
        if isinstance(exc, HTTPException) or is_retryable(exc):
            excluded.add(h.name)
            self._note_dispatch_failure(h, exc)
            self.quarantine.record_death(
                sig, replica=h.name, source="router", texts=texts
            )

    def _attempt(
        self, h: ReplicaHandle, texts: list, *, rows: int, attempt: int,
        excluded: set, saturated: list, sig: str, **call_kw,
    ):
        """One dispatch attempt, hedged when enabled: the primary runs in
        a worker; if it has not answered within the p9x delay AND a
        distinct replica AND a budget token exist, one hedge races it and
        the first answer wins. Sound because scoring is a pure read and
        the version pin holds for both legs. Returns
        ``(out, meta, served_by)``; raises the *primary's* error when no
        leg succeeds (the hedge leg's failure is bookkeeping only)."""
        if not self.hedge_enable:
            out, meta = self._call_one(
                h, texts, rows=rows, attempt=attempt, hedge=False,
                **call_kw,
            )
            return out, meta, h.name
        results: queue.SimpleQueue = queue.SimpleQueue()

        def run(handle: ReplicaHandle, is_hedge: bool) -> None:
            try:
                out, meta = self._call_one(
                    handle, texts, rows=rows, attempt=attempt,
                    hedge=is_hedge, **call_kw,
                )
                results.put(("ok", handle, is_hedge, out, meta))
            except BaseException as e:
                results.put(("err", handle, is_hedge, e))

        threading.Thread(
            target=run, args=(h, False),
            name=f"{self.name}-dispatch-{h.name}", daemon=True,
        ).start()
        first = None
        try:
            first = results.get(timeout=self._hedge_delay_s())
        except queue.Empty:
            pass
        pending = 1
        if first is None:
            # Primary is straggling: arm the hedge — replica first (no
            # token burned when the fleet has no second replica to try),
            # then the budget (hedges self-disable under overload).
            h2 = self._pick(rows, excluded | {h.name})
            if h2 is not None and not self.retry_budget.try_spend(
                reason="hedge"
            ):
                self._release(h2, rows)
                h2 = None
            if h2 is not None:
                REGISTRY.incr("fleet/hedges")
                log_event(
                    _log, "fleet.hedge", primary=h.name, hedge=h2.name,
                    rows=rows, attempt=attempt,
                )
                threading.Thread(
                    target=run, args=(h2, True),
                    name=f"{self.name}-hedge-{h2.name}", daemon=True,
                ).start()
                pending += 1
        primary_exc: Exception | None = None
        while pending:
            item = first if first is not None else results.get()
            first = None
            pending -= 1
            if item[0] == "ok":
                _, handle, is_hedge, out, meta = item
                if is_hedge:
                    REGISTRY.incr("fleet/hedge_wins")
                if pending:
                    # The loser finishes in the background; its failure
                    # (a crash under a query of death!) must still feed
                    # the breaker/quarantine bookkeeping.
                    self._absorb_loser(
                        results, excluded, saturated, sig, texts
                    )
                return out, meta, handle.name
            _, handle, is_hedge, exc = item
            if not isinstance(exc, Exception):
                raise exc  # KeyboardInterrupt/SystemExit: never classified
            if is_hedge:
                self._note_side_failure(
                    handle, exc, excluded, saturated, sig, texts
                )
            else:
                primary_exc = exc
        if primary_exc is None:  # unreachable: primary always reports
            raise RuntimeError("hedged dispatch lost its primary result")
        raise primary_exc

    def _absorb_loser(
        self, results: queue.SimpleQueue, excluded: set, saturated: list,
        sig: str, texts: list,
    ) -> None:
        def absorb() -> None:
            try:
                item = results.get(timeout=self._request_timeout_s + 5.0)
            except Exception:
                return
            if item[0] == "err" and isinstance(item[3], Exception):
                self._note_side_failure(
                    item[1], item[3], excluded, saturated, sig, texts
                )

        threading.Thread(
            target=absorb, name=f"{self.name}-hedge-absorb", daemon=True,
        ).start()

    def score(self, texts, **kw):
        """(float32 [N, L] scores, response metadata incl. ``replica``)."""
        return self._dispatch(list(texts), want_labels=False, **kw)

    def detect(self, texts, **kw):
        """(labels, response metadata incl. ``replica``)."""
        return self._dispatch(list(texts), want_labels=True, **kw)

    def segment(self, texts, *, top_k=None, reject_threshold=None, **kw):
        """(segmentation result dicts, response metadata incl.
        ``replica``) — forwarded verbatim to a replica's
        ``/detect?mode=segment`` (the replica resolves model-default
        knobs; docs/SEGMENTATION.md)."""
        return self._dispatch(
            list(texts), want_labels=False,
            segment_kw={
                "top_k": top_k, "reject_threshold": reject_threshold,
            },
            **kw,
        )

    def _dispatch(
        self,
        texts: list,
        *,
        want_labels: bool,
        segment_kw: dict | None = None,
        priority: str = INTERACTIVE,
        deadline_ms: float | None = None,
        trace_id: str | None = None,
        tenant: str | None = None,
    ):
        rows = len(texts)
        excluded: set[str] = set()
        saturated: list[float] = []
        t0 = time.perf_counter()
        attempt = 0
        # One trace id per routed request, minted here when the caller
        # didn't bring one: the router's fleet/dispatch span and the
        # winning replica's serve spans all stamp the SAME id, which is
        # what lets the stitcher join one request across process captures
        # (docs/OBSERVABILITY.md §14).
        with trace_request(trace_id) as tid:
            return self._dispatch_traced(
                texts, rows=rows, excluded=excluded, saturated=saturated,
                t0=t0, attempt=attempt, want_labels=want_labels,
                segment_kw=segment_kw, priority=priority,
                deadline_ms=deadline_ms, trace_id=tid, tenant=tenant,
            )

    def _dispatch_traced(
        self,
        texts: list,
        *,
        rows: int,
        excluded: set,
        saturated: list,
        t0: float,
        attempt: int,
        want_labels: bool,
        segment_kw: dict | None,
        priority: str,
        deadline_ms: float | None,
        trace_id: str,
        tenant: str | None,
    ):
        # Absolute deadline, stamped once: failover attempts decay the
        # *remaining* budget, never re-spend the original.
        deadline_at = (
            None if deadline_ms is None
            else t0 + float(deadline_ms) / 1e3
        )
        # Hashing every request buys nothing when the table is off
        # (kill drills, quarantine_deaths<=0): empty sig short-circuits
        # every quarantine call below.
        sig = signature_of(texts) if self.quarantine.enabled else ""
        if sig and self.quarantine.check(sig):
            REGISTRY.incr("fleet/quarantine_rejects")
            log_event(
                _log, "fleet.quarantine_reject", signature=sig,
                rows=rows, trace_id=trace_id,
            )
            raise QueryQuarantined(sig, self.quarantine.deaths_threshold)
        while attempt < self.dispatch_attempts:
            attempt_deadline_ms = None
            if deadline_at is not None:
                attempt_deadline_ms = (
                    (deadline_at - time.perf_counter()) * 1e3
                )
                if attempt_deadline_ms < self.deadline_floor_ms:
                    # Below the floor the answer would be dead on
                    # arrival: 504 here, never burn another replica.
                    REGISTRY.incr("fleet/deadline_rejects")
                    raise ServeDeadlineExceeded(
                        f"remaining deadline "
                        f"{max(attempt_deadline_ms, 0.0):.1f}ms is below "
                        f"the {self.deadline_floor_ms:g}ms dispatch floor "
                        f"after {attempt} attempt(s)"
                    )
            # Every attempt past the first is a retry: it must withdraw
            # from the shared budget, so an outage degrades to bounded
            # goodput loss instead of a retry storm.
            if attempt > 0 and not self.retry_budget.try_spend(
                reason="failover"
            ):
                REGISTRY.incr("fleet/shed_requests")
                raise FleetSaturated(
                    f"retry budget exhausted after {attempt} attempt(s) "
                    f"({self.retry_budget.describe()['tokens']} tokens)",
                    reason="retry_budget_exhausted",
                    retry_after_s=max(self.probe_interval_s, 0.05),
                )
            h = self._pick(rows, excluded)
            if h is None:
                break
            attempt += 1
            self.quarantine.note_dispatch(h.name, sig, texts)
            try:
                out, meta, served_by = self._attempt(
                    h, texts, rows=rows, attempt=attempt,
                    excluded=excluded, saturated=saturated, sig=sig,
                    want_labels=want_labels, segment_kw=segment_kw,
                    priority=priority, deadline_ms=attempt_deadline_ms,
                    trace_id=trace_id, tenant=tenant,
                )
            except ServeHTTPError as e:
                if e.status == 503 and e.shed:
                    # Healthy but saturated: not a failure, but this
                    # request must try the rest of the fleet.
                    saturated.append(e.retry_after_s)
                    excluded.add(h.name)
                    REGISTRY.incr("fleet/replica_saturated")
                    continue
                if e.status == 503 or (e.status >= 500 and e.status != 504):
                    # Closed mid-stop, internal error: the replica is in
                    # trouble — failover, and never retry on it.
                    excluded.add(h.name)
                    self._note_dispatch_failure(h, e)
                    continue
                # 400/404/504: the replica ANSWERED — a bad request stays
                # bad and a blown deadline's answer is already worthless
                # (replaying it elsewhere would bill healthy replicas for
                # dead-on-arrival work and mis-feed their breakers).
                raise
            except Exception as e:
                if not (isinstance(e, HTTPException) or is_retryable(e)):
                    raise
                excluded.add(h.name)
                self._note_dispatch_failure(h, e)
                # A connection severed mid-flight is a dispatch that
                # coincided with replica death: charge this request's
                # signature in the query-of-death table.
                self.quarantine.record_death(
                    sig, replica=h.name, source="router", texts=texts
                )
                continue
            self.retry_budget.record_success()
            REGISTRY.incr("fleet/requests")
            REGISTRY.observe("fleet/request_s", time.perf_counter() - t0)
            REGISTRY.observe("fleet/attempts_per_request", attempt)
            meta["replica"] = served_by
            return out, meta
        # Exhausted. Every eligible replica either shed (saturated) or
        # died under this request (excluded) — an explicit, retryable
        # fleet-wide 503 either way, never a hang and never a drop the
        # client can't recover with its Retry-After backoff.
        REGISTRY.incr("fleet/shed_requests")
        if saturated:
            positive = [s for s in saturated if s > 0]
            retry_after = min(positive) if positive else self.probe_interval_s
            raise FleetSaturated(
                f"every ready replica shed ({len(saturated)} saturated, "
                f"{len(excluded) - len(saturated)} failed)",
                reason="fleet_saturated",
                retry_after_s=max(retry_after, 0.001),
            )
        raise NoReadyReplica(
            f"no ready replica (eligible={self.eligible()}, "
            f"excluded={sorted(excluded)})",
            reason="no_ready_replica",
            retry_after_s=max(
                self.probe_interval_s * 2, self.probe_timeout_s / 2, 0.05
            ),
        )

    # ---------------------------------------------- swap coordination hooks --
    def pin_version(self, version: str | None) -> None:
        """Restrict routing to replicas serving ``version`` (None clears).
        The fleet swap pins the old version before the first flip and
        moves the pin exactly once — the cutover — which is what makes
        per-client-stream versions monotonic (docs/SERVING.md §9)."""
        with self._lock:
            self._pin = version
        log_event(_log, "fleet.pin", version=version)

    @property
    def pinned_version(self) -> str | None:
        with self._lock:
            return self._pin

    def set_draining(self, name: str, draining: bool) -> None:
        h = self._handle(name)
        with self._lock:
            h.draining = draining

    def note_version(self, name: str, version: str | None) -> None:
        """Record a replica's serving version without waiting for the
        next probe round (the fleet swap calls this at each flip)."""
        h = self._handle(name)
        with self._lock:
            h.version = version

    def outstanding(self, name: str) -> int:
        h = self._handle(name)
        with self._lock:
            return h.outstanding_rows

    def wait_drained(self, name: str, timeout_s: float | None = None) -> bool:
        """Poll until no routed request is outstanding on ``name``."""
        deadline = time.monotonic() + (
            self.drain_timeout_s if timeout_s is None else timeout_s
        )
        while self.outstanding(name) > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True

    def _handle(self, name: str) -> ReplicaHandle:
        # Locked walk: the handle table mutates under dynamic membership,
        # and an unlocked iteration racing a concurrent remove could skip
        # the element shifted into the removed slot.
        with self._lock:
            for h in self._handles:
                if h.name == name:
                    return h
        raise ValueError(f"unknown replica {name!r}")

    # ------------------------------------------------------------- status ---
    def healthz(self) -> dict:
        with self._lock:
            replicas = [h.describe() for h in self._handles]
            pin = self._pin
        eligible = self.eligible()
        return {
            "ok": bool(eligible),
            "router": True,
            "ready_replicas": eligible,
            "pinned_version": pin,
            "replicas": replicas,
            "uptime_s": round(time.monotonic() - self._started, 3),
            # Storm-defense state (docs/RESILIENCE.md §7): the budget's
            # live token balance and the query-of-death table, so /varz
            # shows WHY the fleet is shedding retries or 422ing a
            # signature.
            "retry_budget": self.retry_budget.describe(),
            "quarantine": self.quarantine.describe(),
            "hedging": {
                "enabled": self.hedge_enable,
                "quantile": self.hedge_quantile,
                "min_ms": self.hedge_min_ms,
                "delay_ms": round(self._hedge_delay_s() * 1e3, 3),
            },
        }

    def readyz(self) -> dict:
        eligible = self.eligible()
        return {
            "ready": bool(eligible),
            "reasons": [] if eligible else ["no_ready_replica"],
            "version": self.pinned_version,
            "ready_replicas": eligible,
        }


class RouterServer(JsonHTTPFront):
    """HTTP front end for the router: the same JSON surface as one
    replica (``/score`` ``/detect`` ``/healthz[/live|/ready]`` ``/varz``
    ``/admin/swap`` ``/admin/rollback``), so :class:`~.client.ServeClient`
    drives a fleet exactly like a single server — responses additionally
    carry the serving ``replica``. Admin endpoints require an attached
    :class:`~.fleet.ServeFleet` (they coordinate the two-phase swap).
    """

    thread_name = "fleet-http"

    def __init__(
        self,
        router: FleetRouter,
        *,
        fleet=None,
        host: str = "127.0.0.1",
        port: int = 8000,
        admin: bool = True,
        collector=None,
        slo=None,
    ):
        self.router = router
        self.fleet = fleet
        self.admin = admin
        # Optional observability plane (docs/OBSERVABILITY.md §14): an
        # elastic fleet hands its FleetCollector + SloEvaluator in so the
        # fleet /varz serves the merged aggregate and /healthz carries
        # the burn-rate verdicts. Both default to the elastic fleet's own
        # instances when started via ElasticFleet.
        self.collector = collector
        self.slo = slo
        super().__init__(host, port)

    # ---------------------------------------------------------- handlers ----
    def score(self, payload: dict, *, labels: bool, mode: str | None = None) -> dict:
        texts = payload.get("texts", payload.get("docs"))
        if not isinstance(texts, list) or not all(
            isinstance(t, str) for t in texts
        ):
            raise ValueError('"texts" must be a list of strings')
        priority = payload.get("priority", INTERACTIVE)
        if priority not in LANES:
            raise ValueError(
                f"unknown priority {priority!r}; expected one of {LANES}"
            )
        deadline_ms = payload.get("deadline_ms")
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
        if mode not in (None, "label", "segment"):
            raise ValueError(
                f"unknown mode {mode!r}; expected 'label' or 'segment'"
            )
        # Tenant pass-through (SERVING.md §12): the router front carries
        # the request's tenant to the serving replica untouched — the
        # replica's zoo resolves it (or 400s on a non-zoo replica),
        # exactly as a direct client would see.
        tenant = payload.get("tenant")
        if labels and mode == "segment":
            # Forwarded knobs only — the serving replica resolves its
            # model's defaults, exactly like a direct client would see.
            out, meta = self.router.segment(
                texts,
                top_k=payload.get("top_k"),
                reject_threshold=payload.get("reject_threshold"),
                priority=priority, deadline_ms=deadline_ms,
                trace_id=payload.get("trace_id"), tenant=tenant,
            )
            meta["mode"] = "segment"
            meta["results"] = out
        elif labels:
            out, meta = self.router.detect(
                texts, priority=priority, deadline_ms=deadline_ms,
                tenant=tenant,
            )
            if meta.get("mode") == "segment":
                # The replica's model answered /detect in its own
                # segment default: keep the honest key.
                meta["results"] = out
            else:
                meta["labels"] = out
        else:
            out, meta = self.router.score(
                texts, priority=priority, deadline_ms=deadline_ms,
                trace_id=payload.get("trace_id"), tenant=tenant,
            )
            # f32 -> f64 -> JSON double round-trips exactly, so routing
            # through this tier stays bit-transparent end to end.
            meta["scores"] = [[float(v) for v in row] for row in out]
        return meta

    @staticmethod
    def _reject_tenant(payload: dict | None) -> None:
        # The fleet swap/rollback is whole-fleet and single-model by
        # construction; silently performing it for a request that named a
        # tenant would mutate the WRONG model (SERVING.md §12's loud-400
        # contract). Tenant-scoped admin goes to a replica's own surface.
        if payload and payload.get("tenant") is not None:
            raise ValueError(
                '"tenant" is not supported by the fleet admin surface; '
                "send tenant-scoped swaps to a zoo-backed replica's "
                "/admin endpoints"
            )

    def swap(self, payload: dict) -> dict:
        if not self.admin:
            raise ServeError("admin endpoints disabled")
        self._reject_tenant(payload)
        if self.fleet is None:
            raise ServeError("no fleet attached to this router front end")
        path = payload.get("path")
        if not isinstance(path, str) or not path:
            raise ValueError('"path" must name a saved model directory')
        version = self.fleet.swap(path, version=payload.get("version"))
        return {"version": version}

    def rollback(self, payload: dict | None = None) -> dict:
        if not self.admin:
            raise ServeError("admin endpoints disabled")
        self._reject_tenant(payload)
        if self.fleet is None:
            raise ServeError("no fleet attached to this router front end")
        return {"version": self.fleet.rollback()}

    def healthz(self) -> dict:
        out = self.router.healthz()
        out["draining"] = self._draining
        out["uptime_s"] = round(time.monotonic() - self._started, 3)
        if self.slo is not None:
            # Burn-rate verdicts join the fleet's reasons surface: a
            # burning objective is an operator-facing "why is this
            # unhealthy" even while routing still succeeds.
            slo = self.slo.status()
            out["slo"] = slo
            if slo["burning"]:
                out["reasons"] = (
                    list(out.get("reasons") or []) + slo["reasons"]
                )
        if self.collector is not None:
            out["telemetry"] = {
                "members": self.collector.members(),
                "scrapes": self.collector.scrapes,
                "scrape_failures": self.collector.scrape_failures,
                "freshness_s": round(self.collector.freshness_s(), 3),
            }
        return out

    def readyz(self) -> dict:
        out = self.router.readyz()
        if self._draining:
            out["ready"] = False
            out["reasons"] = list(out.get("reasons") or []) + ["draining"]
        out["draining"] = self._draining
        return out

    def varz(self) -> dict:
        snap = REGISTRY.snapshot()
        return {
            "stages": REGISTRY.stage_summary(),
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": {
                name: h for name, h in snap["histograms"].items()
                if not name.startswith(("span:", "span_device:"))
            },
            "fleet": self.router.healthz(),
            # The merged fleet view (docs/OBSERVABILITY.md §14): counters
            # summed exactly across replicas (terminal scrapes included,
            # so a drained member's tally survives it), histograms as
            # merged sketches, gauges labelled per replica — plus the
            # per-replica scrape ledger.
            "fleet_telemetry": (
                None if self.collector is None else {
                    "aggregate": self.collector.aggregate(),
                    "replicas": self.collector.per_replica(),
                }
            ),
            "slo": None if self.slo is None else self.slo.status(),
            "config": exec_config.effective_config(),
        }
