"""Stdlib-only HTTP front end for the serving subsystem.

A threaded ``http.server`` speaking JSON, so the whole online stack —
admission, coalescing, shedding, hot-swap — is drivable with nothing but
the standard library (the image bakes in no web framework, and none is
needed: the batcher already serializes device work onto one thread, so
the HTTP layer only has to block cheaply).

Endpoints (docs/SERVING.md §2):

  * ``POST /score``   ``{"texts": [...], "priority"?, "deadline_ms"?}``
    → ``{"scores": [[...]], "version", "trace_id", ...}``
  * ``POST /detect``  same request shape → ``{"labels": [...], ...}``
  * ``GET  /healthz`` combined snapshot (liveness + readiness + queue/
    breaker/version detail)
  * ``GET  /healthz/live``  liveness only: answers 200 whenever the
    process can still serve HTTP at all
  * ``GET  /healthz/ready`` readiness: 200 only when this replica should
    receive traffic — 503 (with machine-readable ``reasons``) while the
    runner's breaker is open, the degraded ladder is active, the server
    is draining, or no model is installed. The distinction is what a
    fleet router keys on (docs/SERVING.md §9): a degraded replica is
    *live* but must not be routed to.
  * ``GET  /varz``    telemetry: stage summaries, counters, gauges, and
    the serve latency histograms
  * ``POST /admin/swap``     ``{"path": "<model dir>"}`` → hot-swap
  * ``POST /admin/rollback`` → previous version

Failure mapping: a shed request answers ``503`` with a ``Retry-After``
header, a blown deadline ``504``, a bad request ``400`` — never a hang
(the acceptance contract: shed means an explicit rejection).

Texts are encoded server-side with the active model's
``predictEncoding`` param, so HTTP clients get byte-identical semantics
to calling ``model.transform`` locally.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..exec import config as exec_config
from ..ops.encoding import UTF8, text_to_bytes
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event
from .batcher import (
    LANES,
    ContinuousBatcher,
    ServeClosed,
    ServeDeadlineExceeded,
    ServeError,
    ServeOverloaded,
)
from .client import ServeHTTPError
from .quarantine import QueryQuarantined
from .registry import ModelRegistry

_log = get_logger("serve.server")

MAX_BODY_BYTES = 64 << 20  # one request can still carry a bulk doc list


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "langdetect-serve"

    # ------------------------------------------------------------ plumbing --
    def log_message(self, fmt, *args):  # route access logs to our logger
        _log.debug("%s %s", self.address_string(), fmt % args)

    def _reply(self, status: int, payload: dict, headers: dict | None = None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length > MAX_BODY_BYTES:
            raise ValueError(f"request body too large ({length} bytes)")
        raw = self.rfile.read(length) if length else b""
        payload = json.loads(raw.decode("utf-8")) if raw else {}
        if not isinstance(payload, dict):
            raise ValueError("request body must be a JSON object")
        return payload

    # ------------------------------------------------------------- routes --
    def do_GET(self):
        try:
            if self.path == "/healthz":
                self._reply(200, self.server.healthz())
            elif self.path == "/healthz/live":
                self._reply(200, self.server.livez())
            elif self.path == "/healthz/ready":
                payload = self.server.readyz()
                # k8s convention: a not-ready replica answers the probe
                # (it is live) but with 503, so dumb LBs drop it too.
                self._reply(200 if payload.get("ready") else 503, payload)
            elif self.path == "/varz":
                self._reply(200, self.server.varz())
            elif self.path == "/telemetryz":
                self._reply(200, self.server.telemetryz())
            else:
                self._reply(404, {"error": f"unknown path {self.path!r}"})
        except Exception as e:  # never let a probe kill the connection
            self._reply(500, {"error": repr(e)})

    def do_POST(self):
        # Tracked so stop() can drain: an accepted request is answered
        # before the batcher is torn down (the zero-loss stop contract).
        with self.server.track_request():
            self._do_post_tracked()

    def _do_post_tracked(self):
        try:
            payload = self._read_json()
        except json.JSONDecodeError as e:  # before ValueError: its subclass
            self._reply(400, {"error": f"bad JSON: {e}"})
            return
        except ValueError as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            from urllib.parse import parse_qs, urlsplit

            parts = urlsplit(self.path)
            route = parts.path
            query = {
                k: v[-1] for k, v in parse_qs(parts.query).items()
            }
            if route == "/score":
                self._reply(200, self.server.score(payload, labels=False))
            elif route == "/detect":
                # ?mode=segment (or a "mode" body key) switches /detect to
                # the span-level segmentation result type; an unadorned
                # /detect follows the active model's resultMode param
                # (docs/SERVING.md §11, docs/SEGMENTATION.md).
                mode = query.get("mode", payload.get("mode"))
                self._reply(
                    200, self.server.score(payload, labels=True, mode=mode)
                )
            elif route == "/admin/swap":
                self._reply(200, self.server.swap(payload))
            elif route == "/admin/rollback":
                self._reply(200, self.server.rollback(payload))
            else:
                self._reply(404, {"error": f"unknown path {self.path!r}"})
        except ServeOverloaded as e:
            self._reply(
                503,
                {"error": str(e), "shed": True, "reason": e.reason},
                {"Retry-After": f"{max(e.retry_after_s, 0.001):.3f}"},
            )
        except ServeDeadlineExceeded as e:
            self._reply(504, {"error": str(e), "deadline": True})
        except ServeClosed as e:
            self._reply(503, {"error": str(e), "closed": True})
        except ServeHTTPError as e:
            # A replica's own verdict surfacing through the router front
            # (a 400/504 the router rightly refuses to retry): mirror the
            # status, payload, and Retry-After instead of flattening it
            # to a 500 — the front presents the same surface as one
            # replica.
            payload = (
                e.payload if isinstance(e.payload, dict)
                else {"error": str(e)}
            )
            headers = {}
            for k, v in (e.headers or {}).items():
                if k.lower() == "retry-after":
                    headers["Retry-After"] = v
            self._reply(e.status, payload, headers)
        except QueryQuarantined as e:
            # Query of death (docs/RESILIENCE.md §7): a well-formed
            # request the fleet refuses to re-serve — 422, with the
            # signature so the caller can find it in the serve DLQ.
            # Before ValueError: QueryQuarantined subclasses it.
            self._reply(422, {
                "error": str(e),
                "quarantined": True,
                "signature": e.signature,
            })
        except (ValueError, KeyError) as e:
            self._reply(400, {"error": repr(e)})
        except Exception as e:
            self._reply(500, {"error": repr(e)})


class JsonHTTPFront(ThreadingHTTPServer):
    """Shared lifecycle for the JSON front ends (one serving replica or
    the fleet router): daemon serve thread, in-flight request tracking,
    and a draining ``stop()`` — mark draining (readiness flips false),
    stop accepting, wait for accepted requests to be answered, only then
    tear the backend down. Subclasses implement the handler surface
    (``score``/``swap``/``rollback``/``healthz``/``readyz``/``varz``)
    and ``_teardown``.
    """

    daemon_threads = True
    thread_name = "serve-http"

    def __init__(self, host: str, port: int):
        self._started = time.monotonic()
        self._thread: threading.Thread | None = None
        self._draining = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        super().__init__((host, port), _Handler)

    # --------------------------------------------------------- lifecycle ----
    @property
    def address(self) -> tuple[str, int]:
        return self.server_address[0], self.server_address[1]

    @property
    def draining(self) -> bool:
        return self._draining

    @contextmanager
    def track_request(self):
        """Count one in-flight HTTP request (the handler wraps every POST
        in this) so a draining stop knows when every accepted request has
        been answered."""
        with self._inflight_cv:
            self._inflight += 1
        try:
            yield
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def start(self):
        """Serve on a daemon thread; returns self (``with`` works too)."""
        self._thread = threading.Thread(
            target=self.serve_forever, name=self.thread_name, daemon=True
        )
        self._thread.start()
        log_event(_log, "serve.http.start", host=self.address[0],
                  port=self.address[1])
        return self

    def stop(self, *, drain: bool = True, drain_timeout_s: float = 30.0):
        """Stop serving. With ``drain`` (the default) this is hitless for
        accepted work: readiness flips false first (a router stops
        sending), the listener stops accepting, every in-flight request
        is answered, and only then is the backend torn down — a stop
        issued mid-burst loses zero accepted requests (pinned by
        ``tests/test_fleet.py``). ``drain=False`` is the abrupt path
        (crash drills): queued requests fail explicitly, never hang."""
        self._draining = True
        self.shutdown()
        if drain:
            deadline = time.monotonic() + drain_timeout_s
            with self._inflight_cv:
                while self._inflight > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        log_event(
                            _log, "serve.http.drain_timeout",
                            inflight=self._inflight, port=self.address[1],
                        )
                        break
                    self._inflight_cv.wait(min(remaining, 0.2))
        self._teardown(drain)
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        log_event(_log, "serve.http.stop", port=self.address[1],
                  drained=drain)

    def _teardown(self, drain: bool) -> None:  # subclass hook
        pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ----------------------------------------------------------- probes -----
    def telemetryz(self) -> dict:
        """The mergeable telemetry scrape (docs/OBSERVABILITY.md §14): the
        process-global registry in :meth:`~..telemetry.registry.Registry.
        mergeable_snapshot` wire form, stamped with this process's
        identity. Both front ends expose it — a replica's scrape feeds
        the fleet collector; the router's is its own local view."""
        from ..telemetry.aggregate import process_identity

        snap = REGISTRY.mergeable_snapshot()
        if not snap.get("identity"):
            snap["identity"] = process_identity()
        return snap

    def livez(self) -> dict:
        """Liveness: answering at all is the signal; the body is detail."""
        return {
            "live": True,
            "draining": self._draining,
            "uptime_s": round(time.monotonic() - self._started, 3),
        }


class ServingServer(JsonHTTPFront):
    """HTTP front end bound to a registry + batcher, or to a model zoo.

    ``registry`` may be a :class:`~.registry.ModelRegistry`, a fitted
    ``LanguageDetectorModel`` (wrapped into a fresh registry), or a
    :class:`~..zoo.ModelZoo` — the multi-tenant form (docs/SERVING.md
    §12): requests carry an optional ``"tenant"`` key, routed to that
    tenant's registry + batcher (no key ⇒ the zoo's default tenant,
    bit-identical to the single-model surface). The batcher defaults to
    env-tuned knobs; pass one to share it with in-process callers.
    ``port=0`` binds an ephemeral port (tests).
    """

    def __init__(
        self,
        registry,
        *,
        host: str = "127.0.0.1",
        port: int = 8000,
        batcher: ContinuousBatcher | None = None,
        admin: bool = True,
        **batcher_kw,
    ):
        if hasattr(registry, "runtime") and hasattr(registry, "tenants"):
            # A ModelZoo (duck-typed: the serve package must not import
            # the zoo eagerly). Per-tenant batchers live in the zoo.
            self.zoo = registry
            self.registry = None
            self._own_batcher = False
            self.batcher = None
        else:
            if not hasattr(registry, "lease"):
                model, registry = registry, ModelRegistry()
                registry.install(model)
            self.zoo = None
            self.registry = registry
            self._own_batcher = batcher is None
            self.batcher = batcher or ContinuousBatcher(
                registry, **batcher_kw
            )
        self.admin = admin
        super().__init__(host, port)

    def _teardown(self, drain: bool) -> None:
        if self.zoo is not None:
            self.zoo.close(drain=drain)
        elif self._own_batcher:
            self.batcher.close(drain=drain)

    # ------------------------------------------------------ tenant routing --
    def _route(self, payload: dict):
        """(registry, batcher, tenant name or None) for one request.

        Single-model servers reject an explicit tenant loudly (a 400 —
        silently ignoring it could answer from the wrong model). On a
        zoo, an absent/None tenant resolves to the default tenant; an
        unknown tenant is a 400; a failed cold load is that tenant's
        503 + Retry-After (docs/SERVING.md §12).
        """
        tenant = payload.get("tenant")
        if self.zoo is None:
            if tenant is not None:
                raise ValueError(
                    '"tenant" requires a model-zoo-backed server'
                )
            return self.registry, self.batcher, None
        entry, rt = self.zoo.runtime(tenant)
        return rt.registry, rt.batcher, entry.name

    # ---------------------------------------------------------- handlers ----
    def _segment_options(self, payload: dict, model):
        """Resolve the decode knobs for one ``/detect`` segment request:
        request body keys (``top_k``, ``reject_threshold``) win, then the
        active model's ``topK``/``rejectThreshold`` params, then the
        :class:`~..segment.SegmentOptions` defaults. Validation lives in
        SegmentOptions itself (a bad knob is a 400, never a dispatch)."""
        from ..segment import SegmentOptions

        defaults = SegmentOptions()
        top_k = payload.get("top_k")
        reject = payload.get("reject_threshold")
        if top_k is None:
            top_k = (
                model.get("topK") if model is not None else defaults.top_k
            )
        if reject is None:
            reject = (
                model.get("rejectThreshold") if model is not None
                else defaults.reject_threshold
            )
        if not isinstance(top_k, int) or isinstance(top_k, bool):
            raise ValueError(f'"top_k" must be an integer, got {top_k!r}')
        if not isinstance(reject, (int, float)) or isinstance(reject, bool):
            raise ValueError(
                f'"reject_threshold" must be a number, got {reject!r}'
            )
        return SegmentOptions(
            top_k=int(top_k), reject_threshold=float(reject)
        )

    def score(self, payload: dict, *, labels: bool, mode: str | None = None) -> dict:
        texts = payload.get("texts", payload.get("docs"))
        if not isinstance(texts, list) or not all(
            isinstance(t, str) for t in texts
        ):
            raise ValueError('"texts" must be a list of strings')
        priority = payload.get("priority", "interactive")
        if priority not in LANES:
            raise ValueError(
                f"unknown priority {priority!r}; expected one of {LANES}"
            )
        deadline_ms = payload.get("deadline_ms")
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
        want_mode = mode
        # A zoo-backed request may race a residency eviction between
        # resolving its tenant's runtime and admitting: the closed
        # batcher rejects explicitly, and re-resolving takes the
        # cold-load path — bounded, so a genuinely closed server still
        # answers 503 rather than looping (docs/SERVING.md §12).
        for attempt in range(3):
            registry, batcher, tenant = self._route(payload)
            # Encoding is resolved at ADMISSION against the active
            # version; a concurrent swap that also changes
            # predictEncoding could dispatch these bytes on the new
            # version. Keep predictEncoding consistent across versions
            # you hot-swap between (or drain first) — swapping the
            # encoding mid-traffic has no well-defined answer for
            # requests already in the queue (docs/SERVING.md §2).
            entry = registry.peek()
            model = entry.model
            encoding = (
                model.get("predictEncoding") if model is not None else UTF8
            )
            # /detect result-type resolution: an explicit ?mode= (or body
            # "mode") wins; otherwise the active model's resultMode param
            # decides, so a segment-mode model serves segmentation by
            # default (docs/SEGMENTATION.md).
            mode = want_mode
            if labels and mode is None and model is not None:
                mode = model.get("resultMode")
            if mode not in (None, "label", "segment"):
                raise ValueError(
                    f"unknown mode {mode!r}; expected 'label' or 'segment'"
                )
            segment_options = None
            if labels and mode == "segment":
                segment_options = self._segment_options(payload, model)
            docs = [text_to_bytes(t, encoding) for t in texts]
            try:
                fut = batcher.submit(
                    docs, priority=priority,
                    want_labels=labels and segment_options is None,
                    segment_options=segment_options,
                    deadline_ms=deadline_ms,
                    trace_id=payload.get("trace_id"),
                )
                result = fut.result()
            except ServeClosed:
                if self.zoo is None or attempt == 2:
                    raise
                continue
            break
        from ..telemetry.aggregate import process_identity

        out = {
            "version": result.version,
            "trace_id": result.trace_id,
            "queue_wait_ms": round(result.queue_wait_s * 1e3, 3),
            "dispatch_ms": round(result.dispatch_s * 1e3, 3),
            # Structured latency attribution (docs/OBSERVABILITY.md §14):
            # the same legs as the top-level ms fields (kept for compat)
            # plus the coalescing context, and the identity of the
            # process that actually served — clients can attribute
            # latency without a telemetry capture.
            "server_timing": {
                "queue_wait_ms": round(result.queue_wait_s * 1e3, 3),
                "dispatch_ms": round(result.dispatch_s * 1e3, 3),
                "rows_coalesced": result.rows_coalesced,
            },
            "server": process_identity(),
        }
        if tenant is not None:
            out["tenant"] = tenant
        if segment_options is not None:
            out["mode"] = "segment"
            out["results"] = result.results
        elif labels:
            out["labels"] = result.labels
        else:
            # float() of a float32 is exact (f32 ⊂ f64) and JSON doubles
            # round-trip, so the wire is bit-transparent for scores.
            out["scores"] = [
                [float(v) for v in row] for row in result.values
            ]
        return out

    def swap(self, payload: dict) -> dict:
        if not self.admin:
            raise ServeError("admin endpoints disabled")
        path = payload.get("path")
        if not isinstance(path, str) or not path:
            raise ValueError('"path" must name a saved model directory')
        if self.zoo is not None:
            tenant = payload.get("tenant")
            version = self.zoo.load(
                tenant, path, version=payload.get("version")
            )
            return {
                "version": version,
                "tenant": tenant or self.zoo.default_tenant,
            }
        if payload.get("tenant") is not None:
            raise ValueError('"tenant" requires a model-zoo-backed server')
        version = self.registry.load(path, version=payload.get("version"))
        return {"version": version}

    def rollback(self, payload: dict | None = None) -> dict:
        if not self.admin:
            raise ServeError("admin endpoints disabled")
        if self.zoo is not None:
            tenant = (payload or {}).get("tenant")
            return {
                "version": self.zoo.rollback(tenant),
                "tenant": tenant or self.zoo.default_tenant,
            }
        if payload is not None and payload.get("tenant") is not None:
            raise ValueError('"tenant" requires a model-zoo-backed server')
        return {"version": self.registry.rollback()}

    def readyz(self) -> dict:
        """Readiness: should this replica receive traffic *right now*?

        Not ready (with a reason) while the server is draining, the
        runner's breaker is anything but closed, the degraded ladder is
        active, or no model is installed. Liveness is deliberately
        looser — a degraded replica is alive (it answers, exactly, via
        the fallback ladder) but a router with healthy alternatives
        should prefer them (docs/SERVING.md §9)."""
        reasons: list[str] = []
        version = None
        if self._draining:
            reasons.append("draining")
        if self.zoo is not None:
            # Zoo readiness: the default tenant must at least be
            # registered (resident or cold — a cold tenant is servable
            # after its first-request load). Per-tenant detail lives in
            # the healthz/varz zoo blocks.
            try:
                version = self.zoo.version(None)
            except ServeError:
                reasons.append("no_default_tenant")
            return {
                "ready": not reasons,
                "reasons": reasons,
                "version": version,
                "tenants": len(self.zoo.tenants()),
                "draining": self._draining,
            }
        try:
            entry = self.registry.peek()
            version = entry.version
            runner = entry.runner
            breaker = getattr(runner, "breaker", None)
            state = breaker.state if breaker is not None else "closed"
            if state != "closed":
                reasons.append(f"breaker_{state}")
            if getattr(runner, "_degraded_mode", False):
                reasons.append("degraded")
        except ServeError:
            reasons.append("no_model")
        return {
            "ready": not reasons,
            "reasons": reasons,
            "version": version,
            "draining": self._draining,
        }

    def healthz(self) -> dict:
        ready = self.readyz()
        out = {
            "ok": True,
            "ready": ready["ready"],
            "reasons": ready["reasons"],
            "draining": self._draining,
            "uptime_s": round(time.monotonic() - self._started, 3),
        }
        if self.zoo is not None:
            # Per-tenant blocks: version, residency, loads, and each
            # tenant's own queue stats incl. its shed tallies — the
            # operator-facing half of tenant isolation (SERVING.md §12).
            out["zoo"] = self.zoo.healthz()
            out["cache"] = (
                None if self.zoo.cache is None else self.zoo.cache.stats()
            )
            return out
        out["batcher"] = self.batcher.stats()
        out["cache"] = (
            None if self.batcher.cache is None
            else self.batcher.cache.stats()
        )
        try:
            entry = self.registry.peek()
            runner = entry.runner
            out["version"] = entry.version
            out["languages"] = len(entry.languages or ())
            breaker = getattr(runner, "breaker", None)
            out["breaker"] = breaker.state if breaker is not None else None
            out["degraded"] = bool(getattr(runner, "_degraded_mode", False))
        except ServeError as e:
            out["ok"] = False
            out["error"] = str(e)
        return out

    def varz(self) -> dict:
        snap = REGISTRY.snapshot()
        return {
            "stages": REGISTRY.stage_summary(),
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": {
                name: h for name, h in snap["histograms"].items()
                if not name.startswith(("span:", "span_device:"))
            },
            "versions": (
                self.registry.versions()
                if hasattr(self.registry, "versions") else []
            ),
            # Per-tenant control-plane state (versions, residency, quota
            # lanes, shed tallies) when a zoo backs this server.
            "zoo": None if self.zoo is None else self.zoo.varz(),
            # Hit rate + occupancy of the serve score cache (None when
            # disabled) — the level-2 half of docs/PERFORMANCE.md §10.
            # Zoo-backed servers share ONE tenant-partitioned cache.
            "cache": (
                (None if self.zoo.cache is None
                 else self.zoo.cache.stats())
                if self.zoo is not None else
                (None if self.batcher.cache is None
                 else self.batcher.cache.stats())
            ),
            # The audited effective config: every LANGDETECT_* knob's live
            # value and provenance (explicit/env/profile/default), plus
            # the active tuning profile and the deprecation table — "which
            # knob is actually driving this deployment" answered from one
            # endpoint (docs/PERFORMANCE.md §9).
            "config": exec_config.effective_config(),
        }


def main(argv: list[str] | None = None) -> int:
    """``python -m spark_languagedetector_tpu.serve.server <model_dir>
    [host:port]`` — load a persisted model and serve it."""
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if not 1 <= len(argv) <= 2 or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m spark_languagedetector_tpu.serve.server "
            "<model_dir> [host:port]",
            file=sys.stderr,
        )
        return 2
    host, port = "127.0.0.1", 8000
    if len(argv) == 2:
        host, _, p = argv[1].rpartition(":")
        host = host or "127.0.0.1"
        port = int(p)
    registry = ModelRegistry()
    registry.load(argv[0])
    server = ServingServer(registry, host=host, port=port)
    print(f"serving {registry.current_version()} on "
          f"{server.address[0]}:{server.address[1]}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
