"""Stdlib-only HTTP front end for the serving subsystem.

A threaded ``http.server`` speaking JSON, so the whole online stack —
admission, coalescing, shedding, hot-swap — is drivable with nothing but
the standard library (the image bakes in no web framework, and none is
needed: the batcher already serializes device work onto one thread, so
the HTTP layer only has to block cheaply).

Endpoints (docs/SERVING.md §2):

  * ``POST /score``   ``{"texts": [...], "priority"?, "deadline_ms"?}``
    → ``{"scores": [[...]], "version", "trace_id", ...}``
  * ``POST /detect``  same request shape → ``{"labels": [...], ...}``
  * ``GET  /healthz`` liveness + queue/breaker/version snapshot
  * ``GET  /varz``    telemetry: stage summaries, counters, gauges, and
    the serve latency histograms
  * ``POST /admin/swap``     ``{"path": "<model dir>"}`` → hot-swap
  * ``POST /admin/rollback`` → previous version

Failure mapping: a shed request answers ``503`` with a ``Retry-After``
header, a blown deadline ``504``, a bad request ``400`` — never a hang
(the acceptance contract: shed means an explicit rejection).

Texts are encoded server-side with the active model's
``predictEncoding`` param, so HTTP clients get byte-identical semantics
to calling ``model.transform`` locally.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..exec import config as exec_config
from ..ops.encoding import UTF8, text_to_bytes
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event
from .batcher import (
    LANES,
    ContinuousBatcher,
    ServeClosed,
    ServeDeadlineExceeded,
    ServeError,
    ServeOverloaded,
)
from .registry import ModelRegistry

_log = get_logger("serve.server")

MAX_BODY_BYTES = 64 << 20  # one request can still carry a bulk doc list


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "langdetect-serve"

    # ------------------------------------------------------------ plumbing --
    def log_message(self, fmt, *args):  # route access logs to our logger
        _log.debug("%s %s", self.address_string(), fmt % args)

    def _reply(self, status: int, payload: dict, headers: dict | None = None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length > MAX_BODY_BYTES:
            raise ValueError(f"request body too large ({length} bytes)")
        raw = self.rfile.read(length) if length else b""
        payload = json.loads(raw.decode("utf-8")) if raw else {}
        if not isinstance(payload, dict):
            raise ValueError("request body must be a JSON object")
        return payload

    # ------------------------------------------------------------- routes --
    def do_GET(self):
        try:
            if self.path == "/healthz":
                self._reply(200, self.server.healthz())
            elif self.path == "/varz":
                self._reply(200, self.server.varz())
            else:
                self._reply(404, {"error": f"unknown path {self.path!r}"})
        except Exception as e:  # never let a probe kill the connection
            self._reply(500, {"error": repr(e)})

    def do_POST(self):
        try:
            payload = self._read_json()
        except json.JSONDecodeError as e:  # before ValueError: its subclass
            self._reply(400, {"error": f"bad JSON: {e}"})
            return
        except ValueError as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            if self.path == "/score":
                self._reply(200, self.server.score(payload, labels=False))
            elif self.path == "/detect":
                self._reply(200, self.server.score(payload, labels=True))
            elif self.path == "/admin/swap":
                self._reply(200, self.server.swap(payload))
            elif self.path == "/admin/rollback":
                self._reply(200, self.server.rollback())
            else:
                self._reply(404, {"error": f"unknown path {self.path!r}"})
        except ServeOverloaded as e:
            self._reply(
                503,
                {"error": str(e), "shed": True, "reason": e.reason},
                {"Retry-After": f"{max(e.retry_after_s, 0.001):.3f}"},
            )
        except ServeDeadlineExceeded as e:
            self._reply(504, {"error": str(e), "deadline": True})
        except ServeClosed as e:
            self._reply(503, {"error": str(e), "closed": True})
        except (ValueError, KeyError) as e:
            self._reply(400, {"error": repr(e)})
        except Exception as e:
            self._reply(500, {"error": repr(e)})


class ServingServer(ThreadingHTTPServer):
    """HTTP front end bound to a registry + batcher.

    ``registry`` may be a :class:`~.registry.ModelRegistry` or a fitted
    ``LanguageDetectorModel`` (wrapped into a fresh registry). The
    batcher defaults to env-tuned knobs; pass one to share it with
    in-process callers. ``port=0`` binds an ephemeral port (tests).
    """

    daemon_threads = True

    def __init__(
        self,
        registry,
        *,
        host: str = "127.0.0.1",
        port: int = 8000,
        batcher: ContinuousBatcher | None = None,
        admin: bool = True,
        **batcher_kw,
    ):
        if not hasattr(registry, "lease"):
            model, registry = registry, ModelRegistry()
            registry.install(model)
        self.registry = registry
        self._own_batcher = batcher is None
        self.batcher = batcher or ContinuousBatcher(registry, **batcher_kw)
        self.admin = admin
        self._started = time.monotonic()
        self._thread: threading.Thread | None = None
        super().__init__((host, port), _Handler)

    # --------------------------------------------------------- lifecycle ----
    @property
    def address(self) -> tuple[str, int]:
        return self.server_address[0], self.server_address[1]

    def start(self) -> "ServingServer":
        """Serve on a daemon thread; returns self (``with`` works too)."""
        self._thread = threading.Thread(
            target=self.serve_forever, name="serve-http", daemon=True
        )
        self._thread.start()
        log_event(_log, "serve.http.start", host=self.address[0],
                  port=self.address[1])
        return self

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if self._own_batcher:
            self.batcher.close()
        log_event(_log, "serve.http.stop", port=self.address[1])

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------------------------------------------------- handlers ----
    def score(self, payload: dict, *, labels: bool) -> dict:
        texts = payload.get("texts", payload.get("docs"))
        if not isinstance(texts, list) or not all(
            isinstance(t, str) for t in texts
        ):
            raise ValueError('"texts" must be a list of strings')
        priority = payload.get("priority", "interactive")
        if priority not in LANES:
            raise ValueError(
                f"unknown priority {priority!r}; expected one of {LANES}"
            )
        deadline_ms = payload.get("deadline_ms")
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
        # Encoding is resolved at ADMISSION against the active version; a
        # concurrent swap that also changes predictEncoding could dispatch
        # these bytes on the new version. Keep predictEncoding consistent
        # across versions you hot-swap between (or drain first) — swapping
        # the encoding mid-traffic has no well-defined answer for requests
        # already in the queue (docs/SERVING.md §2).
        entry = self.registry.peek()
        encoding = (
            entry.model.get("predictEncoding")
            if entry.model is not None else UTF8
        )
        docs = [text_to_bytes(t, encoding) for t in texts]
        fut = self.batcher.submit(
            docs, priority=priority, want_labels=labels,
            deadline_ms=deadline_ms, trace_id=payload.get("trace_id"),
        )
        result = fut.result()
        out = {
            "version": result.version,
            "trace_id": result.trace_id,
            "queue_wait_ms": round(result.queue_wait_s * 1e3, 3),
            "dispatch_ms": round(result.dispatch_s * 1e3, 3),
        }
        if labels:
            out["labels"] = result.labels
        else:
            # float() of a float32 is exact (f32 ⊂ f64) and JSON doubles
            # round-trip, so the wire is bit-transparent for scores.
            out["scores"] = [
                [float(v) for v in row] for row in result.values
            ]
        return out

    def swap(self, payload: dict) -> dict:
        if not self.admin:
            raise ServeError("admin endpoints disabled")
        path = payload.get("path")
        if not isinstance(path, str) or not path:
            raise ValueError('"path" must name a saved model directory')
        version = self.registry.load(path, version=payload.get("version"))
        return {"version": version}

    def rollback(self) -> dict:
        if not self.admin:
            raise ServeError("admin endpoints disabled")
        return {"version": self.registry.rollback()}

    def healthz(self) -> dict:
        out = {
            "ok": True,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "batcher": self.batcher.stats(),
        }
        try:
            entry = self.registry.peek()
            runner = entry.runner
            out["version"] = entry.version
            out["languages"] = len(entry.languages or ())
            breaker = getattr(runner, "breaker", None)
            out["breaker"] = breaker.state if breaker is not None else None
            out["degraded"] = bool(getattr(runner, "_degraded_mode", False))
        except ServeError as e:
            out["ok"] = False
            out["error"] = str(e)
        return out

    def varz(self) -> dict:
        snap = REGISTRY.snapshot()
        return {
            "stages": REGISTRY.stage_summary(),
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": {
                name: h for name, h in snap["histograms"].items()
                if not name.startswith(("span:", "span_device:"))
            },
            "versions": (
                self.registry.versions()
                if hasattr(self.registry, "versions") else []
            ),
            # The audited effective config: every LANGDETECT_* knob's live
            # value and provenance (explicit/env/profile/default), plus
            # the active tuning profile and the deprecation table — "which
            # knob is actually driving this deployment" answered from one
            # endpoint (docs/PERFORMANCE.md §9).
            "config": exec_config.effective_config(),
        }


def main(argv: list[str] | None = None) -> int:
    """``python -m spark_languagedetector_tpu.serve.server <model_dir>
    [host:port]`` — load a persisted model and serve it."""
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if not 1 <= len(argv) <= 2 or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m spark_languagedetector_tpu.serve.server "
            "<model_dir> [host:port]",
            file=sys.stderr,
        )
        return 2
    host, port = "127.0.0.1", 8000
    if len(argv) == 2:
        host, _, p = argv[1].rpartition(":")
        host = host or "127.0.0.1"
        port = int(p)
    registry = ModelRegistry()
    registry.load(argv[0])
    server = ServingServer(registry, host=host, port=port)
    print(f"serving {registry.current_version()} on "
          f"{server.address[0]}:{server.address[1]}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
