"""Online serving subsystem: continuous batching, hot-swap, load shedding.

The offline layers score pre-assembled lists; this package serves many
concurrent small requests through the same compiled dispatch
(docs/SERVING.md):

  * :class:`~.batcher.ContinuousBatcher` — admission queue + coalescing
    dispatcher on the runner's shape lattice, with priority lanes,
    per-request deadlines, and SLO-aware load shedding;
  * :class:`~.registry.ModelRegistry` — versioned models with pre-warmed
    zero-downtime hot-swap and rollback;
  * :class:`~.server.ServingServer` / :class:`~.client.ServeClient` —
    stdlib-only JSON-over-HTTP front end and client, with split
    liveness/readiness probes and ``Retry-After``-honoring client
    retries;
  * :class:`~.fleet.ServeFleet` / :class:`~.router.FleetRouter` /
    :class:`~.router.RouterServer` — N replicas behind a health-checked
    router with replica failover and the coordinated two-phase
    fleet-wide hot-swap (docs/SERVING.md §9).

Importing this package never initializes jax — runners are built by the
models the registry loads.
"""

from __future__ import annotations

from .batcher import (
    BULK,
    INTERACTIVE,
    LANES,
    ContinuousBatcher,
    ServeClosed,
    ServeDeadlineExceeded,
    ServeError,
    ServeOverloaded,
    ServeResult,
)
from .cache import ScoreCache
from .registry import ModelRegistry, ModelVersion

__all__ = [
    "BULK",
    "INTERACTIVE",
    "LANES",
    "ContinuousBatcher",
    "ModelRegistry",
    "ModelVersion",
    "ScoreCache",
    "ServeClosed",
    "ServeDeadlineExceeded",
    "ServeError",
    "ServeOverloaded",
    "ServeResult",
]


def __getattr__(name):
    # The HTTP halves import lazily so `import ...serve` stays light.
    if name in ("ServingServer",):
        from .server import ServingServer

        return ServingServer
    if name in ("ServeClient", "ServeHTTPError"):
        from . import client

        return getattr(client, name)
    if name in (
        "FleetRouter", "RouterServer", "FleetSaturated", "NoReadyReplica",
        "FleetSwapError",
    ):
        from . import router

        return getattr(router, name)
    if name in ("ServeFleet", "ServeReplica"):
        from . import fleet

        return getattr(fleet, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
