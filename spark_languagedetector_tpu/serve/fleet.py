"""A replicated serving fleet: N replicas + router + coordinated swaps.

The pjit/TPUv4 serving lesson (PAPERS.md: arXiv:2204.06514) and GSPMD
portability make replication the cheap axis of scale: every replica runs
the same compiled program at the same speed. This module owns the part
that does NOT replicate for free — construction, failure drills, and the
**two-phase fleet-wide hot-swap** that keeps N independently-swapping
registries from ever serving two model versions to one client stream:

Phase 1 — *prepare everywhere*: each replica's registry builds and
pre-warms the standby runner off its serving path
(:meth:`~.registry.ModelRegistry.prepare`). Any failure — a bad model
directory, an OOM, an injected ``fleet/swap`` fault — aborts the swap on
every replica; nothing was serving-visible, the current version keeps
serving.

Phase 2 — *drain + flip, one replica at a time*: the router marks the
replica draining (readiness false — no new traffic), waits for its
outstanding requests, commits the flip, and moves on. The router's
version pin makes the fleet-level cutover a single monotonic step: it
pins the OLD version before the first flip and moves to the NEW version
immediately after it, so an individual client stream sees
``old … old | new … new`` — never an interleave — while every individual
response is answered by exactly one version (the per-registry lease
contract). A crash mid-phase-2 (injected or real) rolls every
already-flipped replica back, re-pins the old version, and raises: the
fleet converges to one consistent version on either side of the failure,
never a mix.

``bench.py --smoke-fleet`` chaos-tests the whole story on the CPU
substrate: concurrent socket clients, a mid-run replica kill + half-open
re-admission, and a mid-traffic fleet swap, hard-gated on zero dropped
responses and swap atomicity (docs/SERVING.md §9).
"""

from __future__ import annotations

import re
import threading
import time

from ..exec import config as exec_config
from ..resilience import faults
from ..telemetry import REGISTRY, span
from ..utils.logging import get_logger, log_event
from .registry import ModelRegistry
from .router import FleetRouter, FleetSwapError
from .server import ServingServer

_log = get_logger("serve.fleet")


class ServeReplica:
    """One fleet member: its own registry, batcher, and HTTP server.

    The port is pinned on first bind (``port=0`` resolves an ephemeral
    one), so :meth:`kill` / :meth:`revive` cycles — the chaos drill — put
    the replica back at the same address the router knows.
    """

    def __init__(
        self,
        name: str,
        model,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        version: str = "v1",
        prewarm: bool = True,
        **batcher_kw,
    ):
        self.name = name
        self.registry = ModelRegistry()
        self.registry.install(model, version=version, prewarm=prewarm)
        self._host = host
        self._port = port
        self._batcher_kw = dict(batcher_kw)
        self.server: ServingServer | None = None
        self.start()

    # ---------------------------------------------------------- lifecycle ---
    @property
    def address(self) -> tuple[str, int]:
        return (self._host, self._port)

    @property
    def alive(self) -> bool:
        return self.server is not None

    def start(self) -> "ServeReplica":
        """(Re)start the HTTP server + a fresh batcher on the pinned
        address. The registry — versions, history, leases — survives the
        restart: a revived replica serves whatever it served before, and
        the router's version pin keeps it out of rotation if the fleet
        moved on while it was down."""
        if self.server is not None:
            return self
        self.server = ServingServer(
            self.registry, host=self._host, port=self._port,
            **self._batcher_kw,
        ).start()
        self._port = self.server.address[1]
        log_event(_log, "fleet.replica.start", replica=self.name,
                  port=self._port)
        return self

    revive = start

    def kill(self) -> None:
        """Abrupt death (the chaos drill): new connections refuse, queued
        requests fail explicitly with 503 — mid-flight routed requests
        surface as retryable failures the router fails over."""
        if self.server is None:
            return
        self.server.stop(drain=False)
        self.server = None
        log_event(_log, "fleet.replica.killed", replica=self.name)

    def stop(self) -> None:
        """Graceful stop: drain accepted work, then tear down."""
        if self.server is None:
            return
        self.server.stop(drain=True)
        self.server = None
        log_event(_log, "fleet.replica.stop", replica=self.name)

    def batcher_idle(self) -> bool:
        if self.server is None:
            return True
        stats = self.server.batcher.stats()
        return stats["queued_rows"] == 0 and stats["inflight_rows"] == 0


class ServeFleet:
    """N serve replicas behind one :class:`~.router.FleetRouter`.

    ``models`` is one fitted model per replica — distinct instances or
    the same shared object (what :meth:`from_path` does: one copy of the
    weights per process; replicas isolate serving state, not tables).
    """

    def __init__(
        self,
        models,
        *,
        host: str = "127.0.0.1",
        version: str = "v1",
        router_kw: dict | None = None,
        **batcher_kw,
    ):
        models = list(models)
        if not models:
            raise ValueError("a fleet needs at least one replica model")
        # Pre-warm once per DISTINCT model object: with the shared-model
        # form every replica holds the same cached runner, and N-1 of
        # the prewarm scores would be pure repeats.
        seen: set[int] = set()
        self.replicas = []
        self._host = host
        self._batcher_kw = dict(batcher_kw)
        self._name_seq = len(models)  # dynamic members continue r<i>
        for i, model in enumerate(models):
            first = id(model) not in seen
            seen.add(id(model))
            self.replicas.append(ServeReplica(
                f"r{i}", model, host=host, version=version,
                prewarm=first, **batcher_kw,
            ))
        self.router = FleetRouter(self.replicas, **(router_kw or {}))
        self.router.pin_version(version)
        # Serializes swap/rollback AND membership changes: the two-phase
        # protocol assumes one coordinator — two interleaved swaps could
        # wedge the fleet with the pin naming a version no replica
        # serves, and a replica must not join half-way through phase 2.
        # ``_coordinator`` names the current holder so a swap arriving
        # during routine membership churn WAITS for it (bounded by the
        # drain timeout) instead of failing fast with a false "swap
        # already in progress".
        self._swap_lock = threading.Lock()
        self._coordinator: str | None = None

    @classmethod
    def from_path(
        cls,
        path: str,
        *,
        replicas: int | None = None,
        **kw,
    ) -> "ServeFleet":
        """Build ``replicas`` replicas (default: the ``fleet_replicas``
        knob) from one persisted model directory. The model is loaded
        ONCE and shared — in one process there is no reason to hold N
        copies of the same weights or compile N identical programs
        (runners are concurrent-caller-safe, the documented PR-5
        contract); a replica's failure domain is its serving state —
        registry, batcher, HTTP server — not the weights. Replicas in
        separate processes/hosts each load their own copy by
        construction."""
        from ..models.estimator import LanguageDetectorModel

        n = int(exec_config.resolve("fleet_replicas", replicas))
        model = LanguageDetectorModel.load(path)
        return cls([model] * n, **kw)

    # ---------------------------------------------------------- lifecycle ---
    def start(self, *, probe: bool = True) -> "ServeFleet":
        self.router.start(probe=probe)
        return self

    def close(self) -> None:
        self.router.close()
        for rep in self.replicas:
            rep.stop()

    def __enter__(self) -> "ServeFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def replica(self, name: str) -> ServeReplica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise ValueError(f"unknown replica {name!r}")

    # ------------------------------------------------- coordinator lock -----
    def _acquire_coordinator(self, kind: str) -> None:
        """One coordinator at a time. A swap/rollback arriving while
        another swap/rollback runs fails fast (two interleaved protocol
        rounds could wedge the pin); arriving while a bounded membership
        change holds the lock, it WAITS — a scale-down drain is not "a
        swap already in progress" and must not masquerade as one."""
        while True:
            if self._swap_lock.acquire(blocking=kind == "membership"):
                self._coordinator = kind
                return
            holder = self._coordinator
            if kind in ("swap", "rollback") and holder == "membership":
                # Bounded wait: membership changes finish (drain bound),
                # then the protocol round proceeds.
                self._swap_lock.acquire()
                self._coordinator = kind
                return
            raise FleetSwapError(
                f"a fleet {holder or 'swap/rollback'} is already in "
                "progress"
            )

    def _release_coordinator(self) -> None:
        self._coordinator = None
        self._swap_lock.release()

    # -------------------------------------------------------- membership ----
    def add_replica(
        self, model=None, *, path: str | None = None,
        name: str | None = None, prewarm: bool = True,
    ) -> ServeReplica:
        """Grow the fleet by one in-process replica mid-flight
        (docs/SERVING.md §13). The new member installs the version the
        router currently pins (or the fleet's current version), so it is
        immediately swap-consistent; membership changes serialize with
        swaps on the same coordinator lock — a replica can never join
        half-way through phase 2."""
        if (model is None) == (path is None):
            raise ValueError("pass exactly one of model= or path=")
        self._acquire_coordinator("membership")
        try:
            if path is not None:
                from ..models.estimator import LanguageDetectorModel

                model = LanguageDetectorModel.load(path)
            version = self.router.pinned_version or (
                self.replicas[0].registry.current_version()
            )
            if name is None:
                name = f"r{self._name_seq}"
                self._name_seq += 1
            rep = ServeReplica(
                name, model, host=self._host, version=version,
                prewarm=prewarm, **self._batcher_kw,
            )
            self.replicas.append(rep)
            self.router.add_replica(rep, name=name)
            log_event(
                _log, "fleet.replica.joined", replica=name, version=version,
                replicas=len(self.replicas),
            )
            return rep
        finally:
            self._release_coordinator()

    def remove_replica(self, name: str, *, drain: bool = True) -> None:
        """Shrink the fleet by one: router drain-then-detach first (no
        new traffic, outstanding requests waited out), then the replica's
        own graceful stop drains its accepted batcher work — zero dropped
        responses on the scale-down path. Removing the last replica is
        refused (an empty fleet cannot answer anything)."""
        self._acquire_coordinator("membership")
        try:
            rep = self.replica(name)
            if len(self.replicas) == 1:
                raise ValueError(
                    "cannot remove the last replica of a serving fleet"
                )
            self.router.remove_replica(name, drain=drain)
            self.replicas.remove(rep)
            if drain:
                rep.stop()
            else:
                rep.kill()
            log_event(
                _log, "fleet.replica.left", replica=name,
                replicas=len(self.replicas),
            )
        finally:
            self._release_coordinator()

    # ------------------------------------------------------------- swaps ----
    def _next_version(self) -> str:
        n = 0
        for rep in self.replicas:
            for v in rep.registry.versions():
                m = re.fullmatch(r"v(\d+)", v["version"])
                if m:
                    n = max(n, int(m.group(1)))
        return f"v{n + 1}"

    def _load_models(self, path: str) -> list:
        # One load, shared across the in-process replicas — the same
        # one-copy-per-process rule as from_path().
        from ..models.estimator import LanguageDetectorModel

        return [LanguageDetectorModel.load(path)] * len(self.replicas)

    def swap(
        self,
        path: str | None = None,
        *,
        models=None,
        version: str | None = None,
        prewarm: bool = True,
    ) -> str:
        """Fleet-wide two-phase hot-swap; returns the new version name.

        Pass a persisted model directory (loaded once, shared) or
        ``models`` (one per replica). Raises
        :class:`~.router.FleetSwapError` on abort/rollback — the fleet is
        on exactly one version afterwards either way. One swap/rollback
        at a time: a concurrent call fails fast instead of interleaving
        two flips (a double-submitted ``/admin/swap`` must not wedge the
        pin on a version no replica serves).
        """
        self._acquire_coordinator("swap")
        try:
            return self._swap_locked(
                path, models=models, version=version, prewarm=prewarm
            )
        finally:
            self._release_coordinator()

    def _swap_locked(
        self,
        path: str | None,
        *,
        models,
        version: str | None,
        prewarm: bool,
    ) -> str:
        if (path is None) == (models is None):
            raise ValueError("pass exactly one of path= or models=")
        if models is not None:
            models = list(models)
            if len(models) != len(self.replicas):
                raise ValueError(
                    f"need one model per replica ({len(self.replicas)}), "
                    f"got {len(models)}"
                )
        version = version or self._next_version()
        old = self.router.pinned_version or (
            self.replicas[0].registry.current_version()
        )
        t0 = time.perf_counter()
        with span(
            "fleet/swap", replicas=len(self.replicas), version=version
        ):
            if models is None:
                models = self._load_models(path)
            # ---- phase 1: prepare on EVERY replica, off the serving
            # path. Any failure aborts the swap everywhere — nothing was
            # serving-visible yet, the current version keeps serving.
            # (Pre-warm once per distinct model object: shared models
            # share one cached runner.)
            prepared = []
            warmed: set[int] = set()
            for rep, model in zip(self.replicas, models):
                try:
                    faults.inject("fleet/swap")
                    prepared.append(rep.registry.prepare(
                        model, version=version,
                        prewarm=prewarm and id(model) not in warmed,
                        source=path and str(path),
                        metadata={"fleet_swap": version},
                    ))
                    warmed.add(id(model))
                except Exception as e:
                    REGISTRY.incr("fleet/swap_aborts")
                    log_event(
                        _log, "fleet.swap_abort", phase=1, replica=rep.name,
                        version=version, error=repr(e),
                    )
                    raise FleetSwapError(
                        f"phase 1 (prepare) failed on {rep.name}: {e!r}; "
                        f"swap aborted fleet-wide, {old!r} keeps serving"
                    ) from e
            # ---- phase 2: drain + flip one replica at a time. The pin
            # starts on the old version; it moves to the new version
            # exactly once, right after the first flip — the cutover that
            # keeps per-client-stream versions monotonic.
            self.router.pin_version(old)
            flipped: list[ServeReplica] = []
            current: ServeReplica | None = None
            try:
                for i, (rep, prep) in enumerate(
                    zip(self.replicas, prepared)
                ):
                    current = rep
                    self.router.set_draining(rep.name, True)
                    self._drain(rep)
                    faults.inject("fleet/swap")
                    rep.registry.commit(prep)
                    self.router.note_version(rep.name, version)
                    self.router.set_draining(rep.name, False)
                    flipped.append(rep)
                    if i == 0:
                        self.router.pin_version(version)
            except Exception as e:
                # Mid-phase-2 crash: converge BACK — the fleet must never
                # stay mixed. Already-flipped replicas revert to the
                # NAMED old version (activate, not rollback: history may
                # hold retired standbys of earlier aborted swaps; "one
                # step back" would land on those). The old runner is
                # still cached: instant. Then the pin returns and the
                # error surfaces.
                if current is not None:
                    self.router.set_draining(current.name, False)
                for rep in flipped:
                    rep.registry.activate(old)
                    self.router.note_version(rep.name, old)
                self.router.pin_version(old)
                REGISTRY.incr("fleet/swap_aborts")
                log_event(
                    _log, "fleet.swap_abort", phase=2,
                    replica=current.name if current else None,
                    version=version, rolled_back=[r.name for r in flipped],
                    error=repr(e),
                )
                raise FleetSwapError(
                    f"phase 2 (commit) failed on "
                    f"{current.name if current else '?'}: {e!r}; "
                    f"{len(flipped)} flipped replica(s) rolled back to "
                    f"{old!r}"
                ) from e
        REGISTRY.incr("fleet/swaps")
        log_event(
            _log, "fleet.swap", version=version, previous=old,
            replicas=len(self.replicas),
            wall_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        return version

    def rollback(self) -> str:
        """Fleet-wide rollback: the phase-2 protocol (drain + flip one at
        a time behind the version pin) walked backwards — instant per
        replica, since the previous runners are still cached. Mutually
        exclusive with :meth:`swap` (same single-coordinator rule)."""
        self._acquire_coordinator("rollback")
        try:
            return self._rollback_locked()
        finally:
            self._release_coordinator()

    def _rollback_locked(self) -> str:
        old = self.router.pinned_version or (
            self.replicas[0].registry.current_version()
        )
        with span("fleet/rollback", replicas=len(self.replicas)):
            self.router.pin_version(old)
            target: str | None = None
            for i, rep in enumerate(self.replicas):
                self.router.set_draining(rep.name, True)
                try:
                    self._drain(rep)
                    version = rep.registry.rollback()
                finally:
                    self.router.set_draining(rep.name, False)
                if target is None:
                    target = version
                elif version != target:
                    raise FleetSwapError(
                        f"divergent rollback: {rep.name} landed on "
                        f"{version!r}, expected {target!r}"
                    )
                self.router.note_version(rep.name, version)
                if i == 0:
                    self.router.pin_version(version)
        REGISTRY.incr("fleet/rollbacks")
        log_event(_log, "fleet.rollback", version=target, previous=old)
        return target

    def _drain(self, rep: ServeReplica) -> None:
        """Wait until no routed request is outstanding on ``rep`` and its
        batcher is idle (bounded). A timeout proceeds anyway — the
        registry's own lease drain still guarantees in-flight dispatches
        finish on the version they leased."""
        deadline = time.monotonic() + self.router.drain_timeout_s
        self.router.wait_drained(
            rep.name, timeout_s=max(deadline - time.monotonic(), 0.0)
        )
        while not rep.batcher_idle():
            if time.monotonic() >= deadline:
                log_event(_log, "fleet.drain_timeout", replica=rep.name)
                break
            time.sleep(0.002)

    # ------------------------------------------------------------- status ---
    def versions(self) -> dict:
        return {
            rep.name: rep.registry.current_version()
            for rep in self.replicas
        }
