"""Query-of-death quarantine: stop a poison request from eating the fleet.

A *query of death* is a request whose content deterministically crashes
whatever replica serves it (an encoder edge case, a pathological
document). Plain failover makes it worse: the router faithfully replays
the killer onto the next healthy replica, and a single request takes the
fleet down serially — the canonical production-fleet failure shape
(PAPERS.md: arXiv:2204.06514's metastable framing).

This module is the router's memory of that correlation. Every dispatch
records a **content signature** — the same content identity the serve
score cache keys on (the document bytes; :mod:`.cache`), hashed with the
process-independent FNV-1a the fault plane uses, so two routers (and two
runs) agree on every signature. A dispatch that coincides with a replica
death (connection severed mid-flight, or the supervisor's crash-loop
detector reporting the process gone) charges one *correlated death* to
the signature it carried. At ``K`` deaths (``LANGDETECT_QUARANTINE_
DEATHS``) the signature is quarantined: the router answers it with an
explicit 422 (:class:`QueryQuarantined` — a ``ValueError``, so every
layer already classifies it non-retryable) and records the full request
to a serve-level dead-letter queue (:class:`~..resilience.dlq.
DeadLetterQueue` — the same JSONL shape the streaming DLQ writes, so the
same tooling replays it). A poison request can therefore kill at most K
replicas, ever.

Both table operations pass the ``fleet/quarantine`` fault site. An
injected error degrades *open*: a failed lookup answers "not
quarantined" and a failed death-record drops that one observation —
chaos can delay protection but can never reject a healthy request.

Bounded: the suspect and quarantined maps evict oldest-first past
``LANGDETECT_QUARANTINE_MAX_ENTRIES`` — a high-cardinality workload
cannot grow the table without bound.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Sequence

from ..exec import config as exec_config
from ..resilience import faults
from ..resilience.dlq import DeadLetterQueue
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("serve.quarantine")


class QueryQuarantined(ValueError):
    """The request's content signature is quarantined (query of death).

    ``ValueError``-shaped on purpose: every serving layer already maps
    ``ValueError`` to a caller-side 4xx and never retries it — exactly
    the contract a poison request needs. The HTTP fronts answer it 422
    (a well-formed request the fleet refuses to re-serve), keeping it
    distinguishable from a 400 caller bug.
    """

    def __init__(self, signature: str, deaths: int):
        super().__init__(
            f"request signature {signature} is quarantined after "
            f"{deaths} correlated replica death(s); see the serve DLQ"
        )
        self.signature = signature
        self.deaths = deaths


def signature_of(texts: Sequence[str]) -> str:
    """Content signature of one request: order-sensitive FNV-1a over the
    document bytes (the cache's content identity, minus the version/mode
    axes — a killer document kills regardless of model version)."""
    h = 0xCBF29CE484222325
    for t in texts:
        h = (h ^ faults._fnv1a(t)) * 0x100000001B3 & ((1 << 64) - 1)
        h = (h ^ len(t)) * 0x100000001B3 & ((1 << 64) - 1)
    return f"{h:016x}"


class QuarantineTable:
    """Correlated-death ledger: signature → deaths, plus the quarantine set.

    Thread-safe. ``note_dispatch`` remembers the last signature routed to
    each replica so an *out-of-band* death report (the supervisor's
    crash-loop detector, which sees the process die but not the request)
    can still charge the right signature via :meth:`replica_died`.

    ``deaths <= 0`` disables the table (mirroring
    ``RetryBudget(fraction=0)``): nothing is ever suspected or refused.
    That is the opt-out for drills that slaughter replicas under a tiny
    repeating text set on purpose — kill/failover exercises would
    otherwise "poison" their own benign traffic.
    """

    def __init__(
        self,
        deaths: int | None = None,
        max_entries: int | None = None,
        *,
        dlq: DeadLetterQueue | None = None,
        dlq_path: str | None = None,
        name: str = "fleet",
    ):
        self.deaths_threshold = int(
            exec_config.resolve("quarantine_deaths", deaths)
        )
        self.max_entries = max(
            1, int(exec_config.resolve("quarantine_max_entries", max_entries))
        )
        if dlq is None:
            path = exec_config.resolve("quarantine_dlq_path", dlq_path)
            dlq = DeadLetterQueue(path)
        self.dlq = dlq
        self.name = name
        self._lock = threading.Lock()
        self._suspects: OrderedDict[str, dict] = OrderedDict()
        self._quarantined: OrderedDict[str, dict] = OrderedDict()
        self._last_sig: dict[str, tuple[str, list]] = {}

    @property
    def enabled(self) -> bool:
        return self.deaths_threshold >= 1

    # ------------------------------------------------------------- checks ---
    def check(self, sig: str) -> bool:
        """Is ``sig`` quarantined? Degrades open under an injected fault."""
        if not self.enabled:
            return False
        try:
            faults.inject("fleet/quarantine")
        except Exception as e:
            log_event(
                _log, "quarantine.check_degraded", signature=sig,
                error=repr(e),
            )
            return False
        with self._lock:
            return sig in self._quarantined

    def note_dispatch(self, replica: str, sig: str, texts: Sequence[str]) -> None:
        """Remember the signature most recently routed to ``replica`` (the
        supervisor's death reports arrive without request context)."""
        if not self.enabled:
            return
        preview = [t[:80] for t in texts[:4]]
        with self._lock:
            self._last_sig[replica] = (sig, preview)

    # ------------------------------------------------------------- deaths ---
    def record_death(
        self,
        sig: str,
        *,
        replica: str | None = None,
        source: str = "router",
        texts: Sequence[str] | None = None,
    ) -> bool:
        """Charge one correlated replica death to ``sig``; returns True
        when this death crossed the threshold and quarantined it.
        Degrades open (death dropped) under an injected fault."""
        if not self.enabled:
            return False
        try:
            faults.inject("fleet/quarantine")
        except Exception as e:
            log_event(
                _log, "quarantine.record_degraded", signature=sig,
                error=repr(e),
            )
            return False
        preview = (
            [t[:80] for t in texts[:4]] if texts is not None else None
        )
        with self._lock:
            if replica is not None:
                # A charged death consumes the replica's pending
                # signature: the router's mid-flight charge and the
                # supervisor's out-of-band report describe the SAME
                # death event, and must not count it twice (K would
                # silently halve). The next dispatch re-arms it.
                self._last_sig.pop(replica, None)
            if sig in self._quarantined:
                self._quarantined[sig]["deaths"] += 1
                return False
            rec = self._suspects.pop(sig, None)
            if rec is None:
                rec = {"deaths": 0, "replicas": [], "preview": preview}
            self._suspects[sig] = rec  # re-insert: LRU-by-last-death
            rec["deaths"] += 1
            if replica is not None:
                rec["replicas"].append(f"{source}:{replica}")
            if preview is not None:
                rec["preview"] = preview
            deaths = rec["deaths"]
            newly = deaths >= self.deaths_threshold
            if newly:
                self._suspects.pop(sig, None)
                self._quarantined[sig] = rec
            while len(self._suspects) > self.max_entries:
                self._suspects.popitem(last=False)
            while len(self._quarantined) > self.max_entries:
                self._quarantined.popitem(last=False)
            quarantined_n = len(self._quarantined)
            row = {
                "signature": sig,
                "preview": rec["preview"],
                "replicas": list(rec["replicas"]),
                "deaths": deaths,
            }
        log_event(
            _log, "quarantine.death", signature=sig, replica=replica,
            source=source, deaths=deaths, quarantined=newly,
        )
        if newly:
            REGISTRY.incr("fleet/quarantined_signatures")
            REGISTRY.set_gauge(
                "langdetect_fleet_quarantined", float(quarantined_n),
                table=self.name,
            )
            self.dlq.put(
                batch=0, row_index=deaths, row=row,
                error="query_of_death",
            )
        return newly

    def replica_died(self, replica: str, *, source: str = "supervisor") -> bool:
        """Out-of-band death report (the supervisor's crash-loop detector):
        charge the signature last routed to ``replica``, if any."""
        with self._lock:
            last = self._last_sig.get(replica)
        if last is None:
            return False
        sig, preview = last
        return self.record_death(
            sig, replica=replica, source=source,
            texts=preview,
        )

    # ------------------------------------------------------------- status ---
    def describe(self) -> dict:
        """Table state for /varz and the storm drill's assertions."""
        with self._lock:
            return {
                "name": self.name,
                "enabled": self.enabled,
                "deaths_threshold": self.deaths_threshold,
                "suspects": len(self._suspects),
                "quarantined": sorted(self._quarantined),
                "dlq_rows": len(self.dlq),
            }
