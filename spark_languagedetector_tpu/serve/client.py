"""Stdlib HTTP client for the serving front end.

Thin, dependency-free wrapper over :mod:`http.client` mirroring the
server's endpoints — the piece that makes the smoke bench and the tests
drive the whole stack over a real socket. One connection per call keeps
the client trivially thread-safe (concurrent smoke clients share one
``ServeClient``); the server is HTTP/1.1 keep-alive, so per-call
connections cost one local TCP handshake, which is noise next to a
scoring dispatch.

Non-2xx responses raise :class:`ServeHTTPError` carrying the status and
decoded body — a shed (503) or blown deadline (504) is an exception with
context, never a silent empty result.
"""

from __future__ import annotations

import json
from http.client import HTTPConnection
from typing import Sequence

import numpy as np


class ServeHTTPError(RuntimeError):
    """Non-2xx response from the serving front end."""

    def __init__(self, status: int, payload: dict, headers: dict):
        super().__init__(
            f"HTTP {status}: {payload.get('error', payload)!r}"
        )
        self.status = status
        self.payload = payload
        self.headers = headers

    @property
    def shed(self) -> bool:
        return bool(self.payload.get("shed"))

    @property
    def retry_after_s(self) -> float:
        try:
            return float(self.headers.get("Retry-After", 0.0))
        except ValueError:
            return 0.0


class ServeClient:
    """JSON client for one serving endpoint (host, port)."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 60.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s

    # ------------------------------------------------------------- wire -----
    def _request(self, method: str, path: str, payload: dict | None = None):
        conn = HTTPConnection(self.host, self.port, timeout=self.timeout_s)
        try:
            body = None if payload is None else json.dumps(payload)
            headers = {"Content-Type": "application/json"} if body else {}
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            data = json.loads(raw.decode("utf-8")) if raw else {}
            if not 200 <= resp.status < 300:
                raise ServeHTTPError(resp.status, data, dict(resp.getheaders()))
            return data
        finally:
            conn.close()

    # -------------------------------------------------------------- api -----
    def score(
        self,
        texts: Sequence[str],
        *,
        priority: str = "interactive",
        deadline_ms: float | None = None,
        trace_id: str | None = None,
    ) -> tuple[np.ndarray, dict]:
        """(float32 [N, L] scores, response metadata). The JSON wire is
        bit-transparent for float32 (exact f64 embed + round-tripping
        doubles), so these scores equal the server-side arrays exactly."""
        payload: dict = {"texts": list(texts), "priority": priority}
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        if trace_id is not None:
            payload["trace_id"] = trace_id
        data = self._request("POST", "/score", payload)
        scores = np.asarray(data.pop("scores"), dtype=np.float32)
        if scores.size == 0:
            scores = scores.reshape(0, 0)
        return scores, data

    def detect(
        self,
        texts: Sequence[str],
        *,
        priority: str = "interactive",
        deadline_ms: float | None = None,
    ) -> tuple[list[str], dict]:
        """(predicted language labels, response metadata)."""
        payload: dict = {"texts": list(texts), "priority": priority}
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        data = self._request("POST", "/detect", payload)
        return data.pop("labels"), data

    def healthz(self) -> dict:
        return self._request("GET", "/healthz")

    def varz(self) -> dict:
        return self._request("GET", "/varz")

    def swap(self, path: str, *, version: str | None = None) -> str:
        payload: dict = {"path": path}
        if version is not None:
            payload["version"] = version
        return self._request("POST", "/admin/swap", payload)["version"]

    def rollback(self) -> str:
        return self._request("POST", "/admin/rollback")["version"]
