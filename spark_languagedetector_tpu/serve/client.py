"""Stdlib HTTP client for the serving front end.

Thin wrapper over :mod:`http.client` mirroring the server's endpoints —
the piece that makes the smoke bench and the tests drive the whole stack
over a real socket. One connection per call keeps the client trivially
thread-safe (concurrent smoke clients share one ``ServeClient``); the
server is HTTP/1.1 keep-alive, so per-call connections cost one local
TCP handshake, which is noise next to a scoring dispatch.

Non-2xx responses raise :class:`ServeHTTPError` carrying the status and
decoded body — a shed (503) or blown deadline (504) is an exception with
context, never a silent empty result.

Pass a :class:`~..resilience.policy.RetryPolicy` as ``retry_policy`` and
the *idempotent* calls (``score``/``detect`` and every GET) ride it: a
503 shed sleeps ``max(Retry-After, seeded-jitter backoff)`` and retries,
bounded by ``max_attempts`` — the client-side half of load shedding
(the server asks for a later retry; the client grants it). 400 (caller
bug), 422 (quarantined query of death), and 504 (blown deadline) are
never retried; connection-level failures ride the same
:func:`~..resilience.policy.is_retryable` taxonomy the serving layers
use. Admin calls (``swap``/``rollback``) never retry — replaying a
non-idempotent mutation is the caller's decision, not the transport's.

Two storm-defense bounds (docs/RESILIENCE.md §7) cap the retry loop: a
request that carries ``deadline_ms`` never *sleeps* past its own
deadline (a backoff that would end after it surfaces the last error
instead — ``serve/client_deadline_gaveups``), and an attached
:class:`~..resilience.policy.RetryBudget` charges one token per retry so
a client herd cannot amplify an outage beyond the configured fraction of
its own successful traffic.
"""

from __future__ import annotations

import json
import time
from http.client import HTTPConnection, HTTPException
from typing import Sequence

import numpy as np

from ..resilience.policy import RetryBudget, RetryPolicy, is_retryable
from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("serve.client")


class ServeHTTPError(RuntimeError):
    """Non-2xx response from the serving front end."""

    def __init__(self, status: int, payload: dict, headers: dict):
        super().__init__(
            f"HTTP {status}: {payload.get('error', payload)!r}"
        )
        self.status = status
        self.payload = payload
        self.headers = headers

    @property
    def shed(self) -> bool:
        return bool(self.payload.get("shed"))

    @property
    def retry_after_s(self) -> float:
        try:
            return float(self.headers.get("Retry-After", 0.0))
        except ValueError:
            return 0.0


class ServeClient:
    """JSON client for one serving endpoint (host, port).

    ``tenant`` names this client's tenant against a model-zoo-backed
    server (docs/SERVING.md §12): every ``score``/``detect``/``segment``
    call carries it unless overridden per call. Unset (the default), the
    client is byte-identical to the pre-zoo wire — a zoo server answers
    from its default tenant, a single-model server exactly as before.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        timeout_s: float = 60.0,
        retry_policy: RetryPolicy | None = None,
        retry_budget: RetryBudget | None = None,
        tenant: str | None = None,
    ):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.retry_policy = retry_policy
        # Optional storm-defense budget (docs/RESILIENCE.md §7): when
        # set, each retry withdraws one token (successes deposit), so a
        # fleet of clients cannot amplify an outage past the configured
        # fraction of its own successful traffic.
        self.retry_budget = retry_budget
        self.tenant = tenant

    # ------------------------------------------------------------- wire -----
    def _request_once(
        self, method: str, path: str, payload: dict | None = None
    ):
        conn = HTTPConnection(self.host, self.port, timeout=self.timeout_s)
        try:
            body = None if payload is None else json.dumps(payload)
            headers = {"Content-Type": "application/json"} if body else {}
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            data = json.loads(raw.decode("utf-8")) if raw else {}
            if not 200 <= resp.status < 300:
                raise ServeHTTPError(resp.status, data, dict(resp.getheaders()))
            return data
        finally:
            conn.close()

    @staticmethod
    def _retryable(exc: Exception) -> bool:
        """503 (shed/closed: the server asked for a later retry) and
        transport failures retry; 400 and 504 never do — a bad request
        stays bad and a blown deadline's answer is already worthless."""
        if isinstance(exc, ServeHTTPError):
            return exc.status == 503
        return isinstance(exc, HTTPException) or is_retryable(exc)

    def _request(
        self,
        method: str,
        path: str,
        payload: dict | None = None,
        *,
        idempotent: bool | None = None,
        deadline_s: float | None = None,
    ):
        if idempotent is None:
            idempotent = method == "GET"
        policy = self.retry_policy
        budget = self.retry_budget
        attempt = 0
        while True:
            attempt += 1
            try:
                result = self._request_once(method, path, payload)
                if budget is not None:
                    budget.record_success()
                return result
            except Exception as e:
                if (
                    policy is None
                    or not idempotent
                    or not self._retryable(e)
                    or attempt >= policy.max_attempts
                ):
                    raise
                # The server's own estimate wins when it is longer than
                # the schedule: Retry-After says when capacity frees, the
                # seeded-jitter backoff (deterministic per policy seed +
                # attempt — resilience/policy) de-synchronizes the herd.
                delay = policy.backoff_s(attempt)
                if isinstance(e, ServeHTTPError):
                    delay = max(delay, e.retry_after_s)
                if deadline_s is not None:
                    # The request carries a deadline: total retry wall
                    # time is bounded by it. A sleep that would end at or
                    # past the deadline buys a retry whose answer is
                    # already worthless — surface the last error instead.
                    remaining = deadline_s - time.monotonic()
                    if remaining <= 0 or delay >= remaining:
                        REGISTRY.incr("serve/client_deadline_gaveups")
                        log_event(
                            _log, "serve.client.deadline_gaveup",
                            path=path, attempt=attempt,
                            backoff_s=round(delay, 6),
                            remaining_s=round(remaining, 6),
                        )
                        raise
                if budget is not None and not budget.try_spend(
                    reason="client_retry"
                ):
                    raise
                REGISTRY.incr("serve/client_retries")
                log_event(
                    _log, "serve.client.retry", path=path, attempt=attempt,
                    max_attempts=policy.max_attempts,
                    backoff_s=round(delay, 6), error=repr(e),
                )
                if delay > 0:
                    time.sleep(delay)

    # -------------------------------------------------------------- api -----
    def _tenant_key(self, payload: dict, tenant: str | None) -> dict:
        tenant = self.tenant if tenant is None else tenant
        if tenant is not None:
            payload["tenant"] = tenant
        return payload

    def score(
        self,
        texts: Sequence[str],
        *,
        priority: str = "interactive",
        deadline_ms: float | None = None,
        trace_id: str | None = None,
        tenant: str | None = None,
    ) -> tuple[np.ndarray, dict]:
        """(float32 [N, L] scores, response metadata). The JSON wire is
        bit-transparent for float32 (exact f64 embed + round-tripping
        doubles), so these scores equal the server-side arrays exactly."""
        payload: dict = {"texts": list(texts), "priority": priority}
        deadline_s = None
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
            deadline_s = time.monotonic() + float(deadline_ms) / 1e3
        if trace_id is not None:
            payload["trace_id"] = trace_id
        self._tenant_key(payload, tenant)
        data = self._request(
            "POST", "/score", payload, idempotent=True,
            deadline_s=deadline_s,
        )
        scores = np.asarray(data.pop("scores"), dtype=np.float32)
        if scores.size == 0:
            scores = scores.reshape(0, 0)
        return scores, data

    def detect(
        self,
        texts: Sequence[str],
        *,
        priority: str = "interactive",
        deadline_ms: float | None = None,
        trace_id: str | None = None,
        tenant: str | None = None,
    ) -> tuple[list, dict]:
        """(predicted labels, response metadata). When the served model's
        ``resultMode`` is ``"segment"`` the server answers ``/detect``
        with segmentation result dicts instead of label strings
        (``meta["mode"] == "segment"`` says which came back); use
        :meth:`segment` to request that shape explicitly."""
        payload: dict = {"texts": list(texts), "priority": priority}
        deadline_s = None
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
            deadline_s = time.monotonic() + float(deadline_ms) / 1e3
        if trace_id is not None:
            payload["trace_id"] = trace_id
        self._tenant_key(payload, tenant)
        data = self._request(
            "POST", "/detect", payload, idempotent=True,
            deadline_s=deadline_s,
        )
        if "results" in data:
            return data.pop("results"), data
        return data.pop("labels"), data

    def segment(
        self,
        texts: Sequence[str],
        *,
        top_k: int | None = None,
        reject_threshold: float | None = None,
        priority: str = "interactive",
        deadline_ms: float | None = None,
        trace_id: str | None = None,
        tenant: str | None = None,
    ) -> tuple[list[dict], dict]:
        """(segmentation result dicts, response metadata) via
        ``/detect?mode=segment`` — byte-offset spans, calibrated top-k,
        and the unknown reject per document (docs/SEGMENTATION.md).
        ``top_k``/``reject_threshold`` override the served model's params
        for this request only (the serve cache keys on them, so mixed-knob
        traffic never cross-answers)."""
        payload: dict = {"texts": list(texts), "priority": priority}
        if top_k is not None:
            payload["top_k"] = top_k
        if reject_threshold is not None:
            payload["reject_threshold"] = reject_threshold
        deadline_s = None
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
            deadline_s = time.monotonic() + float(deadline_ms) / 1e3
        if trace_id is not None:
            payload["trace_id"] = trace_id
        self._tenant_key(payload, tenant)
        data = self._request(
            "POST", "/detect?mode=segment", payload, idempotent=True,
            deadline_s=deadline_s,
        )
        return data.pop("results"), data

    def healthz(self) -> dict:
        return self._request("GET", "/healthz")

    def livez(self) -> dict:
        return self._request("GET", "/healthz/live")

    def readyz(self) -> dict:
        """The readiness payload, whether ready (200) or not (503) — a
        not-ready replica answering its probe is information, not an
        error (the router keys routing off ``payload["ready"]``). Never
        retried, even with a retry policy: a probe wants the state *now*,
        and retrying a 503 until ready would just re-implement the
        router's re-admission loop badly."""
        try:
            return self._request(
                "GET", "/healthz/ready", idempotent=False
            )
        except ServeHTTPError as e:
            if e.status == 503 and isinstance(e.payload, dict):
                return e.payload
            raise

    def varz(self) -> dict:
        return self._request("GET", "/varz")

    def telemetryz(self) -> dict:
        """The server's mergeable telemetry snapshot (the fleet
        collector's scrape transport). Never retried: a scrape wants the
        registry state *now*, and the collector already counts failures
        (``fleet/agg_scrape_failures``)."""
        return self._request("GET", "/telemetryz", idempotent=False)

    def swap(
        self,
        path: str,
        *,
        version: str | None = None,
        tenant: str | None = None,
    ) -> str:
        payload: dict = {"path": path}
        if version is not None:
            payload["version"] = version
        self._tenant_key(payload, tenant)
        return self._request(
            "POST", "/admin/swap", payload, idempotent=False
        )["version"]

    def rollback(self, *, tenant: str | None = None) -> str:
        payload = self._tenant_key({}, tenant)
        return self._request(
            "POST", "/admin/rollback", payload or None, idempotent=False
        )["version"]
