"""Static contract checker: prove the string-keyed contracts the stack leans on.

Five PRs of growth made correctness hinge on cross-module *string*
contracts — every ``LANGDETECT_*`` knob resolves through
:mod:`..exec.config`'s audited precedence table, every counter name
:mod:`..telemetry.compare` and :mod:`..exec.tune` consume must actually be
emitted somewhere, every ``faults.inject(site)`` literal must be a row in
:data:`..resilience.faults.SITES`, and the OBSERVABILITY/RESILIENCE doc
tables must describe what the code really does. Until this module those
contracts were enforced by reviewer vigilance alone; now they are
machine-verified by a pure-stdlib AST pass that runs in tier-1::

    python -m spark_languagedetector_tpu.analysis.check [--json]

No JAX import, no device work, <5s — the checker never imports the
modules it audits; it parses them (:mod:`.harvest`) and applies the rule
families (:mod:`.rules`):

  * **R1 knob discipline** — env reads of ``LANGDETECT_*`` outside
    ``exec/config.py``; knob literals without a ``KNOBS`` row; knobs the
    OBSERVABILITY.md env table doesn't cover.
  * **R2 telemetry name contract** — names ``telemetry/compare`` /
    ``exec/tune`` consume but nothing emits; emitted names that break the
    ``area/name`` slash-path grammar; doc'd metrics nothing emits.
  * **R3 fault-site registry** — ``inject()`` literals vs ``SITES`` vs
    RESILIENCE.md §4, all three ways.
  * **R4 trace purity** — host-impure calls (env/time/random/telemetry/
    print) inside jit/pjit/shard_map/pallas_call-traced functions.
  * **R5 suppression audit** — ``# contract: ignore[R?] -- reason``
    pragmas and the checked-in :mod:`.allowlist`; stale suppressions are
    themselves violations.

See docs/ANALYSIS.md for the rule catalog, the pragma/allowlist grammar,
and how to add a rule.
"""

from .check import Violation, run_checks  # noqa: F401
