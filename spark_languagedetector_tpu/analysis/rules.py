"""The five contract rule families (R1-R5) over a harvested scan.

Every rule yields :class:`Violation` rows with ``file:line``, the rule
id, and a fix hint — the checker in :mod:`.check` applies suppressions
(R5) and renders them. The rules never import the audited modules; all
contract tables (``KNOBS``, ``SITES``, the compare/tune consumption
sets) come from :mod:`.harvest`'s static extraction, so a module whose
import would pull jax (or crash) is still fully checkable.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import PurePosixPath

from . import harvest
from .harvest import KNOB_TOKEN_RE, PyFile

CONFIG_REL = "exec/config.py"
COMPARE_REL = "telemetry/compare.py"
TUNE_REL = "exec/tune.py"
FAULTS_REL = "resilience/faults.py"
AGGREGATE_REL = "telemetry/aggregate.py"
SLO_REL = "telemetry/slo.py"
OBSERVABILITY_DOC = "docs/OBSERVABILITY.md"
RESILIENCE_DOC = "docs/RESILIENCE.md"

# area/name slash-path grammar for counter and histogram names: lowercase
# [a-z0-9_] segments, at least area + one name segment. Gauges and spans
# may be single-segment (gauge convention is `langdetect_*`; spans nest
# under an ambient parent, so a bare segment is a legal relative name).
_METRIC_NAME_RE = re.compile(r"[a-z0-9_]+(/[a-z0-9_]+)+")
_METRIC_PREFIX_RE = re.compile(r"[a-z0-9_]+/[a-z0-9_/]*")
_LOOSE_NAME_RE = re.compile(r"[a-z0-9_]+(/[a-z0-9_]+)*")

_BACKTICK_RE = re.compile(r"`([^`]+)`")


@dataclass(frozen=True)
class Violation:
    """One contract violation, anchored to a file:line."""

    rule: str
    file: str
    line: int
    message: str
    hint: str = ""


@dataclass
class Scan:
    """Everything harvested from one tree, keyed by package-relative path.

    ``files`` holds the package's own modules; ``extra_files`` sources
    scanned for violations but outside the package namespace (bench.py).
    ``docs`` maps repo-relative doc names to their text.
    """

    files: dict[str, PyFile] = field(default_factory=dict)
    extra_files: dict[str, PyFile] = field(default_factory=dict)
    docs: dict[str, str] = field(default_factory=dict)

    def all_files(self) -> dict[str, PyFile]:
        return {**self.files, **self.extra_files}

    def module_paths(self) -> set[str]:
        """Module-ish tokens (``serve/cache``, ``exec``) that must not be
        mistaken for metric names when they appear in doc prose."""
        out: set[str] = set()
        for rel in self.files:
            p = PurePosixPath(rel)
            stem = p.with_suffix("")
            out.add(str(stem))
            out.update(str(par) for par in stem.parents if str(par) != ".")
        return out


# ------------------------------------------------------------ doc slicing ---
def _doc_section(text: str, title_words: str) -> tuple[str, int]:
    """(section body, 1-based header line) of the ``## … <title words>``
    section; ("", 0) when the doc has no such section."""
    lines = text.splitlines()
    start = None
    for i, line in enumerate(lines):
        if line.startswith("##") and title_words.lower() in line.lower():
            start = i
            break
    if start is None:
        return "", 0
    end = len(lines)
    for j in range(start + 1, len(lines)):
        if lines[j].startswith("## "):
            end = j
            break
    return "\n".join(lines[start:end]), start + 1


# ------------------------------------------------------------------- R1 -----
def check_knob_discipline(scan: Scan) -> list[Violation]:
    """R1: every LANGDETECT_* read goes through exec/config; every knob
    literal has a KNOBS row; the OBSERVABILITY.md env table covers every
    knob."""
    out: list[Violation] = []
    knobs = harvest.knob_table(scan.files.get(CONFIG_REL))
    envs = {env for env, _line in knobs.values() if env}

    for rel, pf in scan.all_files().items():
        if rel == CONFIG_REL:
            continue  # the audited table itself — the one legal reader
        for line, env_name in pf.env_reads:
            out.append(Violation(
                "R1", rel, line,
                f"direct env read of {env_name} outside {CONFIG_REL}",
                "resolve the knob through exec.config.resolve(...) so "
                "/varz effective_config reports it; a genuinely "
                "pre-config read needs an allowlist entry with a reason",
            ))

    def check_tokens(rel: str, tokens) -> None:
        seen: set[tuple[int, str]] = set()
        for line, token, wildcard in tokens:
            if (line, token) in seen:
                continue
            seen.add((line, token))
            if wildcard:
                if not any(e.startswith(token) for e in envs):
                    out.append(Violation(
                        "R1", rel, line,
                        f"knob family {token}* matches no KNOBS row",
                        "fix the family spelling or add the knobs to "
                        "exec/config.KNOBS",
                    ))
            elif token not in envs:
                out.append(Violation(
                    "R1", rel, line,
                    f"knob literal {token} has no exec/config.KNOBS row",
                    "add a Knob(...) row (name, env, type, default) or "
                    "fix the spelling — a knob outside the table is "
                    "invisible to /varz and the tuner",
                ))

    for rel, pf in scan.all_files().items():
        check_tokens(rel, pf.knob_tokens)
    for rel, text in scan.docs.items():
        tokens = []
        for lineno, line in enumerate(text.splitlines(), start=1):
            for m in KNOB_TOKEN_RE.finditer(line):
                token = m.group(0)
                wildcard = token.endswith(("*", "_"))
                token = token.rstrip("*")
                if token == "LANGDETECT_":
                    continue
                tokens.append((lineno, token, wildcard))
        check_tokens(rel, tokens)

    obs = scan.docs.get(OBSERVABILITY_DOC)
    if obs is not None and envs:
        section, header_line = _doc_section(obs, "environment variables")
        covered_exact: set[str] = set()
        covered_prefix: set[str] = set()
        for m in KNOB_TOKEN_RE.finditer(section):
            token = m.group(0)
            if token.endswith(("*", "_")):
                prefix = token.rstrip("*")
                # A generic "every LANGDETECT_* knob" mention documents
                # nothing — only a named family narrows coverage.
                if prefix != "LANGDETECT_":
                    covered_prefix.add(prefix)
            else:
                covered_exact.add(token)
        for env in sorted(envs):
            if env in covered_exact:
                continue
            if any(env.startswith(p) for p in covered_prefix):
                continue
            out.append(Violation(
                "R1", OBSERVABILITY_DOC, header_line or 1,
                f"knob {env} missing from the environment-variable table",
                "add a row (or extend a family row) documenting the knob "
                "— the env table is the operator-facing contract for "
                "exec/config.KNOBS",
            ))
    return out


# ------------------------------------------------------------------- R2 -----
@dataclass
class _Emitted:
    counters: dict[str, tuple[str, int]] = field(default_factory=dict)
    counter_prefixes: dict[str, tuple[str, int]] = field(default_factory=dict)
    hists: dict[str, tuple[str, int]] = field(default_factory=dict)
    hist_prefixes: dict[str, tuple[str, int]] = field(default_factory=dict)
    gauges: dict[str, tuple[str, int]] = field(default_factory=dict)
    gauge_prefixes: dict[str, tuple[str, int]] = field(default_factory=dict)
    spans: dict[str, tuple[str, int]] = field(default_factory=dict)
    span_prefixes: dict[str, tuple[str, int]] = field(default_factory=dict)

    @staticmethod
    def collect(scan: Scan) -> "_Emitted":
        em = _Emitted()
        for rel, pf in scan.all_files().items():
            for attr in (
                "counters", "counter_prefixes", "hists", "hist_prefixes",
                "gauges", "gauge_prefixes", "spans", "span_prefixes",
            ):
                table = getattr(em, attr)
                for name, line in getattr(pf.emits, attr).items():
                    table.setdefault(name, (rel, line))
        return em

    def _known(self, names, prefixes, name: str) -> bool:
        if name in names:
            return True
        return any(name.startswith(p) for p in prefixes)

    def counter(self, name: str) -> bool:
        return self._known(self.counters, self.counter_prefixes, name)

    def hist(self, name: str) -> bool:
        return self._known(self.hists, self.hist_prefixes, name)

    def gauge(self, name: str) -> bool:
        return self._known(self.gauges, self.gauge_prefixes, name)

    def span(self, name: str) -> bool:
        """Spans nest under an ambient parent, so a doc'd full path
        (``score/dispatch``) matches an emitted *relative* name
        (``dispatch``) only as a whole-segment suffix — matching on the
        last segment alone would let any doc'd ghost sharing a leaf name
        with a real span slip through."""
        if self._known(self.spans, self.span_prefixes, name):
            return True
        return any(name.endswith("/" + s) for s in self.spans)

    def any_prefix_overlap(self, prefix: str) -> bool:
        """≥1 emitted name (any kind) under ``prefix``."""
        for table in (self.counters, self.hists, self.gauges, self.spans):
            if any(n.startswith(prefix) for n in table):
                return True
        for table in (
            self.counter_prefixes, self.hist_prefixes,
            self.gauge_prefixes, self.span_prefixes,
        ):
            if any(
                p.startswith(prefix) or prefix.startswith(p) for p in table
            ):
                return True
        return False


def check_telemetry_names(scan: Scan) -> list[Violation]:
    """R2: consumed names are emitted; emitted names parse; doc'd metric
    names exist."""
    out: list[Violation] = []
    em = _Emitted.collect(scan)
    cc = harvest.compare_contracts(scan.files.get(COMPARE_REL))
    tune = harvest.tune_consumed(scan.files.get(TUNE_REL))
    sites = harvest.fault_sites(scan.files.get(FAULTS_REL))

    # --- consumed-but-never-emitted --------------------------------------
    for name, line in sorted(cc.tracked_gauges.items()):
        if not em.gauge(name):
            out.append(Violation(
                "R2", COMPARE_REL, line,
                f"_TRACKED_GAUGES consumes gauge {name!r} no code emits",
                "emit it via REGISTRY.set_gauge or drop the tracked row — "
                "a tracked metric that never appears can't guard anything",
            ))
    for name, line in sorted(cc.tracked_ratio_counters.items()):
        if not em.counter(name):
            out.append(Violation(
                "R2", COMPARE_REL, line,
                f"_TRACKED_RATIOS consumes counter {name!r} no code emits",
                "emit it via REGISTRY.incr or fix the ratio definition",
            ))
    for name, line in sorted(cc.reliability_counters.items()):
        if not em.counter(name):
            out.append(Violation(
                "R2", COMPARE_REL, line,
                f"reliability counter {name!r} is diffed but never emitted",
                "emit it via REGISTRY.incr or drop it from "
                "_RELIABILITY_COUNTERS",
            ))
    for name, line in sorted(cc.informational_counters.items()):
        if not em.counter(name):
            out.append(Violation(
                "R2", COMPARE_REL, line,
                f"informational counter {name!r} is diffed but never "
                "emitted",
                "emit it via REGISTRY.incr or drop it from "
                "_INFORMATIONAL_COUNTERS",
            ))
    for prefix, line in sorted(cc.reliability_prefixes.items()):
        if not em.any_prefix_overlap(prefix):
            out.append(Violation(
                "R2", COMPARE_REL, line,
                f"reliability prefix {prefix!r} matches no emitted counter",
                "emit at least one counter under the prefix or drop it "
                "from _RELIABILITY_COUNTER_PREFIXES",
            ))
    for name, line in sorted(cc.cold_start_histograms.items()):
        if not em.hist(name):
            out.append(Violation(
                "R2", COMPARE_REL, line,
                f"cold-start histogram {name!r} is diffed but never "
                "emitted",
                "emit it via REGISTRY.observe or drop it from "
                "_COLD_START_HISTOGRAMS",
            ))
    for name, (line, kind, is_prefix) in sorted(tune.items()):
        if is_prefix:
            ok = em.any_prefix_overlap(name)
        elif kind == "histogram":
            ok = em.hist(name)
        else:
            ok = em.counter(name)
        if not ok:
            out.append(Violation(
                "R2", TUNE_REL, line,
                f"tune replays {kind} {name!r} no code emits",
                "the autotuner's input signal must be recorded somewhere "
                "— emit it or stop consuming it",
            ))

    # --- fleet observability plane (aggregate + slo) ----------------------
    # The cross-process surface: names the collector's pressure readers
    # sum and the SLO layer differentiates live in other processes, so a
    # rename at the emit site would silently zero the autoscaler's
    # pressure signal rather than crash anything. Same treatment as the
    # compare/tune tables above — consumed names must be emitted — plus
    # one extra bolt: the collector's guard counters must stay pinned in
    # compare's tables, or a scrape-failure regression stops gating.
    fc = harvest.fleet_contracts(
        scan.files.get(AGGREGATE_REL), scan.files.get(SLO_REL)
    )
    for name, line in sorted(fc.consumed_counters.items()):
        if not em.counter(name):
            out.append(Violation(
                "R2", AGGREGATE_REL, line,
                f"fleet aggregate consumes counter {name!r} no code emits",
                "the collector sums this across scraped replicas and the "
                "autoscaler sheds-pressure reads it — emit it via "
                "REGISTRY.incr or drop it from CONSUMED_COUNTERS",
            ))
    for name, line in sorted(fc.slo_counters.items()):
        if not em.counter(name):
            out.append(Violation(
                "R2", SLO_REL, line,
                f"SLO objective consumes counter {name!r} no code emits",
                "a burn rate over a never-emitted counter is identically "
                "zero — emit it or drop it from SLO_INPUT_COUNTERS",
            ))
    for name, line in sorted(fc.slo_histograms.items()):
        if not em.hist(name):
            out.append(Violation(
                "R2", SLO_REL, line,
                f"SLO objective consumes histogram {name!r} no code emits",
                "emit it via REGISTRY.observe or drop it from "
                "SLO_INPUT_HISTOGRAMS",
            ))
    for name, line in sorted(fc.slo_gauges.items()):
        if not em.gauge(name):
            out.append(Violation(
                "R2", SLO_REL, line,
                f"SLO objective consumes gauge {name!r} no code emits",
                "emit it via REGISTRY.set_gauge or drop it from "
                "SLO_INPUT_GAUGES",
            ))
    compare_tracked = set(cc.reliability_counters) | set(
        cc.informational_counters
    )
    for name, line in sorted(fc.guard_counters.items()):
        if not em.counter(name):
            out.append(Violation(
                "R2", AGGREGATE_REL, line,
                f"collector guard counter {name!r} is never emitted",
                "emit it via REGISTRY.incr or drop it from GUARD_COUNTERS",
            ))
        if name not in compare_tracked and not any(
            name.startswith(p) for p in cc.reliability_prefixes
        ):
            out.append(Violation(
                "R2", AGGREGATE_REL, line,
                f"collector guard counter {name!r} is not tracked by "
                f"{COMPARE_REL}",
                "pin it in _RELIABILITY_COUNTERS (gates regressions) or "
                "_INFORMATIONAL_COUNTERS (operator signal) — an untracked "
                "guard counter can appear against a clean baseline "
                "without compare noticing",
            ))

    # --- grammar ----------------------------------------------------------
    for name, (rel, line) in sorted(em.counters.items()):
        if not _METRIC_NAME_RE.fullmatch(name):
            out.append(Violation(
                "R2", rel, line,
                f"counter name {name!r} breaks the area/name slash-path "
                "grammar",
                "use lowercase [a-z0-9_] segments with at least area/name",
            ))
    for name, (rel, line) in sorted(em.hists.items()):
        if not _METRIC_NAME_RE.fullmatch(name):
            out.append(Violation(
                "R2", rel, line,
                f"histogram name {name!r} breaks the area/name slash-path "
                "grammar",
                "use lowercase [a-z0-9_] segments with at least area/name",
            ))
    for table in (em.counter_prefixes, em.hist_prefixes):
        for prefix, (rel, line) in sorted(table.items()):
            if not _METRIC_PREFIX_RE.fullmatch(prefix):
                out.append(Violation(
                    "R2", rel, line,
                    f"dynamic metric name head {prefix!r} breaks the "
                    "area/name grammar",
                    "f-string metric names must start with a literal "
                    "area/ head so consumers can match the family",
                ))
    for table in (em.gauges, em.spans):
        for name, (rel, line) in sorted(table.items()):
            if not _LOOSE_NAME_RE.fullmatch(name):
                out.append(Violation(
                    "R2", rel, line,
                    f"telemetry name {name!r} breaks the naming grammar",
                    "lowercase [a-z0-9_] segments, optionally slash-nested",
                ))

    # --- docs reference only names that exist -----------------------------
    obs = scan.docs.get(OBSERVABILITY_DOC)
    if obs is not None:
        derived = set(cc.tracked_ratio_names)
        skip = scan.module_paths() | set(sites)
        for title in ("span naming", "histograms and counters"):
            section, header_line = _doc_section(obs, title)
            if not section:
                continue
            offset = header_line - 1
            for lineno, line in enumerate(section.splitlines(), start=1):
                for m in _BACKTICK_RE.finditer(line):
                    token = m.group(1)
                    v = _check_doc_metric(
                        token, em, derived, skip,
                        OBSERVABILITY_DOC, offset + lineno,
                    )
                    if v is not None:
                        out.append(v)
    return out


def _check_doc_metric(
    token: str,
    em: _Emitted,
    derived: set[str],
    skip: set[str],
    doc: str,
    line: int,
) -> Violation | None:
    if any(c in token for c in "[]= ,\"'"):
        return None
    token = token.split("{")[0]
    prefix_mode = False
    if "<" in token:
        token, prefix_mode = token.split("<")[0], True
    if token.endswith("*"):
        token, prefix_mode = token.rstrip("*"), True
    if token in skip or token.rstrip("/") in skip:
        return None
    if prefix_mode:
        if not re.fullmatch(r"[a-z0-9_]+/[a-z0-9_/]*", token):
            return None
        if not em.any_prefix_overlap(token):
            return Violation(
                "R2", doc, line,
                f"doc references metric family {token!r}* no code emits",
                "fix the doc row or emit the family",
            )
        return None
    is_gauge_name = re.fullmatch(r"langdetect_[a-z0-9_]+", token)
    is_slash_name = _METRIC_NAME_RE.fullmatch(token)
    if not is_gauge_name and not is_slash_name:
        return None
    if token in derived:
        return None  # compare-derived contract metric (cache/hit_rate)
    if is_gauge_name:
        if em.gauge(token):
            return None
    elif (
        em.counter(token) or em.hist(token)
        or em.gauge(token) or em.span(token)
    ):
        return None
    return Violation(
        "R2", doc, line,
        f"doc references metric {token!r} that no code emits",
        "fix or remove the doc row — the metric tables must describe "
        "what the registry actually carries",
    )


# ------------------------------------------------------------------- R3 -----
def check_fault_sites(scan: Scan) -> list[Violation]:
    """R3: inject literals ∈ SITES; SITES all injected; SITES all in
    RESILIENCE.md §4."""
    out: list[Violation] = []
    sites = harvest.fault_sites(scan.files.get(FAULTS_REL))
    if not sites:
        return out
    used: set[str] = set()
    for rel, pf in scan.all_files().items():
        for line, site in pf.injects:
            used.add(site)
            if site not in sites:
                out.append(Violation(
                    "R3", rel, line,
                    f"faults.inject site {site!r} is not in "
                    "resilience/faults.SITES",
                    "add the site to SITES (and RESILIENCE.md §4) or fix "
                    "the literal — an unregistered site can never fire, "
                    "so its chaos coverage silently vanishes",
                ))
    for site, line in sorted(sites.items()):
        if site not in used:
            out.append(Violation(
                "R3", FAULTS_REL, line,
                f"SITES entry {site!r} has no inject() call site",
                "hook the site or retire the row — a dead registry entry "
                "lets chaos plans 'pass' without testing anything",
            ))
    res = scan.docs.get(RESILIENCE_DOC)
    if res is not None:
        section, header_line = _doc_section(res, "fault injection")
        for site, _line in sorted(sites.items()):
            if site not in section:
                out.append(Violation(
                    "R3", RESILIENCE_DOC, header_line or 1,
                    f"fault site {site!r} is undocumented in the fault-"
                    "injection section",
                    "describe the site (where it hooks, what a firing "
                    "error means) in RESILIENCE.md §4",
                ))
    return out


# ------------------------------------------------------------------- R4 -----
def check_trace_purity(scan: Scan) -> list[Violation]:
    """R4: host-impure calls inside traced (jit/pjit/shard_map/
    pallas_call) functions."""
    out: list[Violation] = []
    for rel, pf in scan.all_files().items():
        for line, context, desc in pf.impure:
            out.append(Violation(
                "R4", rel, line,
                f"host-impure call in traced function {context!r}: {desc}",
                "tracing executes this once and bakes the value into the "
                "compiled program (or silently no-ops per trace) — hoist "
                "it to the host caller or pass the value as an operand",
            ))
    return out


# ------------------------------------------------------------- assembly -----
def run_rules(scan: Scan) -> list[Violation]:
    out: list[Violation] = []
    out += check_knob_discipline(scan)
    out += check_telemetry_names(scan)
    out += check_fault_sites(scan)
    out += check_trace_purity(scan)
    for rel, pf in scan.all_files().items():
        if pf.parse_error:
            out.append(Violation(
                "R5", rel, 1,
                f"unparseable source: {pf.parse_error}",
                "the checker cannot prove contracts it cannot parse",
            ))
    out.sort(key=lambda v: (v.file, v.line, v.rule, v.message))
    return out
