"""The checked-in suppression allowlist for the shipped tree.

Each entry names one *live* exception to a contract rule, with the reason
it is genuinely exceptional — the audited alternative to deleting the
rule or sprinkling pragmas. Staleness is itself a violation: an entry
that no longer matches a real violation fails R5, so a fixed exception
must be removed from this list in the same change (docs/ANALYSIS.md §4).

Prefer fixing over listing. The bar for an entry: the read/emission is
*structurally* unable to go through the audited path (bootstrap ordering,
the module the audited path itself depends on), not merely inconvenient.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Allow:
    """One allowlist row: rule + file suffix + message substring.

    An entry suppresses at most ``count`` matching violations (default
    one): a *second* read of an allowlisted knob in the same file is a
    new regression, not part of the documented exception, and must
    surface instead of being quietly absorbed.
    """

    rule: str
    file: str  # suffix-matched against the violation's relative path
    match: str  # substring of the violation message
    reason: str
    count: int = 1  # max violations this entry may suppress


ALLOWLIST: tuple[Allow, ...] = (
    Allow(
        "R1", "utils/logging.py", "LANGDETECT_TPU_LOGLEVEL",
        "pre-config bootstrap: exec/config imports this module's logger, "
        "so the root level must be readable before the knob table can "
        "exist. config.py re-syncs the level through the audited table "
        "(sync_level_from_config) the moment it finishes importing, and "
        "/varz reports the knob's live value.",
    ),
)
