"""Contract checker CLI + orchestration.

    python -m spark_languagedetector_tpu.analysis.check [--json] [--root DIR]

Scans the package source (plus ``bench.py`` and the ``docs/`` tables when
run from a repo checkout), applies the R1-R4 rule families from
:mod:`.rules`, then the R5 suppression pass: inline
``# contract: ignore[R?] -- reason`` pragmas and the checked-in
:mod:`.allowlist`, where a suppression that no longer suppresses anything
is itself a violation. Exit 0 = clean, 1 = unsuppressed violations,
2 = usage error. ``--json`` emits the machine-readable report (schema
pinned by tests/test_analysis.py) for external CI.

Pure stdlib and purely static — no jax import, no package-module import,
no device work; the whole tree checks in well under the 5s budget.
"""

from __future__ import annotations

import json
import sys
from dataclasses import asdict, dataclass, field
from pathlib import Path

from . import harvest, rules
from .allowlist import ALLOWLIST, Allow
from .rules import Scan, Violation

RULE_IDS = ("R1", "R2", "R3", "R4", "R5")
JSON_SCHEMA_VERSION = 1

# Doc files whose tables are part of the contract surface. Anything
# matching docs/*.md and README.md is scanned for knob literals; these
# two additionally carry table-sync rules (R1 env table, R2 metric
# tables, R3 site table).
_DOC_GLOBS = ("docs/*.md", "README.md")


@dataclass
class Report:
    """One checker run's outcome."""

    package: str
    violations: list[Violation] = field(default_factory=list)
    suppressed: list[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def counts(self) -> dict[str, int]:
        out = {r: 0 for r in RULE_IDS}
        for v in self.violations:
            out[v.rule] = out.get(v.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "schema": JSON_SCHEMA_VERSION,
            "package": self.package,
            "ok": self.ok,
            "total": len(self.violations),
            "counts": self.counts(),
            "violations": [asdict(v) for v in self.violations],
            "suppressed": list(self.suppressed),
        }

    def render(self) -> str:
        lines = []
        for v in self.violations:
            lines.append(f"{v.rule} {v.file}:{v.line}  {v.message}")
            if v.hint:
                lines.append(f"     hint: {v.hint}")
        counts = ", ".join(
            f"{r}={n}" for r, n in self.counts().items() if n
        )
        if self.violations:
            lines.append(
                f"{len(self.violations)} unsuppressed violation(s) "
                f"({counts}); {len(self.suppressed)} suppressed"
            )
        else:
            lines.append(
                f"contracts hold: 0 unsuppressed violations "
                f"({len(self.suppressed)} suppressed)"
            )
        return "\n".join(lines)


# ---------------------------------------------------------------- scan ------
def build_scan(
    package_dir: Path,
    repo_root: Path | None = None,
) -> Scan:
    """Harvest a package tree (+ the repo-level extras when present)."""
    scan = Scan()
    for path in sorted(package_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(package_dir).as_posix()
        scan.files[rel] = harvest.harvest_file(path, rel)
    if repo_root is not None:
        bench = repo_root / "bench.py"
        if bench.is_file():
            scan.extra_files["bench.py"] = harvest.harvest_file(
                bench, "bench.py"
            )
        for glob in _DOC_GLOBS:
            for path in sorted(repo_root.glob(glob)):
                rel = path.relative_to(repo_root).as_posix()
                scan.docs[rel] = path.read_text(encoding="utf-8")
    return scan


# --------------------------------------------------------- suppression ------
def _apply_suppressions(
    scan: Scan,
    violations: list[Violation],
    allowlist: tuple[Allow, ...],
) -> tuple[list[Violation], list[dict]]:
    """(surviving violations, suppressed records) + R5 staleness rows.

    A pragma suppresses a violation of a named rule on its own line or
    the line directly below (pragma-above style). Every pragma and every
    allowlist entry must suppress at least one raw violation — a stale
    suppression hides nothing and therefore *is* a violation (R5), which
    is what keeps the suppression surface honest as code moves. An
    allowlist entry suppresses at most ``count`` matches (default one),
    so a NEW violation that happens to match an existing entry's pattern
    still surfaces instead of riding the documented exception.
    """
    files = scan.all_files()
    used_pragmas: set[tuple[str, int]] = set()
    used_allows: dict[int, int] = {}
    remaining: list[Violation] = []
    suppressed: list[dict] = []

    for v in violations:
        pf = files.get(v.file)
        handled = False
        if pf is not None:
            for pline in (v.line, v.line - 1):
                pragma = pf.pragmas.get(pline)
                if pragma and v.rule in pragma[0]:
                    used_pragmas.add((v.file, pline))
                    suppressed.append({
                        **asdict(v), "via": "pragma", "reason": pragma[1],
                    })
                    handled = True
                    break
        if not handled:
            for i, allow in enumerate(allowlist):
                if (
                    allow.rule == v.rule
                    and v.file.endswith(allow.file)
                    and allow.match in v.message
                    and used_allows.get(i, 0) < allow.count
                ):
                    used_allows[i] = used_allows.get(i, 0) + 1
                    suppressed.append({
                        **asdict(v), "via": "allowlist",
                        "reason": allow.reason,
                    })
                    handled = True
                    break
        if not handled:
            remaining.append(v)

    for rel, pf in files.items():
        for line, (rule_ids, _reason) in sorted(pf.pragmas.items()):
            bogus = [r for r in rule_ids if r not in RULE_IDS]
            if bogus:
                remaining.append(Violation(
                    "R5", rel, line,
                    f"pragma names unknown rule id(s) {bogus}",
                    f"rule ids are {', '.join(RULE_IDS)}",
                ))
            elif (rel, line) not in used_pragmas:
                remaining.append(Violation(
                    "R5", rel, line,
                    "stale suppression pragma: it suppresses nothing",
                    "the violation it covered is gone — delete the pragma "
                    "so the suppression surface tracks reality",
                ))
    for i, allow in enumerate(allowlist):
        if allow.rule not in RULE_IDS:
            remaining.append(Violation(
                "R5", "analysis/allowlist.py", 1,
                f"allowlist entry names unknown rule id {allow.rule!r}",
                f"rule ids are {', '.join(RULE_IDS)}",
            ))
        elif i not in used_allows:
            remaining.append(Violation(
                "R5", "analysis/allowlist.py", 1,
                f"stale allowlist entry ({allow.rule} {allow.file!r} "
                f"matching {allow.match!r}) suppresses nothing",
                "the exception it documented is gone — remove the entry",
            ))
    remaining.sort(key=lambda v: (v.file, v.line, v.rule, v.message))
    return remaining, suppressed


# ----------------------------------------------------------- entry points ---
def run_checks(
    package_dir: Path | None = None,
    repo_root: Path | None = None,
    allowlist: tuple[Allow, ...] | None = None,
) -> Report:
    """Run every rule family over ``package_dir`` and return the report.

    Defaults audit this installed package itself, with the repo-checkout
    extras (bench.py, docs tables) when the package sits inside one.
    """
    if package_dir is None:
        package_dir = Path(__file__).resolve().parent.parent
    if repo_root is None:
        candidate = package_dir.parent
        if (candidate / "docs").is_dir():
            repo_root = candidate
    if allowlist is None:
        allowlist = ALLOWLIST
    scan = build_scan(package_dir, repo_root)
    raw = rules.run_rules(scan)
    remaining, suppressed = _apply_suppressions(scan, raw, allowlist)
    return Report(
        package=str(package_dir), violations=remaining,
        suppressed=suppressed,
    )


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = False
    root: Path | None = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--json":
            as_json = True
            i += 1
        elif a == "--root":
            if i + 1 >= len(argv):
                print("error: --root needs a directory", file=sys.stderr)
                return 2
            root = Path(argv[i + 1])
            i += 2
        elif a in ("-h", "--help"):
            print(
                "usage: python -m spark_languagedetector_tpu.analysis."
                "check [--json] [--root DIR]\n\n"
                "Static contract checker (docs/ANALYSIS.md): knob "
                "discipline, telemetry name contract, fault-site "
                "registry, trace purity, suppression audit.",
            )
            return 0
        else:
            print(f"error: unknown option {a!r}", file=sys.stderr)
            return 2
    if root is not None:
        package_dir = root / "spark_languagedetector_tpu"
        if not package_dir.is_dir():
            print(
                f"error: {package_dir} is not a package checkout",
                file=sys.stderr,
            )
            return 2
        report = run_checks(package_dir, root)
    else:
        report = run_checks()
    if as_json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
